"""GGUF metadata + tokenizer reader (reference lib/llm/src/gguf/:
content.rs metadata extraction + gguf_tokenizer.rs:587 tokenizer
conversion). Pure-python reader of the public GGUF v2/v3 container:
header, typed metadata KV table, and tensor descriptors (tensor DATA is
not loaded — the reference uses GGUF for model metadata + tokenizer the
same way).

Provides:
  - ``read_gguf(path)`` -> (metadata dict, tensor descriptors)
  - ``config_from_gguf(metadata)`` -> ModelConfig (llama-family keys)
  - ``GgufTokenizer`` — a faithful SentencePiece-unigram
    encoder/decoder built from ``tokenizer.ggml.tokens``/``scores``
    (Viterbi segmentation + byte fallback, the llama tokenizer family's
    actual algorithm); BPE-style GGUF vocabs are detected and rejected
    with a clear error rather than approximated.
"""
from __future__ import annotations

import struct
from typing import Any, BinaryIO, Optional

GGUF_MAGIC = b"GGUF"

# metadata value types (spec)
_T_U8, _T_I8, _T_U16, _T_I16, _T_U32, _T_I32, _T_F32, _T_BOOL = range(8)
_T_STRING, _T_ARRAY, _T_U64, _T_I64, _T_F64 = 8, 9, 10, 11, 12

_SCALAR_FMT = {
    _T_U8: "<B", _T_I8: "<b", _T_U16: "<H", _T_I16: "<h",
    _T_U32: "<I", _T_I32: "<i", _T_F32: "<f", _T_U64: "<Q",
    _T_I64: "<q", _T_F64: "<d",
}


def _read_fmt(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    data = f.read(size)
    if len(data) != size:
        raise ValueError("truncated GGUF file")
    return struct.unpack(fmt, data)[0]


def _read_string(f: BinaryIO) -> str:
    n = _read_fmt(f, "<Q")
    if n > 1 << 30:
        raise ValueError("implausible GGUF string length")
    return f.read(n).decode("utf-8", errors="replace")


def _read_value(f: BinaryIO, vtype: int) -> Any:
    if vtype in _SCALAR_FMT:
        return _read_fmt(f, _SCALAR_FMT[vtype])
    if vtype == _T_BOOL:
        return bool(_read_fmt(f, "<B"))
    if vtype == _T_STRING:
        return _read_string(f)
    if vtype == _T_ARRAY:
        etype = _read_fmt(f, "<I")
        count = _read_fmt(f, "<Q")
        if count > 1 << 28:
            raise ValueError("implausible GGUF array length")
        return [_read_value(f, etype) for _ in range(count)]
    raise ValueError(f"unknown GGUF value type {vtype}")


def read_gguf(path: str) -> tuple[dict[str, Any], list[dict[str, Any]]]:
    """Parse header + metadata + tensor descriptors (no tensor data)."""
    with open(path, "rb") as f:
        if f.read(4) != GGUF_MAGIC:
            raise ValueError(f"{path}: not a GGUF file")
        version = _read_fmt(f, "<I")
        if version not in (2, 3):
            raise ValueError(f"unsupported GGUF version {version}")
        n_tensors = _read_fmt(f, "<Q")
        n_kv = _read_fmt(f, "<Q")
        metadata: dict[str, Any] = {"gguf.version": version}
        for _ in range(n_kv):
            key = _read_string(f)
            vtype = _read_fmt(f, "<I")
            metadata[key] = _read_value(f, vtype)
        tensors = []
        for _ in range(n_tensors):
            name = _read_string(f)
            n_dims = _read_fmt(f, "<I")
            dims = [_read_fmt(f, "<Q") for _ in range(n_dims)]
            dtype = _read_fmt(f, "<I")
            offset = _read_fmt(f, "<Q")
            tensors.append({
                "name": name, "dims": dims, "dtype": dtype,
                "offset": offset,
            })
        return metadata, tensors


def config_from_gguf(md: dict[str, Any]) -> "Any":
    """ModelConfig from llama-family GGUF metadata keys."""
    from dynamo_tpu.models.config import ModelConfig

    arch = md.get("general.architecture", "llama")
    if arch not in ("llama", "llama2", "llama3"):
        raise ValueError(f"unsupported GGUF architecture {arch!r}")

    def k(name, default=None):
        return md.get(f"{arch}.{name}", default)

    heads = int(k("attention.head_count"))
    emb = int(k("embedding_length"))
    n_vocab = md.get(f"{arch}.vocab_size")
    if n_vocab is None:
        n_vocab = len(md.get("tokenizer.ggml.tokens", []) or [])
    return ModelConfig(
        vocab_size=int(n_vocab),
        hidden_size=emb,
        intermediate_size=int(k("feed_forward_length")),
        num_layers=int(k("block_count")),
        num_heads=heads,
        num_kv_heads=int(k("attention.head_count_kv", heads)),
        head_dim=int(k("attention.key_length", emb // heads)),
        rope_theta=float(k("rope.freq_base", 10000.0)),
        rms_norm_eps=float(k("attention.layer_norm_rms_epsilon", 1e-5)),
        max_position_embeddings=int(k("context_length", 8192)),
    )


class GgufTokenizer:
    """SentencePiece-unigram tokenizer from GGUF vocab tables.

    Encode = Viterbi segmentation maximizing summed piece scores (the SPM
    algorithm), with byte-fallback pieces (<0xNN>) for uncovered bytes.
    Decode maps pieces back, translating the U+2581 space marker."""

    SPACE = "▁"

    def __init__(self, tokens: list[str], scores: list[float],
                 bos_id: Optional[int] = None, eos_id: Optional[int] = None,
                 add_bos: bool = True, unk_id: int = 0):
        self.tokens = tokens
        self.scores = scores
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.add_bos = add_bos and bos_id is not None
        self.unk_id = unk_id
        self.piece_to_id = {t: i for i, t in enumerate(tokens)}
        self.max_piece_len = max((len(t) for t in tokens), default=1)
        self._byte_ids = {}
        for i, t in enumerate(tokens):
            if len(t) == 6 and t.startswith("<0x") and t.endswith(">"):
                self._byte_ids[int(t[3:5], 16)] = i

    @classmethod
    def from_metadata(cls, md: dict[str, Any]) -> "GgufTokenizer":
        model = md.get("tokenizer.ggml.model", "llama")
        if model not in ("llama", "spm"):
            raise ValueError(
                f"GGUF tokenizer model {model!r} is not supported "
                "(SentencePiece-unigram only; BPE GGUFs need their "
                "original HF tokenizer)"
            )
        tokens = md.get("tokenizer.ggml.tokens")
        scores = md.get("tokenizer.ggml.scores")
        if not tokens:
            raise ValueError("GGUF file carries no tokenizer vocab")
        if not scores:
            scores = [0.0] * len(tokens)
        return cls(
            list(tokens), [float(s) for s in scores],
            bos_id=md.get("tokenizer.ggml.bos_token_id"),
            eos_id=md.get("tokenizer.ggml.eos_token_id"),
            add_bos=bool(md.get("tokenizer.ggml.add_bos_token", True)),
            unk_id=int(md.get("tokenizer.ggml.unknown_token_id", 0) or 0),
        )

    # ---- encode (Viterbi over piece scores) ----

    def _segment(self, text: str) -> list[int]:
        n = len(text)
        NEG = -1e18
        best = [NEG] * (n + 1)
        back: list[Optional[tuple[int, int]]] = [None] * (n + 1)
        best[0] = 0.0
        for i in range(n):
            if best[i] <= NEG / 2:
                continue
            hi = min(n, i + self.max_piece_len)
            for j in range(i + 1, hi + 1):
                pid = self.piece_to_id.get(text[i:j])
                if pid is None:
                    continue
                s = best[i] + self.scores[pid]
                if s > best[j]:
                    best[j] = s
                    back[j] = (i, pid)
            # byte fallback keeps segmentation total (scored far below
            # any real piece, as SPM does)
            bts = text[i].encode("utf-8")
            if all(b in self._byte_ids for b in bts):
                s = best[i] - 1e6 * len(bts)
                if s > best[i + 1]:
                    best[i + 1] = s
                    back[i + 1] = (i, -1)
        if back[n] is None:
            return [self.unk_id]
        out: list[int] = []
        pos = n
        while pos > 0:
            i, pid = back[pos]
            if pid == -1:
                out.extend(reversed([
                    self._byte_ids[b] for b in text[i:pos].encode("utf-8")
                ]))
            else:
                out.append(pid)
            pos = i
        out.reverse()
        return out

    def encode(self, text: str) -> list[int]:
        norm = self.SPACE + text.replace(" ", self.SPACE)
        ids = self._segment(norm)
        if self.add_bos:
            return [self.bos_id] + ids
        return ids

    # ---- decode ----

    def decode(self, ids: list[int]) -> str:
        parts: list[str] = []
        pending: list[int] = []

        def flush_bytes():
            if pending:
                parts.append(bytes(pending).decode("utf-8",
                                                   errors="replace"))
                pending.clear()

        for i in ids:
            if i in (self.bos_id, self.eos_id):
                continue
            t = self.tokens[i] if 0 <= i < len(self.tokens) else ""
            if len(t) == 6 and t.startswith("<0x") and t.endswith(">"):
                pending.append(int(t[3:5], 16))
                continue
            flush_bytes()
            parts.append(t.replace(self.SPACE, " "))
        flush_bytes()
        text = "".join(parts)
        return text[1:] if text.startswith(" ") else text

    @property
    def stop_token_ids(self) -> list[int]:
        return [self.eos_id] if self.eos_id is not None else []
