"""KV block transfer plane — the TPU-native NIXL equivalent.

Reference shape (lib/llm/src/block_manager.rs:54,120-130
``SerializedNixlBlockSet``, block/nixl.rs RemoteBlock,
examples/llm/utils/nixl.py:116): workers export a *blockset descriptor*
(who am I, where is my data plane, what layout do my blocks have) through
the control-plane store, and peers move whole KV pages directly
worker-to-worker with async one-sided reads/writes.

TPU redesign: there is no peer RDMA between separate engine processes, so
the data plane is **host-staged**: pages are gathered on device ([2, L,
kvh, n, ps, hd] in one fused jit), DMA'd to host, streamed over TCP as one
two-part frame (JSON header + raw bytes), and scattered back into the
receiving pool in one donated jit. Within a process/mesh the same
gather/scatter jits move pages over ICI without touching the host. The
wire protocol and descriptor flow are transport-independent, so a future
DCN/ICI fast path slots in behind the same API.

Ops:
  {"op": "write_pages", "pages": [...], "shape": [...], "dtype": "..."} + payload
      -> {"ok": true}
  {"op": "read_pages", "pages": [...]}
      -> {"ok": true, "shape": [...], "dtype": "..."} + payload
"""
from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Optional

import ml_dtypes  # noqa: F401 — registers bfloat16 with np.dtype
import numpy as np

from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.protocol import (
    encode_frame2,
    encode_frame2_header,
    read_frame2,
)

log = logging.getLogger(__name__)


def _write_array_frame(
    writer: asyncio.StreamWriter, header: dict[str, Any], data: np.ndarray
) -> None:
    """Write header + array payload without copying the array: the length
    prefix and header go as one small bytes, the payload as a zero-copy
    byte view (multi-GiB transfers would otherwise pay an extra memcpy and
    2x peak host memory per hop)."""
    data = np.ascontiguousarray(data)
    payload = data.view(np.uint8).reshape(-1)
    writer.write(encode_frame2_header(header, payload.nbytes))
    writer.write(memoryview(payload))

KV_META_PREFIX = "_kvmeta/"


def kvmeta_key(namespace: str, worker_id: str) -> str:
    return f"dynamo://{namespace}/{KV_META_PREFIX}{worker_id}"


@dataclass
class KvCacheLayout:
    """Block geometry; both sides must agree before pages move."""

    num_layers: int
    num_kv_heads: int
    page_size: int
    head_dim: int
    dtype: str = "bfloat16"

    def page_shape(self, n_pages: int) -> tuple[int, ...]:
        # matches llama.gather_pages: [2(k/v), L, kvh, n, ps, hd]
        return (2, self.num_layers, self.num_kv_heads, n_pages,
                self.page_size, self.head_dim)


@dataclass
class BlocksetDescriptor:
    """What a worker publishes so peers can address its KV pool
    (SerializedNixlBlockSet equivalent)."""

    worker_id: str
    host: str
    port: int
    layout: KvCacheLayout

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "BlocksetDescriptor":
        d = json.loads(s)
        d["layout"] = KvCacheLayout(**d["layout"])
        return cls(**d)


async def publish_descriptor(
    kv: KvClient, namespace: str, desc: BlocksetDescriptor, lease: int = 0
) -> None:
    """Metadata via the store (reference: NIXL agent metadata via etcd,
    utils/nixl.py:116). Lease-bound: dies with the worker."""
    await kv.put(kvmeta_key(namespace, desc.worker_id), desc.to_json(),
                 lease=lease)


async def get_descriptor(
    kv: KvClient, namespace: str, worker_id: str
) -> Optional[BlocksetDescriptor]:
    v = await kv.get(kvmeta_key(namespace, worker_id))
    return None if v is None else BlocksetDescriptor.from_json(v)


# ---------------------------------------------------------------------------
# Data-plane server

# read_fn(page_ids) -> np.ndarray [2, L, kvh, n, ps, hd]
# write_fn(page_ids, data) -> None — or (page_ids, data, job_id) when the
# writer tags frames with a job id (disagg guarded writes: the owner
# validates the job is still live before scattering)
ReadFn = Callable[[list[int]], np.ndarray]
WriteFn = Callable[..., None]


class BlockTransferServer:
    """Serves a worker's KV pool for peer page reads/writes.

    The owner supplies read/write callables (the engine's thread-safe
    export/import hooks, or direct pool access in tests); they may block on
    device DMA, so they run in the default executor."""

    def __init__(
        self,
        read_fn: Optional[ReadFn] = None,
        write_fn: Optional[WriteFn] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        read_hashes_fn: Optional[
            Callable[[list[int]], tuple[int, Optional[np.ndarray]]]
        ] = None,
    ):
        self.read_fn = read_fn
        self.write_fn = write_fn
        self.host = host
        self.port = port
        self.read_hashes_fn = read_hashes_fn
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                header, payload = await read_frame2(reader)
                op = header.get("op")
                try:
                    if op == "write_pages":
                        if self.write_fn is None:
                            raise RuntimeError("writes not accepted")
                        pages = [int(p) for p in header["pages"]]
                        data = np.frombuffer(
                            payload, dtype=np.dtype(header["dtype"])
                        ).reshape(header["shape"])
                        args = (pages, data)
                        if header.get("job") is not None:
                            args = (pages, data, header["job"])
                        await loop.run_in_executor(
                            None, self.write_fn, *args
                        )
                        writer.write(encode_frame2({"ok": True}, b""))
                    elif op == "read_pages":
                        if self.read_fn is None:
                            raise RuntimeError("reads not accepted")
                        pages = [int(p) for p in header["pages"]]
                        data = await loop.run_in_executor(
                            None, self.read_fn, pages
                        )
                        _write_array_frame(
                            writer,
                            {"ok": True, "shape": list(data.shape),
                             "dtype": data.dtype.name},
                            data,
                        )
                    elif op == "read_hashes":
                        # G4 remote tier: resolve a chained-hash run
                        # against this worker's sealed pool and export the
                        # longest present prefix (reference
                        # block_manager.rs:69-82 remote CacheLevel)
                        if self.read_hashes_fn is None:
                            raise RuntimeError("hash reads not accepted")
                        hs = [int(h) for h in header["hashes"]]
                        found, data = await loop.run_in_executor(
                            None, self.read_hashes_fn, hs
                        )
                        if not found or data is None:
                            writer.write(encode_frame2(
                                {"ok": True, "found": 0}, b""
                            ))
                        else:
                            _write_array_frame(
                                writer,
                                {"ok": True, "found": int(found),
                                 "shape": list(data.shape),
                                 "dtype": data.dtype.name},
                                data,
                            )
                    else:
                        raise RuntimeError(f"unknown op {op!r}")
                except Exception as e:  # noqa: BLE001 — answer in-band
                    log.exception("block transfer op %s failed", op)
                    writer.write(encode_frame2(
                        {"ok": False, "error": str(e)}, b""
                    ))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError):
            pass
        except (ValueError, json.JSONDecodeError):
            # desynced/oversized framing from a buggy peer: close cleanly
            log.warning("malformed block-transfer frame; closing connection")
        finally:
            writer.close()


# ---------------------------------------------------------------------------
# Data-plane client

class BlockTransferError(RuntimeError):
    pass


async def write_remote_pages(
    host: str, port: int, pages: list[int], data: np.ndarray,
    job_id: Optional[str] = None,
) -> None:
    """One-sided write: push pages into a peer's pool (NIXL-write path —
    prefill pushing computed KV into decode's pre-allocated pages).
    `job_id` tags the frame so the receiver can reject writes for a job it
    has since cancelled (stale-queue protection)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        header = {"op": "write_pages", "pages": [int(p) for p in pages],
                  "shape": list(data.shape), "dtype": data.dtype.name}
        if job_id is not None:
            header["job"] = job_id
        _write_array_frame(writer, header, data)
        await writer.drain()
        header, _ = await read_frame2(reader)
        if not header.get("ok"):
            raise BlockTransferError(header.get("error", "write failed"))
    finally:
        writer.close()


async def read_remote_pages(
    host: str, port: int, pages: list[int]
) -> np.ndarray:
    """One-sided read: pull pages out of a peer's pool (onboard path)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(encode_frame2(
            {"op": "read_pages", "pages": [int(p) for p in pages]}, b""
        ))
        await writer.drain()
        header, payload = await read_frame2(reader)
        if not header.get("ok"):
            raise BlockTransferError(header.get("error", "read failed"))
        return np.frombuffer(
            payload, dtype=np.dtype(header["dtype"])
        ).reshape(header["shape"]).copy()
    finally:
        writer.close()


async def read_remote_hashes(
    host: str, port: int, hashes: list[int]
) -> tuple[int, Optional[np.ndarray]]:
    """One-sided hash-addressed read: ask a peer for the longest prefix of
    the chained-hash run its pool holds (G4 path). Returns (found, pages
    [2, L, kvh, found, ps, hd]) — (0, None) on full miss."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(encode_frame2(
            {"op": "read_hashes", "hashes": [int(h) for h in hashes]}, b""
        ))
        await writer.drain()
        header, payload = await read_frame2(reader)
        if not header.get("ok"):
            raise BlockTransferError(header.get("error", "read failed"))
        found = int(header.get("found", 0))
        if not found:
            return 0, None
        return found, np.frombuffer(
            payload, dtype=np.dtype(header["dtype"])
        ).reshape(header["shape"]).copy()
    finally:
        writer.close()


class RemoteKvFetcher:
    """KVBM G4: the remote cache tier (reference block_manager.rs:69-82
    CacheLevel::G4, storage/nixl.rs:403 NIXL-backed remote storage).

    TPU redesign: instead of a dedicated remote store, the "remote tier"
    is every PEER worker's sealed pool, addressed by chained block hash
    over the existing transfer plane. A prefix that misses G1/G2/G3
    locally is fetched from whichever peer holds it (scaled-up workers
    warm themselves from the fleet instead of recomputing), landing in
    the G2 host tier so the normal onboard path takes over."""

    def __init__(self, kv: KvClient, namespace: str, self_worker_id: str,
                 timeout_s: float = 3.0):
        self.kv = kv
        self.namespace = namespace
        self.self_id = self_worker_id
        self.timeout_s = timeout_s
        self.fetches = 0
        self.hits = 0

    async def fetch(
        self, hashes: list[int]
    ) -> tuple[int, Optional[np.ndarray]]:
        """Probe every peer CONCURRENTLY; the longest returned prefix
        wins. (0, None) if no peer holds anything. timeout_s bounds the
        WHOLE probe round, not each peer — this runs on the
        request-submit path, so dead peers must cost one timeout total,
        never one timeout each."""
        self.fetches += 1
        rows = await self.kv.get_prefix(
            f"dynamo://{self.namespace}/{KV_META_PREFIX}"
        )
        peers = []
        for _key, val, _ver in rows:
            try:
                desc = BlocksetDescriptor.from_json(val)
            except (ValueError, KeyError, TypeError):
                continue
            if desc.worker_id != self.self_id:
                peers.append(desc)
        if not peers:
            return 0, None

        async def probe(desc):
            try:
                return await read_remote_hashes(desc.host, desc.port, hashes)
            except (OSError, BlockTransferError):
                return 0, None

        results = await asyncio.gather(
            *[asyncio.wait_for(probe(d), timeout=self.timeout_s)
              for d in peers],
            return_exceptions=True,
        )
        best: tuple[int, Optional[np.ndarray]] = (0, None)
        for res in results:
            if isinstance(res, BaseException):
                continue
            if res[0] > best[0]:
                best = res
        if best[0]:
            self.hits += 1
        return best


class ArrayFrameServer:
    """One-shot array handoff over the frame2 codec (zero-copy send):
    producers park an array under a ticket; exactly one peer collects it.

    Carries multimodal embedding tensors from the encode worker to the
    LLM worker (reference encode_worker.py:148 moves them via NIXL) —
    a LLaVA-scale image is ~9 MB of f32 rows, which must not transit the
    control-plane RPC as JSON float lists. Unclaimed arrays expire."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 ttl_s: float = 120.0,
                 advertise_host: Optional[str] = None):
        self.bind_host = host
        # what tickets carry: peers on OTHER machines must be able to
        # reach it (the bind address 0.0.0.0 is not routable; loopback
        # only works intra-host)
        self.host = advertise_host or (
            host if host not in ("0.0.0.0", "") else "127.0.0.1"
        )
        self.port = port
        self.ttl_s = ttl_s
        self._parked: dict[str, tuple[float, np.ndarray]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._seq = 0

    def park(self, array: np.ndarray) -> str:
        import time

        self._seq += 1
        ticket = f"t{self._seq}"
        now = time.monotonic()
        self._parked[ticket] = (now, np.ascontiguousarray(array))
        # opportunistic expiry sweep (no background task to manage)
        dead = [t for t, (ts, _) in self._parked.items()
                if now - ts > self.ttl_s]
        for t in dead:
            del self._parked[t]
        return ticket

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._on_conn, self.bind_host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self._parked.clear()

    async def _on_conn(self, reader, writer) -> None:
        try:
            while True:
                header, _ = await read_frame2(reader)
                ent = self._parked.pop(header.get("ticket", ""), None)
                if ent is None:
                    writer.write(encode_frame2(
                        {"ok": False, "error": "unknown or expired ticket"},
                        b"",
                    ))
                else:
                    data = ent[1]
                    _write_array_frame(
                        writer,
                        {"ok": True, "shape": list(data.shape),
                         "dtype": data.dtype.name},
                        data,
                    )
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, ValueError):
            pass
        finally:
            writer.close()


async def take_remote_array(host: str, port: int, ticket: str) -> np.ndarray:
    """Collect (and consume) a parked array."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(encode_frame2({"op": "take", "ticket": ticket}, b""))
        await writer.drain()
        header, payload = await read_frame2(reader)
        if not header.get("ok"):
            raise BlockTransferError(header.get("error", "take failed"))
        return np.frombuffer(
            payload, dtype=np.dtype(header["dtype"])
        ).reshape(header["shape"]).copy()
    finally:
        writer.close()
