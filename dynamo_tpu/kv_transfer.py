"""KV block transfer plane — the TPU-native NIXL equivalent.

Reference shape (lib/llm/src/block_manager.rs:54,120-130
``SerializedNixlBlockSet``, block/nixl.rs RemoteBlock,
examples/llm/utils/nixl.py:116): workers export a *blockset descriptor*
(who am I, where is my data plane, what layout do my blocks have) through
the control-plane store, and peers move whole KV pages directly
worker-to-worker with async one-sided reads/writes.

TPU redesign: there is no peer RDMA between separate engine processes, so
the data plane is **host-staged**: pages are gathered on device ([2, L,
kvh, n, ps, hd] in one fused jit), DMA'd to host, streamed over TCP as
two-part frames (JSON header + raw bytes), and scattered back into the
receiving pool in donated jits. Within a process/mesh the same
gather/scatter jits move pages over ICI without touching the host. The
wire protocol and descriptor flow are transport-independent, so a future
DCN/ICI fast path slots in behind the same API.

Bulk moves are **chunk-pipelined** (DistServe/Mooncake-style): instead of
one monolithic blob, a move is a multi-frame sequence of page chunks over
the same two-part codec — the sender exports+ships chunk i while chunk
i+1 is still being gathered (or, for disagg remote prefill, while the
prefill forward is still computing later chunks), and the receiver
scatters each chunk on arrival. Peak host staging per hop drops from
O(transfer) to O(chunk); the receiver acks once, at eof.

Ops:
  {"op": "write_pages", "pages": [...], "shape": [...], "dtype": "..."} + payload
      -> {"ok": true}
  {"op": "write_pages", ..., "stream": true, "seq": i} + payload
      -> (no reply per chunk; the stream is acked at eof)
  {"op": "write_pages_eof", "chunks": n}
      -> {"ok": true, "chunks": n} | {"ok": false, "error": "..."}
  {"op": "read_pages", "pages": [...]}
      -> {"ok": true, "shape": [...], "dtype": "..."} + payload
  {"op": "read_hashes", "hashes": [...], "probe": true}
      -> {"ok": true, "found": k}                       (no payload)
  {"op": "read_hashes", "hashes": [...], "chunk_pages": c}
      -> {"ok": true, "found": k, "stream": true} then k pages of
         {"seq": i, "shape": [...], "dtype": "...", "eof": bool} + payload
"""
from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import asdict, dataclass, field
from typing import Any, Callable, Iterable, Optional

import ml_dtypes  # noqa: F401 — registers bfloat16 with np.dtype
import numpy as np

from dynamo_tpu.kv_integrity import (
    KV_INTEGRITY,
    KvIntegrityError,
    page_checksums,
    verify_wire_payload,
)
from dynamo_tpu.kv_quant import (
    QuantizedPages,
    attach_wire_scales,
    from_wire,
)
from dynamo_tpu.kv_transfer_metrics import KV_TRANSFER
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.telemetry import timeline as tl
from dynamo_tpu.runtime.protocol import (
    encode_frame2,
    encode_frame2_header,
    read_frame2,
)

log = logging.getLogger(__name__)


def _array_header(data) -> tuple[np.ndarray, dict[str, Any]]:
    """(payload array, geometry header fields) for a dense array OR a
    kv_quant.QuantizedPages bundle — int8 payloads ship their per-block
    scale sidecar in the JSON header (it is ~1/(2*kvh*ps*hd) of the
    payload), so a quantized move is ~half a bf16 move's wire bytes.

    KV page frames (the 6-dim [2, L, kvh, n, ps, hd] geometry) also get
    a per-page ``kv_crc`` content-checksum list, computed over the
    pre-serialization value (bundle incl. scales) so the receiver can
    verify before scattering."""
    fields: dict[str, Any] = {}
    if isinstance(data, QuantizedPages):
        attach_wire_scales(fields, data)
        if data.data.ndim == 6:
            fields["kv_crc"] = page_checksums(data)
        data = data.data
    elif getattr(data, "ndim", 0) == 6:
        fields["kv_crc"] = page_checksums(data)
    fields["shape"] = list(data.shape)
    fields["dtype"] = data.dtype.name
    return data, fields


def _decode_payload(header: dict[str, Any], payload: bytes,
                    copy: bool = False, verify: bool = False):
    """Inverse of _array_header: the dense array, re-bundled with its
    scales when the frame carried a quantized payload. ``copy`` detaches
    the result from the frame buffer (writable, own lifetime).

    The declared geometry is validated against the received byte count
    BEFORE np.frombuffer — a malformed header becomes a typed
    BlockTransferError the server answers in-band, not a ValueError that
    kills the connection. ``verify`` additionally checks the payload
    against the frame's ``kv_crc`` list (KvIntegrityError on mismatch)."""
    try:
        dt = np.dtype(str(header["dtype"]))
        shape = tuple(int(x) for x in header["shape"])
    except (KeyError, TypeError, ValueError) as e:
        raise BlockTransferError(f"malformed frame geometry: {e}") from e
    if any(d < 0 for d in shape):
        raise BlockTransferError(
            f"malformed frame geometry: negative dim in {shape}"
        )
    expect = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
    if expect != len(payload):
        raise BlockTransferError(
            f"frame geometry {list(shape)}/{dt.name} declares {expect} "
            f"payload bytes, got {len(payload)}"
        )
    arr = np.frombuffer(payload, dtype=dt).reshape(shape)
    if copy:
        arr = arr.copy()
    try:
        out = from_wire(arr, header)
    except (TypeError, ValueError) as e:
        raise BlockTransferError(f"malformed scale sidecar: {e}") from e
    if verify:
        verify_wire_payload(header, out, context="kv-transfer frame")
    return out


def _err_kind(e: BaseException) -> str:
    return "integrity" if isinstance(e, KvIntegrityError) else "frame"


def _raise_nack(header: dict[str, Any], default: str) -> None:
    """Re-raise a receiver nack client-side with its type preserved:
    ``kind: integrity`` nacks become the retriable KvIntegrityError."""
    msg = header.get("error", default)
    if header.get("kind") == "integrity":
        raise KvIntegrityError(msg)
    raise BlockTransferError(msg)


def _write_array_frame(
    writer: asyncio.StreamWriter, header: dict[str, Any], data
) -> None:
    """Write header + array payload without copying the array: the length
    prefix and header go as one small bytes, the payload as a zero-copy
    byte view (multi-GiB transfers would otherwise pay an extra memcpy and
    2x peak host memory per hop). ``data`` may be a QuantizedPages
    bundle — its scales join the header, its int8 pages the payload."""
    data, fields = _array_header(data)
    header = {**header, **fields}
    data = np.ascontiguousarray(data)
    # chaos corrupt_frame: wire/DMA corruption on a COPY, after the crc
    # was stamped — the receiver's verify must catch it; the sender's
    # pool (which `data` may alias zero-copy) stays clean
    from dynamo_tpu.resilience.chaos import CHAOS

    data = CHAOS.maybe_corrupt_frame(data)
    payload = data.view(np.uint8).reshape(-1)
    writer.write(encode_frame2_header(header, payload.nbytes))
    writer.write(memoryview(payload))

KV_META_PREFIX = "_kvmeta/"


def kvmeta_key(namespace: str, worker_id: str) -> str:
    return f"dynamo://{namespace}/{KV_META_PREFIX}{worker_id}"


@dataclass
class KvCacheLayout:
    """Block geometry; both sides must agree before pages move."""

    num_layers: int
    num_kv_heads: int
    page_size: int
    head_dim: int
    dtype: str = "bfloat16"

    def page_shape(self, n_pages: int) -> tuple[int, ...]:
        # matches llama.gather_pages: [2(k/v), L, kvh, n, ps, hd]
        return (2, self.num_layers, self.num_kv_heads, n_pages,
                self.page_size, self.head_dim)


@dataclass
class BlocksetDescriptor:
    """What a worker publishes so peers can address its KV pool
    (SerializedNixlBlockSet equivalent)."""

    worker_id: str
    host: str
    port: int
    layout: KvCacheLayout

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "BlocksetDescriptor":
        d = json.loads(s)
        d["layout"] = KvCacheLayout(**d["layout"])
        return cls(**d)


async def publish_descriptor(
    kv: KvClient, namespace: str, desc: BlocksetDescriptor, lease: int = 0
) -> None:
    """Metadata via the store (reference: NIXL agent metadata via etcd,
    utils/nixl.py:116). Lease-bound: dies with the worker."""
    await kv.put(kvmeta_key(namespace, desc.worker_id), desc.to_json(),
                 lease=lease)


async def get_descriptor(
    kv: KvClient, namespace: str, worker_id: str
) -> Optional[BlocksetDescriptor]:
    v = await kv.get(kvmeta_key(namespace, worker_id))
    return None if v is None else BlocksetDescriptor.from_json(v)


# ---------------------------------------------------------------------------
# Data-plane server

# read_fn(page_ids) -> np.ndarray [2, L, kvh, n, ps, hd]
# write_fn(page_ids, data) -> None — or (page_ids, data, job_id) when the
# writer tags frames with a job id (disagg guarded writes: the owner
# validates the job is still live before scattering)
ReadFn = Callable[[list[int]], np.ndarray]
WriteFn = Callable[..., None]


class BlockTransferServer:
    """Serves a worker's KV pool for peer page reads/writes.

    The owner supplies read/write callables (the engine's thread-safe
    export/import hooks, or direct pool access in tests); they may block on
    device DMA, so they run in the default executor."""

    def __init__(
        self,
        read_fn: Optional[ReadFn] = None,
        write_fn: Optional[WriteFn] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        read_hashes_fn: Optional[
            Callable[[list[int]], tuple[int, Optional[np.ndarray]]]
        ] = None,
        # chunk-pipelined serving hooks (both optional; peers fall back
        # to the monolithic ops when absent):
        # count_hashes_fn(hashes) -> int — cheap committed-prefix length
        # (no gather) for the G4 probe round
        count_hashes_fn: Optional[Callable[[list[int]], int]] = None,
        # read_hashes_stream_fn(hashes, chunk_pages) -> (found, iterator
        # of host chunks) — the engine's export_hash_stream
        read_hashes_stream_fn: Optional[Callable[..., tuple[int, Any]]] = None,
    ):
        self.read_fn = read_fn
        self.write_fn = write_fn
        self.host = host
        self.port = port
        self.read_hashes_fn = read_hashes_fn
        self.count_hashes_fn = count_hashes_fn
        self.read_hashes_stream_fn = read_hashes_stream_fn
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._on_conn, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        loop = asyncio.get_running_loop()
        # chunk-stream state for THIS connection: scatter failures inside
        # a stream are remembered (later frames skipped) and reported once
        # in the eof ack — the sender pipelines frames without per-chunk
        # acks, so in-band per-frame errors would desync the protocol
        stream_chunks = 0
        stream_err: Optional[str] = None
        stream_err_kind: Optional[str] = None
        try:
            while True:
                header, payload = await read_frame2(reader)
                op = header.get("op")
                try:
                    if op == "write_pages":
                        if self.write_fn is None:
                            raise RuntimeError("writes not accepted")
                        pages = [int(p) for p in header["pages"]]
                        if header.get("stream"):
                            # one chunk of a pipelined stream: guarded
                            # scatter on arrival, ack deferred to eof
                            stream_chunks += 1
                            if stream_err is not None:
                                continue  # stream already dead
                            t0 = time.monotonic()
                            try:
                                # decode + integrity verify BEFORE the
                                # scatter: corrupt or malformed bytes
                                # never reach the pool
                                data = _decode_payload(
                                    header, payload, verify=True
                                )
                            except (BlockTransferError,
                                    KvIntegrityError) as e:
                                stream_err = str(e)
                                stream_err_kind = _err_kind(e)
                                KV_TRANSFER.inc(
                                    "dynamo_kv_transfer_errors_total"
                                )
                                log.warning(
                                    "chunk rejected mid-stream (job=%s "
                                    "seq=%s kind=%s): %s",
                                    header.get("job"), header.get("seq"),
                                    stream_err_kind, e,
                                )
                                continue
                            args = (pages, data)
                            if header.get("job") is not None:
                                args = (pages, data, header["job"])
                            try:
                                await loop.run_in_executor(
                                    None, self.write_fn, *args
                                )
                            except Exception as e:  # noqa: BLE001
                                stream_err = str(e)
                                stream_err_kind = "scatter"
                                KV_TRANSFER.inc(
                                    "dynamo_kv_transfer_errors_total"
                                )
                                log.warning(
                                    "chunk scatter failed mid-stream "
                                    "(job=%s seq=%s): %s",
                                    header.get("job"),
                                    header.get("seq"), e,
                                )
                            else:
                                KV_TRANSFER.inc(
                                    "dynamo_kv_transfer_rx_chunks_total"
                                )
                                KV_TRANSFER.inc(
                                    "dynamo_kv_transfer_rx_bytes_total",
                                    len(payload),
                                )
                                dt = time.monotonic() - t0
                                KV_TRANSFER.observe(
                                    "dynamo_kv_transfer_chunk_seconds",
                                    dt,
                                )
                                ev_job = header.get("job")
                                tl.STREAM_EVENTS.record(
                                    tl.FRAME_RECV, dt,
                                    seq=header.get("seq"),
                                    pages=len(pages),
                                    bytes=len(payload),
                                    **({"job": ev_job}
                                       if ev_job else {}),
                                )
                            continue  # no per-chunk reply
                        try:
                            data = _decode_payload(
                                header, payload, verify=True
                            )
                        except (BlockTransferError,
                                KvIntegrityError) as e:
                            # typed nack: the sender distinguishes a
                            # retriable integrity miss from a protocol
                            # bug, and the connection stays usable
                            KV_TRANSFER.inc(
                                "dynamo_kv_transfer_errors_total"
                            )
                            log.warning(
                                "write_pages rejected (kind=%s): %s",
                                _err_kind(e), e,
                            )
                            writer.write(encode_frame2(
                                {"ok": False, "error": str(e),
                                 "kind": _err_kind(e)}, b"",
                            ))
                            await writer.drain()
                            continue
                        args = (pages, data)
                        if header.get("job") is not None:
                            args = (pages, data, header["job"])
                        await loop.run_in_executor(
                            None, self.write_fn, *args
                        )
                        KV_TRANSFER.inc("dynamo_kv_transfer_rx_chunks_total")
                        KV_TRANSFER.inc(
                            "dynamo_kv_transfer_rx_bytes_total", len(payload)
                        )
                        writer.write(encode_frame2({"ok": True}, b""))
                    elif op == "write_pages_eof":
                        # close one pipelined stream: single ack carrying
                        # any deferred mid-stream failure (typed, so an
                        # integrity nack stays retriable end-to-end)
                        if stream_err is not None:
                            writer.write(encode_frame2(
                                {"ok": False, "error": stream_err,
                                 "kind": stream_err_kind,
                                 "chunks": stream_chunks}, b"",
                            ))
                        else:
                            writer.write(encode_frame2(
                                {"ok": True, "chunks": stream_chunks}, b"",
                            ))
                        stream_chunks, stream_err = 0, None
                        stream_err_kind = None
                    elif op == "read_pages":
                        if self.read_fn is None:
                            raise RuntimeError("reads not accepted")
                        pages = [int(p) for p in header["pages"]]
                        data = await loop.run_in_executor(
                            None, self.read_fn, pages
                        )
                        _write_array_frame(writer, {"ok": True}, data)
                    elif op == "read_hashes":
                        # G4 remote tier: resolve a chained-hash run
                        # against this worker's sealed pool and export the
                        # longest present prefix (reference
                        # block_manager.rs:69-82 remote CacheLevel)
                        hs = [int(h) for h in header["hashes"]]
                        if header.get("probe") and self.count_hashes_fn:
                            # cheap probe round: committed-prefix length
                            # only, no gather — losers of the peer race
                            # no longer export bytes nobody will use
                            found = await loop.run_in_executor(
                                None, self.count_hashes_fn, hs
                            )
                            writer.write(encode_frame2(
                                {"ok": True, "found": int(found)}, b""
                            ))
                            await writer.drain()
                            continue
                        cp = int(header.get("chunk_pages") or 0)
                        if cp > 0 and self.read_hashes_stream_fn:
                            await self._serve_hash_stream(
                                writer, loop, hs, cp
                            )
                            await writer.drain()
                            continue
                        if self.read_hashes_fn is None:
                            raise RuntimeError("hash reads not accepted")
                        found, data = await loop.run_in_executor(
                            None, self.read_hashes_fn, hs
                        )
                        if not found or data is None:
                            writer.write(encode_frame2(
                                {"ok": True, "found": 0}, b""
                            ))
                        else:
                            _write_array_frame(
                                writer,
                                {"ok": True, "found": int(found)},
                                data,
                            )
                            KV_TRANSFER.inc(
                                "dynamo_kv_transfer_tx_chunks_total")
                            KV_TRANSFER.inc(
                                "dynamo_kv_transfer_tx_bytes_total",
                                data.nbytes,
                            )
                    else:
                        raise RuntimeError(f"unknown op {op!r}")
                except Exception as e:  # noqa: BLE001 — answer in-band
                    log.exception("block transfer op %s failed", op)
                    writer.write(encode_frame2(
                        {"ok": False, "error": str(e)}, b""
                    ))
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError):
            pass
        except (ValueError, json.JSONDecodeError):
            # desynced/oversized framing from a buggy peer: close cleanly
            log.warning("malformed block-transfer frame; closing connection")
        finally:
            writer.close()

    async def _serve_hash_stream(
        self, writer: asyncio.StreamWriter, loop, hashes: list[int],
        chunk_pages: int,
    ) -> None:
        """Serve one chunk-pipelined hash read: lead frame with the found
        count, then one frame per chunk as the engine's export stream
        yields it — the gather/D2H of chunk i+1 runs while chunk i is on
        the wire, and the serving side never stages the whole run."""
        found, chunks = await loop.run_in_executor(
            None, self.read_hashes_stream_fn, hashes, chunk_pages
        )
        writer.write(encode_frame2(
            {"ok": True, "found": int(found), "stream": True}, b""
        ))
        if not found:
            return
        await writer.drain()
        sent_pages = 0
        seq = 0
        it = iter(chunks)
        # sentinel instead of catching StopIteration: a StopIteration
        # raised inside run_in_executor cannot be set on an asyncio
        # Future (the await would hang forever), so exhaustion must be
        # signalled in-band
        _done = object()
        while sent_pages < found:
            try:
                data = await loop.run_in_executor(None, next, it, _done)
            except Exception as e:  # noqa: BLE001 — report in-band
                log.exception("hash-stream export failed mid-stream")
                KV_TRANSFER.inc("dynamo_kv_transfer_errors_total")
                writer.write(encode_frame2(
                    {"ok": False, "error": str(e)}, b""
                ))
                return
            if data is _done:
                break
            sent_pages += int(data.shape[3])
            _write_array_frame(
                writer,
                {"ok": True, "seq": seq, "eof": sent_pages >= found},
                data,
            )
            await writer.drain()
            KV_TRANSFER.inc("dynamo_kv_transfer_tx_chunks_total")
            KV_TRANSFER.inc("dynamo_kv_transfer_tx_bytes_total", data.nbytes)
            seq += 1
        KV_TRANSFER.inc("dynamo_kv_transfer_streams_total")


# ---------------------------------------------------------------------------
# Data-plane client

class BlockTransferError(RuntimeError):
    pass


async def write_remote_pages(
    host: str, port: int, pages: list[int], data: np.ndarray,
    job_id: Optional[str] = None,
) -> None:
    """One-sided write: push pages into a peer's pool (NIXL-write path —
    prefill pushing computed KV into decode's pre-allocated pages).
    `job_id` tags the frame so the receiver can reject writes for a job it
    has since cancelled (stale-queue protection).

    An integrity nack (the receiver's checksum verify failed — the bytes
    rotted on the wire, not at rest) is retried once before the error
    propagates to the caller's fallback path."""
    for attempt in (0, 1):
        try:
            await _write_remote_pages_once(host, port, pages, data,
                                           job_id)
            return
        except KvIntegrityError:
            if attempt:
                raise
            KV_INTEGRITY.inc("dynamo_kv_integrity_retries_total")
            log.warning(
                "integrity nack on write_pages (job=%s); retrying once",
                job_id,
            )


async def _write_remote_pages_once(
    host: str, port: int, pages: list[int], data: np.ndarray,
    job_id: Optional[str] = None,
) -> None:
    reader, writer = await asyncio.open_connection(host, port)
    try:
        header = {"op": "write_pages", "pages": [int(p) for p in pages]}
        if job_id is not None:
            header["job"] = job_id
        _write_array_frame(writer, header, data)
        await writer.drain()
        KV_TRANSFER.inc("dynamo_kv_transfer_tx_chunks_total")
        KV_TRANSFER.inc("dynamo_kv_transfer_tx_bytes_total", data.nbytes)
        header, _ = await read_frame2(reader)
        if not header.get("ok"):
            KV_TRANSFER.inc("dynamo_kv_transfer_errors_total")
            _raise_nack(header, "write failed")
    finally:
        writer.close()


class PageStreamWriter:
    """One chunk-pipelined page push into a peer's pool.

    The sender writes `write_pages` frames tagged ``stream``/``seq`` as
    chunks become available (for disagg remote prefill: as the prefill
    forward commits each run of complete prefix blocks), with no
    per-chunk ack — chunk i rides the wire while chunk i+1 is still
    being computed/gathered. ``commit()`` sends the eof frame and waits
    for the single ack, which carries any deferred mid-stream scatter
    failure. Use ``abort()``/``close()`` on error paths so a dead stream
    never half-writes silently."""

    def __init__(self, host: str, port: int,
                 job_id: Optional[str] = None):
        self.host = host
        self.port = port
        self.job_id = job_id
        self.chunks_sent = 0
        self.bytes_sent = 0
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._t_open: Optional[float] = None

    async def _ensure_conn(self) -> None:
        if self._writer is None:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port
            )
            self._t_open = time.monotonic()

    async def write_chunk(self, pages: list[int], data: np.ndarray) -> None:
        """Ship one chunk (pages aligned with data's page axis)."""
        await self._ensure_conn()
        header = {
            "op": "write_pages", "pages": [int(p) for p in pages],
            "stream": True, "seq": self.chunks_sent,
        }
        if self.job_id is not None:
            header["job"] = self.job_id
        t0 = time.monotonic()
        _write_array_frame(self._writer, header, data)
        await self._writer.drain()
        self.chunks_sent += 1
        self.bytes_sent += data.nbytes
        KV_TRANSFER.inc("dynamo_kv_transfer_tx_chunks_total")
        KV_TRANSFER.inc("dynamo_kv_transfer_tx_bytes_total", data.nbytes)
        dt = time.monotonic() - t0
        KV_TRANSFER.observe("dynamo_kv_transfer_chunk_seconds", dt)
        tl.STREAM_EVENTS.record(
            tl.FRAME_SEND, dt, seq=self.chunks_sent - 1,
            pages=len(pages), bytes=int(data.nbytes),
            **({"job": self.job_id} if self.job_id else {}),
        )

    async def commit(self) -> int:
        """Eof frame + single ack; returns the receiver's chunk count.
        Raises BlockTransferError if any chunk's scatter failed."""
        await self._ensure_conn()
        self._writer.write(encode_frame2(
            {"op": "write_pages_eof", "chunks": self.chunks_sent,
             **({"job": self.job_id} if self.job_id else {})}, b"",
        ))
        await self._writer.drain()
        t_ack = time.monotonic()
        header, _ = await read_frame2(self._reader)
        tl.STREAM_EVENTS.record(
            tl.EOF_ACK_WAIT, time.monotonic() - t_ack,
            chunks=self.chunks_sent,
            **({"job": self.job_id} if self.job_id else {}),
        )
        if not header.get("ok"):
            KV_TRANSFER.inc("dynamo_kv_transfer_errors_total")
            _raise_nack(header, "chunk stream failed")
        KV_TRANSFER.inc("dynamo_kv_transfer_streams_total")
        if self._t_open is not None:
            KV_TRANSFER.observe(
                "dynamo_kv_transfer_seconds",
                time.monotonic() - self._t_open,
            )
        return int(header.get("chunks", self.chunks_sent))

    async def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None
            self._reader = None


async def write_pages_stream(
    host: str, port: int,
    chunks: Iterable[tuple[list[int], np.ndarray]],
    job_id: Optional[str] = None,
) -> int:
    """Push an iterable of (pages, data) chunks as one pipelined stream;
    returns the number of chunks acked. Convenience over PageStreamWriter
    for callers whose chunks are already materialized (tests, onboarding
    batches); the disagg prefill worker drives the writer directly so it
    can interleave sends with prefill progress.

    Chunks are materialized so an integrity nack at eof can replay the
    whole stream once (the nacked copy never reached the pool)."""
    chunks = list(chunks)
    for attempt in (0, 1):
        w = PageStreamWriter(host, port, job_id=job_id)
        try:
            for pages, data in chunks:
                await w.write_chunk(pages, data)
            return await w.commit()
        except KvIntegrityError:
            if attempt:
                raise
            KV_INTEGRITY.inc("dynamo_kv_integrity_retries_total")
            log.warning(
                "integrity nack on page stream (job=%s); retrying once",
                job_id,
            )
        finally:
            await w.close()


async def read_remote_pages(
    host: str, port: int, pages: list[int]
) -> np.ndarray:
    """One-sided read: pull pages out of a peer's pool (onboard path)."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(encode_frame2(
            {"op": "read_pages", "pages": [int(p) for p in pages]}, b""
        ))
        await writer.drain()
        header, payload = await read_frame2(reader)
        if not header.get("ok"):
            _raise_nack(header, "read failed")
        KV_TRANSFER.inc("dynamo_kv_transfer_rx_chunks_total")
        KV_TRANSFER.inc("dynamo_kv_transfer_rx_bytes_total", len(payload))
        return _decode_payload(header, payload, copy=True, verify=True)
    finally:
        writer.close()


async def probe_remote_hashes(
    host: str, port: int, hashes: list[int]
) -> tuple[int, Optional[np.ndarray]]:
    """Cheap G4 probe: how many leading blocks of the chained-hash run
    the peer's pool holds — no page export. A peer without probe support
    answers with the FULL read instead; those bytes already cost a
    gather and a wire trip, so they are decoded and returned (second
    tuple slot) rather than discarded — the caller uses them directly
    instead of asking the peer to export everything again. Raises
    BlockTransferError only when the peer errors outright."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(encode_frame2(
            {"op": "read_hashes", "hashes": [int(h) for h in hashes],
             "probe": True}, b""
        ))
        await writer.drain()
        header, payload = await read_frame2(reader)
        if not header.get("ok"):
            _raise_nack(header, "probe failed")
        found = int(header.get("found", 0))
        if payload and found:
            return found, _decode_payload(header, payload, copy=True,
                                          verify=True)
        return found, None
    finally:
        writer.close()


async def read_remote_hashes(
    host: str, port: int, hashes: list[int],
    chunk_pages: int = 0,
    on_chunk: Optional[Callable[[int, np.ndarray], None]] = None,
) -> tuple[int, Optional[np.ndarray]]:
    """One-sided hash-addressed read: ask a peer for the longest prefix of
    the chained-hash run its pool holds (G4 path). Returns (found, pages
    [2, L, kvh, found, ps, hd]) — (0, None) on full miss.

    With ``chunk_pages`` > 0 the read is chunk-pipelined: the peer
    streams the run as multi-frame chunks (its gather of chunk i+1
    overlaps chunk i's wire time) and each chunk is delivered to
    ``on_chunk(page_offset, array)`` as it arrives — the caller lands it
    (e.g. host-tier put_batch) without ever staging the whole run; the
    returned array is then None. Without ``on_chunk`` the chunks are
    reassembled and returned whole. Peers that don't stream fall back to
    the monolithic reply transparently."""
    reader, writer = await asyncio.open_connection(host, port)
    t0 = time.monotonic()
    try:
        req = {"op": "read_hashes", "hashes": [int(h) for h in hashes]}
        if chunk_pages > 0:
            req["chunk_pages"] = int(chunk_pages)
        writer.write(encode_frame2(req, b""))
        await writer.drain()
        header, payload = await read_frame2(reader)
        if not header.get("ok"):
            _raise_nack(header, "read failed")
        found = int(header.get("found", 0))
        if not found:
            return 0, None
        if not header.get("stream"):
            # monolithic reply (legacy peer or chunking off)
            KV_TRANSFER.inc("dynamo_kv_transfer_rx_chunks_total")
            KV_TRANSFER.inc("dynamo_kv_transfer_rx_bytes_total",
                            len(payload))
            data = _decode_payload(header, payload, copy=True,
                                   verify=True)
            if on_chunk is not None:
                on_chunk(0, data)
                return found, None
            return found, data
        parts: list[np.ndarray] = []
        offset = 0
        while offset < found:
            h, payload = await read_frame2(reader)
            if not h.get("ok"):
                _raise_nack(h, "chunk stream failed")
            arr = _decode_payload(h, payload, copy=True, verify=True)
            KV_TRANSFER.inc("dynamo_kv_transfer_rx_chunks_total")
            KV_TRANSFER.inc("dynamo_kv_transfer_rx_bytes_total",
                            len(payload))
            if on_chunk is not None:
                on_chunk(offset, arr)
            else:
                parts.append(arr)
            offset += int(arr.shape[3])
            if h.get("eof"):
                break
        KV_TRANSFER.observe(
            "dynamo_kv_transfer_seconds", time.monotonic() - t0
        )
        found = min(found, offset)
        if on_chunk is not None:
            return found, None
        return found, np.concatenate(parts, axis=3)
    finally:
        writer.close()


class RemoteKvFetcher:
    """KVBM G4: the remote cache tier (reference block_manager.rs:69-82
    CacheLevel::G4, storage/nixl.rs:403 NIXL-backed remote storage).

    TPU redesign: instead of a dedicated remote store, the "remote tier"
    is every PEER worker's sealed pool, addressed by chained block hash
    over the existing transfer plane. A prefix that misses G1/G2/G3
    locally is fetched from whichever peer holds it (scaled-up workers
    warm themselves from the fleet instead of recomputing), landing in
    the G2 host tier so the normal onboard path takes over.

    With ``chunk_pages`` > 0 the fetch is chunk-pipelined: peers answer a
    CHEAP probe (committed-prefix length, no page export — losers of the
    race no longer gather and ship bytes that get discarded), then the
    winner streams its run chunk by chunk and each chunk lands via
    ``on_chunk`` while later chunks are still on the wire."""

    def __init__(self, kv: KvClient, namespace: str, self_worker_id: str,
                 timeout_s: float = 3.0, chunk_pages: int = 0):
        self.kv = kv
        self.namespace = namespace
        self.self_id = self_worker_id
        self.timeout_s = timeout_s
        self.chunk_pages = chunk_pages
        self.fetches = 0
        self.hits = 0
        self.chunked_fetches = 0

    async def _peers(self) -> list[BlocksetDescriptor]:
        rows = await self.kv.get_prefix(
            f"dynamo://{self.namespace}/{KV_META_PREFIX}"
        )
        peers = []
        for _key, val, _ver in rows:
            try:
                desc = BlocksetDescriptor.from_json(val)
            except (ValueError, KeyError, TypeError):
                continue
            if desc.worker_id != self.self_id:
                peers.append(desc)
        return peers

    async def fetch(
        self, hashes: list[int],
        on_chunk: Optional[Callable[[int, np.ndarray], None]] = None,
        holders: Optional[list[str]] = None,
    ) -> tuple[int, Optional[np.ndarray]]:
        """Probe every peer CONCURRENTLY; the longest returned prefix
        wins. (0, None) if no peer holds anything. timeout_s bounds the
        WHOLE probe round, not each peer — this runs on the
        request-submit path, so dead peers must cost one timeout total,
        never one timeout each. With ``on_chunk`` the winning run is
        delivered incrementally as (page_offset, array) and the returned
        data is None. ``holders`` is the fleet view's hint of which
        worker ids hold the run: hinted peers are consulted alone first
        and the rest of the fleet is only probed when the hint turns out
        stale — dedup admission stops paying a fleet-wide probe round
        for content whose holders are already known."""
        self.fetches += 1
        peers = await self._peers()
        if not peers:
            return 0, None
        if holders:
            hinted_ids = set(holders)
            hinted = [d for d in peers if d.worker_id in hinted_ids]
            rest = [d for d in peers if d.worker_id not in hinted_ids]
            if hinted:
                got = await self._fetch_from(hinted, hashes, on_chunk)
                if got[0] or not rest:
                    return got
                peers = rest  # stale hint: fall back to un-hinted peers
        return await self._fetch_from(peers, hashes, on_chunk)

    async def _fetch_from(
        self, peers: list[BlocksetDescriptor], hashes: list[int],
        on_chunk: Optional[Callable[[int, np.ndarray], None]] = None,
    ) -> tuple[int, Optional[np.ndarray]]:
        if self.chunk_pages > 0 and on_chunk is not None:
            got = await self._fetch_chunked(peers, hashes, on_chunk)
            if got is not None:
                if got:
                    self.hits += 1
                return got, None

        async def probe(desc):
            try:
                return await read_remote_hashes(desc.host, desc.port, hashes)
            except (OSError, BlockTransferError, KvIntegrityError):
                # an integrity failure on a read is just a peer whose
                # copy is bad: treat as a miss (another holder may win)
                return 0, None

        results = await asyncio.gather(
            *[asyncio.wait_for(probe(d), timeout=self.timeout_s)
              for d in peers],
            return_exceptions=True,
        )
        best: tuple[int, Optional[np.ndarray]] = (0, None)
        for res in results:
            if isinstance(res, BaseException):
                continue
            if res[0] > best[0]:
                best = res
        if best[0]:
            self.hits += 1
        if best[0] and on_chunk is not None:
            # legacy monolithic reply: deliver through the same callback
            on_chunk(0, best[1])
            return best[0], None
        return best

    async def _fetch_chunked(
        self, peers: list[BlocksetDescriptor], hashes: list[int],
        on_chunk: Callable[[int, np.ndarray], None],
    ) -> Optional[int]:
        """Probe round + streamed fetch from the winner. None = the
        chunked path couldn't run (probe unsupported everywhere) — the
        caller falls back to the legacy full-read race."""

        async def probe(desc):
            try:
                found, data = await probe_remote_hashes(
                    desc.host, desc.port, hashes
                )
                return found, data, desc
            except (OSError, BlockTransferError, KvIntegrityError):
                return -1, None, desc

        results = await asyncio.gather(
            *[asyncio.wait_for(probe(d), timeout=self.timeout_s)
              for d in peers],
            return_exceptions=True,
        )
        holders: list[tuple[int, BlocksetDescriptor]] = []
        best_full: tuple[int, Optional[np.ndarray]] = (0, None)
        any_answered = False
        for res in results:
            if isinstance(res, BaseException):
                continue
            found, data, desc = res
            if found >= 0:
                any_answered = True
            if found > 0:
                holders.append((found, desc))
                if data is not None and found > best_full[0]:
                    # probe-less peer: it answered with the full export
                    best_full = (found, data)
        if not any_answered:
            # every peer errored/timed out on the probe round; let the
            # caller's legacy full-read race have the last word
            return None
        if not holders:
            return 0
        if best_full[0] >= max(fd[0] for fd in holders):
            # the best run already arrived whole on the probe round (a
            # peer without probe support exports eagerly) — landing it
            # beats asking any peer to gather and ship it all again
            on_chunk(0, best_full[1])
            return best_full[0]
        self.chunked_fetches += 1
        # stream from the longest-prefix holder; a dead/stalled winner
        # must not zero the fetch while a runner-up still holds the run
        # (the legacy full-read race had that redundancy), so walk the
        # holders best-first. Chunks a failed attempt already landed are
        # hash-addressed cache entries — re-delivery is idempotent. ONE
        # stream deadline bounds the whole walk — this runs on the
        # request-submit path, and the pre-chunking contract was a
        # single bounded wait before local-prefill fallback, not one
        # deadline per peer. (The deadline is still far looser than the
        # probe round's: the stream moves real bytes, and a slow host
        # link is not a dead peer.)
        holders.sort(key=lambda fd: fd[0], reverse=True)
        deadline = time.monotonic() + max(self.timeout_s * 20, 60.0)
        for _found, desc in holders:
            budget = deadline - time.monotonic()
            if budget <= 0:
                break
            try:
                found, _ = await asyncio.wait_for(
                    read_remote_hashes(
                        desc.host, desc.port, hashes,
                        chunk_pages=self.chunk_pages, on_chunk=on_chunk,
                    ),
                    timeout=budget,
                )
                return found
            except (OSError, BlockTransferError, KvIntegrityError,
                    asyncio.TimeoutError):
                log.exception("chunked G4 fetch from %s failed",
                              desc.worker_id)
        return 0  # every holder failed or the stream deadline passed


class ArrayFrameServer:
    """One-shot array handoff over the frame2 codec (zero-copy send):
    producers park an array under a ticket; exactly one peer collects it.

    Carries multimodal embedding tensors from the encode worker to the
    LLM worker (reference encode_worker.py:148 moves them via NIXL) —
    a LLaVA-scale image is ~9 MB of f32 rows, which must not transit the
    control-plane RPC as JSON float lists. Unclaimed arrays expire."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 ttl_s: float = 120.0,
                 advertise_host: Optional[str] = None):
        self.bind_host = host
        # what tickets carry: peers on OTHER machines must be able to
        # reach it (the bind address 0.0.0.0 is not routable; loopback
        # only works intra-host)
        self.host = advertise_host or (
            host if host not in ("0.0.0.0", "") else "127.0.0.1"
        )
        self.port = port
        self.ttl_s = ttl_s
        self._parked: dict[str, tuple[float, np.ndarray]] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._seq = 0

    def park(self, array: np.ndarray) -> str:
        import time

        self._seq += 1
        ticket = f"t{self._seq}"
        now = time.monotonic()
        self._parked[ticket] = (now, np.ascontiguousarray(array))
        # opportunistic expiry sweep (no background task to manage)
        dead = [t for t, (ts, _) in self._parked.items()
                if now - ts > self.ttl_s]
        for t in dead:
            del self._parked[t]
        return ticket

    async def start(self) -> tuple[str, int]:
        self._server = await asyncio.start_server(
            self._on_conn, self.bind_host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self.host, self.port

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
        self._parked.clear()

    async def _on_conn(self, reader, writer) -> None:
        try:
            while True:
                header, _ = await read_frame2(reader)
                ent = self._parked.pop(header.get("ticket", ""), None)
                if ent is None:
                    writer.write(encode_frame2(
                        {"ok": False, "error": "unknown or expired ticket"},
                        b"",
                    ))
                else:
                    data = ent[1]
                    _write_array_frame(
                        writer,
                        {"ok": True, "shape": list(data.shape),
                         "dtype": data.dtype.name},
                        data,
                    )
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError, ValueError):
            pass
        finally:
            writer.close()


async def take_remote_array(host: str, port: int, ticket: str) -> np.ndarray:
    """Collect (and consume) a parked array."""
    reader, writer = await asyncio.open_connection(host, port)
    try:
        writer.write(encode_frame2({"op": "take", "ticket": ticket}, b""))
        await writer.drain()
        header, payload = await read_frame2(reader)
        if not header.get("ok"):
            raise BlockTransferError(header.get("error", "take failed"))
        return np.frombuffer(
            payload, dtype=np.dtype(header["dtype"])
        ).reshape(header["shape"]).copy()
    finally:
        writer.close()
