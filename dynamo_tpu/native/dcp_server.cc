// dcp-server: native control-plane store for dynamo-tpu.
//
// etcd-shaped semantics (keys, TTL leases, prefix watches) plus NATS-core
// style pub/sub, over length-prefixed JSON frames — the native counterpart
// of the reference's external etcd+NATS dependency (SURVEY.md §2.1 L0/L1;
// reference lib/runtime/src/transports/{etcd,nats}.rs). Wire protocol:
// dynamo_tpu/runtime/protocol.py; the Python fallback implementation is
// dynamo_tpu/runtime/store.py and both must stay wire-compatible (tested by
// tests/test_native_store.py, which runs the same client suite against
// this binary).
//
// Design: single-threaded poll() loop — the control plane is tiny-message
// metadata traffic; one core handles tens of thousands of ops/s without
// locks. Leases are swept on every loop tick against CLOCK_MONOTONIC.
//
// Build: make -C dynamo_tpu/native   (-> build/dcp-server)
// Run:   dcp-server [port]           (default 7111)

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <signal.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>
#include <sys/socket.h>
#include <time.h>
#include <unistd.h>

#include <deque>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

// ---------------------------------------------------------------------------
// Minimal JSON: flat objects with string / number / bool values. Value
// strings may contain arbitrary escaped content (nested JSON payloads stay
// opaque strings). Sufficient for the dcp wire protocol by construction.

struct JValue {
  enum Kind { STR, NUM, BOOL, NONE } kind = NONE;
  std::string str;
  double num = 0;
  bool b = false;
};

typedef std::map<std::string, JValue> JObject;

static bool utf8_append(std::string &out, unsigned cp) {
  if (cp < 0x80) {
    out += (char)cp;
  } else if (cp < 0x800) {
    out += (char)(0xC0 | (cp >> 6));
    out += (char)(0x80 | (cp & 0x3F));
  } else if (cp < 0x10000) {
    out += (char)(0xE0 | (cp >> 12));
    out += (char)(0x80 | ((cp >> 6) & 0x3F));
    out += (char)(0x80 | (cp & 0x3F));
  } else {
    out += (char)(0xF0 | (cp >> 18));
    out += (char)(0x80 | ((cp >> 12) & 0x3F));
    out += (char)(0x80 | ((cp >> 6) & 0x3F));
    out += (char)(0x80 | (cp & 0x3F));
  }
  return true;
}

struct JParser {
  const char *p, *end;
  bool ok = true;
  explicit JParser(const std::string &s) : p(s.data()), end(s.data() + s.size()) {}

  void ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) p++;
  }
  bool lit(const char *s) {
    size_t n = strlen(s);
    if ((size_t)(end - p) < n || strncmp(p, s, n) != 0) return false;
    p += n;
    return true;
  }
  bool parse_hex4(unsigned &v) {
    if (end - p < 4) return false;
    v = 0;
    for (int i = 0; i < 4; i++) {
      char c = *p++;
      v <<= 4;
      if (c >= '0' && c <= '9') v |= (unsigned)(c - '0');
      else if (c >= 'a' && c <= 'f') v |= (unsigned)(c - 'a' + 10);
      else if (c >= 'A' && c <= 'F') v |= (unsigned)(c - 'A' + 10);
      else return false;
    }
    return true;
  }
  bool parse_string(std::string &out) {
    ws();
    if (p >= end || *p != '"') return false;
    p++;
    out.clear();
    while (p < end && *p != '"') {
      char c = *p++;
      if (c != '\\') {
        out += c;
        continue;
      }
      if (p >= end) return false;
      char e = *p++;
      switch (e) {
        case '"': out += '"'; break;
        case '\\': out += '\\'; break;
        case '/': out += '/'; break;
        case 'b': out += '\b'; break;
        case 'f': out += '\f'; break;
        case 'n': out += '\n'; break;
        case 'r': out += '\r'; break;
        case 't': out += '\t'; break;
        case 'u': {
          unsigned cp;
          if (!parse_hex4(cp)) return false;
          if (cp >= 0xD800 && cp <= 0xDBFF) {  // surrogate pair
            if (end - p < 6 || p[0] != '\\' || p[1] != 'u') return false;
            p += 2;
            unsigned lo;
            if (!parse_hex4(lo)) return false;
            cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
          }
          utf8_append(out, cp);
          break;
        }
        default: return false;
      }
    }
    if (p >= end) return false;
    p++;  // closing quote
    return true;
  }
  bool parse_number(double &out) {
    ws();
    char *q = nullptr;
    out = strtod(p, &q);
    if (q == p) return false;
    p = q;
    return true;
  }
  // Parse a flat object; nested objects/arrays are skipped structurally and
  // recorded as NONE (the protocol never needs them).
  bool skip_value();
  bool parse_object(JObject &obj) {
    ws();
    if (p >= end || *p != '{') return false;
    p++;
    ws();
    if (p < end && *p == '}') { p++; return true; }
    while (true) {
      std::string key;
      if (!parse_string(key)) return false;
      ws();
      if (p >= end || *p != ':') return false;
      p++;
      ws();
      JValue v;
      if (p < end && *p == '"') {
        if (!parse_string(v.str)) return false;
        v.kind = JValue::STR;
      } else if (lit("true")) {
        v.kind = JValue::BOOL; v.b = true;
      } else if (lit("false")) {
        v.kind = JValue::BOOL; v.b = false;
      } else if (lit("null")) {
        v.kind = JValue::NONE;
      } else if (p < end && (*p == '{' || *p == '[')) {
        if (!skip_value()) return false;
        v.kind = JValue::NONE;
      } else {
        if (!parse_number(v.num)) return false;
        v.kind = JValue::NUM;
      }
      obj[key] = v;
      ws();
      if (p < end && *p == ',') { p++; continue; }
      if (p < end && *p == '}') { p++; return true; }
      return false;
    }
  }
};

bool JParser::skip_value() {
  ws();
  if (p >= end) return false;
  if (*p == '"') {
    std::string tmp;
    return parse_string(tmp);
  }
  if (*p == '{' || *p == '[') {
    char open = *p, close = (open == '{') ? '}' : ']';
    int depth = 0;
    while (p < end) {
      if (*p == '"') {
        std::string tmp;
        if (!parse_string(tmp)) return false;
        continue;
      }
      if (*p == open) depth++;
      if (*p == close) {
        depth--;
        if (depth == 0) { p++; return true; }
      }
      p++;
    }
    return false;
  }
  if (lit("true") || lit("false") || lit("null")) return true;
  double d;
  return parse_number(d);
}

static void jesc(std::string &out, const std::string &s) {
  out += '"';
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += (char)c;  // raw UTF-8 passes through
        }
    }
  }
  out += '"';
}

struct JWriter {
  std::string body = "{";
  bool first = true;
  void comma() {
    if (!first) body += ',';
    first = false;
  }
  void key(const char *k) {
    comma();
    jesc(body, k);
    body += ':';
  }
  JWriter &s(const char *k, const std::string &v) { key(k); jesc(body, v); return *this; }
  JWriter &n(const char *k, long long v) {
    key(k);
    char buf[32];
    snprintf(buf, sizeof buf, "%lld", v);
    body += buf;
    return *this;
  }
  JWriter &b(const char *k, bool v) { key(k); body += v ? "true" : "false"; return *this; }
  JWriter &raw(const char *k, const std::string &v) { key(k); body += v; return *this; }
  std::string done() { return body + "}"; }
};

// ---------------------------------------------------------------------------
// Store

static double now_mono() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (double)ts.tv_sec + (double)ts.tv_nsec * 1e-9;
}

struct Conn;

struct WatchRec {
  long long id;
  std::string prefix;  // watch: key prefix; sub: topic pattern
  Conn *conn;
  bool is_sub;
};

// Parked qpop long-poll: answered by the next qpush or the sweep timeout.
struct QWaiter {
  Conn *conn;
  long long req_id;
  double deadline;
};

struct Store {
  std::map<std::string, std::pair<std::string, long long>> kv;  // key -> (val, lease)
  std::unordered_map<long long, double> lease_deadline;
  std::unordered_map<long long, double> lease_ttl;
  std::unordered_map<long long, std::set<std::string>> lease_keys;
  std::map<long long, WatchRec> watches;  // watch/sub id -> rec
  // durable FIFO queues (JetStream-work-queue equivalent; carries the
  // disagg prefill queue) + parked poppers
  std::map<std::string, std::deque<std::string>> queues;
  std::map<std::string, std::deque<QWaiter>> qwaiters;
  long long next_id = 1;
  long long revision = 0;

  void notify(const char *event, const std::string &key, const std::string *value);
  void notify_sub(const std::string &topic, const std::string &value);

  long long put(const std::string &key, const std::string &value, long long lease) {
    if (lease) lease_keys[lease].insert(key);
    auto it = kv.find(key);
    if (it != kv.end() && it->second.second && it->second.second != lease)
      lease_keys[it->second.second].erase(key);
    kv[key] = {value, lease};
    revision++;
    notify("put", key, &value);
    return revision;
  }
  int del(const std::string &key) {
    auto it = kv.find(key);
    if (it == kv.end()) return 0;
    long long lease = it->second.second;
    kv.erase(it);
    if (lease) lease_keys[lease].erase(key);
    revision++;
    notify("delete", key, nullptr);
    return 1;
  }
  long long lease_grant(double ttl) {
    long long id = next_id++;
    lease_deadline[id] = now_mono() + ttl;
    lease_ttl[id] = ttl;
    return id;
  }
  bool lease_keepalive(long long id) {
    auto it = lease_deadline.find(id);
    if (it == lease_deadline.end()) return false;
    it->second = now_mono() + lease_ttl[id];
    return true;
  }
  void lease_revoke(long long id) {
    lease_deadline.erase(id);
    lease_ttl.erase(id);
    auto it = lease_keys.find(id);
    if (it != lease_keys.end()) {
      std::vector<std::string> keys(it->second.begin(), it->second.end());
      lease_keys.erase(it);
      for (auto &k : keys) del(k);
    }
  }
  // Deliver straight to the oldest live parked popper, else enqueue.
  long long qpush(const std::string &q, const std::string &value);

  void sweep() {
    double t = now_mono();
    std::vector<long long> expired;
    for (auto &kvp : lease_deadline)
      if (kvp.second < t) expired.push_back(kvp.first);
    for (long long id : expired) {
      fprintf(stderr, "dcp: lease %lld expired\n", id);
      lease_revoke(id);
    }
    sweep_qwaiters(t);
  }
  void sweep_qwaiters(double t);
};

// ---------------------------------------------------------------------------
// Connections

struct Conn {
  int fd;
  std::string rbuf;
  std::string wbuf;
  std::vector<long long> watch_ids;
  bool dead = false;

  void send_frame(const std::string &body) {
    uint32_t n = htonl((uint32_t)body.size());
    wbuf.append((const char *)&n, 4);
    wbuf.append(body);
  }
};

void Store::notify(const char *event, const std::string &key,
                   const std::string *value) {
  for (auto &w : watches) {
    if (w.second.is_sub) continue;
    if (key.compare(0, w.second.prefix.size(), w.second.prefix) == 0 ||
        w.second.prefix.empty()) {
      if (key.size() < w.second.prefix.size()) continue;
      if (key.compare(0, w.second.prefix.size(), w.second.prefix) != 0) continue;
      JWriter jw;
      jw.n("watch", w.second.id).s("event", event);
      jw.s("key", key);
      if (value) jw.s("value", *value);
      w.second.conn->send_frame(jw.done());
    }
  }
}

long long Store::qpush(const std::string &q, const std::string &value) {
  auto wit = qwaiters.find(q);
  if (wit != qwaiters.end()) {
    while (!wit->second.empty()) {
      QWaiter w = wit->second.front();
      wit->second.pop_front();
      if (w.conn->dead) continue;
      JWriter jw;
      jw.b("ok", true).s("queue", q).s("value", value).n("req_id", w.req_id);
      w.conn->send_frame(jw.done());
      if (wit->second.empty()) qwaiters.erase(wit);
      auto qit = queues.find(q);
      return qit == queues.end() ? 0 : (long long)qit->second.size();
    }
    qwaiters.erase(wit);
  }
  queues[q].push_back(value);
  return (long long)queues[q].size();
}

void Store::sweep_qwaiters(double t) {
  for (auto it = qwaiters.begin(); it != qwaiters.end();) {
    std::deque<QWaiter> keep;
    for (auto &w : it->second) {
      if (w.conn->dead) continue;
      if (w.deadline < t) {
        JWriter jw;
        jw.b("ok", true).s("queue", it->first).b("empty", true)
            .n("req_id", w.req_id);
        w.conn->send_frame(jw.done());
      } else {
        keep.push_back(w);
      }
    }
    if (keep.empty()) {
      it = qwaiters.erase(it);
    } else {
      it->second = std::move(keep);
      ++it;
    }
  }
}

void Store::notify_sub(const std::string &topic, const std::string &value) {
  for (auto &w : watches) {
    if (!w.second.is_sub) continue;
    const std::string &pat = w.second.prefix;
    bool match = (pat == topic);
    if (!match && pat.size() >= 2 && pat.compare(pat.size() - 2, 2, ".>") == 0)
      match = topic.compare(0, pat.size() - 1, pat, 0, pat.size() - 1) == 0;
    if (match) {
      JWriter jw;
      jw.n("sub", w.second.id).s("topic", topic).s("value", value);
      w.second.conn->send_frame(jw.done());
    }
  }
}

static std::string handle(Store &st, Conn *conn, JObject &req) {
  std::string op = req["op"].str;
  JWriter jw;
  if (op == "put") {
    long long lease = (long long)req["lease"].num;
    if (lease && !st.lease_deadline.count(lease)) {
      jw.b("ok", false).s("error", "lease not found");
      return jw.done();
    }
    long long rev = st.put(req["key"].str, req["value"].str, lease);
    jw.b("ok", true).n("rev", rev);
  } else if (op == "get") {
    jw.b("ok", true);
    auto it = st.kv.find(req["key"].str);
    std::string arr = "[";
    if (it != st.kv.end()) {
      std::string one = "[";
      jesc(one, it->first);
      one += ',';
      jesc(one, it->second.first);
      char buf[32];
      snprintf(buf, sizeof buf, ",%lld]", it->second.second);
      one += buf;
      arr += one;
    }
    arr += "]";
    jw.raw("kvs", arr);
  } else if (op == "get_prefix") {
    const std::string &pfx = req["prefix"].str;
    jw.b("ok", true);
    std::string arr = "[";
    bool first = true;
    for (auto it = st.kv.lower_bound(pfx); it != st.kv.end(); ++it) {
      if (it->first.compare(0, pfx.size(), pfx) != 0) break;
      if (!first) arr += ',';
      first = false;
      std::string one = "[";
      jesc(one, it->first);
      one += ',';
      jesc(one, it->second.first);
      char buf[32];
      snprintf(buf, sizeof buf, ",%lld]", it->second.second);
      one += buf;
      arr += one;
    }
    arr += "]";
    jw.raw("kvs", arr);
  } else if (op == "delete") {
    jw.b("ok", true).n("deleted", st.del(req["key"].str));
  } else if (op == "delete_prefix") {
    const std::string &pfx = req["prefix"].str;
    std::vector<std::string> keys;
    for (auto it = st.kv.lower_bound(pfx); it != st.kv.end(); ++it) {
      if (it->first.compare(0, pfx.size(), pfx) != 0) break;
      keys.push_back(it->first);
    }
    for (auto &k : keys) st.del(k);
    jw.b("ok", true).n("deleted", (long long)keys.size());
  } else if (op == "lease_grant") {
    double ttl = req["ttl"].kind == JValue::NUM ? req["ttl"].num : 10.0;
    jw.b("ok", true).n("lease", st.lease_grant(ttl));
  } else if (op == "lease_keepalive") {
    bool ok = st.lease_keepalive((long long)req["lease"].num);
    if (ok) jw.b("ok", true);
    else jw.b("ok", false).s("error", "lease expired");
  } else if (op == "lease_revoke") {
    st.lease_revoke((long long)req["lease"].num);
    jw.b("ok", true);
  } else if (op == "watch" || op == "subscribe") {
    long long id = st.next_id++;
    WatchRec rec;
    rec.id = id;
    rec.prefix = (op == "watch") ? req["prefix"].str : req["topic"].str;
    rec.conn = conn;
    rec.is_sub = (op == "subscribe");
    st.watches[id] = rec;
    conn->watch_ids.push_back(id);
    jw.b("ok", true).n(rec.is_sub ? "sub" : "watch", id);
    if (!rec.is_sub) {
      // snapshot returned atomically with watch registration — single
      // store traversal, so no put/delete can be lost in between
      const std::string &pfx = rec.prefix;
      std::string arr = "[";
      bool first = true;
      for (auto it = st.kv.lower_bound(pfx); it != st.kv.end(); ++it) {
        if (it->first.compare(0, pfx.size(), pfx) != 0) break;
        if (!first) arr += ',';
        first = false;
        std::string one = "[";
        jesc(one, it->first);
        one += ',';
        jesc(one, it->second.first);
        char buf[32];
        snprintf(buf, sizeof buf, ",%lld]", it->second.second);
        one += buf;
        arr += one;
      }
      arr += "]";
      jw.raw("kvs", arr);
    }
  } else if (op == "unwatch") {
    st.watches.erase((long long)req["watch"].num);
    jw.b("ok", true);
  } else if (op == "unsubscribe") {
    st.watches.erase((long long)req["sub"].num);
    jw.b("ok", true);
  } else if (op == "publish") {
    long long n = 0;
    const std::string &topic = req["topic"].str;
    for (auto &w : st.watches) {
      if (!w.second.is_sub) continue;
      const std::string &pat = w.second.prefix;
      bool match = (pat == topic);
      if (!match && pat.size() >= 2 && pat.compare(pat.size() - 2, 2, ".>") == 0)
        match = topic.compare(0, pat.size() - 1, pat, 0, pat.size() - 1) == 0;
      if (match) n++;
    }
    st.notify_sub(topic, req["value"].str);
    jw.b("ok", true).n("receivers", n);
  } else if (op == "qpush") {
    jw.b("ok", true).n("len", st.qpush(req["queue"].str, req["value"].str));
  } else if (op == "qpop") {
    const std::string &q = req["queue"].str;
    auto it = st.queues.find(q);
    if (it != st.queues.end() && !it->second.empty()) {
      std::string v = it->second.front();
      it->second.pop_front();
      if (it->second.empty()) st.queues.erase(it);
      jw.b("ok", true).s("queue", q).s("value", v);
    } else {
      double timeout =
          req["timeout"].kind == JValue::NUM ? req["timeout"].num : 0.0;
      if (timeout > 0) {
        // park the long-poll: answered by the next qpush or sweep timeout
        QWaiter w{conn, (long long)req["req_id"].num, now_mono() + timeout};
        st.qwaiters[q].push_back(w);
        return "";  // deferred — no immediate response
      }
      jw.b("ok", true).s("queue", q).b("empty", true);
    }
  } else if (op == "qlen") {
    auto it = st.queues.find(req["queue"].str);
    jw.b("ok", true).n(
        "len", it == st.queues.end() ? 0 : (long long)it->second.size());
  } else if (op == "ping") {
    jw.b("ok", true);
  } else {
    jw.b("ok", false).s("error", "unknown op '" + op + "'");
  }
  return jw.done();
}

int main(int argc, char **argv) {
  int port = argc > 1 ? atoi(argv[1]) : 7111;
  signal(SIGPIPE, SIG_IGN);

  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof addr);
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons((uint16_t)port);
  if (bind(lfd, (struct sockaddr *)&addr, sizeof addr) != 0) {
    perror("bind");
    return 1;
  }
  if (listen(lfd, 128) != 0) {
    perror("listen");
    return 1;
  }
  // report the actual port (port 0 = ephemeral, used by tests)
  socklen_t alen = sizeof addr;
  getsockname(lfd, (struct sockaddr *)&addr, &alen);
  fprintf(stdout, "dcp-server listening on 127.0.0.1:%d\n", ntohs(addr.sin_port));
  fflush(stdout);

  Store st;
  std::map<int, std::unique_ptr<Conn>> conns;

  while (true) {
    std::vector<struct pollfd> pfds;
    pfds.push_back({lfd, POLLIN, 0});
    for (auto &c : conns) {
      short ev = POLLIN;
      if (!c.second->wbuf.empty()) ev |= POLLOUT;
      pfds.push_back({c.first, ev, 0});
    }
    poll(pfds.data(), (nfds_t)pfds.size(), 100 /* ms: lease sweep tick */);
    st.sweep();

    if (pfds[0].revents & POLLIN) {
      int cfd = accept(lfd, nullptr, nullptr);
      if (cfd >= 0) {
        fcntl(cfd, F_SETFL, O_NONBLOCK);
        setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
        Conn *nc = new Conn();
        nc->fd = cfd;
        conns[cfd] = std::unique_ptr<Conn>(nc);
      }
    }

    for (size_t i = 1; i < pfds.size(); i++) {
      auto it = conns.find(pfds[i].fd);
      if (it == conns.end()) continue;
      Conn *c = it->second.get();
      if (pfds[i].revents & (POLLERR | POLLHUP)) c->dead = true;
      if (!c->dead && (pfds[i].revents & POLLIN)) {
        char buf[65536];
        while (true) {
          ssize_t n = read(c->fd, buf, sizeof buf);
          if (n > 0) {
            c->rbuf.append(buf, (size_t)n);
          } else if (n == 0) {
            c->dead = true;
            break;
          } else {
            if (errno != EAGAIN && errno != EWOULDBLOCK) c->dead = true;
            break;
          }
        }
        // parse complete frames
        while (c->rbuf.size() >= 4) {
          uint32_t len;
          memcpy(&len, c->rbuf.data(), 4);
          len = ntohl(len);
          if (len > (64u << 20)) { c->dead = true; break; }
          if (c->rbuf.size() < 4 + (size_t)len) break;
          std::string body = c->rbuf.substr(4, len);
          c->rbuf.erase(0, 4 + (size_t)len);
          JObject req;
          JParser jp(body);
          if (!jp.parse_object(req)) continue;
          std::string resp = handle(st, c, req);
          if (resp.empty()) continue;  // deferred (parked qpop)
          if (req.count("req_id")) {
            // splice req_id into the response object
            char buf2[48];
            snprintf(buf2, sizeof buf2, ",\"req_id\":%lld}",
                     (long long)req["req_id"].num);
            resp = resp.substr(0, resp.size() - 1) + buf2;
          }
          c->send_frame(resp);
        }
      }
      if (!c->dead && (pfds[i].revents & POLLOUT) && !c->wbuf.empty()) {
        ssize_t n = write(c->fd, c->wbuf.data(), c->wbuf.size());
        if (n > 0) c->wbuf.erase(0, (size_t)n);
        else if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) c->dead = true;
      }
      // opportunistic flush for freshly queued responses
      if (!c->dead && !c->wbuf.empty()) {
        ssize_t n = write(c->fd, c->wbuf.data(), c->wbuf.size());
        if (n > 0) c->wbuf.erase(0, (size_t)n);
        else if (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK) c->dead = true;
      }
    }

    // reap dead connections (NOT their leases — etcd parity: leases only
    // die by TTL or explicit revoke)
    for (auto it2 = conns.begin(); it2 != conns.end();) {
      if (it2->second->dead) {
        for (long long wid : it2->second->watch_ids) st.watches.erase(wid);
        // drop parked qpops held by this conn (pointers would dangle)
        Conn *dying = it2->second.get();
        for (auto qit = st.qwaiters.begin(); qit != st.qwaiters.end();) {
          std::deque<QWaiter> keep;
          for (auto &w : qit->second)
            if (w.conn != dying) keep.push_back(w);
          if (keep.empty()) {
            qit = st.qwaiters.erase(qit);
          } else {
            qit->second = std::move(keep);
            ++qit;
          }
        }
        close(it2->first);
        it2 = conns.erase(it2);
      } else {
        ++it2;
      }
    }
  }
  return 0;
}
