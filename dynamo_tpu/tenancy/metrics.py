"""Tenant-sliced observability: the ``dynamo_tenant_*`` metric plane.

Every other subsystem plane is a fixed family set (CounterRegistry);
tenants are an open set discovered at admission time, so this registry
keys each family's series by tenant id and renders them as
``{tenant="..."}``-labelled Prometheus series under ONE HELP/TYPE head
per family (the text-format grouping requirement). Rendered on all
three scrape surfaces — frontend ``/metrics``, the per-worker system
server, and the aggregating exporter — and snapshot into
``/debug/tenants`` on the first two.
"""
from __future__ import annotations

import threading
from typing import Any, Optional

from dynamo_tpu.telemetry.metrics import Histogram, render_histogram

# (name, type, help) — the metrics contract (tests/test_metrics_contract
# + DTL005): valid TYPE, non-empty HELP, a README Observability row each
FAMILIES = (
    ("dynamo_tenant_admitted_total", "counter",
     "requests admitted past the tenant quota gate, per tenant"),
    ("dynamo_tenant_rejected_total", "counter",
     "requests refused by a tenant's own quota (per-tenant 429s)"),
    ("dynamo_tenant_shed_total", "counter",
     "waiting requests shed under tenant-confined pressure, per tenant"),
    ("dynamo_tenant_http_429_total", "counter",
     "frontend 429 responses attributed to a tenant's quota state"),
    ("dynamo_tenant_queue_depth", "gauge",
     "requests waiting in the admission queue, per tenant"),
    ("dynamo_tenant_queue_tokens", "gauge",
     "prompt tokens waiting for prefill, per tenant"),
    ("dynamo_tenant_adapter_rounds_total", "counter",
     "decode rounds that gathered a non-base resident LoRA adapter "
     "for at least one of the tenant's slots"),
)

HISTOGRAMS = (
    ("dynamo_tenant_request_ttft_seconds",
     "time to first token, sliced by tenant"),
    ("dynamo_tenant_request_queue_seconds",
     "admission queue wait, sliced by tenant"),
)


def _safe_tenant(tenant: str) -> str:
    """Label-safe tenant id: the quote/backslash/newline characters that
    would corrupt the Prometheus text format are stripped, length capped
    (the mint path sanitizes too — this is the render-side backstop)."""
    t = "".join(ch for ch in str(tenant) if ch not in '"\\\n\r')
    return (t or "default")[:64]


class TenantRegistry:
    """Thread-safe per-tenant counters/gauges + histograms.

    API mirrors CounterRegistry but every mutator takes the tenant id;
    render() emits one HELP/TYPE head per family with one
    ``{tenant="..."}`` series per tenant seen so far."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # family -> tenant -> value
        self._values: dict[str, dict[str, float]] = {
            name: {} for name, _, _ in FAMILIES
        }
        # family -> tenant -> Histogram
        self._hists: dict[str, dict[str, Histogram]] = {
            name: {} for name, _ in HISTOGRAMS
        }
        self._hist_help = dict(HISTOGRAMS)

    def inc(self, name: str, tenant: str, n: float = 1.0) -> None:
        assert name in self._values, f"unknown tenant series {name!r}"
        t = _safe_tenant(tenant)
        with self._lock:
            self._values[name][t] = self._values[name].get(t, 0.0) + n

    def set(self, name: str, tenant: str, v: float) -> None:
        assert name in self._values, f"unknown tenant series {name!r}"
        t = _safe_tenant(tenant)
        with self._lock:
            self._values[name][t] = float(v)

    def get(self, name: str, tenant: str) -> float:
        with self._lock:
            return self._values[name].get(_safe_tenant(tenant), 0.0)

    def observe(
        self, name: str, tenant: str, value: float,
        exemplar_id: Optional[str] = None,
    ) -> None:
        self.histogram(name, tenant).observe(value, exemplar_id=exemplar_id)

    def histogram(self, name: str, tenant: str) -> Histogram:
        assert name in self._hists, f"unknown tenant histogram {name!r}"
        t = _safe_tenant(tenant)
        with self._lock:
            h = self._hists[name].get(t)
            if h is None:
                h = self._hists[name][t] = Histogram(
                    name, self._hist_help[name]
                )
            return h

    def percentile(self, name: str, tenant: str, q: float) -> Optional[float]:
        with self._lock:
            h = self._hists[name].get(_safe_tenant(tenant))
        return h.percentile(q) if h is not None else None

    def tenants(self) -> list[str]:
        with self._lock:
            seen: set[str] = set()
            for per in self._values.values():
                seen.update(per)
            for per in self._hists.values():
                seen.update(per)
            return sorted(seen)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """tenant -> {family: value, histogram: {p50, p99, count}} — the
        /debug/tenants wire form."""
        out: dict[str, dict[str, Any]] = {}
        with self._lock:
            families = {n: dict(per) for n, per in self._values.items()}
            hists = {n: dict(per) for n, per in self._hists.items()}
        for name, per in families.items():
            for t, v in per.items():
                out.setdefault(t, {})[name] = v
        for name, per in hists.items():
            for t, h in per.items():
                out.setdefault(t, {})[name] = {
                    "count": h.count,
                    "p50_s": h.percentile(0.5),
                    "p99_s": h.percentile(0.99),
                }
        return out

    def reset(self) -> None:
        with self._lock:
            for per in self._values.values():
                per.clear()
            for per in self._hists.values():
                per.clear()

    def render(self, openmetrics: bool = False) -> str:
        """One HELP/TYPE head per family; tenant-labelled series under
        it. Families with no tenants yet still emit their heads so the
        scrape contract is visible from the first scrape."""
        with self._lock:
            values = {n: dict(per) for n, per in self._values.items()}
            hists = {n: dict(per) for n, per in self._hists.items()}
        lines: list[str] = []
        for name, typ, help_ in FAMILIES:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {typ}")
            for t in sorted(values[name]):
                v = values[name][t]
                lines.append(
                    f'{name}{{tenant="{t}"}} '
                    f"{int(v) if v == int(v) else v}"
                )
        for name, help_ in HISTOGRAMS:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} histogram")
            for t in sorted(hists[name]):
                # per-family head emitted once above; per-tenant series
                # drop render_histogram's own HELP/TYPE lines
                lines.extend(render_histogram(
                    name, help_, hists[name][t].snapshot(),
                    label=f'tenant="{t}"', openmetrics=openmetrics,
                )[2:])
        return "\n".join(lines) + "\n"


TENANT = TenantRegistry()
