"""Per-tenant admission quotas and weighted fair share.

Sits ON TOP of the PR 6 overload plane: the global
``AdmissionController`` still bounds the whole backlog; ``TenantQuotas``
additionally bounds each tenant's OWN slice of it, so one tenant's
storm exhausts that tenant's budget — and bounces with a Retry-After
derived from that tenant's own observed queue waits — long before it
can crowd the global queue.

Tenant identity is minted at the frontend (``X-Tenant-Id`` header or
the ``nvext.tenant`` body field; legacy traffic falls into the
``default`` tenant) and rides ``PreprocessedRequest.tenant`` end to
end. Fair share uses start-time virtual clocks (SFQ): each tenant
advances a virtual-finish-time counter by prompt-cost / weight per
enqueued request, and the engine's waiting queue orders same-priority
entries by that stamp — a storming tenant's backlog self-paces behind
its own stamps while a light tenant's fresh arrival lands near the
global virtual clock, i.e. near the queue head.
"""
from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Optional

from dynamo_tpu.overload.admission import (
    DEFAULT_QUEUE_WAIT_S,
    RETRY_AFTER_MAX_S,
    RETRY_AFTER_MIN_S,
)
from dynamo_tpu.overload.errors import EngineOverloadedError

log = logging.getLogger(__name__)

TENANT_HEADER = "X-Tenant-Id"
DEFAULT_TENANT = "default"

# per-tenant queue-wait observations kept for the p50 retry hint
_WAIT_WINDOW = 128


def parse_tenant(value: Any) -> str:
    """Header/body tenant value -> a label-safe tenant id. Malformed or
    empty values fall into the default tenant — a bad hint must not
    fail the request."""
    if value is None:
        return DEFAULT_TENANT
    t = "".join(
        ch for ch in str(value).strip() if ch not in '"\\\n\r'
    )
    return t[:64] or DEFAULT_TENANT


class TenantQuotas:
    """Pure per-tenant budget arithmetic + queue-wait accounting.

    Budgets are UNIFORM caps applied to each tenant's own backlog
    (0 = unbounded, matching AdmissionController's convention);
    ``weights`` biases the fair-share dequeue order, not the budgets."""

    def __init__(
        self,
        max_waiting_requests: int = 0,
        max_waiting_prefill_tokens: int = 0,
        weights: Optional[dict[str, float]] = None,
    ):
        self.max_waiting_requests = max(0, int(max_waiting_requests))
        self.max_waiting_prefill_tokens = max(
            0, int(max_waiting_prefill_tokens)
        )
        self._weights = dict(weights or {})
        self._lock = threading.Lock()
        self._waits: dict[str, deque] = {}

    @property
    def bounded(self) -> bool:
        return bool(self.max_waiting_requests
                    or self.max_waiting_prefill_tokens)

    def weight(self, tenant: str) -> float:
        """Fair-share weight (default 1.0; floored so a mistyped zero
        weight can't divide the virtual clock by zero)."""
        return max(1e-3, float(self._weights.get(tenant, 1.0)))

    def note_queue_wait(self, tenant: str, wait_s: float) -> None:
        with self._lock:
            dq = self._waits.get(tenant)
            if dq is None:
                dq = self._waits[tenant] = deque(maxlen=_WAIT_WINDOW)
            dq.append(float(wait_s))

    def queue_wait_p50(self, tenant: str) -> Optional[float]:
        with self._lock:
            dq = self._waits.get(tenant)
            if not dq:
                return None
            vals = sorted(dq)
        return vals[len(vals) // 2]

    def retry_after_s(self, tenant: str, waiting_requests: int) -> float:
        """Expected drain time of THIS tenant's backlog: the tenant's
        own observed per-request queue wait (p50) x its depth, clamped
        to the overload plane's sane window."""
        per_req = self.queue_wait_p50(tenant)
        if per_req is None or per_req <= 0:
            per_req = DEFAULT_QUEUE_WAIT_S
        est = max(1, waiting_requests) * per_req
        return min(RETRY_AFTER_MAX_S, max(RETRY_AFTER_MIN_S, est))

    def over_budget(self, waiting_requests: int,
                    waiting_tokens: int) -> bool:
        if (self.max_waiting_requests
                and waiting_requests >= self.max_waiting_requests):
            return True
        if (self.max_waiting_prefill_tokens
                and waiting_tokens >= self.max_waiting_prefill_tokens):
            return True
        return False

    def check(self, tenant: str, waiting_requests: int,
              waiting_tokens: int) -> None:
        """Raise the retriable overload error — carrying the tenant key
        and a TENANT-derived Retry-After — when the tenant's backlog is
        at its budget."""
        if not self.over_budget(waiting_requests, waiting_tokens):
            return
        raise EngineOverloadedError(
            f"tenant {tenant!r} over quota: {waiting_requests} waiting "
            f"requests / {waiting_tokens} waiting prefill tokens "
            f"(max {self.max_waiting_requests} requests, "
            f"{self.max_waiting_prefill_tokens} tokens per tenant)",
            retry_after_s=self.retry_after_s(tenant, waiting_requests),
            tenant=tenant,
        )

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """Per-tenant quota view for /debug/tenants."""
        with self._lock:
            tenants = list(self._waits)
        out: dict[str, dict[str, Any]] = {}
        for t in tenants:
            out[t] = {
                "weight": self.weight(t),
                "queue_wait_p50_s": self.queue_wait_p50(t),
            }
        return out
