"""Multi-tenant serving plane: resident LoRA adapter multiplexing,
per-tenant quota/fairness, and tenant-sliced observability.

Three planes (see each module's doc):
  adapters.py  resident LoRA banks + the variant-name registry
               (imported lazily — it needs jax; quota/metrics don't)
  quotas.py    per-tenant admission budgets, SFQ fair-share stamps,
               tenant-derived Retry-After
  metrics.py   the ``dynamo_tenant_*`` labelled metric families
"""
from dynamo_tpu.tenancy.metrics import TENANT, TenantRegistry
from dynamo_tpu.tenancy.quotas import (
    DEFAULT_TENANT,
    TENANT_HEADER,
    TenantQuotas,
    parse_tenant,
)

__all__ = [
    "TENANT",
    "TenantRegistry",
    "TENANT_HEADER",
    "DEFAULT_TENANT",
    "TenantQuotas",
    "parse_tenant",
]
