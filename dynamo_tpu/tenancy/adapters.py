"""Resident LoRA adapter banks: many fine-tune variants, one engine.

The bank is a per-site stack of low-rank A/B factor pairs —
``{site: {"a": [N, L, d_in, r], "b": [N, L, r, d_out]}}`` — resident in
HBM alongside the base weights. It rides INSIDE the engine's params
pytree (``params["adapters"]``), so every existing jitted program
(fused round, prefill, batched prefill) carries it with zero signature
churn; the model functions look it up with ``params.get("adapters")``,
a trace-time presence check, so engines without a bank trace the
identical pre-tenancy programs.

Adapter 0 is the all-zeros identity — the base model, exactly: the
rank-r delta ``(x @ A) @ B`` is exactly 0.0 for zero factors, so
adapter_id=0 requests are greedy token-identical to an engine with no
bank at all. Per-slot adapter ids live in the device state
(``dev["adapter"]``), gathered inside the fused round program as a
batched row gather + rank-r einsum fused into the existing
qkv/o/mlp matmuls — mixed adapter ids in one decode batch cost zero
extra dispatches.

``AdapterRegistry`` maps servable variant model names to
``(base_model, adapter_id)`` so the frontend/model_resolver can route
variant requests onto the base engine with the right bank row.
"""
from __future__ import annotations

import threading
from typing import Any, Optional

import jax
import numpy as np

# weight sites carrying an adapter pair. The MoE expert stacks are NOT
# adapted (dense-dispatch einsums have no per-token weight identity);
# MoE models adapt attention only.
ATTN_SITES = ("wq", "wk", "wv", "wo")
MLP_SITES = ("wg", "wu", "wd")


def adapter_site_dims(config: Any) -> dict[str, tuple[int, int]]:
    """site -> (d_in, d_out) for the model's adaptable matmuls."""
    c = config
    dims = {
        "wq": (c.hidden_size, c.q_dim),
        "wk": (c.hidden_size, c.kv_dim),
        "wv": (c.hidden_size, c.kv_dim),
        "wo": (c.q_dim, c.hidden_size),
    }
    if c.moe is None:
        dims.update({
            "wg": (c.hidden_size, c.intermediate_size),
            "wu": (c.hidden_size, c.intermediate_size),
            "wd": (c.intermediate_size, c.hidden_size),
        })
    return dims


def init_adapter_bank(config: Any, n_adapters: int, rank: int):
    """Zero-initialized resident bank for ``n_adapters`` slots (id 0 =
    identity base model) at LoRA rank ``rank``. f32 factors — they cast
    to the activation dtype at the delta einsum, and the bank is tiny
    next to the base weights (2 * d * r per site-layer)."""
    import jax.numpy as jnp

    c = config
    n = max(1, int(n_adapters))
    r = max(1, int(rank))
    bank = {}
    for site, (d_in, d_out) in adapter_site_dims(c).items():
        bank[site] = {
            "a": jnp.zeros((n, c.num_layers, d_in, r), jnp.float32),
            "b": jnp.zeros((n, c.num_layers, r, d_out), jnp.float32),
        }
    return bank


def set_adapter(bank, adapter_id: int, weights: dict):
    """Functionally install one adapter's factors into the bank.

    ``weights`` maps site -> {"a": [L, d_in, r], "b": [L, r, d_out]}
    (numpy or jax arrays); sites absent from ``weights`` keep their
    current rows. Returns the updated bank (callers re-device_put /
    re-merge into params). Adapter 0 is the identity by contract —
    refusing to overwrite it keeps the base model addressable."""
    import jax.numpy as jnp

    aid = int(adapter_id)
    if aid == 0:
        raise ValueError("adapter 0 is the identity base model")
    out = {}
    for site, ab in bank.items():
        w = weights.get(site)
        if w is None:
            out[site] = ab
            continue
        a = jnp.asarray(np.asarray(w["a"], np.float32))
        b = jnp.asarray(np.asarray(w["b"], np.float32))
        if a.shape != ab["a"].shape[1:] or b.shape != ab["b"].shape[1:]:
            raise ValueError(
                f"adapter factors for site {site!r} have shape "
                f"{a.shape}/{b.shape}, bank rows are "
                f"{ab['a'].shape[1:]}/{ab['b'].shape[1:]}"
            )
        out[site] = {
            "a": ab["a"].at[aid].set(a),
            "b": ab["b"].at[aid].set(b),
        }
    return out


def random_adapter(config: Any, rank: int, seed: int = 0,
                   scale: float = 0.05) -> dict:
    """Small random factors for every site — test/bench fixture for a
    visibly non-identity adapter."""
    rng = np.random.default_rng(seed)
    c = config
    out = {}
    for site, (d_in, d_out) in adapter_site_dims(c).items():
        out[site] = {
            "a": rng.standard_normal(
                (c.num_layers, d_in, rank)).astype(np.float32) * scale,
            "b": rng.standard_normal(
                (c.num_layers, rank, d_out)).astype(np.float32) * scale,
        }
    return out


def replicate_bank(bank, mesh):
    """Device-put the bank fully replicated (it is tiny; replication
    keeps the delta einsums local to every shard of the base matmul)."""
    sh = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
    return jax.tree.map(lambda x: jax.device_put(x, sh), bank)


class AdapterRegistry:
    """Servable variant names -> (base model, adapter id).

    The frontend registers each fine-tune variant as its own model name
    (``my-org/base:support-bot``); resolution hands back the base chain
    plus the bank row to stamp onto the request. Thread-safe — the
    watcher registers from asyncio while handlers resolve."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._variants: dict[str, tuple[str, int]] = {}

    def register(self, name: str, base: str, adapter_id: int) -> None:
        if int(adapter_id) <= 0:
            raise ValueError(
                "variant adapter ids start at 1 (0 is the base model)"
            )
        with self._lock:
            self._variants[name] = (base, int(adapter_id))

    def unregister(self, name: str) -> None:
        with self._lock:
            self._variants.pop(name, None)

    def resolve(self, name: str) -> Optional[tuple[str, int]]:
        with self._lock:
            return self._variants.get(name)

    def variants(self) -> dict[str, tuple[str, int]]:
        with self._lock:
            return dict(self._variants)
