"""Llama-family forward pass (Llama-2/3/3.x, DeepSeek-R1-Distill-Llama).

Design notes (TPU-first):
  - Parameters are a pytree whose per-layer leaves are STACKED on a leading
    layer axis and the decoder runs as one ``lax.scan`` — one compiled layer
    body regardless of depth (compile time stays flat from 4 to 80 layers).
  - The KV cache is a paged pool per layer: ``[L, num_pages, page_size,
    kv_heads, head_dim]``; requests address it through page tables. Page 0
    is a reserved scratch page: page-table entries BEYOND a request's
    allocated pages point at it, so whole-page padding writes and inactive
    decode slots never corrupt real pages. Padding tokens within a
    request's own tail page DO write garbage KV into that page's tail slots
    — they are never valid context (masked by seq_len/ctx_len, and decode
    overwrites them in order), but attention kernels MUST keep the validity
    mask and the prefix cache must only ever share complete pages.
  - Tensor parallelism is pure GSPMD: `param_shardings`/`cache_shardings`
    put head/hidden dims on the ``tp`` mesh axis; XLA inserts the ICI
    collectives. No hand-written comm (contrast: reference engines use NCCL
    inside vLLM — SURVEY.md §2.5).
  - Prefill is B=1 over a padded token bucket (positions q_start..q_start+T);
    decode is a fixed-slot batch, one token per slot. Both are jittable with
    static shapes; the engine buckets prompt lengths to bound recompiles.

Parity: this is the TPU engine the reference delegates to vLLM for
(launch/dynamo-run subprocess engines; SURVEY.md §2.1 L3).
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.attention import paged_decode_attention, prefill_attention
from dynamo_tpu.ops.rope import apply_rope, rope_cos_sin, rope_inv_freq

Params = dict[str, Any]
Cache = dict[str, jnp.ndarray]


# ---------------------------------------------------------------------------
# Parameters

def init_params(config: ModelConfig, rng: jax.Array | int = 0) -> Params:
    """Random-init parameters (bf16). Weight values only matter for quality,
    not performance, so benchmarks use this; serving uses load_hf_params."""
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    c = config
    dtype = jnp.dtype(c.dtype)
    keys = jax.random.split(rng, 12)

    def rnd(key, *shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-2] if len(shape) > 1 else shape[-1]))
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    L, H, I, V = c.num_layers, c.hidden_size, c.intermediate_size, c.vocab_size
    params: Params = {
        "embed": rnd(keys[0], V, H, scale=0.02),
        "layers": {
            "ln1": jnp.ones((L, H), dtype),
            "ln2": jnp.ones((L, H), dtype),
            "wq": rnd(keys[1], L, H, c.q_dim),
            "wk": rnd(keys[2], L, H, c.kv_dim),
            "wv": rnd(keys[3], L, H, c.kv_dim),
            "wo": rnd(keys[4], L, c.q_dim, H),
            "wg": rnd(keys[5], L, H, I),
            "wu": rnd(keys[6], L, H, I),
            "wd": rnd(keys[7], L, I, H),
        },
        "norm_f": jnp.ones((H,), dtype),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = rnd(keys[8], H, V, scale=0.02)
    return params


def param_shardings(config: ModelConfig, mesh: Mesh) -> Params:
    """NamedSharding pytree: Megatron-style TP over the `tp` mesh axis.
    qkv/gate/up shard the output (head/hidden) dim; o/down shard the input
    dim; embedding + lm_head shard the vocab dim."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    out: Params = {
        "embed": ns("tp", None),
        "layers": {
            "ln1": ns(None, None),
            "ln2": ns(None, None),
            "wq": ns(None, None, "tp"),
            "wk": ns(None, None, "tp"),
            "wv": ns(None, None, "tp"),
            "wo": ns(None, "tp", None),
            "wg": ns(None, None, "tp"),
            "wu": ns(None, None, "tp"),
            "wd": ns(None, "tp", None),
        },
        "norm_f": ns(None),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = ns(None, "tp")
    return out


# ---------------------------------------------------------------------------
# KV cache

def init_cache(
    config: ModelConfig, num_pages: int, page_size: int, dtype=None
) -> Cache:
    """Paged KV pool. Page 0 is the reserved scratch page (see module doc)."""
    c = config
    dtype = dtype or jnp.dtype(c.dtype)
    shape = (c.num_layers, num_pages, page_size, c.num_kv_heads, c.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_shardings(config: ModelConfig, mesh: Mesh) -> Cache:
    s = NamedSharding(mesh, P(None, None, None, "tp", None))
    return {"k": s, "v": s}


# ---------------------------------------------------------------------------
# Forward pieces

def rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _mlp(h, wg, wu, wd):
    return (jax.nn.silu(h @ wg) * (h @ wu)) @ wd


def _layer_body(c: ModelConfig, lp, h, cos, sin, write_kv, attend):
    """Shared decoder-layer body for prefill and decode.

    `write_kv(k_pages, v_pages, k, v)` scatters new KV into the page pool;
    `attend(q, k_pages, v_pages)` runs attention over it. `h` is [N, H]
    (N = padded tokens for prefill, batch slots for decode).
    """
    N = h.shape[0]
    x = rms_norm(h, lp["ln1"], c.rms_norm_eps)
    q = (x @ lp["wq"]).reshape(N, c.num_heads, c.head_dim)
    k = (x @ lp["wk"]).reshape(N, c.num_kv_heads, c.head_dim)
    v = (x @ lp["wv"]).reshape(N, c.num_kv_heads, c.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    k_pages, v_pages = write_kv(k, v)
    attn = attend(q, k_pages, v_pages)
    h = h + attn.reshape(N, c.q_dim) @ lp["wo"]
    x2 = rms_norm(h, lp["ln2"], c.rms_norm_eps)
    h = h + _mlp(x2, lp["wg"], lp["wu"], lp["wd"])
    return h, (k_pages, v_pages)


def _logits(config: ModelConfig, params: Params, h: jnp.ndarray) -> jnp.ndarray:
    h = rms_norm(h, params["norm_f"], config.rms_norm_eps)
    w = params["embed"].T if config.tie_word_embeddings else params["lm_head"]
    # f32 accumulation without materializing an f32 copy of the [H, V] matrix
    return jnp.matmul(h, w, preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# Prefill

@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def prefill(
    config: ModelConfig,
    params: Params,
    cache: Cache,
    tokens: jnp.ndarray,      # [T] int32, padded to a page-size multiple
    page_table: jnp.ndarray,  # [max_pages] int32 (pages covering [0, padded end))
    q_start: jnp.ndarray,     # scalar int32: #tokens already cached (page-aligned)
    seq_len: jnp.ndarray,     # scalar int32: total valid context length
) -> tuple[Cache, jnp.ndarray]:
    """Run T new tokens through the model, writing their KV into pages.

    Returns (cache, logits[vocab]) where logits are for the LAST VALID token
    (position seq_len-1). Supports prefix-cache continuation: with q_start>0
    the first q_start tokens' KV is already in the pages listed by
    page_table and is attended to but not recomputed.

    CALLER CONTRACT (checked host-side by the engine scheduler, not here —
    lax.dynamic_slice silently clamps under jit): q_start must be
    page-aligned and q_start//page_size + T//page_size <= len(page_table),
    with all written entries real (non-zero) pages.
    """
    c = config
    T = tokens.shape[0]
    ps = cache["k"].shape[2]
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = q_start + jnp.arange(T, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, inv_freq)

    h = params["embed"][tokens].astype(cache["k"].dtype)

    # page indices that receive the new tokens' KV
    n_new_pages = T // ps
    write_idx = jax.lax.dynamic_slice_in_dim(
        page_table, q_start // ps, n_new_pages
    )  # [T/ps]

    def layer_fn(h, xs):
        (lp, k_pages, v_pages) = xs

        def write_kv(k, v):
            shape = (n_new_pages, ps, c.num_kv_heads, c.head_dim)
            return (
                k_pages.at[write_idx].set(k.reshape(shape)),
                v_pages.at[write_idx].set(v.reshape(shape)),
            )

        def attend(q, kp, vp):
            return prefill_attention(q, kp, vp, page_table, q_start, seq_len)

        return _layer_body(c, lp, h, cos, sin, write_kv, attend)

    h, (k_new, v_new) = jax.lax.scan(
        layer_fn, h, (params["layers"], cache["k"], cache["v"])
    )
    last = seq_len - q_start - 1  # index of last valid token within T
    logits = _logits(c, params, h[last])
    return {"k": k_new, "v": v_new}, logits


# ---------------------------------------------------------------------------
# Decode

@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(2,))
def decode_step(
    config: ModelConfig,
    params: Params,
    cache: Cache,
    tokens: jnp.ndarray,       # [B] int32 — last sampled token per slot
    page_tables: jnp.ndarray,  # [B, max_pages] int32 (inactive slots: zeros)
    ctx_lens: jnp.ndarray,     # [B] int32 — context length INCLUDING this token
) -> tuple[Cache, jnp.ndarray]:
    """One decode step for all slots. Returns (cache, logits [B, vocab])."""
    c = config
    B = tokens.shape[0]
    ps = cache["k"].shape[2]
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = jnp.maximum(ctx_lens - 1, 0)
    cos, sin = rope_cos_sin(positions, inv_freq)  # [B, hd]

    h = params["embed"][tokens].astype(cache["k"].dtype)  # [B, H]

    page_idx = jnp.take_along_axis(
        page_tables, (positions // ps)[:, None], axis=1
    )[:, 0]                       # [B] page receiving this token's KV
    offset = positions % ps       # [B]

    def layer_fn(h, xs):
        (lp, k_pages, v_pages) = xs

        def write_kv(k, v):
            return (
                k_pages.at[page_idx, offset].set(k),
                v_pages.at[page_idx, offset].set(v),
            )

        def attend(q, kp, vp):
            return paged_decode_attention(q, kp, vp, page_tables, ctx_lens)

        return _layer_body(c, lp, h, cos, sin, write_kv, attend)

    h, (k_new, v_new) = jax.lax.scan(
        layer_fn, h, (params["layers"], cache["k"], cache["v"])
    )
    logits = _logits(c, params, h)
    return {"k": k_new, "v": v_new}, logits


# ---------------------------------------------------------------------------
# HF weight loading

_HF_LAYER_MAP = {
    "input_layernorm.weight": ("ln1", False),
    "post_attention_layernorm.weight": ("ln2", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "mlp.gate_proj.weight": ("wg", True),
    "mlp.up_proj.weight": ("wu", True),
    "mlp.down_proj.weight": ("wd", True),
}


def params_from_state_dict(
    config: ModelConfig, raw: dict[str, jnp.ndarray], dtype=None
) -> Params:
    """Build our param pytree from HF-named tensors (torch state_dict names).

    Torch linear weights are [out, in]; ours are [in, out] — transposed here.
    Per-layer tensors are stacked on the leading layer axis.
    """
    dtype = jnp.dtype(config.dtype) if dtype is None else jnp.dtype(dtype)
    L = config.num_layers
    layers: dict[str, list] = {k: [None] * L for (k, _) in _HF_LAYER_MAP.values()}
    for hf_suffix, (ours, transpose) in _HF_LAYER_MAP.items():
        for l in range(L):
            t = jnp.asarray(raw[f"model.layers.{l}.{hf_suffix}"])
            layers[ours][l] = t.T if transpose else t

    params: Params = {
        "embed": jnp.asarray(raw["model.embed_tokens.weight"], dtype),
        "layers": {
            k: jnp.stack(v).astype(dtype) for k, v in layers.items()
        },
        "norm_f": jnp.asarray(raw["model.norm.weight"], dtype),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(raw["lm_head.weight"]).T.astype(dtype)
    return params


def load_hf_params(
    config: ModelConfig, model_dir: str, dtype=None, shardings: Params | None = None
) -> Params:
    """Load llama safetensors weights from a local HF model directory.

    Tensors are read and stacked on the host CPU (never staged through an
    accelerator); with `shardings` each stacked leaf is device_put straight
    to its target sharding, so peak accelerator memory is one sharded copy.
    """
    import glob
    import os

    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no safetensors in {model_dir}")
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        raw: dict[str, jnp.ndarray] = {}
        for fp in files:
            with safe_open(fp, framework="flax") as f:
                for name in f.keys():
                    raw[name] = f.get_tensor(name)
        params = params_from_state_dict(config, raw, dtype)
        del raw
    if shardings is not None:
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, shardings
        )
    return params
