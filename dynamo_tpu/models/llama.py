"""Llama-family forward pass (Llama-2/3/3.x, DeepSeek-R1-Distill-Llama).

Design notes (TPU-first):
  - Parameters are a pytree whose per-layer leaves are STACKED on a leading
    layer axis and the decoder runs as one ``lax.scan`` — one compiled layer
    body regardless of depth (compile time stays flat from 4 to 80 layers).
  - The KV cache is a paged pool per layer: ``[L, kv_heads, num_pages,
    page_size, head_dim]`` (head-leading so one (head, page) block is a
    clean TPU tile and the kv_heads axis shards over ``tp``); requests
    address it through page tables. Page 0
    is a reserved scratch page: page-table entries BEYOND a request's
    allocated pages point at it, so whole-page padding writes and inactive
    decode slots never corrupt real pages. Padding tokens within a
    request's own tail page DO write garbage KV into that page's tail slots
    — they are never valid context (masked by seq_len/ctx_len, and decode
    overwrites them in order), but attention kernels MUST keep the validity
    mask and the prefix cache must only ever share complete pages.
  - Tensor parallelism is pure GSPMD: `param_shardings`/`cache_shardings`
    put head/hidden dims on the ``tp`` mesh axis; XLA inserts the ICI
    collectives. No hand-written comm (contrast: reference engines use NCCL
    inside vLLM — SURVEY.md §2.5).
  - Prefill is B=1 over a padded token bucket (positions q_start..q_start+T);
    decode is a fixed-slot batch, one token per slot. Both are jittable with
    static shapes; the engine buckets prompt lengths to bound recompiles.

Parity: this is the TPU engine the reference delegates to vLLM for
(launch/dynamo-run subprocess engines; SURVEY.md §2.1 L3).
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.attention import paged_decode_attention, prefill_attention
from dynamo_tpu.ops.rope import apply_rope, rope_cos_sin, rope_inv_freq

Params = dict[str, Any]
Cache = dict[str, jnp.ndarray]


# ---------------------------------------------------------------------------
# Parameters

def init_params(config: ModelConfig, rng: jax.Array | int = 0) -> Params:
    """Random-init parameters (bf16). Weight values only matter for quality,
    not performance, so benchmarks use this; serving uses load_hf_params."""
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    c = config
    dtype = jnp.dtype(c.dtype)
    keys = jax.random.split(rng, 12)

    def rnd(key, *shape, scale=None):
        scale = scale or (1.0 / np.sqrt(shape[-2] if len(shape) > 1 else shape[-1]))
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    L, H, I, V = c.num_layers, c.hidden_size, c.intermediate_size, c.vocab_size
    params: Params = {
        "embed": rnd(keys[0], V, H, scale=0.02),
        "layers": {
            "ln1": jnp.ones((L, H), dtype),
            "ln2": jnp.ones((L, H), dtype),
            "wq": rnd(keys[1], L, H, c.q_dim),
            "wk": rnd(keys[2], L, H, c.kv_dim),
            "wv": rnd(keys[3], L, H, c.kv_dim),
            "wo": rnd(keys[4], L, c.q_dim, H),
            "wg": rnd(keys[5], L, H, I),
            "wu": rnd(keys[6], L, H, I),
            "wd": rnd(keys[7], L, I, H),
        },
        "norm_f": jnp.ones((H,), dtype),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = rnd(keys[8], H, V, scale=0.02)
    return params


def param_shardings(config: ModelConfig, mesh: Mesh) -> Params:
    """NamedSharding pytree: Megatron-style TP over the `tp` mesh axis.
    qkv/gate/up shard the output (head/hidden) dim; o/down shard the input
    dim; embedding + lm_head shard the vocab dim."""
    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    out: Params = {
        "embed": ns("tp", None),
        "layers": {
            "ln1": ns(None, None),
            "ln2": ns(None, None),
            "wq": ns(None, None, "tp"),
            "wk": ns(None, None, "tp"),
            "wv": ns(None, None, "tp"),
            "wo": ns(None, "tp", None),
            "wg": ns(None, None, "tp"),
            "wu": ns(None, None, "tp"),
            "wd": ns(None, "tp", None),
        },
        "norm_f": ns(None),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = ns(None, "tp")
    return out


# ---------------------------------------------------------------------------
# KV cache

def init_cache(
    config: ModelConfig, num_pages: int, page_size: int, dtype=None
) -> Cache:
    """Paged KV pool. Page 0 is the reserved scratch page (see module doc)."""
    c = config
    dtype = dtype or jnp.dtype(c.dtype)
    shape = (c.num_layers, c.num_kv_heads, num_pages, page_size, c.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_shardings(config: ModelConfig, mesh: Mesh) -> Cache:
    s = NamedSharding(mesh, P(None, "tp", None, None, None))
    return {"k": s, "v": s}


def init_ring(
    config: ModelConfig, batch: int, ring_len: int, dtype=None
) -> Cache:
    """Per-slot decode write ring ``[L, kv_heads, B, R, head_dim]``.

    Decode steps write their token's KV here (a cheap dynamic-update-slice)
    instead of scattering into the page pool; `flush` batch-scatters a full
    ring into the pool once per R steps. This keeps the multi-GB pool out
    of the per-step program entirely (it is read-only between flushes) —
    per-step scatter into the pool costs a full pool materialization on
    backends without in-place buffer aliasing, and a slow scatter even with
    it. Ring slot r of batch lane b holds the token at position
    ``ring_base[b] + r``.
    """
    c = config
    dtype = dtype or jnp.dtype(c.dtype)
    shape = (c.num_layers, c.num_kv_heads, batch, ring_len, c.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def ring_shardings(config: ModelConfig, mesh: Mesh) -> Cache:
    s = NamedSharding(mesh, P(None, "tp", None, None, None))
    return {"k": s, "v": s}


# ---------------------------------------------------------------------------
# Forward pieces

def rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _mlp(h, wg, wu, wd):
    return (jax.nn.silu(h @ wg) * (h @ wu)) @ wd


def _layer_body(c: ModelConfig, lp, h, cos, sin, write_kv, attend):
    """Shared decoder-layer body for prefill and decode.

    `write_kv(k, v)` scatters new KV into the carried cache and returns it;
    `attend(q, cache)` runs attention over the updated cache. `h` is [N, H]
    (N = padded tokens for prefill, batch slots for decode).
    """
    N = h.shape[0]
    x = rms_norm(h, lp["ln1"], c.rms_norm_eps)
    q = (x @ lp["wq"]).reshape(N, c.num_heads, c.head_dim)
    k = (x @ lp["wk"]).reshape(N, c.num_kv_heads, c.head_dim)
    v = (x @ lp["wv"]).reshape(N, c.num_kv_heads, c.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    new_cache = write_kv(k, v)
    attn = attend(q, new_cache)
    h = h + attn.reshape(N, c.q_dim) @ lp["wo"]
    x2 = rms_norm(h, lp["ln2"], c.rms_norm_eps)
    h = h + _mlp(x2, lp["wg"], lp["wu"], lp["wd"])
    return h, new_cache


def _logits(config: ModelConfig, params: Params, h: jnp.ndarray) -> jnp.ndarray:
    h = rms_norm(h, params["norm_f"], config.rms_norm_eps)
    w = params["embed"].T if config.tie_word_embeddings else params["lm_head"]
    # f32 accumulation without materializing an f32 copy of the [H, V] matrix
    return jnp.matmul(h, w, preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# Prefill

def prefill_impl(
    config: ModelConfig,
    params: Params,
    cache: Cache,
    tokens: jnp.ndarray,      # [T] int32, padded to a page-size multiple
    page_table: jnp.ndarray,  # [max_pages] int32 (pages covering [0, padded end))
    q_start: jnp.ndarray,     # scalar int32: #tokens already cached (page-aligned)
    seq_len: jnp.ndarray,     # scalar int32: total valid context length
) -> tuple[Cache, jnp.ndarray]:
    """Run T new tokens through the model, writing their KV into pages.

    Returns (cache, logits[vocab]) where logits are for the LAST VALID token
    (position seq_len-1). Supports prefix-cache continuation: with q_start>0
    the first q_start tokens' KV is already in the pages listed by
    page_table and is attended to but not recomputed.

    CALLER CONTRACT (checked host-side by the engine scheduler, not here —
    lax.dynamic_slice silently clamps under jit): q_start must be
    page-aligned and q_start//page_size + T//page_size <= len(page_table),
    with all written entries real (non-zero) pages.
    """
    c = config
    T = tokens.shape[0]
    ps = cache["k"].shape[3]
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = q_start + jnp.arange(T, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, inv_freq)

    h = params["embed"][tokens].astype(cache["k"].dtype)

    # page indices that receive the new tokens' KV
    n_new_pages = T // ps
    write_idx = jax.lax.dynamic_slice_in_dim(
        page_table, q_start // ps, n_new_pages
    )  # [T/ps]

    # Layers are UNROLLED (python loop, static layer index): XLA's aliasing
    # analysis keeps the donated cache update chain in place, whereas a
    # lax.scan carrying the cache re-materializes it every iteration (the
    # attention read-after-scatter defeats carry aliasing).
    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])

        def write_kv(k, v, l=l):
            # [T, kvh, hd] -> [n_new_pages, kvh, ps, hd]: the int l counts
            # as an advanced index alongside write_idx (separated by the
            # slice), so their broadcast dim [n] leads the result
            def to_pages(x):
                return x.reshape(
                    n_new_pages, ps, c.num_kv_heads, c.head_dim
                ).transpose(0, 2, 1, 3)

            ck = cache["k"].at[l, :, write_idx].set(to_pages(k))
            cv = cache["v"].at[l, :, write_idx].set(to_pages(v))
            return {"k": ck, "v": cv}

        def attend(q, new_cache, l=l):
            return prefill_attention(
                q, new_cache["k"], new_cache["v"], jnp.int32(l),
                page_table, q_start, seq_len,
            )

        h, cache = _layer_body(c, lp, h, cos, sin, write_kv, attend)

    last = seq_len - q_start - 1  # index of last valid token within T
    logits = _logits(c, params, h[last])
    return cache, logits


prefill = jax.jit(prefill_impl, static_argnums=(0,), donate_argnums=(2,))


# ---------------------------------------------------------------------------
# Decode

def decode_step_impl(
    config: ModelConfig,
    params: Params,
    cache: Cache,              # page pool — READ-ONLY here (see init_ring)
    ring: Cache,               # [L, kvh, B, R, hd] write ring
    tokens: jnp.ndarray,       # [B] int32 — last sampled token per slot
    page_tables: jnp.ndarray,  # [B, max_pages] int32 (inactive slots: zeros)
    ctx_lens: jnp.ndarray,     # [B] int32 — context length INCLUDING this token
    ring_base: jnp.ndarray,    # [B] int32 — position held by ring slot 0
    ring_pos: jnp.ndarray,     # scalar int32 — ring slot receiving this token
) -> tuple[Cache, jnp.ndarray]:
    """One decode step for all slots. Returns (ring, logits [B, vocab]).

    The new token's KV lands in ring slot `ring_pos` (its position is
    ``ctx-1 == ring_base + ring_pos`` for live slots); attention covers
    pool pages for positions < ring_base plus ring entries
    [ring_base, ctx). The pool is immutable between `flush` calls.
    """
    c = config
    B = tokens.shape[0]
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = jnp.maximum(ctx_lens - 1, 0)
    cos, sin = rope_cos_sin(positions, inv_freq)  # [B, hd]

    h = params["embed"][tokens].astype(cache["k"].dtype)  # [B, H]

    # unrolled layers — see prefill_impl for why not lax.scan
    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])

        def write_kv(k, v, l=l):
            # one DUS per layer: [B, kvh, hd] -> ring[l, :, :, ring_pos, :]
            def put(r, x):
                upd = x.transpose(1, 0, 2)[None, :, :, None, :]
                return jax.lax.dynamic_update_slice(
                    r, upd.astype(r.dtype), (l, 0, 0, ring_pos, 0)
                )

            return {"k": put(ring["k"], k), "v": put(ring["v"], v)}

        def attend(q, new_ring, l=l):
            return paged_decode_attention(
                q, cache["k"], cache["v"],
                new_ring["k"], new_ring["v"], jnp.int32(l),
                page_tables, ctx_lens, ring_base,
            )

        h, ring = _layer_body(c, lp, h, cos, sin, write_kv, attend)

    logits = _logits(c, params, h)
    return ring, logits


decode_step = jax.jit(decode_step_impl, static_argnums=(0,), donate_argnums=(3,))


def flush_impl(
    config: ModelConfig,
    cache: Cache,
    ring: Cache,
    page_tables: jnp.ndarray,  # [B, W] int32 — MUST cover every position
                               # written this round (see contract below)
    ring_base: jnp.ndarray,    # [B] int32
    valid_len: jnp.ndarray,    # [B] int32 — #real tokens in the ring per slot
) -> Cache:
    """Batch-scatter a full ring into the page pool (once per round).

    Ring entry (b, r) holds position ring_base[b]+r and goes to page
    page_tables[b, pos//ps] at offset pos%ps; entries with r >= valid_len[b]
    (garbage beyond a finished/clamped slot) are redirected to scratch page
    0. This is the only writer of the pool besides prefill.

    CONTRACT: the table may be width-bucketed, but every position in
    [ring_base, ring_base+valid_len) must map inside it — the engine's
    _ensure_coverage guarantees this. Positions falling OUTSIDE the table
    width are routed to scratch page 0 (dropped KV -> visibly wrong
    output), never clamped into another sequence's page (silent KV
    corruption).
    """
    c = config
    ps = cache["k"].shape[3]
    L, kvh, B, R, hd = ring["k"].shape
    r_idx = jnp.arange(R, dtype=jnp.int32)[None, :]          # [1, R]
    pos = ring_base[:, None] + r_idx                          # [B, R]
    page_slot = pos // ps
    W = page_tables.shape[1]
    in_range = page_slot < W
    page = jnp.take_along_axis(
        page_tables, jnp.clip(page_slot, 0, W - 1), axis=1
    )  # [B, R]
    valid = (r_idx < valid_len[:, None]) & in_range
    page = jnp.where(valid, page, 0)
    offset = pos % ps
    pflat = page.reshape(-1)       # [B*R]
    oflat = offset.reshape(-1)

    out = {}
    for name in ("k", "v"):
        pool = cache[name]
        upd = ring[name].transpose(0, 2, 3, 1, 4).reshape(L, B * R, kvh, hd)
        for l in range(L):
            # advanced dims ([B*R]) lead: target [B*R, kvh, hd]
            pool = pool.at[l, :, pflat, oflat].set(upd[l])
        out[name] = pool
    return out


flush = jax.jit(flush_impl, static_argnums=(0,), donate_argnums=(1,))


# ---------------------------------------------------------------------------
# Sequence-parallel (ring) prefill — long-context path (SURVEY §2.5 SP
# row / §7.11: the reference has no sequence parallelism; this is the
# TPU-native long-context answer). The prompt is sharded over the `sp`
# mesh axis; every layer's attention runs as ring attention (KV blocks
# rotate over ICI via ppermute) so per-device memory is O(T/sp).

def sp_prefill(
    config: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,    # [T] int32, sp-sharded, T % sp == 0
    seq_len: jnp.ndarray,   # scalar int32 — valid length
    mesh: Mesh,
    axis: str = "sp",
) -> tuple[Cache, jnp.ndarray]:
    """Returns (kv, logits[vocab]) where kv = {"k","v"}: [L, kvh, T, hd]
    sp-sharded on the T axis (callers page/commit it as needed) and the
    logits are for position seq_len-1. Weights are replicated over sp;
    only KV blocks move (one ICI hop per ring step)."""
    from dynamo_tpu.ops.ring_attention import ring_attention

    c = config
    T = int(tokens.shape[0])
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, inv_freq)
    h = params["embed"][tokens].astype(jnp.dtype(c.dtype))

    ks, vs = [], []
    rep = c.num_heads // c.num_kv_heads
    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])

        def write_kv(k, v):
            ks.append(k)
            vs.append(v)
            return (k, v)

        def attend(q, kv):
            k, v = kv
            return ring_attention(
                q, jnp.repeat(k, rep, axis=1),
                jnp.repeat(v, rep, axis=1), mesh, axis,
            )

        h, _ = _layer_body(c, lp, h, cos, sin, write_kv, attend)

    logits = _logits(c, params, h[seq_len - 1])
    kv = {
        "k": jnp.stack(ks).transpose(0, 2, 1, 3),  # [L, kvh, T, hd]
        "v": jnp.stack(vs).transpose(0, 2, 1, 3),
    }
    return kv, logits


# ---------------------------------------------------------------------------
# Encoder path (embeddings API): full self-attention over the prompt with
# no KV cache — the /v1/embeddings endpoint pools the final hidden states
# (reference protocols/openai embeddings surface; the reference delegates
# embedding models to its engines)

def encode_impl(
    config: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,   # [T] int32, padded
    seq_len: jnp.ndarray,  # scalar int32: valid length
) -> jnp.ndarray:
    """Mean-pooled, L2-normalized final hidden state [H] over the valid
    tokens. Cache-free causal attention (prompt-sized, one shot)."""
    c = config
    T = tokens.shape[0]
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, inv_freq)
    h = params["embed"][tokens].astype(jnp.dtype(c.dtype))
    valid = positions < seq_len                                   # [T]
    causal = (positions[None, :] <= positions[:, None]) & valid[None, :]

    def attend(q, kv):
        k, v = kv
        # GQA: repeat kv heads to match q heads
        rep = c.num_heads // c.num_kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
        scores = jnp.einsum("qhd,khd->hqk", q, k) / np.sqrt(c.head_dim)
        scores = jnp.where(causal[None], scores.astype(jnp.float32),
                           -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        return jnp.einsum("hqk,khd->qhd", w, v)

    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])
        h, _ = _layer_body(
            c, lp, h, cos, sin,
            write_kv=lambda k, v: (k, v),
            attend=attend,
        )
    h = rms_norm(h, params["norm_f"], c.rms_norm_eps)
    maskf = valid.astype(jnp.float32)[:, None]
    pooled = (h.astype(jnp.float32) * maskf).sum(0) / jnp.maximum(
        maskf.sum(), 1.0
    )
    return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)


encode = jax.jit(encode_impl, static_argnums=(0,))


# ---------------------------------------------------------------------------
# KV page export/import (the block-transfer data plane's device ops;
# reference analogue: NIXL block read/write, block_manager/block/transfer.rs)

def gather_pages_impl(cache: Cache, page_ids: jnp.ndarray) -> jnp.ndarray:
    """Pull whole pages out of the pool: [2, L, kvh, n, ps, hd] (k then v).
    Callers bucket n to a pow2 (padding with scratch page 0) to bound
    recompiles; the host slices the padding off after fetch."""
    return jnp.stack(
        [cache["k"][:, :, page_ids], cache["v"][:, :, page_ids]]
    )


def scatter_pages_impl(
    cache: Cache, page_ids: jnp.ndarray, data: jnp.ndarray
) -> Cache:
    """Write whole pages into the pool (inverse of gather_pages). Padding
    entries must point at scratch page 0 — it is garbage by contract."""
    return {
        "k": cache["k"].at[:, :, page_ids].set(data[0].astype(cache["k"].dtype)),
        "v": cache["v"].at[:, :, page_ids].set(data[1].astype(cache["v"].dtype)),
    }


gather_pages = jax.jit(gather_pages_impl)
scatter_pages = jax.jit(scatter_pages_impl, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# HF weight loading

_HF_LAYER_MAP = {
    "input_layernorm.weight": ("ln1", False),
    "post_attention_layernorm.weight": ("ln2", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "mlp.gate_proj.weight": ("wg", True),
    "mlp.up_proj.weight": ("wu", True),
    "mlp.down_proj.weight": ("wd", True),
}


def params_from_state_dict(
    config: ModelConfig, raw: dict[str, jnp.ndarray], dtype=None
) -> Params:
    """Build our param pytree from HF-named tensors (torch state_dict names).

    Torch linear weights are [out, in]; ours are [in, out] — transposed here.
    Per-layer tensors are stacked on the leading layer axis.
    """
    dtype = jnp.dtype(config.dtype) if dtype is None else jnp.dtype(dtype)
    L = config.num_layers
    layers: dict[str, list] = {k: [None] * L for (k, _) in _HF_LAYER_MAP.values()}
    for hf_suffix, (ours, transpose) in _HF_LAYER_MAP.items():
        for l in range(L):
            t = jnp.asarray(raw[f"model.layers.{l}.{hf_suffix}"])
            layers[ours][l] = t.T if transpose else t

    params: Params = {
        "embed": jnp.asarray(raw["model.embed_tokens.weight"], dtype),
        "layers": {
            k: jnp.stack(v).astype(dtype) for k, v in layers.items()
        },
        "norm_f": jnp.asarray(raw["model.norm.weight"], dtype),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(raw["lm_head.weight"]).T.astype(dtype)
    return params


def load_hf_params(
    config: ModelConfig, model_dir: str, dtype=None, shardings: Params | None = None
) -> Params:
    """Load llama safetensors weights from a local HF model directory.

    Tensors are read and stacked on the host CPU (never staged through an
    accelerator); with `shardings` each stacked leaf is device_put straight
    to its target sharding, so peak accelerator memory is one sharded copy.
    """
    import glob
    import os

    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no safetensors in {model_dir}")
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        raw: dict[str, jnp.ndarray] = {}
        for fp in files:
            with safe_open(fp, framework="flax") as f:
                for name in f.keys():
                    raw[name] = f.get_tensor(name)
        params = params_from_state_dict(config, raw, dtype)
        del raw
    if shardings is not None:
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, shardings
        )
    return params
