"""Llama-family forward pass (Llama-2/3/3.x, DeepSeek-R1-Distill-Llama).

Design notes (TPU-first, round-4 layout):
  - Parameters are a pytree whose per-layer leaves are STACKED on a leading
    layer axis; the decoder is an unrolled python loop with static layer
    indices (XLA's aliasing keeps donated KV updates in place, which a
    lax.scan carry defeats).
  - SERVING CONTEXT is contiguous per slot: ``ctx_kv [L, kv_heads, B+1,
    S_max, head_dim]`` — slot b's tokens live at [.., b, 0:ctx). Decode
    scatters one row per slot per step and attention streams dense slabs
    (ops/flash_decode.py); prefill writes a contiguous span. Lane B is a
    SCRATCH lane: freed slots' in-flight garbage steps are redirected
    there (``dest`` argument), so a slot being prefilled for a new request
    is never corrupted by a stale pipelined step.
  - The PAGED POOL ``[L, kv_heads, num_pages, page_size, head_dim]`` is
    prefix-cache STORAGE only: sealed blocks are copied ctx->pool
    (seal_blocks) and prefix hits are copied pool->ctx at admission
    (load_ctx_pages). Paging is thereby removed from the per-step hot path
    entirely — the round-3 paged decode kernel spent 15.9 ms/step on
    page-grid overhead. Page 0 stays reserved as scratch for padded
    pool I/O (gather/scatter/seal padding).
  - Tensor parallelism is pure GSPMD: `param_shardings`/`cache_shardings`/
    `ctx_shardings` put head/hidden dims on the ``tp`` mesh axis; XLA
    inserts the ICI collectives. No hand-written comm (contrast: reference
    engines use NCCL inside vLLM — SURVEY.md §2.5).
  - Prefill is B=1 over a padded token bucket (positions q_start..q_start+T);
    decode is a fixed-slot batch, one token per slot. Both are jittable with
    static shapes; the engine buckets prompt lengths to bound recompiles.

Parity: this is the TPU engine the reference delegates to vLLM for
(launch/dynamo-run subprocess engines; SURVEY.md §2.1 L3).
"""
from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from dynamo_tpu.kv_quant import (
    SCALE_EPS,
    dequantize_groups,
    requantize_groups,
)
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.attention import (
    ctx_decode_attention,
    ctx_prefill_attention,
    flash_prefill_attention,
)
from dynamo_tpu.ops.rope import apply_rope, rope_cos_sin, rope_inv_freq

Params = dict[str, Any]
Cache = dict[str, jnp.ndarray]


# ---------------------------------------------------------------------------
# Parameters

def init_params(config: ModelConfig, rng: jax.Array | int = 0) -> Params:
    """Random-init parameters (bf16, or w8a16 when config.quant="int8").
    Weight values only matter for quality, not performance, so benchmarks
    use this; serving uses load_hf_params.

    With quant, int8 leaves are generated DIRECTLY (uniform int8 + a
    constant per-channel scale matched to the dense init's std) — an 8B's
    dense weights can never be materialized on a 16 GB chip, so there is
    no dense-then-quantize step here."""
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    c = config
    dtype = jnp.dtype(c.dtype)
    keys = jax.random.split(rng, 12)
    quant8 = c.quant == "int8"

    def rnd(key, *shape, scale=None, qaxis=-2):
        scale = scale or (1.0 / np.sqrt(shape[-2] if len(shape) > 1 else shape[-1]))
        if quant8 and qaxis is not None:
            q = jax.random.randint(key, shape, -127, 128, jnp.int8)
            s_shape = tuple(np.delete(shape, len(shape) + qaxis))
            # uniform[-127,127] has std ~73.3; s recovers the dense std
            s = jnp.full(s_shape, scale / 73.3, jnp.float32)
            return {"q": q, "s": s}
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    L, H, I, V = c.num_layers, c.hidden_size, c.intermediate_size, c.vocab_size
    layers: dict[str, Any] = {
        "ln1": jnp.ones((L, H), dtype),
        "ln2": jnp.ones((L, H), dtype),
        "wq": rnd(keys[1], L, H, c.q_dim),
        "wk": rnd(keys[2], L, H, c.kv_dim),
        "wv": rnd(keys[3], L, H, c.kv_dim),
        "wo": rnd(keys[4], L, c.q_dim, H),
    }
    if c.moe is not None:
        E = c.moe_dict["num_experts"]
        layers.update(
            wr=rnd(keys[5], L, H, E, qaxis=None),  # router stays dense
            we_g=rnd(keys[6], L, E, H, I),
            we_u=rnd(keys[7], L, E, H, I),
            we_d=rnd(keys[9], L, E, I, H),
        )
    else:
        layers.update(
            wg=rnd(keys[5], L, H, I),
            wu=rnd(keys[6], L, H, I),
            wd=rnd(keys[7], L, I, H),
        )
    params: Params = {
        "embed": rnd(keys[0], V, H, scale=0.02, qaxis=-1),
        "layers": layers,
        "norm_f": jnp.ones((H,), dtype),
    }
    if not c.tie_word_embeddings:
        params["lm_head"] = rnd(keys[8], H, V, scale=0.02)
    return params


def param_shardings(config: ModelConfig, mesh: Mesh) -> Params:
    """NamedSharding pytree: Megatron-style TP over the `tp` mesh axis.
    qkv/gate/up shard the output (head/hidden) dim; o/down shard the input
    dim; embedding + lm_head shard the vocab dim. Quantized leaves get the
    weight's spec on "q" and the spec minus the reduced axis on "s"."""
    quant8 = config.quant == "int8"

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    def w(name, *spec):
        if quant8 and name in _QUANT_AXIS:
            axis = len(spec) + _QUANT_AXIS[name]
            s_spec = tuple(p for i, p in enumerate(spec) if i != axis)
            return {"q": ns(*spec), "s": ns(*s_spec)}
        return ns(*spec)

    layers: Params = {
        "ln1": ns(None, None),
        "ln2": ns(None, None),
        "wq": w("wq", None, None, "tp"),
        "wk": w("wk", None, None, "tp"),
        "wv": w("wv", None, None, "tp"),
        "wo": w("wo", None, "tp", None),
    }
    if config.moe is not None:
        # experts over ep, expert hidden over tp (wide-EP shape §2.5)
        layers.update(
            wr=ns(None, None, None),
            we_g=w("we_g", None, "ep", None, "tp"),
            we_u=w("we_u", None, "ep", None, "tp"),
            we_d=w("we_d", None, "ep", "tp", None),
        )
    else:
        layers.update(
            wg=w("wg", None, None, "tp"),
            wu=w("wu", None, None, "tp"),
            wd=w("wd", None, "tp", None),
        )
    out: Params = {
        "embed": w("embed", "tp", None),
        "layers": layers,
        "norm_f": ns(None),
    }
    if not config.tie_word_embeddings:
        out["lm_head"] = w("lm_head", None, "tp")
    return out


# ---------------------------------------------------------------------------
# KV cache

def init_cache(
    config: ModelConfig, num_pages: int, page_size: int, dtype=None,
    kv_quant: str = "none",
) -> Cache:
    """Paged KV pool — prefix-cache STORAGE (see module doc). Page 0 is the
    reserved scratch page for padded pool I/O.

    With ``kv_quant="int8"`` the pool holds int8 pages plus
    per-block-per-layer absmax scales (``k_scale``/``v_scale``: f32
    [L, num_pages]) — half the HBM residency of a bf16 pool, so the same
    chip holds ~2x the hittable prefix corpus. The hot decode path is
    untouched: quantize fuses into seal_blocks (ctx->pool), dequantize
    into load_ctx_pages (pool->ctx)."""
    c = config
    shape = (c.num_layers, c.num_kv_heads, num_pages, page_size, c.head_dim)
    if kv_quant == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros((c.num_layers, num_pages), jnp.float32),
            "v_scale": jnp.zeros((c.num_layers, num_pages), jnp.float32),
        }
    dtype = dtype or jnp.dtype(c.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_shardings(
    config: ModelConfig, mesh: Mesh, kv_quant: str = "none"
) -> Cache:
    s = NamedSharding(mesh, P(None, "tp", None, None, None))
    out = {"k": s, "v": s}
    if kv_quant == "int8":
        # per-(layer, page) scales: no head axis, replicated over tp
        sc = NamedSharding(mesh, P(None, None))
        out["k_scale"] = sc
        out["v_scale"] = sc
    return out


def cache_is_quantized(cache: Cache) -> bool:
    return "k_scale" in cache


def init_ctx(
    config: ModelConfig, batch: int, ctx_len: int, dtype=None,
    kv_quant: str = "none", group: int = 128,
) -> Cache:
    """Contiguous per-slot serving context ``[L, kvh, batch+1, S, hd]``.
    Lane `batch` is the scratch lane for freed slots' in-flight garbage
    steps (see module doc / engine dest redirection).

    With ``kv_quant="int8"`` the region is int8 plus per-(layer, lane,
    position-group) f32 absmax scales ``k_scale``/``v_scale``
    [L, batch+1, S/group] — the flash-decode kernel dequantizes each KV
    chunk in VMEM after the DMA, halving live-context HBM traffic.
    ``group`` must be the engine's page_size so pool<->ctx copies at
    seal/admission are raw int8 page moves (the scale grids coincide);
    S is padded up to a multiple of it (the engine's max_context is
    already page-aligned, so no padding in practice)."""
    c = config
    shape = (c.num_layers, c.num_kv_heads, batch + 1, ctx_len, c.head_dim)
    if kv_quant == "int8":
        S = -(-ctx_len // group) * group
        shape = shape[:3] + (S,) + shape[4:]
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(
                (c.num_layers, batch + 1, S // group), jnp.float32),
            "v_scale": jnp.zeros(
                (c.num_layers, batch + 1, S // group), jnp.float32),
        }
    dtype = dtype or jnp.dtype(c.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def ctx_shardings(config: ModelConfig, mesh: Mesh,
                  kv_quant: str = "none") -> Cache:
    s = NamedSharding(mesh, P(None, "tp", None, None, None))
    out = {"k": s, "v": s}
    if kv_quant == "int8":
        # scales have no head axis: replicated over tp
        sc = NamedSharding(mesh, P(None, None, None))
        out["k_scale"] = sc
        out["v_scale"] = sc
    return out


def ctx_is_quantized(ctx_kv: Cache) -> bool:
    return "k_scale" in ctx_kv


def ctx_group_size(ctx_kv: Cache) -> int:
    """Position-group width of the int8 ctx scale grid."""
    return ctx_kv["k"].shape[3] // ctx_kv["k_scale"].shape[2]


def _ctx_compute_dtype(config: ModelConfig, ctx_kv: Cache):
    """Dtype activations/attention run in. The dense ctx region doubles
    as the compute dtype carrier; an int8 region cannot, so quantized
    mode computes in the model dtype (engines pair cache_dtype with the
    model dtype, so this is the same grid either way)."""
    if ctx_is_quantized(ctx_kv):
        return jnp.dtype(config.dtype)
    return ctx_kv["k"].dtype


def _ctx_slot_slab(ctx_kv: Cache, name: str, l: int, slot: jnp.ndarray,
                   dtype, span: int = 0) -> jnp.ndarray:
    """One slot's [kvh, S, hd] ctx slab in the compute dtype —
    dequantizing on read when the region is int8 (prefill/score reads;
    the decode hot path dequantizes inside the kernel instead)."""
    slab = jax.lax.dynamic_index_in_dim(
        ctx_kv[name][l], slot, axis=1, keepdims=False
    )  # [kvh, S, hd]
    if span > 0:
        slab = slab[:, :span]
    if not ctx_is_quantized(ctx_kv):
        return slab
    g = ctx_group_size(ctx_kv)
    sc = jax.lax.dynamic_index_in_dim(
        ctx_kv[name + "_scale"][l], slot, axis=0, keepdims=False
    )  # [nG]
    sc = jnp.repeat(sc, g)  # [S] per-position
    if span > 0:
        sc = sc[:span]
    return (slab.astype(jnp.float32) * sc[None, :, None]).astype(dtype)


def _quant_store_span(
    buf: jnp.ndarray,      # int8 [L, kvh, lanes, S, hd]
    scale: jnp.ndarray,    # f32 [L, lanes, nG]
    slot: jnp.ndarray,     # scalar i32
    start: jnp.ndarray,    # scalar i32 — span start position
    span: jnp.ndarray,     # float [L, kvh, T, hd] — new KV rows
    group: int,
    valid_t: Optional[jnp.ndarray] = None,  # scalar i32 — leading span
                           # rows that are REAL (rest is bucket padding)
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize-on-store of a contiguous span into one slot's int8 ctx.

    Works on the minimal group-aligned window covering [start, start+T):
    gather window -> dequant -> overlay span -> requantize with fresh
    absmax scales for the overlapped groups (absmax over the request's
    own prefix + the span ONLY — stale suffix bytes from a previous
    occupant never feed a scale, keeping quantization deterministic per
    request history; see kv_quant.requantize_groups)."""
    L, kvh, lanes, S, hd = buf.shape
    nG = scale.shape[2]
    T = span.shape[2]
    nW = min((T + group - 1) // group + 1, nG)
    W = nW * group
    start = start.astype(jnp.int32)
    g0 = jnp.clip(start // group, 0, nG - nW)
    off = start - g0 * group  # in [0, W - T] by the window choice
    flat = buf.reshape(L, kvh, lanes * S, hd)
    base = slot.astype(jnp.int32) * S + g0 * group
    win = jax.lax.dynamic_slice(
        flat, (jnp.int32(0), jnp.int32(0), base, jnp.int32(0)),
        (L, kvh, W, hd),
    )[:, :, None]  # [L, kvh, 1, W, hd]
    sw = jax.lax.dynamic_slice(
        scale, (jnp.int32(0), slot.astype(jnp.int32), g0), (L, 1, nW)
    )  # [L, 1, nW]
    wf = dequantize_groups(win, sw, group)
    wf = jax.lax.dynamic_update_slice(
        wf, span.astype(jnp.float32)[:, :, None],
        (0, 0, 0, off, 0),
    )
    vt = T if valid_t is None else jnp.clip(
        valid_t.astype(jnp.int32), 0, T)
    w_idx = jnp.arange(W, dtype=jnp.int32)
    valid = (w_idx < off + vt)[None]                    # [1, W]
    j = jnp.arange(nW, dtype=jnp.int32)
    written = (((j + 1) * group > off) & (j * group < off + vt))[None]
    q, s_new = requantize_groups(wf, sw, valid, written, group)
    flat = jax.lax.dynamic_update_slice(
        flat, q[:, :, 0], (jnp.int32(0), jnp.int32(0), base, jnp.int32(0))
    )
    scale = jax.lax.dynamic_update_slice(
        scale, s_new, (jnp.int32(0), slot.astype(jnp.int32), g0)
    )
    return flat.reshape(L, kvh, lanes, S, hd), scale


def init_ring(
    config: ModelConfig, batch: int, ring_len: int, dtype=None
) -> Cache:
    """Per-slot decode write ring ``[L, kv_heads, B, R, head_dim]``.

    Decode steps write their token's KV here (a cheap small-buffer
    update); ``flush_ctx`` scatters a full ring into the ctx region once
    per round. This keeps the GB-scale ctx region READ-ONLY inside the
    round program — per-layer writes interleaved with the attention
    custom calls force XLA to materialize full copies of it (measured:
    ~7 GB temps, 120 ms/step). Ring slot r of lane b holds the token at
    position ``ring_base[b] + r``.
    """
    c = config
    dtype = dtype or jnp.dtype(c.dtype)
    shape = (c.num_layers, c.num_kv_heads, batch, ring_len, c.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def ring_shardings(config: ModelConfig, mesh: Mesh) -> Cache:
    s = NamedSharding(mesh, P(None, "tp", None, None, None))
    return {"k": s, "v": s}


# ---------------------------------------------------------------------------
# Quantization (w8a16: per-output-channel symmetric int8 weights)
#
# A quantized weight is the leaf pair {"q": int8 [..., in, out],
# "s": f32 [..., out]}; every matmul site routes through _mm/_embed_rows
# so dense and quantized params are interchangeable. The int8 tensor is
# what streams from HBM (half the weight-pass bytes of bf16 — the decode
# roofline — and what fits an 8B on a 16 GB v5e, BASELINE config 1); the
# dequantize (convert + per-channel scale) fuses into the matmul epilogue.
# Reference analogue: the FP8 serving recipes
# (examples/llm/benchmarks/README.md:28).

_QUANT_AXIS = {
    # reduction axis for the per-output-channel scale, per weight name
    # (all weights are stored [in, out]-style; embed is row-gathered)
    "wq": -2, "wk": -2, "wv": -2, "wo": -2,
    "wg": -2, "wu": -2, "wd": -2,
    "we_g": -2, "we_u": -2, "we_d": -2,
    "embed": -1, "lm_head": -2,
}


def _is_quant(w) -> bool:
    return isinstance(w, dict) and "q" in w


def _mm(x: jnp.ndarray, w) -> jnp.ndarray:
    """x @ w for a dense or quantized weight."""
    if _is_quant(w):
        return jnp.matmul(x, w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


# ---------------------------------------------------------------------------
# Resident LoRA adapters (dynamo_tpu/tenancy/adapters.py builds the bank)
#
# The bank rides inside `params` as params["adapters"] = {site: {"a":
# [N, L, d_in, r], "b": [N, L, r, d_out]}}; presence is a TRACE-TIME
# check, so engines without a bank trace the identical pre-tenancy
# programs. Adapter 0 is all-zeros — the delta is exactly 0.0 and the
# base model's outputs are bit-identical.

def _lora_delta(x: jnp.ndarray, a, b) -> jnp.ndarray:
    """Rank-r LoRA delta for x [T, d_in] (or [B, d_in] in decode).
    Shared-id factors are 2-D ([d_in, r] / [r, d_out]); per-row decode
    factors are 3-D ([B, d_in, r] / [B, r, d_out]) — one gathered row
    per batch lane, contracted with that lane's activation only."""
    a = a.astype(x.dtype)
    b = b.astype(x.dtype)
    if a.ndim == 2:
        return (x @ a) @ b
    t = jnp.einsum("nd,ndr->nr", x, a)
    return jnp.einsum("nr,nro->no", t, b)


def _mm_ad(x: jnp.ndarray, w, ab) -> jnp.ndarray:
    """x @ w plus the site's adapter delta (``ab`` = (a, b) or None)."""
    y = _mm(x, w)
    if ab is not None:
        y = y + _lora_delta(x, ab[0], ab[1])
    return y


def _gather_adapters(bank, ids):
    """Gather each site's factor rows by adapter id: a scalar id yields
    per-site [L, d, r]; a [B] id row yields [B, L, d, r] (the per-slot
    decode gather — ids are constant within a round, so XLA hoists the
    gather out of the fused step loop)."""
    if bank is None or ids is None:
        return None
    return jax.tree.map(lambda x: x[ids], bank)


def _adapter_layer(gathered, l: int, per_row: bool):
    """Layer-l (a, b) slices of a gathered bank, keyed by site — the
    ``ad`` argument of _layer_body. None stays None (no-LoRA trace)."""
    if gathered is None:
        return None
    sl = (lambda x: x[:, l]) if per_row else (lambda x: x[l])
    return {s: (sl(ab["a"]), sl(ab["b"])) for s, ab in gathered.items()}


def _embed_rows(params: Params, tokens: jnp.ndarray, dtype) -> jnp.ndarray:
    """Embedding gather for dense or quantized embed tables."""
    e = params["embed"]
    if _is_quant(e):
        return (e["q"][tokens].astype(dtype)
                * e["s"][tokens][..., None].astype(dtype))
    return e[tokens].astype(dtype)


def quantize_tensor(w, axis: int):
    """Symmetric per-channel int8: scale = amax/127 over `axis`."""
    wf = jnp.asarray(w, jnp.float32)
    s = jnp.max(jnp.abs(wf), axis=axis) / 127.0
    s = jnp.maximum(s, 1e-10)
    q = jnp.clip(
        jnp.round(wf / jnp.expand_dims(s, axis)), -127, 127
    ).astype(jnp.int8)
    return {"q": q, "s": s.astype(jnp.float32)}


def quantize_params(params: Params) -> Params:
    """Post-load transform: dense params -> w8a16. Norms and the MoE
    router stay dense (tiny, accuracy-sensitive)."""
    out = dict(params)
    layers = dict(params["layers"])
    for name, axis in _QUANT_AXIS.items():
        if name in layers:
            layers[name] = quantize_tensor(layers[name], axis)
    out["layers"] = layers
    out["embed"] = quantize_tensor(params["embed"], _QUANT_AXIS["embed"])
    if "lm_head" in params:
        out["lm_head"] = quantize_tensor(
            params["lm_head"], _QUANT_AXIS["lm_head"]
        )
    return out


# ---------------------------------------------------------------------------
# Forward pieces

def rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _mlp(h, wg, wu, wd, ad=None):
    ad = ad or {}
    gate = _mm_ad(h, wg, ad.get("wg"))
    up = _mm_ad(h, wu, ad.get("wu"))
    return _mm_ad(jax.nn.silu(gate) * up, wd, ad.get("wd"))


def _moe_ffn(c: ModelConfig, lp, x: jnp.ndarray,
             valid=None) -> jnp.ndarray:
    """GShard-style dense-dispatch MoE FFN ``[T, H] -> [T, H]``.

    Pure einsums with a static per-expert capacity — jittable with static
    shapes and GSPMD-shardable: experts shard over `ep`, the expert hidden
    dim over `tp`; XLA inserts the all_to_alls over ICI (idiomatic TPU
    replacement for the reference's DeepEP dispatch, SURVEY §2.5 EP row).
    Tokens beyond an expert's capacity are dropped (standard GShard
    semantics); top-k gate weights are renormalized. `valid` [T] masks
    tokens OUT of routing entirely — padding / garbage decode lanes must
    not steal expert capacity from live tokens (and masking makes output
    independent of the co-batched garbage, keeping decode bit-exact
    regardless of slot occupancy)."""
    from dynamo_tpu.models.moe import MoEConfig

    md = c.moe_dict
    mcfg = MoEConfig(
        hidden_size=c.hidden_size,
        intermediate_size=c.intermediate_size,
        num_experts=md["num_experts"],
        top_k=md.get("top_k", 2),
        capacity_factor=md.get("capacity_factor", 1.25),
    )
    T = x.shape[0]
    E, K = mcfg.num_experts, mcfg.top_k
    C = mcfg.capacity(T)

    logits = jnp.matmul(x, lp["wr"], preferred_element_type=jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)            # [T, E]
    gate_w, sel = jax.lax.top_k(gates, K)              # [T, K]
    gate_w = gate_w / jnp.maximum(gate_w.sum(-1, keepdims=True), 1e-9)
    mask = jax.nn.one_hot(sel, E, dtype=jnp.float32)   # [T, K, E]
    if valid is not None:
        mask = mask * valid.astype(jnp.float32)[:, None, None]
    mask_f = mask.reshape(T * K, E)
    # 1-based arrival order of each (token, pick) in its expert's buffer
    pos = jnp.cumsum(mask_f, axis=0) * mask_f
    keep = (pos > 0) & (pos <= C)
    slot = jax.nn.one_hot(pos - 1, C, dtype=jnp.float32) * keep[..., None]
    # dispatch: [T*K, E, C] x [T*K, H] -> [E, C, H]
    x_rep = jnp.broadcast_to(x[:, None], (T, K, c.hidden_size))
    x_rep = x_rep.reshape(T * K, c.hidden_size)
    buf = jnp.einsum("sec,sh->ech", slot, x_rep.astype(jnp.float32))
    buf = buf.astype(x.dtype)
    def emm(spec, a, w):
        # expert einsum, dense or quantized (scale is per [E, out])
        if _is_quant(w):
            return (jnp.einsum(spec, a, w["q"].astype(a.dtype))
                    * w["s"][:, None, :].astype(a.dtype))
        return jnp.einsum(spec, a, w)

    y = (jax.nn.silu(emm("ech,ehi->eci", buf, lp["we_g"]))
         * emm("ech,ehi->eci", buf, lp["we_u"]))
    y = emm("eci,eih->ech", y, lp["we_d"])             # [E, C, H]
    out = jnp.einsum("sec,ech->sh", slot, y.astype(jnp.float32))
    out = out.reshape(T, K, c.hidden_size) * gate_w[..., None]
    return out.sum(axis=1).astype(x.dtype)


def _ffn(c: ModelConfig, lp, x: jnp.ndarray, valid=None,
         ad=None) -> jnp.ndarray:
    if c.moe is not None:
        # MoE expert stacks are not adapted (tenancy/adapters.py)
        return _moe_ffn(c, lp, x, valid)
    return _mlp(x, lp["wg"], lp["wu"], lp["wd"], ad)


def _layer_body(c: ModelConfig, lp, h, cos, sin, write_kv, attend,
                ffn_valid=None, ad=None):
    """Shared decoder-layer body for prefill and decode.

    `write_kv(k, v)` scatters new KV into the carried cache and returns it;
    `attend(q, cache)` runs attention over the updated cache. `h` is [N, H]
    (N = padded tokens for prefill, batch slots for decode). `ad` is the
    layer's adapter-factor slices (``_adapter_layer``) or None — the
    rank-r LoRA deltas fuse into the existing site matmuls.
    """
    N = h.shape[0]
    ad = ad or {}
    x = rms_norm(h, lp["ln1"], c.rms_norm_eps)
    q = _mm_ad(x, lp["wq"], ad.get("wq")).reshape(N, c.num_heads, c.head_dim)
    k = _mm_ad(x, lp["wk"], ad.get("wk")).reshape(N, c.num_kv_heads, c.head_dim)
    v = _mm_ad(x, lp["wv"], ad.get("wv")).reshape(N, c.num_kv_heads, c.head_dim)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    new_cache = write_kv(k, v)
    attn = attend(q, new_cache)
    h = h + _mm_ad(attn.reshape(N, c.q_dim), lp["wo"], ad.get("wo"))
    x2 = rms_norm(h, lp["ln2"], c.rms_norm_eps)
    h = h + _ffn(c, lp, x2, ffn_valid, ad)
    return h, new_cache


def _logits(config: ModelConfig, params: Params, h: jnp.ndarray) -> jnp.ndarray:
    h = rms_norm(h, params["norm_f"], config.rms_norm_eps)
    w = params["embed"] if config.tie_word_embeddings else params["lm_head"]
    if _is_quant(w):
        q = w["q"].T if config.tie_word_embeddings else w["q"]  # [H, V]
        y = jnp.matmul(
            h, q.astype(h.dtype), preferred_element_type=jnp.float32
        )
        return y * w["s"]  # s is [V] for both orientations
    if config.tie_word_embeddings:
        w = w.T
    # f32 accumulation without materializing an f32 copy of the [H, V] matrix
    return jnp.matmul(h, w, preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# Prefill

def prefill_impl(
    config: ModelConfig,
    params: Params,
    ctx_kv: Cache,
    tokens: jnp.ndarray,      # [T] int32, bucket-padded
    slot: jnp.ndarray,        # scalar int32 — destination slot lane
    q_start: jnp.ndarray,     # scalar int32: #tokens already in the region
    seq_len: jnp.ndarray,     # scalar int32: total valid context length
    embeds: Optional[jnp.ndarray] = None,       # [T, H] override rows
    embeds_mask: Optional[jnp.ndarray] = None,  # [T] bool — True: use
                              # `embeds` instead of the token embedding
                              # (multimodal image tokens; vision.py)
    adapter_id: Optional[jnp.ndarray] = None,   # scalar i32 — resident
                              # LoRA bank row (0 = identity base model);
                              # ignored when params carry no bank
) -> tuple[Cache, jnp.ndarray]:
    """Run T new tokens through the model, writing their KV into the
    slot's contiguous context region at [q_start, q_start+T).

    Returns (ctx_kv, logits[vocab]) where logits are for the LAST VALID
    token (position seq_len-1). Supports prefix-cache continuation: with
    q_start>0 the first q_start tokens' KV is already in the region
    (loaded from the pool by load_ctx_pages) and is attended to but not
    recomputed.

    CALLER CONTRACT (checked host-side by the engine scheduler, not here —
    dynamic_update_slice silently clamps under jit): q_start+T must fit the
    region.
    """
    c = config
    T = tokens.shape[0]
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = q_start + jnp.arange(T, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, inv_freq)

    cdt = _ctx_compute_dtype(c, ctx_kv)
    h = _embed_rows(params, tokens, cdt)
    if embeds is not None:
        h = jnp.where(embeds_mask[:, None], embeds.astype(h.dtype), h)

    # Layers are UNROLLED (python loop, static layer index). The region is
    # READ-ONLY during the layer stack: each layer's chunk KV is carried in
    # values and attention takes it directly (ctx_prefill_attention); ALL
    # writes land in one tail pass after the last read, so the donated
    # update chain aliases in place (interleaved write/read of the GB-
    # scale buffer would force XLA to materialize copies of it).
    ag = _gather_adapters(params.get("adapters"), adapter_id)
    new_ks: list[jnp.ndarray] = []
    new_vs: list[jnp.ndarray] = []
    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])

        def write_kv(k, v):
            new_ks.append(k)
            new_vs.append(v)
            return (k, v)

        def attend(q, kv, l=l):
            k_new, v_new = kv
            k_ctx = _ctx_slot_slab(ctx_kv, "k", l, slot, cdt)
            v_ctx = _ctx_slot_slab(ctx_kv, "v", l, slot, cdt)
            return ctx_prefill_attention(
                q, k_ctx, v_ctx, k_new, v_new, q_start, seq_len
            )

        # padding tokens must not claim MoE expert capacity
        h, _ = _layer_body(c, lp, h, cos, sin, write_kv, attend,
                           ffn_valid=positions < seq_len,
                           ad=_adapter_layer(ag, l, per_row=False))

    # tail: one contiguous span write per buffer (all reads are done)
    upd_k = jnp.stack(new_ks).transpose(0, 2, 1, 3)  # [L, kvh, T, hd]
    upd_v = jnp.stack(new_vs).transpose(0, 2, 1, 3)
    if ctx_is_quantized(ctx_kv):
        g = ctx_group_size(ctx_kv)
        ck, ksc = _quant_store_span(
            ctx_kv["k"], ctx_kv["k_scale"], slot, q_start, upd_k, g,
            valid_t=seq_len - q_start)
        cv, vsc = _quant_store_span(
            ctx_kv["v"], ctx_kv["v_scale"], slot, q_start, upd_v, g,
            valid_t=seq_len - q_start)
        out_ctx = {"k": ck, "v": cv, "k_scale": ksc, "v_scale": vsc}
    else:
        ck, cv = ctx_kv["k"], ctx_kv["v"]
        ck = jax.lax.dynamic_update_slice(
            ck, upd_k[:, :, None].astype(ck.dtype), (0, 0, slot, q_start, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cv, upd_v[:, :, None].astype(cv.dtype), (0, 0, slot, q_start, 0)
        )
        out_ctx = {"k": ck, "v": cv}

    last = seq_len - q_start - 1  # index of last valid token within T
    logits = _logits(c, params, h[last])
    return out_ctx, logits


prefill = jax.jit(prefill_impl, static_argnums=(0,), donate_argnums=(2,))


def _batch_forward(
    config: ModelConfig,
    params: Params,
    ctx_kv: Cache,
    tokens: jnp.ndarray,    # [K, T] int32, bucket-padded per request
    slots: jnp.ndarray,     # [K] i32
    q_starts: jnp.ndarray,  # [K] i32
    seq_lens: jnp.ndarray,  # [K] i32
    ctx_span: int,
    adapter_ids: Optional[jnp.ndarray] = None,  # [K] i32 bank rows
    depths: Optional[jnp.ndarray] = None,       # [K, T] i32 tree depths
                            # (RoPE position = q_start + depth; -1 pad)
    chunk_masks: Optional[jnp.ndarray] = None,  # [K, T, T] bool tree-
                            # causal in-chunk visibility (spec tree)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Read-only vmapped layer stack shared by batch_prefill and
    batch_score: K chunks through the model in one program. Returns
    (ks, vs, h) — stacked per-layer KV [K, L, T, kvh, hd] and final
    hidden states [K, T, H]; region writes happen OUTSIDE the vmap (a
    shared-buffer update inside vmap would be a scatter with
    lane-conflict semantics).

    Tree mode (``depths``/``chunk_masks`` given, always together): the
    chunk is a packed token TREE, not a linear run — node t's RoPE
    position is q_start + depths[t] (siblings at one depth share a
    position) and in-chunk attention follows the caller's ancestor mask
    instead of index order. Tree chunks never carry adapters (spec is
    confined to the base model)."""
    c = config
    K, T = tokens.shape
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )

    cdt = _ctx_compute_dtype(c, ctx_kv)
    # gather bank rows OUTSIDE the vmap ([K, L, d, r] per site), then vmap
    # over the gathered rows so each lane sees its own [L, d, r] factors
    ag = _gather_adapters(params.get("adapters"), adapter_ids)
    if depths is not None:
        assert ag is None, "tree chunks are base-model only"

    def compute(toks, slot, q_start, seq_len, ag_row, depth_row=None,
                cm_row=None):
        if depth_row is None:
            positions = q_start + jnp.arange(T, dtype=jnp.int32)
            node_valid = positions < seq_len
        else:
            # padding nodes (depth -1) pin to position q_start and are
            # masked out of attention (cm_row) and MoE routing below
            positions = q_start + jnp.maximum(depth_row, 0)
            node_valid = (positions < seq_len) & (depth_row >= 0)
        cos, sin = rope_cos_sin(positions, inv_freq)
        h = _embed_rows(params, toks, cdt)
        new_ks: list[jnp.ndarray] = []
        new_vs: list[jnp.ndarray] = []
        for l in range(c.num_layers):
            lp = jax.tree.map(lambda x: x[l], params["layers"])

            def write_kv(k, v):
                new_ks.append(k)
                new_vs.append(v)
                return (k, v)

            def attend(q, kv, l=l):
                k_new, v_new = kv
                if ctx_span > 0:
                    k_ctx = _ctx_slot_slab(
                        ctx_kv, "k", l, slot, cdt, span=ctx_span)
                    v_ctx = _ctx_slot_slab(
                        ctx_kv, "v", l, slot, cdt, span=ctx_span)
                else:
                    k_ctx = v_ctx = None
                return flash_prefill_attention(
                    q, k_ctx, v_ctx, k_new, v_new, q_start, seq_len,
                    chunk_mask=cm_row,
                )

            h, _ = _layer_body(c, lp, h, cos, sin, write_kv, attend,
                               ffn_valid=node_valid,
                               ad=_adapter_layer(ag_row, l, per_row=False))
        return (
            jnp.stack(new_ks).astype(cdt),
            jnp.stack(new_vs).astype(cdt),
            h,
        )

    if depths is not None:
        return jax.vmap(
            lambda t, s, q, sl, d, cm: compute(t, s, q, sl, None, d, cm)
        )(tokens, slots, q_starts, seq_lens, depths, chunk_masks)
    if ag is None:
        return jax.vmap(
            lambda t, s, q, sl: compute(t, s, q, sl, None)
        )(tokens, slots, q_starts, seq_lens)
    return jax.vmap(compute)(tokens, slots, q_starts, seq_lens, ag)


def _write_chunks(
    ctx_kv: Cache,
    ks: jnp.ndarray,        # [K, L, T, kvh, hd]
    vs: jnp.ndarray,
    slots: jnp.ndarray,
    q_starts: jnp.ndarray,
    seq_lens: Optional[jnp.ndarray] = None,  # [K] i32 — bounds the rows
                            # feeding int8 scales (padding excluded)
) -> Cache:
    """Tail pass: K span writes per buffer, K static (unrolled), after
    every read — the donated update chain aliases in place. Quantized
    regions route each span through the group-requantize window
    (_quant_store_span) instead of a raw DUS."""
    K = ks.shape[0]
    if ctx_is_quantized(ctx_kv):
        g = ctx_group_size(ctx_kv)
        ck, ksc = ctx_kv["k"], ctx_kv["k_scale"]
        cv, vsc = ctx_kv["v"], ctx_kv["v_scale"]
        for i in range(K):
            vt = None if seq_lens is None else seq_lens[i] - q_starts[i]
            ck, ksc = _quant_store_span(
                ck, ksc, slots[i], q_starts[i],
                ks[i].transpose(0, 2, 1, 3), g, valid_t=vt)
            cv, vsc = _quant_store_span(
                cv, vsc, slots[i], q_starts[i],
                vs[i].transpose(0, 2, 1, 3), g, valid_t=vt)
        return {"k": ck, "v": cv, "k_scale": ksc, "v_scale": vsc}
    ck, cv = ctx_kv["k"], ctx_kv["v"]
    for i in range(K):
        upd_k = ks[i].transpose(0, 2, 1, 3)[:, :, None]  # [L,kvh,1,T,hd]
        upd_v = vs[i].transpose(0, 2, 1, 3)[:, :, None]
        at = (0, 0, slots[i], q_starts[i], 0)
        ck = jax.lax.dynamic_update_slice(ck, upd_k, at)
        cv = jax.lax.dynamic_update_slice(cv, upd_v, at)
    return {"k": ck, "v": cv}


def batch_prefill_impl(
    config: ModelConfig,
    params: Params,
    ctx_kv: Cache,
    tokens: jnp.ndarray,    # [K, T] int32, bucket-padded per request
    slots: jnp.ndarray,     # [K] i32 — destination slot lanes (distinct)
    q_starts: jnp.ndarray,  # [K] i32 — tokens already in each region
    seq_lens: jnp.ndarray,  # [K] i32 — total valid context per request
    ctx_span: int = 0,      # STATIC: prior-context window to attend
                            # (pow2 >= max(q_starts); 0 = fresh prefill,
                            # no context read compiled at all)
    adapter_ids: Optional[jnp.ndarray] = None,  # [K] i32 — resident LoRA
                            # bank rows (0 = identity; padding lanes 0)
) -> tuple[Cache, jnp.ndarray]:
    """Batched multi-request prefill: K chunks through the model in ONE
    program — the TTFT lever for concurrent arrivals (reference analogue:
    vLLM's max_num_batched_tokens prefill batching; the per-request
    `prefill` above keeps the multimodal-embeds and odd-shape paths).

    Matmuls see [K*T, H] rows (the MXU-utilization win over K separate
    [T, H] dispatches); attention is the blocked flash scan
    (ops/attention.py flash_prefill_attention), so no [T, S+T] score
    tensor materializes. Per-request KV lands in each slot's contiguous
    region at [q_start_k, q_start_k+T); all writes happen in one tail
    pass after the last read (the round-4 no-interleave discipline —
    models/llama.py module doc). Returns (ctx_kv, logits[K, vocab]) with
    each row the last valid token's logits.

    Padding lanes (group smaller than the compiled K): point slot at the
    scratch lane (batch index B) with seq_len=0 — ffn_valid masks their
    tokens out of MoE routing and their region writes hit scratch.
    """
    ks, vs, h = _batch_forward(
        config, params, ctx_kv, tokens, slots, q_starts, seq_lens, ctx_span,
        adapter_ids,
    )
    ctx_kv = _write_chunks(ctx_kv, ks, vs, slots, q_starts, seq_lens)
    last = jnp.maximum(seq_lens - q_starts - 1, 0)
    h_last = jnp.take_along_axis(h, last[:, None, None], axis=1)[:, 0]
    logits = _logits(config, params, h_last)
    return ctx_kv, logits


batch_prefill = jax.jit(
    batch_prefill_impl, static_argnums=(0, 7), donate_argnums=(2,)
)


def batch_score_impl(
    config: ModelConfig,
    params: Params,
    ctx_kv: Cache,
    tokens: jnp.ndarray,    # [K, T] int32 — T = pending + proposed tokens
    slots: jnp.ndarray,     # [K] i32 (dummies -> scratch lane)
    q_starts: jnp.ndarray,  # [K] i32 — tokens already in each region
    seq_lens: jnp.ndarray,  # [K] i32 — q_start + T for live rows, 0 dummy
    ctx_span: int,          # STATIC prior-context window (always > 0 here)
) -> tuple[Cache, jnp.ndarray]:
    """Speculative-verification scorer: identical to batch_prefill — same
    chunked q_start>0 forward, same optimistic KV tail write — but
    returns logits for EVERY chunk position [K, T, V], not just the last.
    Row t of a chunk scores the target's distribution for the token
    FOLLOWING tokens[:, t] — the verifier (spec/verifier.py) compares
    those rows against the proposed tokens. The KV rows written for
    later-rejected tokens are dead weight past the committed length:
    attention masks by seq_len and the next write over the lane
    overwrites them, so rollback is pointer truncation, not a device op.
    """
    ks, vs, h = _batch_forward(
        config, params, ctx_kv, tokens, slots, q_starts, seq_lens, ctx_span
    )
    ctx_kv = _write_chunks(ctx_kv, ks, vs, slots, q_starts, seq_lens)
    return ctx_kv, _logits(config, params, h)


def batch_score_tree_impl(
    config: ModelConfig,
    params: Params,
    ctx_kv: Cache,
    tokens: jnp.ndarray,       # [B, T] i32 packed tree (node 0 = pending)
    slots: jnp.ndarray,        # [B] i32 (dummies -> scratch lane)
    q_starts: jnp.ndarray,     # [B] i32 — tokens already in each region
    seq_lens: jnp.ndarray,     # [B] i32 — q_start + T live, 0 dummy
    depths: jnp.ndarray,       # [B, T] i32 node depths (-1 = padding)
    chunk_masks: jnp.ndarray,  # [B, T, T] bool ancestor-or-self
    ctx_span: int,             # STATIC prior-context window (> 0)
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Tree-verification scorer: one q_start>0 batched forward over a
    packed token TREE per slot — RoPE by node depth, in-chunk attention
    by ancestor mask — returning logits for EVERY node [B, T, V]. Row t
    scores the target's distribution for the token FOLLOWING node t's
    root-to-node path.

    Unlike batch_score_impl this does NOT write ctx: a tree's rows are
    position-aliased (siblings share a RoPE position), so the optimistic
    linear tail write would land sibling KV in rows the accepted path
    must own. The caller runs acceptance on device, gathers exactly the
    accepted path's rows out of the returned (ks, vs), and commits them
    via commit_tree_path — rollback stays pointer-shaped."""
    ks, vs, h = _batch_forward(
        config, params, ctx_kv, tokens, slots, q_starts, seq_lens,
        ctx_span, None, depths, chunk_masks,
    )
    return ks, vs, _logits(config, params, h)


def commit_tree_path(
    ctx_kv: Cache,
    ks: jnp.ndarray,          # [B, L, T, kvh, hd] from batch_score_tree_impl
    vs: jnp.ndarray,
    path: jnp.ndarray,        # [B, T] i32 — accepted node index per output
                              # position (path[:, 0] == 0, the pending
                              # token; entries past n_out are ignored)
    slots: jnp.ndarray,       # [B] i32
    q_starts: jnp.ndarray,    # [B] i32
    commit_lens: jnp.ndarray,  # [B] i32 — q_start + n_out (live), 0 dummy
) -> Cache:
    """Commit ONLY the accepted root-to-leaf path's KV rows: reorder the
    fresh-chunk KV by the path's node indices (sibling rows are simply
    never gathered) and span-write at [q_start, commit_len). Rows past
    n_out gather clamped garbage but stay dead — attention masks by
    seq_len, the quantized store bounds its scale window at
    commit_len - q_start, and the next round's write starts exactly at
    commit_len. This is what keeps tree rollback pointer truncation."""
    idx = jnp.clip(path, 0, ks.shape[2] - 1)[:, None, :, None, None]
    ks_path = jnp.take_along_axis(ks, idx, axis=2)
    vs_path = jnp.take_along_axis(vs, idx, axis=2)
    return _write_chunks(ctx_kv, ks_path, vs_path, slots, q_starts,
                         commit_lens)


def batch_draft_impl(
    config: ModelConfig,
    params: Params,
    ctx_kv: Cache,
    tokens: jnp.ndarray,    # [B, T] i32 — per-slot history catch-up chunk
    slots: jnp.ndarray,     # [B] i32 (dummies -> scratch lane)
    q_starts: jnp.ndarray,  # [B] i32 — draft KV already in each region
    seq_lens: jnp.ndarray,  # [B] i32 — q_start + chunk for live rows, 0 dummy
    ctx_span: int,          # STATIC prior-context window
    k: int,                 # STATIC draft depth
    m: int = 1,             # STATIC branches per level (comb tree; 1 =
                            # the original linear chain, bit-identical)
) -> tuple[Cache, jnp.ndarray]:
    """Draft ``k`` greedy continuation tokens for EVERY speculating slot
    in ONE program: the catch-up chunk (the tokens accepted since the
    slot's last draft) runs as a batch_prefill-shaped forward, then a
    ``lax.fori_loop`` runs k-1 single-token batched steps with argmax
    feedback entirely on device — the cross-slot fusion of what
    DraftModelProposer.propose dispatched as 1 + (k-1) programs PER SLOT.
    Returns (ctx_kv, drafted [B, k] i32); nothing touches the host.

    KV bookkeeping matches the per-slot path: the catch-up chunk lands at
    [q_start, seq_len), draft step s writes at seq_len + s, and the last
    drafted token's KV is never computed (it is never fed back). Rollback
    stays pointer truncation. Dummy rows (seq_len 0) write the scratch
    lane at position 0 and are masked out of attention and MoE routing.

    ``m > 1`` (tree drafts): each fori step records the top-m candidates
    instead of just the argmax, but ONLY the top-1 "spine" feeds back
    (and owns the KV written at seq_len + s) — a comb-shaped tree, depth
    k with m-way fan at every level, from the same program at the same
    dispatch cost. Returns drafted [B, k*m] in level-major node order
    (level s occupies columns [s*m, s*m + m), column s*m = the spine);
    spec/proposer.py comb_parents gives the matching parent pointers.
    """
    B, T = tokens.shape
    ks, vs, h = _batch_forward(
        config, params, ctx_kv, tokens, slots, q_starts, seq_lens, ctx_span
    )
    ctx_kv = _write_chunks(ctx_kv, ks, vs, slots, q_starts, seq_lens)
    last = jnp.maximum(seq_lens - q_starts - 1, 0)
    h_last = jnp.take_along_axis(h, last[:, None, None], axis=1)[:, 0]
    logits = _logits(config, params, h_last)
    live = seq_lens > 0

    if m > 1:
        drafted = jnp.zeros((B, k * m), jnp.int32)
        _, top0 = jax.lax.top_k(logits, m)  # idx 0 == argmax (ties: low)
        drafted = jax.lax.dynamic_update_slice_in_dim(
            drafted, top0.astype(jnp.int32), 0, axis=1
        )
        if k == 1:
            return ctx_kv, drafted

        def body_m(s, carry):
            ctx_kv, drafted = carry
            # feed level s's spine (column s*m) back, as the m=1 path
            # feeds its single candidate
            toks_s = jax.lax.dynamic_slice_in_dim(drafted, s * m, 1, axis=1)
            pos = jnp.where(live, seq_lens + s, 0)
            sl = jnp.where(live, pos + 1, 0)
            ks, vs, h = _batch_forward(
                config, params, ctx_kv, toks_s, slots, pos, sl, ctx_span
            )
            ctx_kv = _write_chunks(ctx_kv, ks, vs, slots, pos, sl)
            logits = _logits(config, params, h[:, 0])
            _, nxt = jax.lax.top_k(logits, m)
            drafted = jax.lax.dynamic_update_slice_in_dim(
                drafted, nxt.astype(jnp.int32), (s + 1) * m, axis=1
            )
            return ctx_kv, drafted

        return jax.lax.fori_loop(0, k - 1, body_m, (ctx_kv, drafted))

    drafted = jnp.zeros((B, k), jnp.int32)
    drafted = drafted.at[:, 0].set(
        jnp.argmax(logits, axis=-1).astype(jnp.int32)
    )
    if k == 1:
        return ctx_kv, drafted

    def body(s, carry):
        ctx_kv, drafted = carry
        toks_s = jax.lax.dynamic_slice_in_dim(drafted, s, 1, axis=1)
        # dummy rows stay pinned at (pos 0, seq_len 0): their garbage
        # writes target scratch row 0 and attention masks them entirely
        pos = jnp.where(live, seq_lens + s, 0)
        sl = jnp.where(live, pos + 1, 0)
        ks, vs, h = _batch_forward(
            config, params, ctx_kv, toks_s, slots, pos, sl, ctx_span
        )
        ctx_kv = _write_chunks(ctx_kv, ks, vs, slots, pos, sl)
        logits = _logits(config, params, h[:, 0])
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        drafted = jax.lax.dynamic_update_slice_in_dim(
            drafted, nxt[:, None], s + 1, axis=1
        )
        return ctx_kv, drafted

    ctx_kv, drafted = jax.lax.fori_loop(0, k - 1, body, (ctx_kv, drafted))
    return ctx_kv, drafted


batch_draft = jax.jit(
    batch_draft_impl, static_argnums=(0, 7, 8, 9), donate_argnums=(2,)
)


# ---------------------------------------------------------------------------
# Decode

def decode_step_impl(
    config: ModelConfig,
    params: Params,
    ctx_kv: Cache,             # [L, kvh, B+1, S, hd] — READ-ONLY here
    ring: Cache,               # [L, kvh, B, R, hd] write ring
    tokens: jnp.ndarray,       # [B] int32 — last sampled token per slot
    ctx_lens: jnp.ndarray,     # [B] int32 — context length INCLUDING this token
    ring_base: jnp.ndarray,    # [B] int32 — position held by ring slot 0
    ring_pos: jnp.ndarray,     # scalar int32 — ring slot receiving this token
    live: Optional[jnp.ndarray] = None,  # [B] bool — garbage lanes masked
                               # out of MoE expert routing
    adapter_ids: Optional[jnp.ndarray] = None,  # [B] i32 — per-slot
                               # resident LoRA bank rows (0 = identity);
                               # mixed ids batch into ONE program via a
                               # row gather + rank-r einsum per site
) -> tuple[Cache, jnp.ndarray]:
    """One decode step for all slots. Returns (ring, logits [B, vocab]).

    The new token's KV lands in ring slot `ring_pos` (its position is
    ``ctx-1 == ring_base + ring_pos`` for live slots); attention covers
    the ctx region for positions < ring_base plus ring entries
    [ring_base, ctx). The ctx region is immutable between `flush_ctx`
    calls — the write/read interleave on the GB-scale buffer is what
    forces XLA copies (see init_ring).
    """
    c = config
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = jnp.maximum(ctx_lens - 1, 0)
    cos, sin = rope_cos_sin(positions, inv_freq)  # [B, hd]

    h = _embed_rows(params, tokens, _ctx_compute_dtype(c, ctx_kv))  # [B, H]
    quant = ctx_is_quantized(ctx_kv)
    # [B, L, d, r] per site — ids are round-constant, so XLA hoists the
    # gather out of the fori_loop wrapping this step in the fused round
    ag = _gather_adapters(params.get("adapters"), adapter_ids)

    # unrolled layers — see prefill_impl for why not lax.scan
    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])

        def write_kv(k, v, l=l):
            # one DUS per layer: [B, kvh, hd] -> ring[l, :, :, ring_pos, :]
            def put(r, x):
                upd = x.transpose(1, 0, 2)[None, :, :, None, :]
                return jax.lax.dynamic_update_slice(
                    r, upd.astype(r.dtype), (l, 0, 0, ring_pos, 0)
                )

            return {"k": put(ring["k"], k), "v": put(ring["v"], v)}

        def attend(q, new_ring, l=l):
            return ctx_decode_attention(
                q, ctx_kv["k"], ctx_kv["v"],
                new_ring["k"], new_ring["v"], jnp.int32(l),
                ctx_lens, ring_base,
                ctx_k_scale=ctx_kv["k_scale"] if quant else None,
                ctx_v_scale=ctx_kv["v_scale"] if quant else None,
            )

        h, ring = _layer_body(c, lp, h, cos, sin, write_kv, attend,
                              ffn_valid=live,
                              ad=_adapter_layer(ag, l, per_row=True))

    logits = _logits(c, params, h)
    return ring, logits


decode_step = jax.jit(decode_step_impl, static_argnums=(0,), donate_argnums=(3,))


def flush_ctx_impl(
    ctx_kv: Cache,
    ring: Cache,
    dest: jnp.ndarray,       # [B] int32 — live: own lane; freed: scratch B
    ring_base: jnp.ndarray,  # [B] int32
    valid_len: jnp.ndarray,  # [B] int32 — #real tokens in the ring per slot
) -> Cache:
    """Scatter a full ring into the ctx region (once per round, AFTER all
    of the round's reads — the single write aliases in place under
    donation). Ring entry (b, r) holds position ring_base[b]+r and goes to
    lane dest[b]; entries beyond valid_len[b], beyond the region length,
    or belonging to freed slots are redirected to the scratch lane.

    Quantized regions (ctx_is_quantized) instead requantize the minimal
    group-aligned WINDOW around each lane's ring span: gather old int8
    window + scales, dequantize, overlay the valid ring entries, fresh
    absmax scales for the groups the span overlaps (absmax over the
    lane's own prefix + the new entries — never stale suffix bytes), and
    scatter int8 + scales back. Still one fused pass inside the round
    program — zero extra dispatches."""
    L, kvh, B, R, hd = ring["k"].shape
    S = ctx_kv["k"].shape[3]
    scratch = ctx_kv["k"].shape[2] - 1
    r_idx = jnp.arange(R, dtype=jnp.int32)[None, :]   # [1, R]
    pos = ring_base[:, None] + r_idx                  # [B, R]
    valid = (r_idx < valid_len[:, None]) & (pos < S)
    if ctx_is_quantized(ctx_kv):
        return _flush_ctx_quant(ctx_kv, ring, dest, ring_base, valid_len,
                                valid)
    lane = jnp.where(valid, dest[:, None], scratch)   # [B, R]
    pos = jnp.where(valid, pos, 0)
    lflat = lane.reshape(-1)                          # [B*R]
    pflat = pos.reshape(-1)

    out = {}
    for name in ("k", "v"):
        buf = ctx_kv[name]
        upd = ring[name].transpose(0, 2, 3, 1, 4).reshape(L, B * R, kvh, hd)
        for l in range(L):
            # advanced dims ([B*R]) lead: target [B*R, kvh, hd]
            buf = buf.at[l, :, lflat, pflat].set(upd[l])
        out[name] = buf
    return out


def _flush_ctx_quant(
    ctx_kv: Cache,
    ring: Cache,
    dest: jnp.ndarray,       # [B] i32 (freed slots -> scratch lane)
    ring_base: jnp.ndarray,  # [B] i32
    valid_len: jnp.ndarray,  # [B] i32
    valid: jnp.ndarray,      # [B, R] bool — precomputed entry validity
) -> Cache:
    """Ring flush into an int8 ctx region (see flush_ctx_impl doc)."""
    L, kvh, B, R, hd = ring["k"].shape
    lanes, S = ctx_kv["k"].shape[2], ctx_kv["k"].shape[3]
    g = ctx_group_size(ctx_kv)
    nG = S // g
    # window: enough group slots to hold a ring span at any alignment
    nW = min(-(-R // g) + 1, nG)
    W = nW * g
    base = jnp.clip(ring_base.astype(jnp.int32), 0, S)
    g0 = jnp.clip(base // g, 0, nG - nW)                       # [B]
    lane = jnp.clip(dest.astype(jnp.int32), 0, lanes - 1)      # [B]
    off = base - g0 * g                                        # [B]
    # where each ring entry lands inside its lane's window; invalid
    # entries (past valid_len / region end / vacated lanes) index W and
    # are DROPPED from the overlay rather than redirected
    w_of_r = jnp.where(
        valid, off[:, None] + jnp.arange(R, dtype=jnp.int32)[None, :], W
    )                                                          # [B, R]
    # absmax inputs: the lane's own prefix + the new valid entries; the
    # suffix beyond the span (stale bytes) never feeds a scale
    w_idx = jnp.arange(W, dtype=jnp.int32)[None, :]            # [1, W]
    span_end = off + jnp.clip(valid_len.astype(jnp.int32), 0, R)
    valid_w = w_idx < span_end[:, None]                        # [B, W]
    j = jnp.arange(nW, dtype=jnp.int32)[None, :]
    written = ((j + 1) * g > off[:, None]) & (j * g < span_end[:, None])
    written &= (valid_len > 0)[:, None]                        # [B, nW]
    # per-lane flat gather/scatter indices for the int8 window
    widx = (lane * S + g0 * g)[:, None] + jnp.arange(W)[None, :]
    widx_f = widx.reshape(-1)                                  # [B*W]
    gidx = g0[:, None] + j                                     # [B, nW]
    b_idx = jnp.arange(B, dtype=jnp.int32)[:, None]            # [B, 1]

    out = {}
    for name in ("k", "v"):
        flat = ctx_kv[name].reshape(L, kvh, lanes * S, hd)
        win = flat[:, :, widx_f].reshape(L, kvh, B, W, hd)
        sw = ctx_kv[name + "_scale"][:, lane[:, None], gidx]   # [L, B, nW]
        wf = dequantize_groups(win, sw, g)
        overlay = ring[name].astype(jnp.float32)               # [L,kvh,B,R,hd]
        wf = wf.at[:, :, b_idx, w_of_r].set(overlay, mode="drop")
        q, s_new = requantize_groups(wf, sw, valid_w, written, g)
        # vacated lanes all alias the scratch lane: overlapping windows
        # write garbage over garbage (scratch is garbage by contract)
        flat = flat.at[:, :, widx_f].set(q.reshape(L, kvh, B * W, hd))
        out[name] = flat.reshape(L, kvh, lanes, S, hd)
        out[name + "_scale"] = ctx_kv[name + "_scale"].at[
            :, lane[:, None], gidx
        ].set(s_new)
    return out


flush_ctx = jax.jit(flush_ctx_impl, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# prefix-cache <-> context copies (admission / block seal)

def load_ctx_pages_impl(
    ctx_kv: Cache,
    cache: Cache,
    slot: jnp.ndarray,      # scalar int32 — destination lane
    page_ids: jnp.ndarray,  # [n] int32 — pow2-padded; padding = scratch 0
) -> Cache:
    """Copy a matched prefix run of pool pages into the slot's context
    region at [0, n*ps). The admission-side half of prefix reuse: padding
    pages write scratch-page garbage BEYOND the valid prefix (the engine
    passes q_start = real_blocks*ps, so garbage is never attended).

    The page list is pow2-padded by the caller, so n*ps can EXCEED the
    region length (e.g. 46 matched pages pad to 64 while the region holds
    52 — a dynamic_update_slice whose update outgrows the operand is a
    trace-time TypeError that kills the whole engine round). The load is
    clamped to the region statically: overflow pages are dropped, which
    is always safe because real matched runs fit the region by admission
    contract — only padding can overflow."""
    n = page_ids.shape[0]
    ps = cache["k"].shape[3]
    S = ctx_kv["k"].shape[3]
    usable = min(n, S // ps)
    if usable <= 0:
        return dict(ctx_kv)
    page_ids = page_ids[:usable]
    pool_q = cache_is_quantized(cache)
    ctx_q = ctx_is_quantized(ctx_kv)
    if ctx_q:
        # int8 ctx: the scale grids coincide (group == page_size by
        # init_ctx contract), so a quantized pool admits as a RAW int8
        # page copy + scale copy — no dequantize pass at all; the
        # decode kernel dequantizes per chunk in VMEM. A dense pool
        # (cross-mode peer) quantizes per page on the way in.
        g = ctx_group_size(ctx_kv)
        assert g == ps, (
            f"int8 ctx group ({g}) must equal pool page_size ({ps}) — "
            "init_ctx(group=page_size) is the engine contract"
        )
        out = dict(ctx_kv)
        for name in ("k", "v"):
            pages = cache[name][:, :, page_ids]  # [L, kvh, u, ps, hd]
            L, kvh, _, _, hd = pages.shape
            if pool_q:
                q = pages
                s = cache[name + "_scale"][:, page_ids]   # [L, u]
            else:
                pf = pages.astype(jnp.float32)
                s = jnp.maximum(
                    jnp.max(jnp.abs(pf), axis=(1, 3, 4)) / 127.0,
                    SCALE_EPS)
                q = jnp.clip(
                    jnp.round(pf / s[:, None, :, None, None]), -127, 127
                ).astype(jnp.int8)
            span = q.reshape(L, kvh, usable * ps, hd)
            out[name] = jax.lax.dynamic_update_slice(
                ctx_kv[name], span[:, :, None], (0, 0, slot, 0, 0)
            )
            out[name + "_scale"] = jax.lax.dynamic_update_slice(
                ctx_kv[name + "_scale"], s[:, None], (0, slot, 0)
            )
        return out
    out = {}
    for name in ("k", "v"):
        pages = cache[name][:, :, page_ids]      # [L, kvh, usable, ps, hd]
        if pool_q:
            # fused dequant: int8 pages * per-(layer, page) scale, in the
            # same admission-copy program — never a separate dispatch
            s = cache[name + "_scale"][:, page_ids]       # [L, usable]
            pages = (pages.astype(jnp.float32)
                     * s[:, None, :, None, None])
        L, kvh, _, _, hd = pages.shape
        span = pages.reshape(L, kvh, usable * ps, hd)
        out[name] = jax.lax.dynamic_update_slice(
            ctx_kv[name], span[:, :, None].astype(ctx_kv[name].dtype),
            (0, 0, slot, 0, 0),
        )
    return out


load_ctx_pages = jax.jit(load_ctx_pages_impl, donate_argnums=(0,))


def write_ctx_span_impl(
    ctx_kv: Cache,
    slot: jnp.ndarray,  # scalar int32
    kv: Cache,          # {"k","v"}: [L, kvh, T, hd] (e.g. sp_prefill output)
) -> Cache:
    """Write a whole computed KV span into a slot's region at [0, T) —
    how sp_prefill's ring-computed prompt KV enters the serving context
    (GSPMD gathers the sp-sharded span into the replicated region).
    Int8 ctx quantizes on store (fresh absmax scales for the covered
    groups — same grid as the in-round writes)."""
    if ctx_is_quantized(ctx_kv):
        out = dict(ctx_kv)
        g = ctx_group_size(ctx_kv)
        T = kv["k"].shape[2]
        zero = jnp.int32(0)
        for name in ("k", "v"):
            out[name], out[name + "_scale"] = _quant_store_span(
                ctx_kv[name], ctx_kv[name + "_scale"], slot, zero,
                kv[name], g, valid_t=jnp.int32(T),
            )
        return out
    out = {}
    for name in ("k", "v"):
        upd = kv[name][:, :, None]  # [L, kvh, 1, T, hd]
        out[name] = jax.lax.dynamic_update_slice(
            ctx_kv[name], upd.astype(ctx_kv[name].dtype),
            (0, 0, slot, 0, 0),
        )
    return out


write_ctx_span = jax.jit(write_ctx_span_impl, donate_argnums=(0,))


def seal_blocks_impl(
    cache: Cache,
    ctx_kv: Cache,
    slots: jnp.ndarray,   # [n] int32 — source lanes (pow2-padded)
    starts: jnp.ndarray,  # [n] int32 — block start positions
    pages: jnp.ndarray,   # [n] int32 — destination pool pages
                          # (padding entries -> scratch page 0)
    page_size: int,
) -> Cache:
    """Copy sealed blocks ctx->pool (the storage half of commit). Each
    entry copies ctx_kv[:, :, slots[i], starts[i]:+ps] into pool page
    pages[i]. Padding rows target scratch page 0 (garbage by contract).

    Quantized pools (cache_is_quantized) quantize in the SAME fused
    gather: per-(layer, page) absmax scales over the block's
    [kvh, ps, hd] elements, int8 payload + scale scattered together.
    When the ctx region is int8 too (same group == page_size grid) the
    seal degenerates to a RAW int8 copy: blocks and their scales move
    verbatim, no requantize pass at the boundary at all."""
    ps = page_size
    pool_q = cache_is_quantized(cache)
    ctx_q = ctx_is_quantized(ctx_kv)
    if ctx_q:
        g = ctx_group_size(ctx_kv)
        assert g == ps, (
            f"int8 ctx group ({g}) must equal pool page_size ({ps})"
        )
    out = {}
    for name in ("k", "v"):
        # ONE gather over the (lane, position)-flattened axis. The
        # previous vmap(dynamic_index + dynamic_slice) materialized the
        # full [L, kvh, S, hd] LANE per entry before slicing — at long
        # context (S 3328, n 512) that is ~28 GB of temps and the seal
        # program OOMs at compile
        src = ctx_kv[name]
        L, kvh, lanes, S, hd = src.shape
        flat = src.reshape(L, kvh, lanes * S, hd)
        idx = (slots * S + starts)[:, None] + jnp.arange(ps)[None, :]
        blocks = flat[:, :, idx]                 # [L, kvh, n, ps, hd]
        if ctx_q:
            # blocks are already int8; their ctx scales are page-aligned
            # (starts are block starts, ps == group), so the pool entry
            # is the ctx entry moved verbatim
            sc = ctx_kv[name + "_scale"][
                :, slots, starts // ps
            ]                                    # [L, n]
            if pool_q:
                out[name] = cache[name].at[:, :, pages].set(blocks)
                out[name + "_scale"] = (
                    cache[name + "_scale"].at[:, pages].set(sc)
                )
            else:
                # cross-mode pool (dense): dequantize the blocks in the
                # same fused gather before the dense scatter
                dense = (blocks.astype(jnp.float32)
                         * sc[:, None, :, None, None])
                out[name] = cache[name].at[:, :, pages].set(
                    dense.astype(cache[name].dtype))
            continue
        if pool_q:
            bf = blocks.astype(jnp.float32)
            s = jnp.max(jnp.abs(bf), axis=(1, 3, 4)) / 127.0   # [L, n]
            s = jnp.maximum(s, 1e-8)
            q = jnp.clip(
                jnp.round(bf / s[:, None, :, None, None]), -127, 127
            ).astype(jnp.int8)
            out[name] = cache[name].at[:, :, pages].set(q)
            out[name + "_scale"] = (
                cache[name + "_scale"].at[:, pages].set(s)
            )
        else:
            out[name] = cache[name].at[:, :, pages].set(blocks)
    return out


seal_blocks = jax.jit(
    seal_blocks_impl, static_argnames=("page_size",), donate_argnums=(0,)
)


# ---------------------------------------------------------------------------
# Sequence-parallel (ring) prefill — long-context path (SURVEY §2.5 SP
# row / §7.11: the reference has no sequence parallelism; this is the
# TPU-native long-context answer). The prompt is sharded over the `sp`
# mesh axis; every layer's attention runs as ring attention (KV blocks
# rotate over ICI via ppermute) so per-device memory is O(T/sp).

def sp_prefill(
    config: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,    # [T] int32, sp-sharded, T % sp == 0
    seq_len: jnp.ndarray,   # scalar int32 — valid length
    mesh: Mesh,
    axis: str = "sp",
) -> tuple[Cache, jnp.ndarray]:
    """Returns (kv, logits[vocab]) where kv = {"k","v"}: [L, kvh, T, hd]
    sp-sharded on the T axis (callers page/commit it as needed) and the
    logits are for position seq_len-1. Weights are replicated over sp;
    only KV blocks move (one ICI hop per ring step)."""
    from dynamo_tpu.ops.ring_attention import ring_attention

    c = config
    T = int(tokens.shape[0])
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, inv_freq)
    h = _embed_rows(params, tokens, jnp.dtype(c.dtype))

    ks, vs = [], []
    rep = c.num_heads // c.num_kv_heads
    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])

        def write_kv(k, v):
            ks.append(k)
            vs.append(v)
            return (k, v)

        def attend(q, kv):
            k, v = kv
            return ring_attention(
                q, jnp.repeat(k, rep, axis=1),
                jnp.repeat(v, rep, axis=1), mesh, axis,
            )

        h, _ = _layer_body(c, lp, h, cos, sin, write_kv, attend)

    logits = _logits(c, params, h[seq_len - 1])
    kv = {
        "k": jnp.stack(ks).transpose(0, 2, 1, 3),  # [L, kvh, T, hd]
        "v": jnp.stack(vs).transpose(0, 2, 1, 3),
    }
    return kv, logits


# ---------------------------------------------------------------------------
# Encoder path (embeddings API): full self-attention over the prompt with
# no KV cache — the /v1/embeddings endpoint pools the final hidden states
# (reference protocols/openai embeddings surface; the reference delegates
# embedding models to its engines)

def encode_impl(
    config: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,   # [T] int32, padded
    seq_len: jnp.ndarray,  # scalar int32: valid length
) -> jnp.ndarray:
    """Mean-pooled, L2-normalized final hidden state [H] over the valid
    tokens. Cache-free causal attention (prompt-sized, one shot)."""
    c = config
    T = tokens.shape[0]
    inv_freq = jnp.asarray(
        rope_inv_freq(c.head_dim, c.rope_theta, c.rope_scaling_dict)
    )
    positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rope_cos_sin(positions, inv_freq)
    h = _embed_rows(params, tokens, jnp.dtype(c.dtype))
    valid = positions < seq_len                                   # [T]
    causal = (positions[None, :] <= positions[:, None]) & valid[None, :]

    def attend(q, kv):
        k, v = kv
        # GQA: repeat kv heads to match q heads
        rep = c.num_heads // c.num_kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
        scores = jnp.einsum("qhd,khd->hqk", q, k) / np.sqrt(c.head_dim)
        scores = jnp.where(causal[None], scores.astype(jnp.float32),
                           -1e30)
        w = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
        return jnp.einsum("hqk,khd->qhd", w, v)

    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])
        h, _ = _layer_body(
            c, lp, h, cos, sin,
            write_kv=lambda k, v: (k, v),
            attend=attend,
        )
    h = rms_norm(h, params["norm_f"], c.rms_norm_eps)
    maskf = valid.astype(jnp.float32)[:, None]
    pooled = (h.astype(jnp.float32) * maskf).sum(0) / jnp.maximum(
        maskf.sum(), 1.0
    )
    return pooled / jnp.maximum(jnp.linalg.norm(pooled), 1e-9)


encode = jax.jit(encode_impl, static_argnums=(0,))


# ---------------------------------------------------------------------------
# KV page export/import (the block-transfer data plane's device ops;
# reference analogue: NIXL block read/write, block_manager/block/transfer.rs)

def gather_pages_impl(cache: Cache, page_ids: jnp.ndarray) -> jnp.ndarray:
    """Pull whole pages out of the pool: [2, L, kvh, n, ps, hd] (k then v).
    Callers bucket n to a pow2 (padding with scratch page 0) to bound
    recompiles; the host slices the padding off after fetch."""
    return jnp.stack(
        [cache["k"][:, :, page_ids], cache["v"][:, :, page_ids]]
    )


def scatter_pages_impl(
    cache: Cache, page_ids: jnp.ndarray, data: jnp.ndarray
) -> Cache:
    """Write whole pages into the pool (inverse of gather_pages). Padding
    entries must point at scratch page 0 — it is garbage by contract."""
    return {
        "k": cache["k"].at[:, :, page_ids].set(data[0].astype(cache["k"].dtype)),
        "v": cache["v"].at[:, :, page_ids].set(data[1].astype(cache["v"].dtype)),
    }


def gather_pages_q_impl(
    cache: Cache, page_ids: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """gather_pages for a quantized pool: (int8 pages [2, L, kvh, n, ps,
    hd], scales [2, L, n]) — the int8 payload plus its scale sidecar is
    what every downstream tier/transfer consumer moves."""
    data = jnp.stack(
        [cache["k"][:, :, page_ids], cache["v"][:, :, page_ids]]
    )
    scales = jnp.stack(
        [cache["k_scale"][:, page_ids], cache["v_scale"][:, page_ids]]
    )
    return data, scales


def scatter_pages_q_impl(
    cache: Cache, page_ids: jnp.ndarray,
    data: jnp.ndarray, scales: jnp.ndarray,
) -> Cache:
    """Inverse of gather_pages_q: int8 pages + scales into the pool."""
    return {
        "k": cache["k"].at[:, :, page_ids].set(data[0]),
        "v": cache["v"].at[:, :, page_ids].set(data[1]),
        "k_scale": cache["k_scale"].at[:, page_ids].set(scales[0]),
        "v_scale": cache["v_scale"].at[:, page_ids].set(scales[1]),
    }


gather_pages = jax.jit(gather_pages_impl)
scatter_pages = jax.jit(scatter_pages_impl, donate_argnums=(0,))
gather_pages_q = jax.jit(gather_pages_q_impl)
scatter_pages_q = jax.jit(scatter_pages_q_impl, donate_argnums=(0,))


# ---------------------------------------------------------------------------
# HF weight loading

_HF_LAYER_MAP = {
    "input_layernorm.weight": ("ln1", False),
    "post_attention_layernorm.weight": ("ln2", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "mlp.gate_proj.weight": ("wg", True),
    "mlp.up_proj.weight": ("wu", True),
    "mlp.down_proj.weight": ("wd", True),
}


def params_from_state_dict(
    config: ModelConfig, raw: dict[str, jnp.ndarray], dtype=None
) -> Params:
    """Build our param pytree from HF-named tensors (torch state_dict names).

    Torch linear weights are [out, in]; ours are [in, out] — transposed here.
    Per-layer tensors are stacked on the leading layer axis.
    """
    dtype = jnp.dtype(config.dtype) if dtype is None else jnp.dtype(dtype)
    L = config.num_layers
    layers: dict[str, list] = {k: [None] * L for (k, _) in _HF_LAYER_MAP.values()}
    for hf_suffix, (ours, transpose) in _HF_LAYER_MAP.items():
        for l in range(L):
            t = jnp.asarray(raw[f"model.layers.{l}.{hf_suffix}"])
            layers[ours][l] = t.T if transpose else t

    params: Params = {
        "embed": jnp.asarray(raw["model.embed_tokens.weight"], dtype),
        "layers": {
            k: jnp.stack(v).astype(dtype) for k, v in layers.items()
        },
        "norm_f": jnp.asarray(raw["model.norm.weight"], dtype),
    }
    if not config.tie_word_embeddings:
        params["lm_head"] = jnp.asarray(raw["lm_head.weight"]).T.astype(dtype)
    return params


def load_hf_params(
    config: ModelConfig, model_dir: str, dtype=None, shardings: Params | None = None
) -> Params:
    """Load llama safetensors weights from a local HF model directory.

    Tensors are read and stacked on the host CPU (never staged through an
    accelerator); with `shardings` each stacked leaf is device_put straight
    to its target sharding, so peak accelerator memory is one sharded copy.
    """
    import glob
    import os

    from safetensors import safe_open

    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no safetensors in {model_dir}")
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        raw: dict[str, jnp.ndarray] = {}
        for fp in files:
            with safe_open(fp, framework="flax") as f:
                for name in f.keys():
                    raw[name] = f.get_tensor(name)
        params = params_from_state_dict(config, raw, dtype)
        del raw
        if config.quant == "int8":
            # quantize on the host: the dense 8B never touches the chip
            params = quantize_params(params)
    if shardings is not None:
        params = jax.tree.map(
            lambda x, s: jax.device_put(x, s), params, shardings
        )
    return params
