"""Mixture-of-Experts layer with expert parallelism over the `ep` mesh
axis.

Parity target: the reference's wide-EP story is SGLang+DeepEP on 104 GPUs
(SURVEY §2.5 EP row, examples/sglang/dsr1-wideep.md) — DP attention with
expert-parallel MoE and all_to_all dispatch. TPU-native redesign
(GShard/Switch-style): tokens are sharded over `ep`; each device routes
its tokens top-k, packs them into a capacity-bounded dispatch tensor
[E, C, H], exchanges slices with `jax.lax.all_to_all` over ICI, runs its
LOCAL experts as one batched einsum (E_local lanes on the MXU), and
all_to_alls results back for the weighted combine. Per-device memory is
O(E_local) expert weights + O(E·C) activations; overflow beyond capacity
is dropped (standard GShard semantics).

Shapes (per device, inside shard_map; n = ep size):
  h:    [Tl, H]            tokens on this shard
  wr:   [H, E]             router (replicated)
  wg/wu:[E_local, H, I]    local experts' gate/up
  wd:   [E_local, I, H]    local experts' down
"""
from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclass(frozen=True)
class MoEConfig:
    hidden_size: int
    intermediate_size: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25

    def capacity(self, tokens_per_shard: int) -> int:
        """Per-expert, per-source-shard token slots. capacity_factor <= 0
        means DROPLESS: every (token, pick) gets a slot (C = T*K). That is
        the serving default — capacity drops make a token's activations
        depend on what it was co-batched with, which breaks prefix-cache
        reproducibility (a resend recomputing a chunk alone would get
        different KV than the original). Capacity-bounded mode is for
        throughput-oriented deployments that accept the approximation."""
        if self.capacity_factor <= 0:
            return max(tokens_per_shard * self.top_k, 1)
        c = math.ceil(
            tokens_per_shard * self.top_k * self.capacity_factor
            / self.num_experts
        )
        return max(c, 1)


def init_moe_params(cfg: MoEConfig, rng: jax.Array | int = 0,
                    dtype=jnp.float32) -> dict:
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    E, H, I = cfg.num_experts, cfg.hidden_size, cfg.intermediate_size

    def rnd(k, *s):
        return (jax.random.normal(k, s, jnp.float32)
                / np.sqrt(s[-2])).astype(dtype)

    return {
        "wr": rnd(k1, H, E),
        "wg": rnd(k2, E, H, I),
        "wu": rnd(k3, E, H, I),
        "wd": rnd(k4, E, I, H),
    }


def moe_params_shardings(mesh: Mesh) -> dict:
    """Experts shard over ep; the router is replicated."""
    return {
        "wr": NamedSharding(mesh, P(None, None)),
        "wg": NamedSharding(mesh, P("ep", None, None)),
        "wu": NamedSharding(mesh, P("ep", None, None)),
        "wd": NamedSharding(mesh, P("ep", None, None)),
    }


def _expert_ffn(x, wg, wu, wd):
    # x [E_local, S, H]; one batched einsum per projection: E_local lanes
    g = jnp.einsum("esh,ehi->esi", x, wg)
    u = jnp.einsum("esh,ehi->esi", x, wu)
    return jnp.einsum("esi,eih->esh", jax.nn.silu(g) * u, wd)


def moe_layer(
    h: jnp.ndarray,        # [T, H], sharded over ep on T
    params: dict,
    cfg: MoEConfig,
    mesh: Mesh,
    axis: str = "ep",
) -> jnp.ndarray:
    """Top-k routed MoE FFN with all_to_all expert dispatch. Returns
    [T, H] with the same sharding as `h`."""
    n = mesh.shape[axis]
    T = h.shape[0]
    if T % n:
        raise ValueError(f"tokens {T} not divisible by ep={n}")
    if cfg.num_experts % n:
        raise ValueError(
            f"experts {cfg.num_experts} not divisible by ep={n}"
        )
    Tl = T // n
    run = _build_moe(mesh, axis, cfg, n, Tl)
    return run(h, params["wr"], params["wg"], params["wu"], params["wd"])


@functools.lru_cache(maxsize=64)
def _build_moe(mesh: Mesh, axis: str, cfg: MoEConfig, n: int, Tl: int):
    """Cached shard_map program per (mesh, axis, config, geometry) — a
    fresh closure per call would re-trace every layer every step."""
    E = cfg.num_experts
    E_local = E // n
    K = cfg.top_k
    C = cfg.capacity(Tl)
    H = cfg.hidden_size

    tok_spec = P(axis, None)
    exp_spec = P(axis, None, None)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(tok_spec, P(None, None), exp_spec, exp_spec, exp_spec),
        out_specs=tok_spec,
    )
    def run(hl, wr, wg, wu, wd):
        # ---- route ----
        logits = (hl @ wr).astype(jnp.float32)          # [Tl, E]
        gates = jax.nn.softmax(logits, axis=-1)
        gate_w, sel = jax.lax.top_k(gates, K)           # [Tl, K]
        gate_w = gate_w / jnp.maximum(
            gate_w.sum(-1, keepdims=True), 1e-9
        )

        # ---- pack into the capacity-bounded dispatch tensor ----
        sel_f = sel.reshape(-1)                          # [Tl*K]
        onehot = jax.nn.one_hot(sel_f, E, dtype=jnp.int32)
        pos = jnp.cumsum(onehot, axis=0) - onehot        # arrival order
        pos_f = jnp.sum(pos * onehot, axis=-1)           # [Tl*K]
        keep = pos_f < C
        pos_c = jnp.minimum(pos_f, C - 1)
        h_rep = jnp.repeat(hl, K, axis=0)                # [Tl*K, H]
        contrib = jnp.where(keep[:, None], h_rep, 0).astype(hl.dtype)
        disp = jnp.zeros((E, C, H), hl.dtype).at[sel_f, pos_c].add(contrib)

        # ---- all_to_all: every shard sends each expert-slice home ----
        # [E, C, H] -> [n, E_local, C, H]; slice j goes to device j
        recv = jax.lax.all_to_all(
            disp.reshape(n, E_local, C, H), axis, 0, 0
        )                                                # [n, E_local, C, H]
        xin = recv.transpose(1, 0, 2, 3).reshape(E_local, n * C, H)

        # ---- local experts, one batched einsum ----
        y = _expert_ffn(xin, wg, wu, wd)                 # [E_local, n*C, H]

        # ---- return results to their source shards ----
        back = jax.lax.all_to_all(
            y.reshape(E_local, n, C, H).transpose(1, 0, 2, 3), axis, 0, 0
        )                                                # [n, E_local, C, H]
        out_ecH = back.reshape(E, C, H)

        # ---- weighted combine ----
        picked = out_ecH[sel_f, pos_c]                   # [Tl*K, H]
        picked = jnp.where(keep[:, None], picked, 0)
        picked = picked.reshape(Tl, K, H)
        return jnp.einsum(
            "tk,tkh->th", gate_w.astype(picked.dtype), picked
        ).astype(hl.dtype)

    return run


def moe_reference(h, params, cfg: MoEConfig) -> jnp.ndarray:
    """Single-device dense reference (no capacity drops) for testing."""
    logits = (h @ params["wr"]).astype(jnp.float32)
    gates = jax.nn.softmax(logits, axis=-1)
    gate_w, sel = jax.lax.top_k(gates, cfg.top_k)
    gate_w = gate_w / jnp.maximum(gate_w.sum(-1, keepdims=True), 1e-9)
    outs = _expert_ffn(
        jnp.broadcast_to(h, (cfg.num_experts, *h.shape)),
        params["wg"], params["wu"], params["wd"],
    )                                                    # [E, T, H]
    picked = outs[sel.T, jnp.arange(h.shape[0])[None]]   # [K, T, H]
    return jnp.einsum(
        "tk,kth->th", gate_w.astype(picked.dtype), picked
    ).astype(h.dtype)
