"""ViT-style vision encoder for multimodal serving (reference
examples/multimodal: LLaVA/Qwen-VL encode worker,
components/encode_worker.py:148).

TPU-first: patchify via a single reshape+matmul (a conv with
stride==kernel IS a patch matmul — MXU-friendly), pre-norm transformer
blocks as one unrolled loop over stacked per-layer weights (same compile
discipline as models/llama.py), bidirectional attention, and a projector
to the language model's hidden size. The output is a sequence of image
tokens the llama prefill consumes in place of ``<image>`` placeholder
embeddings (llama.prefill token_embeds).

The parameter tree is CLIP-vision-tower shaped (biases, class token,
pre-embedding layernorm, post layernorm, LLaVA-style 2-layer projector)
so real checkpoints load via ``load_vision_params`` — random init keeps
the same tree with zero biases and identity norms.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    out_hidden_size: int = 4096   # language model hidden size
    layer_norm_eps: float = 1e-5
    # CLIP prepends a learned class token; LLaVA drops it from the
    # projector input (patch tokens only)
    use_class_token: bool = False

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def num_positions(self) -> int:
        return self.num_patches + (1 if self.use_class_token else 0)

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, out_hidden_size: int = 64, **kw) -> "VisionConfig":
        """CPU-test shapes."""
        base = dict(image_size=16, patch_size=4, hidden_size=32,
                    intermediate_size=64, num_layers=2, num_heads=4,
                    out_hidden_size=out_hidden_size)
        base.update(kw)
        return cls(**base)

    @classmethod
    def from_hf(cls, d: dict[str, Any],
                out_hidden_size: int = 4096) -> "VisionConfig":
        """From a HF ``vision_config`` section (CLIPVisionConfig keys)."""
        return cls(
            image_size=d.get("image_size", 224),
            patch_size=d.get("patch_size", 14),
            hidden_size=d.get("hidden_size", 1024),
            intermediate_size=d.get("intermediate_size", 4096),
            num_layers=d.get("num_hidden_layers", 24),
            num_heads=d.get("num_attention_heads", 16),
            out_hidden_size=out_hidden_size,
            layer_norm_eps=d.get("layer_norm_eps", 1e-5),
            use_class_token=True,
        )


def init_vision_params(cfg: VisionConfig, rng: jax.Array | int = 0,
                       dtype=jnp.float32) -> Params:
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    keys = jax.random.split(rng, 12)
    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size

    def rnd(k, *s):
        return (jax.random.normal(k, s, jnp.float32)
                / np.sqrt(s[-2] if len(s) > 1 else s[-1])).astype(dtype)

    params: Params = {
        "patch_embed": rnd(keys[0], cfg.patch_dim, H),
        "patch_bias": jnp.zeros((H,), dtype),
        "pos_embed": (jax.random.normal(keys[1], (cfg.num_positions, H),
                                        jnp.float32) * 0.02).astype(dtype),
        "ln_pre": jnp.ones((H,), dtype),
        "ln_pre_b": jnp.zeros((H,), dtype),
        "layers": {
            "ln1": jnp.ones((L, H), dtype),
            "ln1_b": jnp.zeros((L, H), dtype),
            "ln2": jnp.ones((L, H), dtype),
            "ln2_b": jnp.zeros((L, H), dtype),
            "wq": rnd(keys[2], L, H, H), "bq": jnp.zeros((L, H), dtype),
            "wk": rnd(keys[3], L, H, H), "bk": jnp.zeros((L, H), dtype),
            "wv": rnd(keys[4], L, H, H), "bv": jnp.zeros((L, H), dtype),
            "wo": rnd(keys[5], L, H, H), "bo": jnp.zeros((L, H), dtype),
            "w1": rnd(keys[6], L, H, I), "b1": jnp.zeros((L, I), dtype),
            "w2": rnd(keys[7], L, I, H), "b2": jnp.zeros((L, H), dtype),
        },
        "ln_f": jnp.ones((H,), dtype),
        "ln_f_b": jnp.zeros((H,), dtype),
        "proj": rnd(keys[8], H, cfg.out_hidden_size),
        "proj_b": jnp.zeros((cfg.out_hidden_size,), dtype),
    }
    if cfg.use_class_token:
        params["cls"] = (jax.random.normal(keys[9], (H,), jnp.float32)
                         * 0.02).astype(dtype)
    return params


def _ln(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w + b


def encode_image_impl(
    cfg: VisionConfig, params: Params, image: jnp.ndarray
) -> jnp.ndarray:
    """[H, W, 3] float image (0..1) -> [num_patches, out_hidden] tokens.
    With a class token it joins the transformer but is dropped before the
    projector (the LLaVA select_feature="patch" convention)."""
    c = cfg
    p = c.patch_size
    n = c.image_size // p
    # patchify: [n, p, n, p, 3] -> [n*n, p*p*3] (stride==kernel conv)
    patches = image.reshape(n, p, n, p, 3).transpose(0, 2, 1, 3, 4)
    patches = patches.reshape(n * n, c.patch_dim)
    h = (patches.astype(params["patch_embed"].dtype)
         @ params["patch_embed"] + params["patch_bias"])
    if c.use_class_token:
        h = jnp.concatenate([params["cls"][None], h], axis=0)
    h = h + params["pos_embed"]
    h = _ln(h, params["ln_pre"], params["ln_pre_b"], c.layer_norm_eps)

    nh, hd = c.num_heads, c.head_dim
    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])
        x = _ln(h, lp["ln1"], lp["ln1_b"], c.layer_norm_eps)
        q = (x @ lp["wq"] + lp["bq"]).reshape(-1, nh, hd)
        k = (x @ lp["wk"] + lp["bk"]).reshape(-1, nh, hd)
        v = (x @ lp["wv"] + lp["bv"]).reshape(-1, nh, hd)
        s = jnp.einsum("qhd,khd->hqk", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(hd)
        w = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("hqk,khd->qhd", w.astype(v.dtype), v,
                          preferred_element_type=jnp.float32)
        h = h + (attn.astype(h.dtype).reshape(-1, c.hidden_size)
                 @ lp["wo"] + lp["bo"])
        x2 = _ln(h, lp["ln2"], lp["ln2_b"], c.layer_norm_eps)
        h = h + (jax.nn.gelu(x2 @ lp["w1"] + lp["b1"]) @ lp["w2"]
                 + lp["b2"])

    h = _ln(h, params["ln_f"], params["ln_f_b"], c.layer_norm_eps)
    if c.use_class_token:
        h = h[1:]                 # patch tokens only
    h = h @ params["proj"] + params["proj_b"]
    if "proj2" in params:         # LLaVA 2-layer projector
        h = jax.nn.gelu(h) @ params["proj2"] + params["proj2_b"]
    return h                      # [num_patches, out_hidden]


encode_image = jax.jit(encode_image_impl, static_argnums=(0,))


# ---------------------------------------------------------------------------
# Checkpoint loading (CLIP vision tower + LLaVA projector names)

_TOWER_PREFIXES = (
    "vision_tower.vision_model.",     # LLaVA checkpoints
    "vision_model.",                  # bare CLIPVisionModel
    "model.vision_tower.vision_model.",
)


def load_vision_params(
    cfg: VisionConfig, model_dir: str, dtype=jnp.float32
) -> Params:
    """Load a CLIP-shape vision tower (+ optional LLaVA
    ``multi_modal_projector``) from a HF model directory's safetensors.

    The conv patch embedding [H, 3, p, p] becomes our patch matmul
    [p*p*3, H] (stride==kernel conv == matmul over flattened patches —
    flatten order (p_h, p_w, chan) matches encode_image_impl's
    patchify). Torch linears are [out, in] and transpose, exactly like
    models/llama.py params_from_state_dict."""
    import glob
    import os

    from safetensors import safe_open

    raw: dict[str, np.ndarray] = {}
    for fp in sorted(glob.glob(os.path.join(model_dir, "*.safetensors"))):
        with safe_open(fp, framework="numpy") as f:
            for name in f.keys():
                raw[name] = f.get_tensor(name)

    prefix = None
    for cand in _TOWER_PREFIXES:
        if any(k.startswith(cand) for k in raw):
            prefix = cand
            break
    if prefix is None:
        raise FileNotFoundError(
            f"no CLIP vision tower found in {model_dir} "
            f"(looked for prefixes {_TOWER_PREFIXES})"
        )

    def t(name: str) -> np.ndarray:
        return np.asarray(raw[prefix + name], np.float32)

    L, H = cfg.num_layers, cfg.hidden_size
    conv = t("embeddings.patch_embedding.weight")      # [H, 3, p, p]
    patch_embed = conv.transpose(2, 3, 1, 0).reshape(cfg.patch_dim, H)
    layers: dict[str, list] = {k: [] for k in (
        "ln1", "ln1_b", "ln2", "ln2_b", "wq", "bq", "wk", "bk",
        "wv", "bv", "wo", "bo", "w1", "b1", "w2", "b2",
    )}
    for l in range(L):
        p = f"encoder.layers.{l}."
        layers["ln1"].append(t(p + "layer_norm1.weight"))
        layers["ln1_b"].append(t(p + "layer_norm1.bias"))
        layers["ln2"].append(t(p + "layer_norm2.weight"))
        layers["ln2_b"].append(t(p + "layer_norm2.bias"))
        for ours, theirs in (("q", "q_proj"), ("k", "k_proj"),
                             ("v", "v_proj"), ("o", "out_proj")):
            layers[f"w{ours}"].append(t(p + f"self_attn.{theirs}.weight").T)
            layers[f"b{ours}"].append(t(p + f"self_attn.{theirs}.bias"))
        layers["w1"].append(t(p + "mlp.fc1.weight").T)
        layers["b1"].append(t(p + "mlp.fc1.bias"))
        layers["w2"].append(t(p + "mlp.fc2.weight").T)
        layers["b2"].append(t(p + "mlp.fc2.bias"))

    params: Params = {
        "patch_embed": jnp.asarray(patch_embed, dtype),
        "patch_bias": jnp.asarray(
            raw.get(prefix + "embeddings.patch_embedding.bias",
                    np.zeros(H, np.float32)), dtype),
        "pos_embed": jnp.asarray(
            t("embeddings.position_embedding.weight"), dtype),
        "ln_pre": jnp.asarray(
            raw.get(prefix + "pre_layrnorm.weight",
                    np.ones(H, np.float32)), dtype),
        "ln_pre_b": jnp.asarray(
            raw.get(prefix + "pre_layrnorm.bias",
                    np.zeros(H, np.float32)), dtype),
        "layers": {
            k: jnp.asarray(np.stack(v), dtype) for k, v in layers.items()
        },
        "ln_f": jnp.asarray(t("post_layernorm.weight"), dtype),
        "ln_f_b": jnp.asarray(t("post_layernorm.bias"), dtype),
    }
    if cfg.use_class_token:
        params["cls"] = jnp.asarray(t("embeddings.class_embedding"), dtype)

    proj_w = raw.get("multi_modal_projector.linear_1.weight")
    if proj_w is not None:
        params["proj"] = jnp.asarray(np.asarray(proj_w, np.float32).T, dtype)
        params["proj_b"] = jnp.asarray(
            raw.get("multi_modal_projector.linear_1.bias",
                    np.zeros(proj_w.shape[0], np.float32)), dtype)
        w2 = raw.get("multi_modal_projector.linear_2.weight")
        if w2 is not None:
            params["proj2"] = jnp.asarray(np.asarray(w2, np.float32).T, dtype)
            params["proj2_b"] = jnp.asarray(
                raw.get("multi_modal_projector.linear_2.bias",
                        np.zeros(w2.shape[0], np.float32)), dtype)
    elif cfg.out_hidden_size == H:
        params["proj"] = jnp.eye(H, dtype=dtype)
        params["proj_b"] = jnp.zeros((H,), dtype)
    else:
        raise ValueError(
            "no multi_modal_projector in checkpoint and out_hidden_size "
            f"{cfg.out_hidden_size} != tower hidden {H}"
        )
    return params
