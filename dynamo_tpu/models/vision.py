"""ViT-style vision encoder for multimodal serving (reference
examples/multimodal: LLaVA/Qwen-VL encode worker,
components/encode_worker.py:148).

TPU-first: patchify via a single reshape+matmul (a conv with
stride==kernel IS a patch matmul — MXU-friendly), pre-norm transformer
blocks as one unrolled loop over stacked per-layer weights (same compile
discipline as models/llama.py), bidirectional attention, and a projector
to the language model's hidden size. The output is a sequence of image
tokens the llama prefill consumes in place of ``<image>`` placeholder
embeddings (llama.prefill token_embeds).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Params = dict[str, Any]


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 224
    patch_size: int = 14
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_layers: int = 24
    num_heads: int = 16
    out_hidden_size: int = 4096   # language model hidden size
    layer_norm_eps: float = 1e-5

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @classmethod
    def tiny(cls, out_hidden_size: int = 64) -> "VisionConfig":
        """CPU-test shapes."""
        return cls(image_size=16, patch_size=4, hidden_size=32,
                   intermediate_size=64, num_layers=2, num_heads=4,
                   out_hidden_size=out_hidden_size)


def init_vision_params(cfg: VisionConfig, rng: jax.Array | int = 0,
                       dtype=jnp.float32) -> Params:
    if isinstance(rng, int):
        rng = jax.random.PRNGKey(rng)
    keys = jax.random.split(rng, 10)
    L, H, I = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size

    def rnd(k, *s):
        return (jax.random.normal(k, s, jnp.float32)
                / np.sqrt(s[-2] if len(s) > 1 else s[-1])).astype(dtype)

    return {
        "patch_embed": rnd(keys[0], cfg.patch_dim, H),
        "pos_embed": (jax.random.normal(keys[1], (cfg.num_patches, H),
                                        jnp.float32) * 0.02).astype(dtype),
        "layers": {
            "ln1": jnp.ones((L, H), dtype),
            "ln2": jnp.ones((L, H), dtype),
            "wq": rnd(keys[2], L, H, H),
            "wk": rnd(keys[3], L, H, H),
            "wv": rnd(keys[4], L, H, H),
            "wo": rnd(keys[5], L, H, H),
            "w1": rnd(keys[6], L, H, I),
            "w2": rnd(keys[7], L, I, H),
        },
        "ln_f": jnp.ones((H,), dtype),
        "proj": rnd(keys[8], H, cfg.out_hidden_size),
    }


def _ln(x, w, eps):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def encode_image_impl(
    cfg: VisionConfig, params: Params, image: jnp.ndarray
) -> jnp.ndarray:
    """[H, W, 3] float image (0..1) -> [num_patches, out_hidden] tokens."""
    c = cfg
    p = c.patch_size
    n = c.image_size // p
    # patchify: [n, p, n, p, 3] -> [n*n, p*p*3] (stride==kernel conv)
    patches = image.reshape(n, p, n, p, 3).transpose(0, 2, 1, 3, 4)
    patches = patches.reshape(n * n, c.patch_dim)
    h = patches.astype(params["patch_embed"].dtype) @ params["patch_embed"]
    h = h + params["pos_embed"]

    nh, hd = c.num_heads, c.head_dim
    for l in range(c.num_layers):
        lp = jax.tree.map(lambda x: x[l], params["layers"])
        x = _ln(h, lp["ln1"], c.layer_norm_eps)
        q = (x @ lp["wq"]).reshape(-1, nh, hd)
        k = (x @ lp["wk"]).reshape(-1, nh, hd)
        v = (x @ lp["wv"]).reshape(-1, nh, hd)
        s = jnp.einsum("qhd,khd->hqk", q, k,
                       preferred_element_type=jnp.float32) / np.sqrt(hd)
        w = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("hqk,khd->qhd", w.astype(v.dtype), v,
                          preferred_element_type=jnp.float32)
        h = h + attn.astype(h.dtype).reshape(-1, c.hidden_size) @ lp["wo"]
        x2 = _ln(h, lp["ln2"], c.layer_norm_eps)
        h = h + jax.nn.gelu(x2 @ lp["w1"]) @ lp["w2"]

    h = _ln(h, params["ln_f"], c.layer_norm_eps)
    return h @ params["proj"]   # [num_patches, out_hidden]


encode_image = jax.jit(encode_image_impl, static_argnums=(0,))
