"""Model families: pure-JAX forward passes designed for the paged-KV engine.

Each model module exposes:
  - ``init_params(config, rng)``: random-init parameter pytree (bf16).
  - ``load_hf_params(config, path)``: load safetensors weights from an HF dir.
  - ``prefill(...)`` / ``decode_step(...)``: jittable forward entry points
    operating on the paged KV cache.
  - ``param_shardings(config, mesh)``: NamedSharding pytree for TP over mesh.

The flagship family is llama (covers Llama-2/3/3.x and
DeepSeek-R1-Distill-Llama, the reference benchmark model —
/root/reference examples use DeepSeek-R1-Distill-Llama-8B).
"""

from dynamo_tpu.models.config import ModelConfig  # noqa: F401
