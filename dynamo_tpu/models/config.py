"""Model architecture configuration.

Read from a HuggingFace ``config.json`` (the model-card plane hands the
engine a local model directory, mirroring the reference's
ModelDeploymentCard/ModelInfoType flow — lib/llm/src/model_card/model.rs:37-63)
or constructed directly for tests/benchmarks.
"""
from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass(frozen=True)
class ModelConfig:
    """Llama-family architecture hyperparameters."""

    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    # Hashable (the config is a jit static arg): tuple of sorted (key, value)
    # pairs, e.g. (("factor", 8.0), ("rope_type", "llama3"), ...). Use
    # `rope_scaling_dict` to read.
    rope_scaling: Optional[tuple[tuple[str, Any], ...]] = None
    rms_norm_eps: float = 1e-5
    max_position_embeddings: int = 8192
    tie_word_embeddings: bool = False
    model_type: str = "llama"
    dtype: str = "bfloat16"
    # Mixture-of-Experts FFN (hashable, like rope_scaling): tuple of sorted
    # (key, value) pairs with keys num_experts / top_k / capacity_factor.
    # None = dense MLP. Experts shard over the `ep` mesh axis, expert
    # hidden dim over `tp` (the sglang wide-EP shape, SURVEY §2.5).
    moe: Optional[tuple[tuple[str, Any], ...]] = None
    # Weight quantization: None (dense, `dtype`) or "int8" (w8a16:
    # per-output-channel symmetric int8 weights dequantized inside the
    # matmul — llama.py _mm). Halves weight bytes, which both halves the
    # decode weight-pass floor and is what fits an 8B on a 16 GB v5e
    # (the reference's FP8 recipes, examples/llm/benchmarks/README.md:28).
    quant: Optional[str] = None

    @property
    def rope_scaling_dict(self) -> Optional[dict[str, Any]]:
        return dict(self.rope_scaling) if self.rope_scaling else None

    @property
    def moe_dict(self) -> Optional[dict[str, Any]]:
        return dict(self.moe) if self.moe else None

    @property
    def q_dim(self) -> int:
        return self.num_heads * self.head_dim

    @property
    def kv_dim(self) -> int:
        return self.num_kv_heads * self.head_dim

    @classmethod
    def from_hf_dict(cls, d: dict[str, Any]) -> "ModelConfig":
        num_heads = d["num_attention_heads"]
        head_dim = d.get("head_dim") or d["hidden_size"] // num_heads
        return cls(
            vocab_size=d["vocab_size"],
            hidden_size=d["hidden_size"],
            intermediate_size=d["intermediate_size"],
            num_layers=d["num_hidden_layers"],
            num_heads=num_heads,
            num_kv_heads=d.get("num_key_value_heads", num_heads),
            head_dim=head_dim,
            rope_theta=d.get("rope_theta", 10000.0),
            rope_scaling=(
                tuple(sorted(d["rope_scaling"].items()))
                if d.get("rope_scaling")
                else None
            ),
            rms_norm_eps=d.get("rms_norm_eps", 1e-5),
            max_position_embeddings=d.get("max_position_embeddings", 8192),
            tie_word_embeddings=d.get("tie_word_embeddings", False),
            model_type=d.get("model_type", "llama"),
        )

    @classmethod
    def from_pretrained(cls, model_dir: str) -> "ModelConfig":
        with open(os.path.join(model_dir, "config.json")) as f:
            return cls.from_hf_dict(json.load(f))

    # ---- canned configs for tests / benchmarks (shapes only; weights are
    # random unless load_hf_params is used) ----

    @classmethod
    def tiny(cls, **kw) -> "ModelConfig":
        """4-layer toy model for CPU tests."""
        base = dict(
            vocab_size=256,
            hidden_size=64,
            intermediate_size=128,
            num_layers=4,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            max_position_embeddings=512,
        )
        base.update(kw)
        return cls(**base)

    @classmethod
    def tiny_wide(cls, **kw) -> "ModelConfig":
        """Toy model with 4 kv heads — shardable to tp=4 (multi-host CPU
        tests / the cross-host CLI path)."""
        base = dict(num_kv_heads=4, num_heads=8)
        base.update(kw)
        return cls.tiny(**base)

    @classmethod
    def tiny_moe(cls, **kw) -> "ModelConfig":
        """Toy MoE model (8 experts, top-2, dropless) for CPU tests / the
        dryrun — the served stand-in for the reference's wide-EP DeepSeek
        shape. capacity_factor 0 = dropless (see moe.MoEConfig.capacity:
        capacity drops break prefix-cache reproducibility)."""
        base = dict(
            moe=(("capacity_factor", 0.0), ("num_experts", 8),
                 ("top_k", 2)),
        )
        base.update(kw)
        return cls.tiny(**base)

    @classmethod
    def llama3_1b(cls, **kw) -> "ModelConfig":
        """Llama-3.2-1B shapes (fits one v5e chip in bf16 with room for KV)."""
        base = dict(
            vocab_size=128256,
            hidden_size=2048,
            intermediate_size=8192,
            num_layers=16,
            num_heads=32,
            num_kv_heads=8,
            head_dim=64,
            rope_theta=500000.0,
            max_position_embeddings=131072,
            tie_word_embeddings=True,
        )
        base.update(kw)
        return cls(**base)

    @classmethod
    def llama3_8b(cls, **kw) -> "ModelConfig":
        """Llama-3.1-8B / DeepSeek-R1-Distill-Llama-8B shapes (the reference
        benchmark model, BASELINE.json)."""
        base = dict(
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position_embeddings=131072,
        )
        base.update(kw)
        return cls(**base)

    @classmethod
    def llama3_8b_int8(cls) -> "ModelConfig":
        """BASELINE config 1's model on one 16 GB v5e: w8a16 int8 weights
        (~8 GB) — bf16 cannot fit."""
        return cls.llama3_8b(quant="int8")

    @classmethod
    def llama3_1b_int8(cls) -> "ModelConfig":
        return cls.llama3_1b(quant="int8")

    @classmethod
    def llama3_70b(cls) -> "ModelConfig":
        return cls(
            vocab_size=128256,
            hidden_size=8192,
            intermediate_size=28672,
            num_layers=80,
            num_heads=64,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position_embeddings=131072,
        )

    def num_params(self) -> int:
        """Approximate parameter count (for memory planning)."""
        h, i, v = self.hidden_size, self.intermediate_size, self.vocab_size
        per_layer = (
            h * self.q_dim + 2 * h * self.kv_dim + self.q_dim * h  # attn
            + 3 * h * i  # mlp
            + 2 * h  # norms
        )
        embed = v * h * (1 if self.tie_word_embeddings else 2)
        return self.num_layers * per_layer + embed + h
