"""Runtime configuration + logging initialization.

Parity: reference lib/runtime/src/config.rs:44,103-127 — figment layering
(defaults <- TOML file <- ``DYN_RUNTIME_*`` env) — and logging.rs:24-62 —
``DYN_LOG`` level filter, ``DYN_LOGGING_JSONL`` structured mode.

Here: dataclass defaults <- TOML file (``DYNTPU_CONFIG`` or ./dynamo_tpu
.toml) <- ``DYNTPU_*`` environment variables. Logging:

    DYNTPU_LOG=debug            root level (or "pkg=debug,other=info")
    DYNTPU_LOGGING_JSONL=1      one JSON object per line
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import sys
from dataclasses import dataclass, field
from typing import Any, Optional

log = logging.getLogger(__name__)

ENV_PREFIX = "DYNTPU_"


@dataclass
class RuntimeConfig:
    """Process-wide runtime knobs (RuntimeConfig, config.rs:44).

    ``control_plane`` is None unless a file/env layer sets it — it doubles
    as the discovery-mode opt-in, so a baked-in default would silently
    flip every invocation into distributed mode."""

    control_plane: Optional[str] = None
    namespace: str = "dynamo"
    http_host: str = "0.0.0.0"
    http_port: int = 8080
    # worker defaults
    page_size: int = 64
    num_pages: int = 512
    max_decode_slots: int = 8
    cache_dtype: str = "bfloat16"
    # paged-pool KV quantization: "none" | "int8" (int8 pages with
    # per-block scales across the G1-G4 tiers and the transfer plane)
    kv_quant: str = "none"
    host_offload_pages: int = 0
    disk_offload_pages: int = 0
    disk_offload_path: Optional[str] = None
    # eager G3 startup scrub (kv_integrity): verify every manifest entry
    # against the backing file at attach instead of lazily at gather
    scrub_on_start: bool = False
    # speculative decoding (dynamo_tpu/spec/): off | ngram | draft
    speculative: str = "off"
    num_speculative_tokens: int = 4
    # acceptance-adaptive K (per-slot effective K in [spec_min_k, K])
    spec_adaptive: bool = True
    spec_min_k: int = 1
    # tree speculation: multi-branch drafts under one tree-causal verify
    # (budget 0 = auto: 1 + K * branches)
    spec_tree: bool = False
    spec_branches: int = 4
    spec_tree_budget: int = 0
    # acceptance gating (0.0 = off) + re-arm pacing
    spec_gate_acceptance: float = 0.0
    spec_gate_window: int = 4
    spec_rearm_tokens: int = 256
    # chunk-pipelined KV-transfer plane (kv_transfer.py): pages per
    # streamed chunk (0 = monolithic single-blob transfers), chunk
    # gathers/D2H copies in flight per export stream, and the deadline
    # for one queued page export/import op
    kv_transfer_chunk_pages: int = 8
    kv_transfer_inflight_chunks: int = 2
    xfer_op_timeout_s: float = 120.0
    # idle-timeout reclaiming a chunked export stream whose receiver
    # stalled (pinned gather handles/page refs freed after this long
    # without progress)
    kv_transfer_stream_idle_timeout_s: float = 15.0
    # fleet prefix economy (kv_router/fleet.py + prefetch.py): desired
    # fleet copies of a hot block (<= 1 disables the replication
    # controller), top-K hot chains examined/pushed per tick, the
    # controller tick period, the indexer's access-heat decay half-life
    # (0 = raw undecayed counters), and the dedup-admission gate
    kv_replication_target: int = 2
    kv_prefetch_hot_k: int = 8
    kv_prefetch_interval_s: float = 2.0
    kv_freq_halflife_s: float = 600.0
    kv_dedup_admission: bool = True
    # overload plane (dynamo_tpu/overload/): bounded admission budgets
    # (0 = unbounded) + the running-preemption flag
    max_waiting_requests: int = 0
    max_waiting_prefill_tokens: int = 0
    preempt_running: bool = False
    # double-buffered round pipelining (engine/engine.py _round): hide
    # host bookkeeping under device execution; off = legacy serialized
    # round order (the differential-test baseline)
    round_pipeline: bool = True
    # performance-attribution plane (telemetry/prof.py): per-round
    # host-segment timers + the SLO burn-rate gauges
    # dynamo_slo_{ttft,itl}_burn_rate over these targets
    prof_attribution: bool = True
    slo_ttft_target_s: float = 0.5
    slo_itl_target_s: float = 0.05
    slo_objective: float = 0.99
    # tail-latency forensics (telemetry/forensics.py): SLO breaches are
    # ALWAYS captured into the /debug/outliers dossier ring; this adds a
    # coin-flip sample of healthy requests as a comparison baseline
    # (0 = breaches only)
    forensics_sample_rate: float = 0.0

    @property
    def store_host_port(self) -> tuple[str, int]:
        host, _, port = (self.control_plane or "").partition(":")
        return host or "127.0.0.1", int(port or 7111)


def _coerce(value: str, target_type) -> Any:
    if target_type is bool:
        return value.lower() in ("1", "true", "yes", "on")
    if target_type is int:
        return int(value)
    if target_type is float:
        return float(value)
    return value


def load_config(
    path: Optional[str] = None, env: Optional[dict[str, str]] = None
) -> RuntimeConfig:
    """defaults <- TOML file <- DYNTPU_* env (later layers win). The cwd
    fallback file (./dynamo_tpu.toml) applies only under the real process
    environment — an explicit ``env`` asks for isolation."""
    from_process_env = env is None
    env = os.environ if env is None else env
    values: dict[str, Any] = {}

    path = path or env.get(ENV_PREFIX + "CONFIG")
    if path is None and from_process_env and os.path.exists("dynamo_tpu.toml"):
        path = "dynamo_tpu.toml"
    if path:
        import tomllib

        with open(path, "rb") as f:
            data = tomllib.load(f)
        section = data.get("runtime", data)  # [runtime] table or flat
        for f_ in dataclasses.fields(RuntimeConfig):
            if f_.name in section:
                values[f_.name] = section[f_.name]

    for f_ in dataclasses.fields(RuntimeConfig):
        key = ENV_PREFIX + f_.name.upper()
        if key in env:
            # field types are stringified (future annotations); the
            # default value's concrete type is the coercion target
            try:
                values[f_.name] = _coerce(env[key], type(f_.default))
            except ValueError:
                log.warning("ignoring invalid %s=%r", key, env[key])
    return RuntimeConfig(**values)


# ---------------------------------------------------------------------------
# logging (logging.rs:24-62)


class JsonlFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": self.formatTime(record, "%Y-%m-%dT%H:%M:%S"),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, separators=(",", ":"))


def init_logging(env: Optional[dict[str, str]] = None) -> None:
    """Configure root logging from DYNTPU_LOG / DYNTPU_LOGGING_JSONL.
    Idempotent; a pre-configured root (tests, embedders) is respected."""
    env = os.environ if env is None else env
    root = logging.getLogger()
    if root.handlers:
        _apply_filters(env.get(ENV_PREFIX + "LOG", ""), root)
        return

    handler = logging.StreamHandler(sys.stderr)
    if env.get(ENV_PREFIX + "LOGGING_JSONL", "").lower() in ("1", "true"):
        handler.setFormatter(JsonlFormatter())
    else:
        handler.setFormatter(logging.Formatter(
            "%(asctime)s %(levelname)s %(name)s %(message)s"
        ))
    root.addHandler(handler)
    root.setLevel(logging.INFO)
    _apply_filters(env.get(ENV_PREFIX + "LOG", ""), root)
    # jax is chatty at INFO in some builds
    logging.getLogger("jax").setLevel(logging.WARNING)


def _apply_filters(spec: str, root: logging.Logger) -> None:
    """'debug' or 'dynamo_tpu=debug,aiohttp=warning' (DYN_LOG shape)."""
    if not spec:
        return
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        try:
            if "=" in part:
                name, _, level = part.partition("=")
                logging.getLogger(name.strip()).setLevel(
                    level.strip().upper()
                )
            else:
                root.setLevel(part.upper())
        except ValueError:
            # a typo'd level must not crash every CLI invocation
            log.warning("ignoring invalid log filter %r", part)
