"""Mocker: a deterministic fake engine with simulated paged-KV and timing.

The reference calls this the keystone of its CPU test strategy
(lib/llm/src/mocker/engine.rs:60 MockVllmEngine, mocker/kv_manager.rs,
mocker/scheduler.rs:197, MockEngineArgs mocker/protocols.rs:72-94): a fake
engine that behaves like the real one — continuous batching, paged-KV
allocation with prefix reuse and LRU eviction, preemption under pressure,
per-step timing scaled by ``speedup_ratio`` — while publishing REAL
KvCacheEvents and ForwardPassMetrics. It lets the router, disagg path,
planner, frontend, and fault-injection tests run on CPU with no JAX model.

This implementation reuses the engine's actual host-side state machinery:
`PageAllocator` (same events, same LRU/refcount semantics) and
`TokenBlockSequence` (same chained xxh3 block hashes the KV router indexes),
so mocker-driven router tests validate real hash parity.

Generated tokens are deterministic: step i of a request yields
``prompt[(i + len(prompt)) % len(prompt)]`` — stable across runs and
schedulings, like the reference's deterministic mock outputs.
"""
from __future__ import annotations

import asyncio
import time
from collections import deque
from dataclasses import dataclass, field
from typing import AsyncIterator, Callable, Optional

from dynamo_tpu.engine.cache import PageAllocator
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.telemetry import metrics as tmetrics
from dynamo_tpu.telemetry.metrics import (
    TelemetryRegistry,
    request_histograms,
)
from dynamo_tpu.tokens import TokenBlockSequence


@dataclass
class MockerArgs:
    """Knobs of the simulated engine (reference MockEngineArgs
    mocker/protocols.rs:72-94: num_gpu_blocks, block_size, speedup_ratio,
    max_num_seqs, watermark...)."""

    num_pages: int = 128
    page_size: int = 16
    max_decode_slots: int = 8
    max_pages_per_seq: int = 64
    # simulated timing (wall-clock sleeps, divided by speedup_ratio)
    prefill_time_per_token_s: float = 0.00005
    decode_time_per_step_s: float = 0.002
    speedup_ratio: float = 1.0
    enable_prefix_caching: bool = True
    worker_id: str = "mocker"
    # overload plane (dynamo_tpu/overload/): bounded admission budgets
    # over the waiting queue (0 = unbounded), so router/frontend
    # overload paths test on CPU. Unlike TpuEngine the bound applies to
    # every priority class (no preemption machinery here).
    max_waiting_requests: int = 0
    max_waiting_prefill_tokens: int = 0
    # tenancy plane (dynamo_tpu/tenancy/): per-tenant admission budgets
    # over the waiting queue (0 = unbounded) and fair-share weights —
    # the same knobs as TpuEngine, so quota/fairness paths test on CPU
    tenant_max_waiting_requests: int = 0
    tenant_max_waiting_prefill_tokens: int = 0
    tenant_weights: Optional[dict] = None


@dataclass
class _MockRequest:
    req: PreprocessedRequest
    seq: TokenBlockSequence
    out: asyncio.Queue
    orig_prompt: list[int] = field(default_factory=list)  # pre-preemption
    pages: list[int] = field(default_factory=list)
    produced: int = 0
    last_token: int = -1
    cancelled: bool = False
    prefilling: bool = False
    enqueue_time: float = field(default_factory=time.monotonic)
    # forensics/timeline anchors (mocker-clock monotonic seconds)
    admit_time: Optional[float] = None
    first_token_time: Optional[float] = None

    # current (possibly restart-extended) prompt — kept separate from
    # req.token_ids so preemption never mutates the caller's request object
    prompt: list[int] = field(default_factory=list)
    # SFQ virtual finish stamp (tenancy fair share — same scheme as
    # TpuEngine._enqueue_waiting)
    vft: float = 0.0


class MockerEngine:
    """AsyncEngine-contract fake engine; single asyncio loop, no threads."""

    def __init__(
        self,
        args: Optional[MockerArgs] = None,
        *,
        on_kv_event: Optional[Callable[[KvCacheEvent], None]] = None,
        on_metrics: Optional[Callable[[ForwardPassMetrics], None]] = None,
        clock: Optional["Clock"] = None,
    ):
        from dynamo_tpu.fleetsim.clock import REAL_CLOCK

        self.args = args or MockerArgs()
        self.on_metrics = on_metrics
        # every sim-visible timestamp (queue waits, deadlines, idle-beat
        # cadence, simulated prefill/decode sleeps) reads THIS clock, so
        # a fleetsim VirtualClock compresses the whole engine; the real
        # clock default keeps production behavior byte-identical
        self.clock = clock or REAL_CLOCK
        self.allocator = PageAllocator(
            self.args.num_pages,
            self.args.page_size,
            worker_id=self.args.worker_id,
            on_event=on_kv_event,
            enable_prefix_caching=self.args.enable_prefix_caching,
        )
        self._waiting: list[_MockRequest] = []
        self._active: list[_MockRequest] = []
        self._task: Optional[asyncio.Task] = None
        self._wake = asyncio.Event()
        self._draining = False
        self._last_idle_beat = 0.0
        self.step_count = 0
        self.tokens_generated = 0
        self.preemptions = 0
        # overload plane: bounded admission + deadline shedding, with a
        # load-derived Retry-After from recently observed queue waits
        from dynamo_tpu.overload import AdmissionController

        self._queue_waits: deque = deque(maxlen=32)
        # latency histograms on the SAME canonical ladders as the real
        # engine (fleet merge sums only identical ladders), shipped in
        # ForwardPassMetrics.histograms so fleet-feed / planner / bench
        # paths exercise on CPU; exemplars carry request ids
        self.telemetry = request_histograms(TelemetryRegistry(),
                                            engine=True)
        self._h_ttft = self.telemetry.get(tmetrics.TTFT[0])
        self._h_e2e = self.telemetry.get(tmetrics.E2E[0])
        self._h_queue = self.telemetry.get(tmetrics.QUEUE[0])
        self.admission = AdmissionController(
            self.args.max_waiting_requests,
            self.args.max_waiting_prefill_tokens,
            queue_wait_s=lambda: (
                sum(self._queue_waits) / len(self._queue_waits)
                if self._queue_waits else None
            ),
        )
        # tenancy plane: per-tenant budgets + tenant-sliced metrics,
        # mirroring TpuEngine so CPU tests exercise the same contract
        from dynamo_tpu.tenancy import TenantQuotas

        self.tenant_quotas = TenantQuotas(
            self.args.tenant_max_waiting_requests,
            self.args.tenant_max_waiting_prefill_tokens,
            weights=self.args.tenant_weights,
        )
        # SFQ virtual clocks (same scheme as TpuEngine): per-tenant
        # finish stamps self-pace a storming tenant's backlog behind its
        # own stamps; single-tenant traffic degenerates to exact FIFO
        self._tenant_vnow: dict[str, float] = {}
        self._vclock = 0.0
        self.sheds = 0

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> None:
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # no loop yet; generate() starts the task lazily
        if self._task is None or self._task.done():
            self._task = loop.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def clear_kv_blocks(self) -> int:
        return self.allocator.clear()

    # ---- graceful drain (resilience/drain.py DrainController contract) --

    def begin_drain(self) -> None:
        self._draining = True

    def drained(self) -> bool:
        return self._draining and not self._active and not self._waiting

    # ------------------------------------------------------------------
    # AsyncEngine surface

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[LLMEngineOutput]:
        if self._draining:
            from dynamo_tpu.resilience.drain import WorkerDrainingError

            raise WorkerDrainingError(
                "worker draining: not admitting new requests"
            )
        if self._task is None or self._task.done():
            self.start()
        if not request.token_ids:
            raise ValueError("empty prompt")
        tenant = getattr(request, "tenant", "") or "default"
        if (request.deadline is not None
                and self.clock.time() > request.deadline):
            from dynamo_tpu.overload import OVERLOAD
            from dynamo_tpu.tenancy import TENANT

            self.sheds += 1
            OVERLOAD.inc("dynamo_overload_shed_total")
            TENANT.inc("dynamo_tenant_shed_total", tenant)
            yield LLMEngineOutput(
                token_ids=[], finish_reason=FinishReason.DEADLINE,
                annotations={"shed": {"reason": "deadline",
                                      "queued_s": 0.0}},
            )
            return
        # the bound applies to EVERY priority class here: the mocker has
        # no waiting-entry preemption, so force-admitting high-priority
        # traffic would leave its queue unbounded (priority preemption
        # is a TpuEngine feature — see engine.py _enforce_bounds)
        if self.admission.bounded:
            from dynamo_tpu.overload import OVERLOAD
            from dynamo_tpu.tenancy import TENANT

            waiting = len(self._waiting)
            tokens = sum(len(w.prompt) for w in self._waiting)
            try:
                self.admission.check(waiting, tokens)
            except Exception:
                OVERLOAD.inc("dynamo_overload_rejected_total")
                TENANT.inc("dynamo_tenant_rejected_total", tenant)
                raise
        if self.tenant_quotas.bounded:
            from dynamo_tpu.overload import OVERLOAD
            from dynamo_tpu.tenancy import TENANT

            t_waiting = sum(1 for w in self._waiting
                            if self._tenant_of(w) == tenant)
            t_tokens = sum(len(w.prompt) for w in self._waiting
                           if self._tenant_of(w) == tenant)
            try:
                self.tenant_quotas.check(tenant, t_waiting, t_tokens)
            except Exception:
                OVERLOAD.inc("dynamo_overload_rejected_total")
                TENANT.inc("dynamo_tenant_rejected_total", tenant)
                raise
        from dynamo_tpu.tenancy import TENANT as _TENANT

        _TENANT.inc("dynamo_tenant_admitted_total", tenant)
        r = _MockRequest(
            req=request,
            seq=TokenBlockSequence.from_tokens(
                request.token_ids, self.args.page_size, salt=request.model
            ),
            out=asyncio.Queue(),
            orig_prompt=list(request.token_ids),
            prompt=list(request.token_ids),
            enqueue_time=self.clock.monotonic(),
        )
        # weighted fair-share enqueue: stamp a virtual finish time and
        # insert before the first waiting entry with a larger stamp
        cost = max(1, len(request.token_ids))
        vft = (max(self._tenant_vnow.get(tenant, 0.0), self._vclock)
               + cost / self.tenant_quotas.weight(tenant))
        r.vft = vft
        self._tenant_vnow[tenant] = vft
        for i, wr in enumerate(self._waiting):
            # never jump a preempted restart (it holds produced tokens)
            if wr.produced == 0 and wr.vft > vft:
                self._waiting.insert(i, r)
                break
        else:
            self._waiting.append(r)
        self._wake.set()
        try:
            while True:
                item = await r.out.get()
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            r.cancelled = True
            self._wake.set()

    @staticmethod
    def _tenant_of(r: _MockRequest) -> str:
        return getattr(r.req, "tenant", "") or "default"

    def tenant_debug(self) -> dict:
        """Same shape as TpuEngine.tenant_debug — tools/tenant_stats.py
        and the system server's /debug/tenants read either engine."""
        from dynamo_tpu.tenancy import TENANT

        q = self.tenant_quotas
        tenants: dict[str, dict] = {}
        snap = TENANT.snapshot()
        qsnap = q.snapshot()
        names = ({self._tenant_of(w) for w in self._waiting}
                 | {self._tenant_of(w) for w in self._active}
                 | set(qsnap) | set(snap))
        for t in sorted(names):
            tenants[t] = {
                "waiting_requests": sum(
                    1 for w in self._waiting if self._tenant_of(w) == t),
                "waiting_prefill_tokens": sum(
                    len(w.prompt) for w in self._waiting
                    if self._tenant_of(w) == t),
                **qsnap.get(t, {}),
                "metrics": snap.get(t, {}),
            }
        return {
            "bounded": q.bounded,
            "max_waiting_requests": q.max_waiting_requests,
            "max_waiting_prefill_tokens": q.max_waiting_prefill_tokens,
            "n_adapters": 0,
            "tenants": tenants,
        }

    def metrics(self) -> ForwardPassMetrics:
        from dynamo_tpu.tenancy import TENANT

        by_tenant: dict[str, list] = {}
        for w in self._waiting:
            by_tenant.setdefault(self._tenant_of(w), []).append(w)
        for t, ws in by_tenant.items():
            TENANT.set("dynamo_tenant_queue_depth", t, len(ws))
            TENANT.set("dynamo_tenant_queue_tokens", t,
                       sum(len(w.prompt) for w in ws))
        a = self.allocator
        return ForwardPassMetrics(
            worker_id=self.args.worker_id,
            worker_stats=WorkerStats(
                request_active_slots=len(self._active),
                request_total_slots=self.args.max_decode_slots,
                num_requests_waiting=len(self._waiting),
                num_waiting_prefill_tokens=sum(
                    len(w.prompt) for w in self._waiting
                ),
                max_waiting_requests=self.args.max_waiting_requests,
                max_waiting_prefill_tokens=(
                    self.args.max_waiting_prefill_tokens
                ),
            ),
            kv_stats=KvStats(
                kv_active_blocks=a.active_pages,
                kv_total_blocks=a.total_pages,
                gpu_cache_usage_perc=a.usage(),
                gpu_prefix_cache_hit_rate=a.hit_rate(),
            ),
            histograms={
                name: self.telemetry.get(name).snapshot()
                for name, _ in (tmetrics.TTFT, tmetrics.ITL,
                                tmetrics.E2E, tmetrics.QUEUE)
            },
        )

    # ------------------------------------------------------------------
    # simulated engine loop

    def _idle_beat(self) -> None:
        """Heartbeat while idle: the health plane's soft leases
        (resilience/health.py heartbeat_ttl_s) read metrics-stream
        silence as wedged, so an idle engine must keep publishing —
        same contract as TpuEngine's idle heartbeat."""
        if self.on_metrics is None:
            return
        now = self.clock.monotonic()
        if now - self._last_idle_beat >= 0.5:
            self._last_idle_beat = now
            self.on_metrics(self.metrics())

    async def _run(self) -> None:
        a = self.args
        self._last_idle_beat = 0.0
        while True:
            self._sweep_cancelled()
            self._admit()
            if not self._active:
                self._wake.clear()
                self._idle_beat()
                if not self._waiting:
                    # bounded park so the idle heartbeat keeps ticking.
                    # NOT asyncio.wait_for: on 3.10 a stop() cancel that
                    # races the wake future's completion is SWALLOWED by
                    # wait_for and the loop becomes uncancellable;
                    # asyncio.wait propagates outer cancellation always.
                    waiter = asyncio.ensure_future(self._wake.wait())
                    try:
                        # park timeout is 0.5s of ENGINE time (idle beats
                        # must keep their cadence under compression)
                        await asyncio.wait(
                            {waiter}, timeout=self.clock.to_wall(0.5)
                        )
                    finally:
                        if not waiter.done():
                            waiter.cancel()
                else:
                    # waiting but unadmittable (page pressure): idle-tick
                    await self.clock.sleep(
                        a.decode_time_per_step_s / a.speedup_ratio
                    )
                continue
            # one simulated decode step for the whole batch
            await self.clock.sleep(a.decode_time_per_step_s / a.speedup_ratio)
            self.step_count += 1
            for r in list(self._active):
                self._decode_one(r)
            if self.on_metrics is not None:
                self.on_metrics(self.metrics())

    def _sweep_cancelled(self) -> None:
        for r in list(self._active):
            if r.cancelled:
                self._release(r)
        self._waiting = [r for r in self._waiting if not r.cancelled]

    def _admit(self) -> None:
        a = self.args
        # deadline-aware shedding: drop still-WAITING requests whose
        # deadline passed (zero tokens, DEADLINE finish) — never one
        # that already produced output (preemption re-queues those)
        now = self.clock.time()
        kept = []
        for r in self._waiting:
            if (r.produced == 0 and not r.prefilling
                    and r.req.deadline is not None
                    and now > r.req.deadline):
                from dynamo_tpu.overload import OVERLOAD

                self.sheds += 1
                OVERLOAD.inc("dynamo_overload_shed_total")
                r.out.put_nowait(LLMEngineOutput(
                    token_ids=[], finish_reason=FinishReason.DEADLINE,
                    annotations={"shed": {
                        "reason": "deadline",
                        "queued_s": round(
                            self.clock.monotonic() - r.enqueue_time, 3),
                    }},
                ))
            else:
                kept.append(r)
        self._waiting = kept
        while self._waiting and len(self._active) < a.max_decode_slots:
            r = self._waiting[0]
            ps = a.page_size
            hashes = r.seq.block_hashes()
            matched = self.allocator.match_prefix(
                hashes[: max(0, (len(r.prompt) - 1) // ps)]
            )
            n_pages = (len(r.prompt) + ps - 1) // ps
            if n_pages > min(self.allocator.total_pages, a.max_pages_per_seq):
                # can never fit: fail instead of blocking the queue forever
                self.allocator.free(matched)
                self._waiting.pop(0)
                r.out.put_nowait(ValueError("prompt does not fit page table"))
                continue
            fresh = self.allocator.allocate(n_pages - len(matched))
            if fresh is None:
                self.allocator.free(matched)
                return  # head-of-line blocks until space frees
            r.pages = matched + fresh
            r.prefilling = True
            r.admit_time = self.clock.monotonic()
            # the admitted stamp advances the global virtual clock, so
            # later arrivals can't be stamped into the served past
            self._vclock = max(self._vclock, r.vft)
            wait = r.admit_time - r.enqueue_time
            self._queue_waits.append(wait)
            self._h_queue.observe(
                wait, exemplar_id=r.req.request_id or None)
            from dynamo_tpu.tenancy import TENANT

            t = self._tenant_of(r)
            self.tenant_quotas.note_queue_wait(t, wait)
            TENANT.observe("dynamo_tenant_request_queue_seconds", t, wait,
                           exemplar_id=r.req.request_id or None)
            self._waiting.pop(0)
            self._active.append(r)
            # simulated prefill cost for the non-cached suffix
            n_uncached = len(r.prompt) - len(matched) * ps
            delay = n_uncached * a.prefill_time_per_token_s / a.speedup_ratio
            # commit complete prompt blocks (prefix-shareable immediately)
            for blk in r.seq.blocks[len(matched):]:
                if blk.position < len(r.pages):
                    self.allocator.commit(
                        r.pages[blk.position], blk.block_hash, blk.parent_hash
                    )
            asyncio.get_running_loop().create_task(
                self._emit_first(r, delay)
            )

    async def _emit_first(self, r: _MockRequest, delay: float) -> None:
        if delay > 0:
            await self.clock.sleep(delay)
        r.prefilling = False
        if r.cancelled or r not in self._active:
            return  # preempted mid-prefill; readmission re-schedules
        self._emit_token(r, self._next_token(r))

    def _next_token(self, r: _MockRequest) -> int:
        # derived from the ORIGINAL prompt + absolute step index, so the
        # stream is identical regardless of preemption/restart scheduling
        p = r.orig_prompt
        return p[(r.produced + len(p)) % len(p)]

    def _decode_one(self, r: _MockRequest) -> None:
        a = self.args
        if r not in self._active:
            return  # preempted/released earlier in this same round
        if r.prefilling or r.produced == 0:
            return  # still in simulated prefill
        # seal/commit the block completed by the previous emitted token;
        # clear last_token afterwards so a preemption between sealing and
        # the next emission doesn't re-append it to the restart prompt
        if r.last_token >= 0:
            for blk in r.seq.extend([r.last_token]):
                if blk.position < len(r.pages):
                    self.allocator.commit(
                        r.pages[blk.position], blk.block_hash, blk.parent_hash
                    )
            r.last_token = -1
        # grow the page table for the next position; total context derives
        # from the ORIGINAL prompt (preemption folds generated tokens into
        # r.prompt, but produced already counts them)
        total = len(r.orig_prompt) + r.produced
        need_pages = total // a.page_size + 1
        while len(r.pages) < min(need_pages, a.max_pages_per_seq):
            got = self.allocator.allocate(1)
            if got is None:
                if not self._try_preempt(exclude=r):
                    self._preempt(r)
                    return
                continue
            r.pages.extend(got)
        self._emit_token(r, self._next_token(r))

    def _lp_fields(self, r: _MockRequest, tok: int) -> dict:
        """Synthetic-but-shaped logprobs when the request asks for them —
        lets HTTP-level logprob plumbing be tested without a real model."""
        n = r.req.output_options.logprobs
        if n is None:
            return {}
        pairs = [[tok + i, -0.1 - 1.0 * i] for i in range(max(int(n), 1))]
        return {"log_probs": [-0.1], "top_logprobs": [pairs[: int(n)]]}

    def _finish_annotations(self, r: _MockRequest) -> dict:
        """Timing + worker trace spans for the finishing output — the
        same annotation shapes TpuEngine._final_annotations ships, so
        the frontend's forensics/request-stats paths join mocker
        requests identically (span starts anchored off the shared
        clock's monotonic->wall offset)."""
        now_m = self.clock.monotonic()
        now_w = self.clock.time()

        def wall(t_mono: float) -> float:
            return round(now_w - (now_m - t_mono), 6)

        e2e = now_m - r.enqueue_time
        self._h_e2e.observe(e2e, exemplar_id=r.req.request_id or None)
        timing: dict = {"e2e_s": round(e2e, 6),
                        "output_tokens": r.produced}
        spans: list[dict] = []
        if r.admit_time is not None:
            q = r.admit_time - r.enqueue_time
            timing["queue_s"] = round(q, 6)
            spans.append({"name": "queue", "start_s": wall(r.enqueue_time),
                          "duration_s": round(q, 6), "attrs": {}})
        if r.first_token_time is not None:
            timing["ttft_s"] = round(r.first_token_time - r.enqueue_time, 6)
            if r.admit_time is not None:
                spans.append({
                    "name": "prefill", "start_s": wall(r.admit_time),
                    "duration_s": round(
                        r.first_token_time - r.admit_time, 6),
                    "attrs": {"tokens": len(r.orig_prompt)},
                })
            spans.append({
                "name": "decode", "start_s": wall(r.first_token_time),
                "duration_s": round(now_m - r.first_token_time, 6),
                "attrs": {"tokens": r.produced},
            })
        return {"timing": timing, "trace": {"spans": spans}}

    def _emit_token(self, r: _MockRequest, tok: int) -> None:
        sc = r.req.stop_conditions
        if r.produced == 0:
            r.first_token_time = self.clock.monotonic()
            self._h_ttft.observe(
                r.first_token_time - r.enqueue_time,
                exemplar_id=r.req.request_id or None)
            from dynamo_tpu.tenancy import TENANT

            TENANT.observe(
                "dynamo_tenant_request_ttft_seconds", self._tenant_of(r),
                r.first_token_time - r.enqueue_time,
                exemplar_id=r.req.request_id or None)
        r.produced += 1
        self.tokens_generated += 1
        hit_eos = (
            not sc.ignore_eos
            and tok in (sc.stop_token_ids or [])
            and (sc.min_tokens is None or r.produced >= sc.min_tokens)
        )
        if hit_eos:
            r.out.put_nowait(
                LLMEngineOutput(token_ids=[], finish_reason=FinishReason.EOS,
                                annotations=self._finish_annotations(r))
            )
            self._release(r)
            return
        r.last_token = tok
        if sc.max_tokens is not None and r.produced >= sc.max_tokens:
            r.out.put_nowait(
                LLMEngineOutput(
                    token_ids=[tok], finish_reason=FinishReason.LENGTH,
                    annotations=self._finish_annotations(r),
                    **self._lp_fields(r, tok),
                )
            )
            self._release(r)
            return
        r.out.put_nowait(
            LLMEngineOutput(token_ids=[tok], **self._lp_fields(r, tok))
        )

    def _release(self, r: _MockRequest) -> None:
        self.allocator.free(r.pages)
        r.pages = []
        if r in self._active:
            self._active.remove(r)

    def _try_preempt(self, exclude: Optional[_MockRequest] = None) -> bool:
        """Preempt the most recently admitted active request (LIFO, like the
        engine and the reference mocker's eviction of the youngest)."""
        victims = [r for r in self._active if r is not exclude and r.produced > 0]
        if not victims:
            return False
        self._preempt(max(victims, key=lambda r: r.enqueue_time))
        return True

    def _preempt(self, victim: _MockRequest) -> None:
        self.preemptions += 1
        self.allocator.free(victim.pages)
        victim.pages = []
        new_prompt = victim.seq.tokens + (
            [victim.last_token] if victim.last_token >= 0 else []
        )
        victim.prompt = new_prompt
        victim.seq = TokenBlockSequence.from_tokens(
            new_prompt, self.args.page_size, salt=victim.req.model
        )
        victim.last_token = -1
        if victim in self._active:
            self._active.remove(victim)
        self._waiting.insert(0, victim)
