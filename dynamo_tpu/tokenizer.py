"""Tokenizer wrapper + incremental detokenization.

Wraps HuggingFace `tokenizers` (fast path) or a `transformers` tokenizer,
exposing encode/decode plus `DecodeStream` — incremental detokenization that
only emits UTF-8-complete text and handles sentencepiece-style leading-space
merges by decoding a sliding window (prefix/read offsets), mirroring the
reference's DecodeStream (lib/llm/src/tokenizers.rs:159).
"""
from __future__ import annotations

import json
import os
from typing import Optional, Protocol, Sequence


class Tokenizer(Protocol):
    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]: ...
    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str: ...
    @property
    def eos_token_ids(self) -> list[int]: ...
    @property
    def vocab_size(self) -> int: ...


class HfTokenizer:
    """Adapter over tokenizers.Tokenizer (fast) with HF-dir loading."""

    def __init__(self, tok, eos_token_ids: Optional[list[int]] = None, bos_token_id: Optional[int] = None):
        self._tok = tok
        self._eos = list(eos_token_ids or [])
        self.bos_token_id = bos_token_id

    @classmethod
    def from_dir(cls, path: str) -> "HfTokenizer":
        """Load from a HF model directory (tokenizer.json + *_config.json)."""
        from tokenizers import Tokenizer as RustTokenizer

        tok = RustTokenizer.from_file(os.path.join(path, "tokenizer.json"))
        eos: list[int] = []
        bos = None
        cfg_path = os.path.join(path, "config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                cfg = json.load(f)
            e = cfg.get("eos_token_id")
            if e is not None:
                eos = e if isinstance(e, list) else [e]
            bos = cfg.get("bos_token_id")
        tc_path = os.path.join(path, "tokenizer_config.json")
        if not eos and os.path.exists(tc_path):
            with open(tc_path) as f:
                tc = json.load(f)
            e = tc.get("eos_token")
            if isinstance(e, dict):
                e = e.get("content")
            if isinstance(e, str):
                tid = tok.token_to_id(e)
                if tid is not None:
                    eos = [tid]
        return cls(tok, eos, bos)

    def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
        return self._tok.encode(text, add_special_tokens=add_special_tokens).ids

    def decode(self, ids: Sequence[int], skip_special_tokens: bool = True) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=skip_special_tokens)

    @property
    def eos_token_ids(self) -> list[int]:
        return self._eos

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()


class DecodeStream:
    """Incremental detokenizer.

    decode() returns only text that is (a) new relative to what was already
    emitted and (b) not ending in an incomplete UTF-8 replacement char, so
    multi-token unicode sequences emit once complete.
    """

    REPLACEMENT = "�"

    def __init__(self, tokenizer: Tokenizer, prompt_ids: Sequence[int] = (), skip_special_tokens: bool = True):
        self._tok = tokenizer
        self._skip = skip_special_tokens
        # keep a short tail of prompt tokens so the first generated token
        # detokenizes with correct leading-space context
        self._ids: list[int] = list(prompt_ids)[-6:]
        self._prefix_text = tokenizer.decode(self._ids, self._skip) if self._ids else ""
        self._emitted_upto = len(self._prefix_text)

    def step(self, token_id: int) -> str:
        """Feed one token; return newly-complete text (possibly empty)."""
        self._ids.append(int(token_id))
        text = self._tok.decode(self._ids, self._skip)
        if text.endswith(self.REPLACEMENT):
            # mid-codepoint; wait for the rest — but still bound the window
            # against degenerate streams that never complete a codepoint
            if len(self._ids) > 256:
                self._trim(text, keep=64)
            return ""
        new = text[self._emitted_upto :]
        self._emitted_upto = len(text)
        # bound memory: everything is emitted now, safe to drop head tokens
        if len(self._ids) > 64:
            self._trim(text, keep=32)
        return new

    def _trim(self, full_text: str, keep: int) -> None:
        unemitted = len(full_text) - self._emitted_upto
        self._ids = self._ids[-keep:]
        head = self._tok.decode(self._ids, self._skip)
        self._emitted_upto = max(0, len(head) - unemitted)


def make_test_tokenizer(vocab_words: Optional[list[str]] = None):
    """Tiny offline tokenizer for tests/CI (no model downloads).

    Whitespace pre-tokenized WordLevel over a fixed vocab + byte fallback to
    <unk>; good enough to exercise encode/decode/stop-string paths.
    """
    from tokenizers import Tokenizer as RustTokenizer
    from tokenizers.models import WordLevel
    from tokenizers.pre_tokenizers import WhitespaceSplit

    words = vocab_words or [f"w{i}" for i in range(100)]
    vocab = {"<unk>": 0, "<s>": 1, "</s>": 2}
    for w in words:
        if w not in vocab:
            vocab[w] = len(vocab)
    tok = RustTokenizer(WordLevel(vocab, unk_token="<unk>"))
    tok.pre_tokenizer = WhitespaceSplit()

    class _WordTok:
        eos_token_ids = [2]
        bos_token_id = 1

        def __init__(self):
            self._t = tok
            self._inv = {v: k for k, v in vocab.items()}

        def encode(self, text: str, add_special_tokens: bool = True) -> list[int]:
            return self._t.encode(text).ids

        def decode(self, ids, skip_special_tokens: bool = True) -> str:
            specials = {0, 1, 2} if skip_special_tokens else set()
            # ids beyond the vocab (e.g. sampled from a larger model head)
            # decode to <unk> rather than raising
            return " ".join(
                self._inv.get(i, "<unk>") for i in ids if i not in specials
            )

        @property
        def vocab_size(self) -> int:
            return len(vocab)

    return _WordTok()
