"""Retry and circuit-breaker policy objects.

``RetryPolicy`` is the one backoff implementation shared by the runtime
client's connect loop and the router's re-route/migration attempts —
exponential with equal-style jitter (the delay lands uniformly in the
top ``jitter`` fraction of the backoff window), so a fleet of clients
recovering from a control-plane blip doesn't stampede it on synchronized
retry ticks while still guaranteeing a floor between attempts.

``CircuitBreaker`` is the classic three-state machine, one per worker
(health.py): CLOSED passes traffic; ``failure_threshold`` consecutive
failures trip it OPEN (the worker leaves routing); after
``reset_timeout_s`` the next ``allow()`` grants exactly one HALF_OPEN
probe — its success re-closes the breaker, its failure re-opens with the
timer restarted. The clock is injectable so the state machine unit-tests
with a fake clock.
"""
from __future__ import annotations

import enum
import random
import time
from dataclasses import dataclass
from typing import Callable, Optional

from dynamo_tpu.resilience.metrics import RESILIENCE


@dataclass
class RetryPolicy:
    """Jittered exponential backoff: delay(i) lands uniformly in
    ((1-jitter) * b, b] for b = min(base * multiplier^i, max) — equal-
    style jitter: randomized spread with a guaranteed inter-attempt
    floor (full U(0, b] jitter would allow near-immediate retries)."""

    max_attempts: int = 4
    base_delay_s: float = 0.25
    max_delay_s: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.5          # fraction of the delay randomized away
    rng: Optional[random.Random] = None

    def delay(self, attempt: int) -> float:
        """Backoff before retry ``attempt`` (0-based: the sleep taken
        after the attempt-th failure)."""
        base = min(
            self.base_delay_s * (self.multiplier ** max(attempt, 0)),
            self.max_delay_s,
        )
        r = (self.rng or random).random()
        return base * (1.0 - self.jitter * r)

    async def sleep(self, attempt: int) -> None:
        import asyncio

        RESILIENCE.inc("dynamo_resilience_retries_total")
        d = self.delay(attempt)
        if d > 0:
            await asyncio.sleep(d)


class BreakerState(str, enum.Enum):
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Consecutive-failure breaker with a single half-open probe."""

    def __init__(
        self,
        failure_threshold: int = 3,
        reset_timeout_s: float = 5.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        self.state = BreakerState.CLOSED
        self.consecutive_failures = 0
        self.trips = 0
        self._opened_at = 0.0
        self._probe_outstanding = False

    def peek_allow(self) -> bool:
        """Side-effect-free: could a request be sent right now? Routing
        filters use this — the probe grant must only be CONSUMED
        (begin_probe) for the worker a request is actually dispatched to,
        or a probe 'spent' on a routing decision that picked another
        worker would starve the recovered worker forever."""
        if self.state is BreakerState.CLOSED:
            return True
        if self.state is BreakerState.OPEN:
            return self.clock() - self._opened_at >= self.reset_timeout_s
        return not self._probe_outstanding  # HALF_OPEN: one probe at a time

    def begin_probe(self) -> None:
        """A request is being dispatched while not CLOSED: this is the
        half-open probe. Resolves via record_success/record_failure."""
        if self.state is BreakerState.OPEN:
            self.state = BreakerState.HALF_OPEN
        self._probe_outstanding = True

    def allow(self) -> bool:
        """May a request be sent right now? OPEN past the reset timeout
        grants exactly ONE half-open probe (consumed — the caller WILL
        dispatch); further calls return False until that probe resolves
        via record_success/record_failure."""
        if not self.peek_allow():
            return False
        if self.state is not BreakerState.CLOSED:
            self.begin_probe()
        return True

    def record_success(self) -> None:
        if self.state is BreakerState.CLOSED:
            self.consecutive_failures = 0
            return
        if self._probe_outstanding:
            # the half-open probe succeeded: re-close
            self._probe_outstanding = False
            self.consecutive_failures = 0
            self.state = BreakerState.CLOSED
        # else: a STRAY success (a stream that was already in flight when
        # the breaker tripped, completing late) — it says nothing about
        # whether NEW requests succeed, so it must not short-circuit the
        # reset timeout + probe protocol

    def record_failure(self) -> None:
        self.consecutive_failures += 1
        self._probe_outstanding = False
        if self.state is BreakerState.HALF_OPEN:
            self._trip()
        elif (self.state is BreakerState.CLOSED
              and self.consecutive_failures >= self.failure_threshold):
            self._trip()

    def _trip(self) -> None:
        self.state = BreakerState.OPEN
        self._opened_at = self.clock()
        self.trips += 1
        RESILIENCE.inc("dynamo_resilience_breaker_trips_total")
