"""Graceful drain: stop admitting, finish in-flight, then exit.

The planner's scale-down path: killing a warm worker throws away its KV
cache AND its in-flight streams; draining lets the streams finish (and the
router stop choosing it) before the process exits. Two triggers share one
controller: ``POST /drain`` on the worker's system server, and SIGTERM on
the worker process (what LocalConnector sends on retirement).

Engine contract (TpuEngine and MockerEngine implement it):
  begin_drain()     stop admitting — new generate() calls raise
                    WorkerDrainingError (a ConnectionError, so routers
                    re-route instead of failing the request)
  drained() -> bool in-flight work is done
"""
from __future__ import annotations

import asyncio
import logging
import time
from typing import Any, Callable, Optional

from dynamo_tpu.resilience.metrics import RESILIENCE

log = logging.getLogger(__name__)


class WorkerDrainingError(ConnectionError):
    """Raised by a draining engine's generate(): retriable by routers
    (the drain is this worker's problem, not the request's)."""


class DrainController:
    """Orchestrates one process's drain:

      1. deregister (optional hook — revoke the lease so discovery stops
         routing here; racing requests bounce off WorkerDrainingError)
      2. engine.begin_drain(): refuse new admissions
      3. poll engine.drained() until in-flight requests finish (or the
         timeout passes — then exit anyway, the supervisor's SIGKILL
         equivalent)
      4. fire on_drained (the worker loop exits on it)
    """

    def __init__(
        self,
        engine: Any,
        *,
        on_deregister: Optional[Callable[[], Any]] = None,
        on_drained: Optional[Callable[[], Any]] = None,
        timeout_s: float = 60.0,
        poll_s: float = 0.05,
    ):
        self.engine = engine
        self.on_deregister = on_deregister
        self.on_drained = on_drained
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        self.state = "serving"           # serving | draining | drained
        self.requested_at: Optional[float] = None
        self.drained_event = asyncio.Event()
        self._task: Optional[asyncio.Task] = None

    def request_drain(self, reason: str = "") -> asyncio.Event:
        """Idempotent; safe from signal handlers on the event loop.
        Admissions stop SYNCHRONOUSLY (before the deregister round-trip
        can lose a race with new arrivals); the wait runs as a task."""
        if self.state == "serving":
            self.state = "draining"
            self.requested_at = time.monotonic()
            RESILIENCE.set("dynamo_resilience_draining", 1)
            log.warning("drain requested%s", f" ({reason})" if reason else "")
            begin = getattr(self.engine, "begin_drain", None)
            if begin is not None:
                begin()
            self._task = asyncio.get_running_loop().create_task(self._run())
        return self.drained_event

    async def wait_drained(self) -> None:
        await self.drained_event.wait()

    async def _run(self) -> None:
        try:
            if self.on_deregister is not None:
                out = self.on_deregister()
                if asyncio.iscoroutine(out):
                    await out
        except Exception:  # noqa: BLE001 — drain proceeds regardless
            log.exception("drain: deregister hook failed")
        deadline = time.monotonic() + self.timeout_s
        drained_fn = getattr(self.engine, "drained", None)
        while drained_fn is not None and not drained_fn():
            if time.monotonic() > deadline:
                log.warning(
                    "drain timed out after %.1fs; exiting with requests "
                    "in flight", self.timeout_s,
                )
                break
            await asyncio.sleep(self.poll_s)
        self.state = "drained"
        RESILIENCE.set("dynamo_resilience_draining", 0)
        RESILIENCE.inc("dynamo_resilience_drains_total")
        log.warning("drain complete (%.2fs)",
                    time.monotonic() - (self.requested_at or 0.0))
        self.drained_event.set()
        try:
            if self.on_drained is not None:
                out = self.on_drained()
                if asyncio.iscoroutine(out):
                    await out
        except Exception:  # noqa: BLE001
            log.exception("drain: on_drained hook failed")

    def status(self) -> dict[str, Any]:
        return {
            "state": self.state,
            "requested_at": self.requested_at,
            "timeout_s": self.timeout_s,
        }
