"""Cross-frontend circuit-breaker sharing over the runtime store.

Breaker state was per-frontend: each frontend re-discovered a dead
worker independently, paying ``failure_threshold`` failed requests per
frontend before tripping. The board closes that gap over the store's
pub/sub plane (the same transport KV events and metrics ride):

  - a LOCAL trip publishes ``{worker_id, state: "open", until}`` on the
    namespace's breaker topic; sibling frontends block routing to that
    worker for the remainder of the reset window
    (``WorkerHealthTracker.note_remote_open``);
  - a LOCAL probe success publishes ``state: "closed"``, lifting the
    remote block early everywhere — one frontend's recovery probe
    re-opens traffic fleet-wide.

Remote state is advisory: it never feeds a local breaker's failure
counts (another frontend's view is not this one's evidence), and it
expires on its own — a partitioned publisher can delay rediscovery by
at most one reset window. Events carry an origin id so a frontend
ignores its own publications, and absolute unix ``until`` times so the
window survives the process-boundary hop.
"""
from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from typing import Any, Optional

log = logging.getLogger(__name__)

BREAKER_TOPIC = "health_breakers"


def breaker_topic(namespace: str) -> str:
    return f"{BREAKER_TOPIC}.{namespace}"


class SharedBreakerBoard:
    """Publish local breaker transitions; apply siblings' to the local
    health tracker."""

    def __init__(self, kv: Any, health: Any, namespace: str = "dynamo",
                 origin: Optional[str] = None):
        self.kv = kv
        self.health = health
        self.namespace = namespace
        self.origin = origin or uuid.uuid4().hex
        self.published = 0
        self.applied = 0
        self._task: Optional[asyncio.Task] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None

    async def start(self) -> "SharedBreakerBoard":
        self._loop = asyncio.get_running_loop()
        sub = await self.kv.subscribe(breaker_topic(self.namespace))
        self._task = self._loop.create_task(self._follow(sub))
        self.health.on_state_change = self._on_local_change
        return self

    async def stop(self) -> None:
        if self.health.on_state_change == self._on_local_change:
            self.health.on_state_change = None
        if self._task is not None:
            self._task.cancel()
            self._task = None

    # ---- local -> fleet ----

    def _on_local_change(self, worker_id: str, state: str,
                         window_s: float) -> None:
        """Health-tracker hook; runs synchronously wherever
        record_failure/success happened, so the publish is scheduled
        onto the board's loop (best-effort — a lost publish only costs
        siblings their own rediscovery)."""
        if self._loop is None or self._loop.is_closed():
            return
        payload = json.dumps({
            "worker_id": worker_id,
            "state": state,
            "until": time.time() + max(0.0, window_s),
            "origin": self.origin,
        })

        async def _pub() -> None:
            try:
                await self.kv.publish(
                    breaker_topic(self.namespace), payload
                )
                self.published += 1
            except (ConnectionError, OSError):
                log.debug("breaker publish failed (store unreachable)")

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is self._loop:
            self._loop.create_task(_pub())
        else:
            asyncio.run_coroutine_threadsafe(_pub(), self._loop)

    # ---- fleet -> local ----

    async def _follow(self, sub) -> None:
        async for ev in sub:
            try:
                msg = json.loads(ev["value"])
                wid = msg["worker_id"]
                state = msg["state"]
            except (KeyError, ValueError, TypeError):
                continue
            if msg.get("origin") == self.origin:
                continue  # our own publication echoing back
            if state == "open":
                window = float(msg.get("until", 0.0)) - time.time()
                if window > 0:
                    self.health.note_remote_open(wid, window)
                    self.applied += 1
            elif state == "closed":
                self.health.clear_remote_open(wid)
                self.applied += 1
