"""Resilience plane: worker failure as a first-class serving event.

The reference Dynamo ships a fault-tolerance suite (tests/fault_tolerance/
configs/agg_tp_2_dp_4.yaml), lease-based liveness and request migration so
a dead engine never kills an in-flight stream. This package is the
TPU-native analogue, spanning every serving layer:

  policy.py     RetryPolicy (jittered exponential backoff) and the
                CircuitBreaker state machine (CLOSED -> OPEN -> HALF_OPEN)
  health.py     WorkerHealthTracker: per-worker heartbeat leases fed by
                the existing load-metrics stream + one breaker per worker
  migration.py  mid-stream request migration: rebuild a dead worker's
                stream as prompt + emitted tokens and replay it as a
                prefill on a healthy worker (Llumnix-style live
                migration; the paged-KV prefix cache makes the replay
                mostly a cache hit)
  drain.py      graceful drain: stop admitting, finish in-flight, exit —
                the planner's scale-down path (/drain on the system
                server, SIGTERM on the worker process)
  chaos.py      fault-injection harness: kill_worker / stall_stream /
                drop_response / delay hooks armed via env, CLI, or the
                system server's /chaos control (tools/chaos.py)
  shared.py     SharedBreakerBoard: breaker trips/closes published on
                the store's pub/sub plane so sibling frontends stop
                routing to a dead worker without re-discovering it
  metrics.py    dynamo_migration_* / dynamo_resilience_* counters
                rendered on all three scrape surfaces
"""
from dynamo_tpu.resilience.chaos import CHAOS, ChaosHooks, ChaosPoint
from dynamo_tpu.resilience.drain import DrainController, WorkerDrainingError
from dynamo_tpu.resilience.health import WorkerHealthTracker
from dynamo_tpu.resilience.metrics import RESILIENCE, ResilienceMetrics
from dynamo_tpu.resilience.migration import MigrationPolicy, build_replay_request
from dynamo_tpu.resilience.policy import BreakerState, CircuitBreaker, RetryPolicy
from dynamo_tpu.resilience.shared import SharedBreakerBoard

__all__ = [
    "BreakerState",
    "SharedBreakerBoard",
    "CHAOS",
    "ChaosHooks",
    "ChaosPoint",
    "CircuitBreaker",
    "DrainController",
    "MigrationPolicy",
    "RESILIENCE",
    "ResilienceMetrics",
    "RetryPolicy",
    "WorkerDrainingError",
    "WorkerHealthTracker",
    "build_replay_request",
]
