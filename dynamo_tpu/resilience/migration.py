"""Mid-stream request migration (Llumnix-style live replay).

When a streaming worker dies, the request is reconstructed as
``prompt + tokens-emitted-so-far`` and replayed as a *prefill* on a
healthy worker. The replay prompt IS the suppression of the replayed
suffix: the new worker's first sampled token is the next token of the
generation, so the client stream carries every token exactly once by
construction, and under greedy decoding the merged stream is
token-identical to an uninterrupted run (the continuation depends only on
sequence content). The paged-KV prefix cache makes the replayed prefill
mostly a G1/G2 hit when the new worker served this prefix before.

Stop conditions shift with the replay: ``max_tokens``/``min_tokens``
count tokens already delivered, so LENGTH fires at the same total and
``min_tokens`` suppression doesn't repeat.
"""
from __future__ import annotations

import copy
from dataclasses import dataclass
from typing import Optional

from dynamo_tpu.protocols.common import PreprocessedRequest


@dataclass
class MigrationPolicy:
    """Knobs for the router's mid-stream migration path."""

    enabled: bool = True
    # migrations attempted for ONE request before giving up (each targets
    # a different worker; the dead ones are excluded from re-routing)
    max_migrations: int = 3

    def budget(self, n_workers: int) -> int:
        return min(self.max_migrations, max(n_workers - 1, 0))


def build_replay_request(
    request: PreprocessedRequest, emitted: list[int]
) -> Optional[PreprocessedRequest]:
    """The replay form of a partially-streamed request, or None when the
    request cannot migrate (its token budget is already spent — the caller
    should finish it with LENGTH instead of replaying a 0-token tail)."""
    sc = request.stop_conditions
    if sc.max_tokens is not None and len(emitted) >= sc.max_tokens:
        return None
    replay = copy.copy(request)
    replay.token_ids = list(request.token_ids) + list(emitted)
    replay.stop_conditions = copy.copy(sc)
    if sc.max_tokens is not None:
        replay.stop_conditions.max_tokens = sc.max_tokens - len(emitted)
    if sc.min_tokens is not None:
        replay.stop_conditions.min_tokens = max(
            0, sc.min_tokens - len(emitted)
        )
    # the router annotates per-route; never reuse the dead worker's hint
    replay.estimated_prefix_hit_num_blocks = None
    return replay
