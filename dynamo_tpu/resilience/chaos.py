"""Fault-injection harness (reference tests/fault_tolerance scenarios).

A process-global registry of named injection points, armed via:

  env   DYNAMO_CHAOS="kill_worker:p=0.5:after=3,delay:t=0.05"
  CLI   dynamo-tpu run ... --chaos "stall_stream:t=30"
  HTTP  POST /chaos on the worker system server (tools/chaos.py arms a
        running deployment without restarts)

Points (all injected into the remote-engine serving path, i.e. the worker
side of the push-RPC plane — exactly where a real worker death manifests):

  kill_worker    after ``after`` outputs, die mid-stream: the connection
                 drops with no done-frame, the client sees transport loss
                 (EndpointConnectionError) and the router migrates
  stall_stream   after ``after`` outputs, hang for ``t`` seconds
                 (wedged-device shape; no error raised)
  drop_response  silently swallow one output (lossy-worker shape — for
                 testing loss DETECTION; migration can't repair in-band
                 loss)
  delay          sleep ``t`` seconds before each output (slow worker)
  storm          synthetic overload: refuse the request AT STREAM START
                 with the retriable EngineOverloadedError (``t`` is the
                 Retry-After hint) — exercises the whole 429/spill/
                 backpressure machinery without generating real load

KV data-integrity points (kv_integrity.py plane — all three corrupt
*copies* of KV bytes in flight, never a pool, so detection-and-recompute
is the only way back to correct tokens):

  flip_kv_bits   flip one random bit per fired page in a tier gather's
                 output (G2/G3 onboard path) — silent DRAM/disk rot
  corrupt_frame  flip one byte of an outgoing kv_transfer payload frame
                 (on a copy; the sender's pool stays clean) — wire/DMA
                 corruption, caught by the receiver's kv_crc verify
  truncate_g3    zero the tail half of the G3 pool before a gather —
                 lost/torn disk writes (a live ftruncate would SIGBUS
                 through the active mmap)
  corrupt_prefetch  rot one byte of a fleet-PREFETCHED page after it
                 lands in the host tier (post-crc-seal, in the pool:
                 _PageTier.rot_page) — proves a bad prefetched block is
                 quarantined at onboard verify instead of serving
                 divergent tokens

Control-plane points (runtime/store.py serving loop — the store process
itself as the fault domain):

  kill_store       on the next store op, crash the store server: stop
                   accepting, hard-abort every live client connection
                   (RST, not FIN), kill the sweeper — a store process
                   death; clients must resync via StoreSession
  partition_store  hold every reply for ``t`` seconds: the TCP conn stays
                   up but the store goes silent (network partition shape;
                   no error raised)

Entry grammar: comma-separated ``name[:key=value]*`` with keys
``p`` (probability, default 1), ``t`` (seconds), ``after`` (output count).
"""
from __future__ import annotations

import asyncio
import logging
import random
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Optional

import numpy as np

from dynamo_tpu.resilience.metrics import RESILIENCE

log = logging.getLogger(__name__)

POINT_NAMES = ("kill_worker", "stall_stream", "drop_response", "delay",
               "storm", "flip_kv_bits", "corrupt_frame", "truncate_g3",
               "corrupt_prefetch", "kill_store", "partition_store")


class ChaosInjectedError(ConnectionResetError):
    """The kill_worker fault: raised inside the worker's stream handler so
    the endpoint server drops the connection without a done-frame —
    indistinguishable from a real worker death to the client."""


@dataclass
class ChaosPoint:
    name: str
    armed: bool = False
    probability: float = 1.0
    delay_s: float = 0.0
    after_outputs: int = 0
    # one-shot fuse: disarm after the first injection (deterministic tests)
    once: bool = False
    injected_total: int = 0

    def to_dict(self) -> dict[str, Any]:
        return {
            "name": self.name, "armed": self.armed,
            "probability": self.probability, "delay_s": self.delay_s,
            "after_outputs": self.after_outputs, "once": self.once,
            "injected_total": self.injected_total,
        }


class ChaosHooks:
    """The injection-point registry + the stream wrapper applying it."""

    def __init__(self, rng: Optional[random.Random] = None):
        self.points: dict[str, ChaosPoint] = {
            name: ChaosPoint(name) for name in POINT_NAMES
        }
        self.rng = rng or random.Random()

    # ---- arming ----

    def arm(self, name: str, *, probability: float = 1.0,
            delay_s: float = 0.0, after_outputs: int = 0,
            once: bool = False) -> ChaosPoint:
        p = self.points[name]
        p.armed = True
        p.probability = probability
        p.delay_s = delay_s
        p.after_outputs = after_outputs
        p.once = once
        log.warning("chaos point armed: %s", p.to_dict())
        return p

    def disarm(self, name: str) -> None:
        self.points[name].armed = False

    def disarm_all(self) -> None:
        for p in self.points.values():
            p.armed = False

    def reset(self) -> None:
        """Disarm everything and zero the injection counters (tests)."""
        for name in list(self.points):
            self.points[name] = ChaosPoint(name)

    def list_points(self) -> list[dict[str, Any]]:
        return [p.to_dict() for p in self.points.values()]

    def configure(self, spec: str) -> None:
        """Parse the env/CLI grammar and arm the named points."""
        for entry in (spec or "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            fields = entry.split(":")
            name = fields[0].strip()
            if name not in self.points:
                raise ValueError(
                    f"unknown chaos point {name!r} (have {POINT_NAMES})"
                )
            kw: dict[str, Any] = {}
            for f in fields[1:]:
                k, _, v = f.partition("=")
                k = k.strip()
                if k == "p":
                    kw["probability"] = float(v)
                elif k == "t":
                    kw["delay_s"] = float(v)
                elif k == "after":
                    kw["after_outputs"] = int(v)
                elif k == "once":
                    kw["once"] = v.strip().lower() in ("1", "true", "yes", "")
                else:
                    raise ValueError(f"unknown chaos key {k!r} in {entry!r}")
            self.arm(name, **kw)

    def any_armed(self) -> bool:
        return any(p.armed for p in self.points.values())

    # ---- injection ----

    def _record(self, p: ChaosPoint) -> None:
        """Shared injection bookkeeping: counters, one-shot disarm, log."""
        p.injected_total += 1
        RESILIENCE.inc("dynamo_resilience_chaos_injections_total")
        if p.once:
            p.armed = False
        log.warning("chaos injected: %s (#%d)", p.name, p.injected_total)

    def _fire(self, p: ChaosPoint) -> bool:
        if not p.armed or self.rng.random() >= p.probability:
            return False
        self._record(p)
        return True

    def fire(self, name: str) -> bool:
        """Synchronous one-roll injection check for data-path points
        (truncate_g3): True when the armed point fires this call."""
        p = self.points.get(name)
        return p is not None and self._fire(p)

    def maybe_flip_bits(self, arr) -> int:
        """flip_kv_bits: per page of a gathered KV batch ``[2, L, kvh,
        n, ps, hd]`` (a contiguous copy, never a pool), roll the point's
        probability and flip one random bit. Returns pages flipped."""
        p = self.points.get("flip_kv_bits")
        if p is None or not p.armed or arr is None:
            return 0
        u8 = np.ascontiguousarray(arr).view(np.uint8)
        flipped = 0
        for i in range(arr.shape[3]):
            if not p.armed or self.rng.random() >= p.probability:
                continue
            idx = tuple(
                self.rng.randrange(d) if ax != 3 else i
                for ax, d in enumerate(u8.shape)
            )
            u8[idx] ^= 1 << self.rng.randrange(8)
            self._record(p)
            flipped += 1
        if flipped and not np.may_share_memory(u8, arr):
            # ascontiguousarray copied (non-contiguous input): write the
            # damage back so the caller's array actually carries it
            arr[...] = u8.view(arr.dtype).reshape(arr.shape)
        return flipped

    def maybe_corrupt_frame(self, payload: np.ndarray) -> np.ndarray:
        """corrupt_frame: flip one byte of an outgoing wire payload on a
        COPY (zero-copy sends alias live pools; chaos must corrupt the
        wire, not the sender's cache). Returns the array to transmit."""
        p = self.points.get("corrupt_frame")
        if p is None or not self._fire(p) or payload.size == 0:
            return payload
        dirty = np.ascontiguousarray(payload).copy()
        u8 = dirty.view(np.uint8).reshape(-1)
        u8[self.rng.randrange(u8.size)] ^= 1 << self.rng.randrange(8)
        return dirty

    async def maybe_stall(self, name: str, n_outputs: int) -> bool:
        """Public injection hook for non-stream data paths (the disagg
        chunk push): fire `name` once its after_outputs threshold is
        reached and the probability roll passes, sleeping the point's
        delay_s. Returns True when an injection fired."""
        p = self.points.get(name)
        if p is None or not p.armed or n_outputs < p.after_outputs:
            return False
        if not self._fire(p):
            return False
        await asyncio.sleep(p.delay_s)
        return True

    async def wrap_stream(
        self, stream: AsyncIterator[Any]
    ) -> AsyncIterator[Any]:
        """Apply armed points to one response stream (worker side)."""
        storm = self.points["storm"]
        if storm.armed and self._fire(storm):
            # synthetic overload: bounce BEFORE any output, exactly like
            # a full admission queue would — retriable, with the point's
            # delay as the Retry-After hint
            from dynamo_tpu.overload.errors import EngineOverloadedError

            raise EngineOverloadedError(
                "chaos: storm (synthetic overload)",
                retry_after_s=storm.delay_s or 1.0,
            )
        n = 0
        kill = self.points["kill_worker"]
        stall = self.points["stall_stream"]
        drop = self.points["drop_response"]
        delay = self.points["delay"]
        # per-stream trigger decisions are made once at stream start so a
        # p=0.5 kill doesn't re-roll on every output
        do_kill = kill.armed and self.rng.random() < kill.probability
        do_stall = stall.armed and self.rng.random() < stall.probability
        async for item in stream:
            # re-check armed at injection time: the per-stream trigger is
            # latched at stream start, but a once-fused point disarmed by
            # a CONCURRENT stream's injection must not fire again
            if do_kill and kill.armed and n >= kill.after_outputs:
                self._record(kill)
                raise ChaosInjectedError("chaos: worker killed mid-stream")
            if do_stall and stall.armed and n >= stall.after_outputs:
                self._record(stall)
                do_stall = False  # stall once per stream
                await asyncio.sleep(stall.delay_s)
            if delay.armed and self._fire(delay):
                await asyncio.sleep(delay.delay_s)
            n += 1
            if drop.armed and self._fire(drop):
                continue
            yield item


# process-wide hooks: the worker serving path consults this instance; the
# system server's /chaos control and the env/CLI config mutate it
CHAOS = ChaosHooks()
