"""Worker health/lease plane: heartbeats from the metrics stream + one
circuit breaker per worker.

The distributed runtime already has hard liveness (registration keys die
with the worker's store lease). This tracker adds the SOFT layer routers
need *between* lease expiries: every ForwardPassMetrics publication is a
heartbeat (the metrics plane ticks every engine round, far faster than
the lease TTL), and per-worker breakers trip a worker out of routing
after consecutive request failures — a worker can be lease-alive yet
unable to serve (wedged device, chaos-injected stalls), and waiting for
the lease to expire would feed it traffic the whole time.
"""
from __future__ import annotations

import logging
import time
from typing import Callable, Iterable, Optional

from dynamo_tpu.resilience.metrics import RESILIENCE
from dynamo_tpu.resilience.policy import BreakerState, CircuitBreaker

log = logging.getLogger(__name__)


class WorkerHealthTracker:
    """Per-worker breaker + last-heartbeat table.

    ``heartbeat_ttl_s`` only applies to workers that have heartbeated at
    least once: a fleet without a wired metrics stream (unit tests,
    embedded local engines) stays fully routable on breaker state alone.
    """

    def __init__(
        self,
        *,
        failure_threshold: int = 3,
        reset_timeout_s: float = 5.0,
        heartbeat_ttl_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.heartbeat_ttl_s = heartbeat_ttl_s
        self.clock = clock
        self._breakers: dict[str, CircuitBreaker] = {}
        self._last_seen: dict[str, float] = {}
        # cross-frontend breaker sharing (resilience/shared.py): trips
        # observed ELSEWHERE block routing here until their window ends;
        # local trips/closes fire the hook so a board can publish them.
        # Remote state is advisory only — it never feeds the LOCAL
        # breaker's failure counts (a remote frontend's view of a worker
        # is not this frontend's evidence).
        self._remote_open: dict[str, float] = {}   # wid -> blocked until
        self.on_state_change: Optional[
            Callable[[str, str, float], None]
        ] = None    # (worker_id, "open"|"closed", window_s)
        # control-plane degraded mode (StoreSession listener): while
        # frozen, heartbeat staleness never blocks — the metrics stream
        # rides the store, so silence during an outage says nothing about
        # worker health (stale-while-revalidate, not amnesia)
        self._frozen_at: Optional[float] = None

    def breaker(self, worker_id: str) -> CircuitBreaker:
        b = self._breakers.get(worker_id)
        if b is None:
            b = self._breakers[worker_id] = CircuitBreaker(
                self.failure_threshold, self.reset_timeout_s, self.clock
            )
        return b

    # ---- heartbeats (fed by the load-metrics stream) ----

    def heartbeat(self, worker_id: str) -> None:
        self._last_seen[worker_id] = self.clock()

    def observe_metrics(self, m) -> None:
        """Feed one ForwardPassMetrics publication (watcher/exporter tap)."""
        wid = getattr(m, "worker_id", "") or ""
        if wid:
            self.heartbeat(wid)

    def stale(self, worker_id: str) -> bool:
        if self.heartbeat_ttl_s is None or self._frozen_at is not None:
            return False
        seen = self._last_seen.get(worker_id)
        if seen is None:
            return False  # never heartbeated: no signal, not stale
        return self.clock() - seen > self.heartbeat_ttl_s

    # ---- control-plane degraded mode ----

    def freeze(self) -> None:
        """Store unreachable: hold the last-known picture. Breakers keep
        working off live request outcomes; only heartbeat-staleness (a
        store-derived signal) is suspended."""
        if self._frozen_at is None:
            self._frozen_at = self.clock()
            log.warning("health view frozen (control plane degraded)")

    def thaw(self) -> None:
        """Store back: give every known worker one full heartbeat TTL to
        resume publishing before staleness can block it again."""
        if self._frozen_at is None:
            return
        now = self.clock()
        for wid in self._last_seen:
            self._last_seen[wid] = now
        self._frozen_at = None
        log.info("health view thawed (control plane resynced)")

    # ---- routing decisions ----

    def blocked(self, worker_ids: Iterable[str]) -> set[str]:
        """Workers that must NOT receive traffic right now. Side-effect
        free (peek_allow): the half-open probe grant is consumed only by
        ``on_routed`` for the worker actually dispatched to — consuming
        it here would starve a recovered worker whenever the scheduler
        picked someone else for that decision."""
        out = set()
        now = self.clock()
        for wid in worker_ids:
            if self.stale(wid):
                out.add(wid)
                continue
            until = self._remote_open.get(wid)
            if until is not None:
                if until > now:
                    out.add(wid)
                    continue
                del self._remote_open[wid]   # window over: probe freely
            b = self._breakers.get(wid)
            if b is not None and not b.peek_allow():
                out.add(wid)
        self._export_open_gauge()
        return out

    def on_routed(self, worker_id: str) -> None:
        """A request is being dispatched to this worker: if its breaker
        is not CLOSED, this dispatch IS the half-open probe."""
        b = self._breakers.get(worker_id)
        if b is not None and b.state is not BreakerState.CLOSED:
            b.begin_probe()
            self._export_open_gauge()

    def record_success(self, worker_id: str) -> None:
        b = self._breakers.get(worker_id)
        if b is not None:
            was_open = b.state is not BreakerState.CLOSED
            b.record_success()
            if was_open and b.state is BreakerState.CLOSED:
                # probe succeeded: lift any remote block too and tell
                # sibling frontends the worker recovered
                self._remote_open.pop(worker_id, None)
                self._fire(worker_id, "closed", 0.0)
            self._export_open_gauge()

    def record_failure(self, worker_id: str) -> None:
        b = self.breaker(worker_id)
        trips_before = b.trips
        b.record_failure()
        if b.trips > trips_before:
            self._fire(worker_id, "open", self.reset_timeout_s)
        self._export_open_gauge()

    # ---- cross-frontend sharing (resilience/shared.py) ----

    def note_remote_open(self, worker_id: str, window_s: float) -> None:
        """A sibling frontend's breaker tripped for this worker: block
        routing here for the remainder of its reset window."""
        if window_s <= 0:
            return
        self._remote_open[worker_id] = self.clock() + window_s
        self._export_open_gauge()

    def clear_remote_open(self, worker_id: str) -> None:
        self._remote_open.pop(worker_id, None)

    def _fire(self, worker_id: str, state: str, window_s: float) -> None:
        if self.on_state_change is None:
            return
        try:
            self.on_state_change(worker_id, state, window_s)
        except Exception:  # noqa: BLE001 — publishing is best-effort
            log.warning("breaker state-change publish failed for %s",
                        worker_id, exc_info=True)

    def forget(self, worker_id: str) -> None:
        """Worker left the fleet: drop its breaker + lease state."""
        self._breakers.pop(worker_id, None)
        self._last_seen.pop(worker_id, None)
        self._remote_open.pop(worker_id, None)
        self._export_open_gauge()

    def states(self) -> dict[str, str]:
        return {w: b.state.value for w, b in self._breakers.items()}

    def _export_open_gauge(self) -> None:
        RESILIENCE.set(
            "dynamo_resilience_breaker_open",
            sum(1 for b in self._breakers.values()
                if b.state is not BreakerState.CLOSED),
        )
