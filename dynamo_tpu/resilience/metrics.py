"""Resilience counters: one process-wide registry, three scrape surfaces.

Migration, breaker, drain, retry and chaos events increment counters here;
the frontend ``/metrics``, the per-worker system server and the
aggregating exporter all append ``render()``'s Prometheus text to their
output, so the series exist on every surface (zero-valued where the event
class can't occur in that process). Every family carries HELP/TYPE and is
documented in README's Observability section — the metrics-contract test
enforces both.
"""
from __future__ import annotations

from dynamo_tpu.telemetry.metrics import CounterRegistry

# (name, type, help) — the fixed family set. Counters follow the
# Prometheus naming contract (`*_total`); gauges are plain names.
FAMILIES: tuple[tuple[str, str, str], ...] = (
    ("dynamo_migration_total", "counter",
     "mid-stream request migrations completed (stream resumed on a new worker)"),
    ("dynamo_migration_failed_total", "counter",
     "mid-stream migrations that found no healthy worker or failed replay"),
    ("dynamo_migration_replayed_tokens_total", "counter",
     "emitted tokens replayed as prefill context during migrations"),
    ("dynamo_resilience_reroute_total", "counter",
     "pre-first-token re-routes after an unreachable worker"),
    ("dynamo_resilience_breaker_trips_total", "counter",
     "circuit breakers tripped open (consecutive-failure threshold hit)"),
    ("dynamo_resilience_breaker_open", "gauge",
     "workers currently tripped out of routing (breaker OPEN or HALF_OPEN)"),
    ("dynamo_resilience_retries_total", "counter",
     "retry attempts made under a RetryPolicy (backoff sleeps taken)"),
    ("dynamo_resilience_chaos_injections_total", "counter",
     "chaos faults injected by armed injection points"),
    ("dynamo_resilience_draining", "gauge",
     "1 while this process is draining (stop admitting, finish in-flight)"),
    ("dynamo_resilience_drains_total", "counter",
     "graceful drains completed by this process"),
)

# kept as a name for importers; the machinery lives in CounterRegistry
ResilienceMetrics = CounterRegistry

# process-wide registry: router, frontend, drain controller, chaos hooks
# and retry policies in one process share it (parity with telemetry.TRACES)
RESILIENCE = CounterRegistry(FAMILIES, label="resilience")
