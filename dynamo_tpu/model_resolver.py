"""Model source resolution (reference lib/llm/src/local_model.rs:39
LocalModelBuilder: path-or-HF-hub-id -> local model directory).

Resolution order for a ``--model-path`` value:
  1. an existing local directory — used as-is;
  2. a GGUF file — returned with kind="gguf" (metadata/tokenizer via
     dynamo_tpu.gguf);
  3. an HF hub id (org/name) already present in the local HF cache
     (HF_HOME / HF_HUB_CACHE snapshot layout) — the newest snapshot dir;
  4. otherwise: a clear error. Serving hosts run with zero egress, so
     unlike the reference we never download — the cache must be
     pre-populated (e.g. by `huggingface-cli download` on a bastion).
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Optional


@dataclass(frozen=True)
class ResolvedModel:
    path: str
    kind: str  # "dir" | "gguf"


# Aliasing fix: two specs naming the SAME on-disk model (a symlinked
# variant directory, a trailing-slash or relative spelling, a hub id
# whose snapshot another spec already resolved) used to come back as
# DIFFERENT ResolvedModel paths — and everything downstream that keys on
# the path (weight loads, model cards, engine registries) duplicated the
# work, loading the same checkpoint once per alias. Resolutions are now
# canonicalised by realpath: the first resolution of an on-disk target
# wins, and every alias returns that same shared object.
_CANONICAL: dict[tuple[str, str], ResolvedModel] = {}


def resolver_cache_clear() -> None:
    """Drop canonical resolutions (tests re-point HF cache env vars)."""
    _CANONICAL.clear()


def _canonical(rm: ResolvedModel) -> ResolvedModel:
    key = (os.path.realpath(rm.path), rm.kind)
    return _CANONICAL.setdefault(key, rm)


def _hub_cache_dirs() -> list[str]:
    roots = []
    if os.environ.get("HF_HUB_CACHE"):
        roots.append(os.environ["HF_HUB_CACHE"])
    hf_home = os.environ.get(
        "HF_HOME", os.path.join(os.path.expanduser("~"), ".cache",
                                "huggingface")
    )
    roots.append(os.path.join(hf_home, "hub"))
    return roots


def _cached_snapshot(repo_id: str) -> Optional[str]:
    """Newest locally-cached snapshot dir for an HF repo id."""
    safe = "models--" + repo_id.replace("/", "--")
    for root in _hub_cache_dirs():
        snap_root = os.path.join(root, safe, "snapshots")
        if not os.path.isdir(snap_root):
            continue
        snaps = [
            os.path.join(snap_root, s) for s in os.listdir(snap_root)
            if os.path.isdir(os.path.join(snap_root, s))
        ]
        if snaps:
            return max(snaps, key=os.path.getmtime)
    return None


def resolve_model(spec: str) -> ResolvedModel:
    """Resolve a model spec to a local path (never downloads)."""
    if os.path.isdir(spec):
        return _canonical(ResolvedModel(path=spec, kind="dir"))
    if os.path.isfile(spec) and spec.endswith(".gguf"):
        return _canonical(ResolvedModel(path=spec, kind="gguf"))
    looks_like_hub_id = (
        spec.count("/") == 1 and not spec.startswith(("/", ".", "~"))
    )
    if looks_like_hub_id and not os.path.exists(spec):
        snap = _cached_snapshot(spec)
        if snap is not None:
            return _canonical(ResolvedModel(path=snap, kind="dir"))
        raise FileNotFoundError(
            f"model {spec!r} is not a local path and is not in the HF "
            f"cache ({', '.join(_hub_cache_dirs())}). Serving hosts have "
            "no egress: pre-populate the cache (huggingface-cli download "
            f"{spec}) or pass a local directory."
        )
    raise FileNotFoundError(
        f"model path {spec!r} does not exist (expected a local HF model "
        "directory, a .gguf file, or a cached hub id like org/name)"
    )
