"""Standalone KV-router component (reference components/router — the
dynamo-router binary, src/main.rs:53-77): a routing service OTHER
processes query, instead of routing embedded in the frontend.

It watches a component's worker instances, feeds its KvRouter from the
``kv_events`` pub/sub plane (events filtered to the watched fleet; a
departed worker's blocks leave the indexer), and serves a ``find_best``
endpoint on the runtime: ``{token_ids, request_id?, salt?} ->
{worker_id, overlap_blocks, request_id}``. Callers (custom frontends,
gateways, schedulers) direct-route to the chosen worker themselves and
SHOULD send ``{"op": "free", "request_id": ...}`` on completion so the
predicted-load estimate stays honest; unfreed requests are swept after
``request_ttl_s`` as a backstop. The reference's router-as-a-service
deployment shape (one component per router instance).
"""
from __future__ import annotations

import asyncio
import json
import logging
import time
import uuid
from collections import deque
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.kv_router.protocols import KvCacheEvent
from dynamo_tpu.kv_router.router import KvRouter
from dynamo_tpu.kv_router.scheduler import KvRouterConfig
from dynamo_tpu.runtime.publisher import KV_EVENTS_TOPIC

log = logging.getLogger(__name__)


class RouterService:
    """Routing-as-a-service over the distributed runtime."""

    def __init__(
        self,
        rt: Any,
        namespace: str = "dynamo",
        component: str = "backend",
        endpoint: str = "generate",
        block_size: int = 64,
        router_config: Optional[KvRouterConfig] = None,
        worker_id: str = "router-0",
        request_ttl_s: float = 600.0,
    ):
        self.rt = rt
        self.namespace = namespace
        self.component = component
        self.endpoint = endpoint
        self.router = KvRouter(block_size, router_config)
        self.worker_id = worker_id
        self.request_ttl_s = request_ttl_s
        self.requests_routed = 0
        self._client = None
        self._served = None
        self._sub_task: Optional[asyncio.Task] = None
        self._sweep_task: Optional[asyncio.Task] = None
        self._fleet: set[str] = set()
        # routed-request ages for the TTL backstop sweep
        self._routed: dict[str, float] = {}
        # events racing discovery wait here and replay on fleet change
        self._deferred: deque = deque(maxlen=256)

    async def start(self) -> "RouterService":
        # watch the worker fleet
        self._client = await self.rt.namespace(self.namespace).component(
            self.component
        ).endpoint(self.endpoint).client()
        self._client.on_change = lambda instances: self._sync_fleet(
            {str(i.id) for i in instances}
        )
        self._sync_fleet(
            {str(i.id) for i in self._client.instances.values()}
        )
        # follow the KV-event plane (all workers of the watched component);
        # supervised — routing quality decays silently if this loop dies
        # (reference utils/task.rs:42)
        from dynamo_tpu.runtime.tasks import CriticalTask

        sub = await self.rt.kv.subscribe(f"{KV_EVENTS_TOPIC}.>")
        self._sub_task = CriticalTask(
            lambda: self._follow(sub), "router-kv-events"
        ).start()
        # serve find_best
        ep = self.rt.namespace(self.namespace).component(
            f"{self.component}-router"
        ).endpoint("find_best")
        self._served = await ep.serve(self._handle, worker_id=self.worker_id)
        self._sweep_task = CriticalTask(
            self._sweep_loop, "router-ttl-sweep"
        ).start()
        return self

    def _sync_fleet(self, fleet: set[str]) -> None:
        """Apply fleet membership: departed workers leave the indexer
        (their blocks died with them — watcher.py does the same), arrivals
        get racing events replayed."""
        for wid in self._fleet - fleet:
            self.router.indexer.remove_worker(wid)
        grew = bool(fleet - self._fleet)
        self._fleet = fleet
        self.router.update_workers(sorted(fleet))
        if grew and self._deferred:
            deferred, self._deferred = list(self._deferred), deque(maxlen=256)
            for event in deferred:
                self._apply_event(event)

    def _apply_event(self, event: KvCacheEvent) -> None:
        if event.worker_id in self._fleet:
            self.router.indexer.apply_event(event)
        else:
            # unknown worker: either foreign (dropped at replay too once
            # it never joins) or racing discovery (replayed on join)
            self._deferred.append(event)

    async def _sweep_loop(self) -> None:
        """TTL backstop: callers that never send free must not inflate
        predicted load forever."""
        while True:
            await asyncio.sleep(min(self.request_ttl_s / 4, 30.0))
            cutoff = time.monotonic() - self.request_ttl_s
            for rid, t in list(self._routed.items()):
                if t < cutoff:
                    self._routed.pop(rid, None)
                    self.router.free(rid)

    async def _follow(self, sub) -> None:
        async for ev in sub:
            try:
                event = KvCacheEvent.from_dict(json.loads(ev["value"]))
            except (KeyError, ValueError, TypeError):
                continue
            self._apply_event(event)

    async def _handle(self, payload: dict) -> AsyncIterator[dict]:
        if payload.get("op") == "free":
            rid = payload.get("request_id", "")
            self._routed.pop(rid, None)
            self.router.free(rid)
            yield {"freed": rid}
            return
        tokens = payload.get("token_ids") or []
        rid = payload.get("request_id") or uuid.uuid4().hex
        worker_id, overlap = self.router.find_best_match(
            rid, tokens, salt=payload.get("salt", "")
        )
        self._routed[rid] = time.monotonic()
        self.requests_routed += 1
        yield {"worker_id": worker_id, "overlap_blocks": overlap,
               "request_id": rid}

    async def stop(self) -> None:
        for t in (self._sub_task, self._sweep_task):
            if t is not None:
                await t.stop()
        self._sub_task = self._sweep_task = None
        if self._served is not None:
            await self._served.shutdown()
            self._served = None
        if self._client is not None:
            await self._client.stop()
            self._client = None


async def run_router(args) -> None:
    """CLI entry: `dynamo-tpu router` (the dynamo-router binary shape)."""
    from dynamo_tpu.runtime.component import DistributedRuntime

    host, _, port = args.control_plane.partition(":")
    rt = await DistributedRuntime.connect(
        host=host or "127.0.0.1", port=int(port or 7111)
    )
    svc = await RouterService(
        rt,
        namespace=args.namespace,
        component=args.component,
        endpoint=args.endpoint_name,
        block_size=args.block_size,
        router_config=KvRouterConfig(
            router_temperature=args.router_temperature
        ),
    ).start()
    print(f"router serving {args.namespace}/{args.component}-router/"
          f"find_best (watching {args.component}/{args.endpoint_name})")
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await svc.stop()
        await rt.close()
