"""Synthetic request-trace generator + analyzer (mooncake-style traces).

Parity: reference benchmarks/data_generator — synthesizes mooncake-format
traces (timestamp, input/output lengths, hash_ids encoding shared-prefix
structure) for router/cache benchmarking, and analyzes real traces for
the statistics the synthesizer mimics.

Trace record (JSONL, mooncake-compatible field names):
    {"timestamp": ms, "input_length": n, "output_length": m,
     "hash_ids": [...]}   # block ids; shared prefix == shared leading ids

The generator models multi-turn sessions: a session's turn t reuses the
full token history of turns < t (the prefix-sharing pattern KV routing
and the G2 offload tier exploit), with Poisson arrivals.
"""
from __future__ import annotations

import json
import math
from dataclasses import dataclass
from typing import Any, Iterator, Optional

import numpy as np


@dataclass
class TraceConfig:
    num_requests: int = 100
    request_rate_per_s: float = 2.0       # Poisson arrival rate
    isl_mean: int = 256                   # fresh input tokens per turn
    isl_cv: float = 0.5                   # coefficient of variation
    osl_mean: int = 128
    osl_cv: float = 0.5
    block_size: int = 64                  # tokens per hash id
    num_sessions: int = 20                # concurrent conversations
    turns_mean: float = 4.0               # mean turns per session
    seed: int = 0


def synthesize(cfg: TraceConfig) -> list[dict[str, Any]]:
    """Generate a trace; records are sorted by timestamp."""
    rng = np.random.RandomState(cfg.seed)
    next_hash = [1]

    def fresh_blocks(n_tokens: int) -> list[int]:
        n = max(1, math.ceil(n_tokens / cfg.block_size))
        ids = list(range(next_hash[0], next_hash[0] + n))
        next_hash[0] += n
        return ids

    def lognorm(mean: float, cv: float) -> int:
        sigma = math.sqrt(math.log(1 + cv * cv))
        mu = math.log(max(mean, 1)) - sigma * sigma / 2
        return max(1, int(rng.lognormal(mu, sigma)))

    sessions = [
        {"history": [], "hist_tokens": 0}
        for _ in range(max(1, cfg.num_sessions))
    ]
    records: list[dict[str, Any]] = []
    t_ms = 0.0
    for _ in range(cfg.num_requests):
        t_ms += rng.exponential(1000.0 / cfg.request_rate_per_s)
        s = sessions[rng.randint(len(sessions))]
        # session reset models a finished conversation
        if s["history"] and rng.random() < 1.0 / max(cfg.turns_mean, 1.0):
            s["history"] = []
            s["hist_tokens"] = 0
        new_in = lognorm(cfg.isl_mean, cfg.isl_cv)
        out = lognorm(cfg.osl_mean, cfg.osl_cv)
        hash_ids = list(s["history"]) + fresh_blocks(new_in)
        records.append({
            "timestamp": int(t_ms),
            "input_length": s["hist_tokens"] + new_in,
            "output_length": out,
            "hash_ids": hash_ids,
        })
        # next turn's history includes this turn's input AND output
        s["history"] = hash_ids + fresh_blocks(out)
        s["hist_tokens"] += new_in + out
    return records


def write_trace(records: list[dict[str, Any]], path: str) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for r in records:
            f.write(json.dumps(r, separators=(",", ":")) + "\n")


def read_trace(path: str) -> Iterator[dict[str, Any]]:
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


def analyze(records: list[dict[str, Any]]) -> dict[str, Any]:
    """Trace statistics (the reference analyzer's core numbers): length
    distributions, arrival rate, and the theoretical cache-hit ratio — the
    fraction of input blocks already seen earlier in the trace."""
    if not records:
        return {"num_requests": 0}
    isl = np.array([r["input_length"] for r in records])
    osl = np.array([r["output_length"] for r in records])
    ts = np.array([r["timestamp"] for r in records], dtype=np.float64)
    seen: set[int] = set()
    total_blocks = 0
    reused_blocks = 0
    for r in records:
        for h in r.get("hash_ids", []):
            total_blocks += 1
            if h in seen:
                reused_blocks += 1
            else:
                seen.add(h)
    span_s = max((ts.max() - ts.min()) / 1000.0, 1e-9)
    return {
        "num_requests": len(records),
        "isl_mean": float(isl.mean()),
        "isl_p95": float(np.percentile(isl, 95)),
        "osl_mean": float(osl.mean()),
        "osl_p95": float(np.percentile(osl, 95)),
        "request_rate_per_s": (len(records) - 1) / span_s,
        "prefix_reuse_ratio": reused_blocks / max(total_blocks, 1),
        "unique_blocks": len(seen),
    }


def run_datagen(args) -> None:
    if args.analyze:
        stats = analyze(list(read_trace(args.analyze)))
        print(json.dumps(stats, indent=1))
        return
    cfg = TraceConfig(
        num_requests=args.num,
        request_rate_per_s=args.rate,
        isl_mean=args.isl, osl_mean=args.osl,
        block_size=args.block_size,
        num_sessions=args.sessions,
        turns_mean=args.turns,
        seed=args.seed,
    )
    records = synthesize(cfg)
    write_trace(records, args.output)
    print(f"wrote {len(records)} records to {args.output}")
    print(json.dumps(analyze(records), indent=1))
