"""`dynamo-tpu run in=<input> out=<engine>` launcher.

Mirrors the reference `dynamo-run` CLI (launch/dynamo-run/src/lib.rs:94-165,
flags.rs): pick an input plane (http | text | stdin | batch:<file>) and an
engine (echo | mocker | tpu), wire the chain, run it.

Inputs (reference entrypoint/input.rs:29-45):
  in=http        OpenAI HTTP frontend on --http-port
  in=text        one-shot prompt from --prompt (or interactive REPL)
  in=stdin       read prompts line-by-line from stdin
  in=batch:FILE  JSONL of {"prompt": ...}; writes completions JSONL to stdout

Engines:
  out=echo       deterministic token echo (tests/smoke)
  out=mocker     simulated paged-KV engine (CPU, timing-faithful)
  out=tpu        the JAX TPU engine (requires --model-path or canned config)
"""
from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from typing import Any, Optional


def build_parser() -> argparse.ArgumentParser:
    # layered defaults: dataclass <- TOML <- DYNTPU_* env <- CLI flags
    # (reference figment layering, config.rs:103-127)
    from dynamo_tpu.config import load_config

    cfg = load_config()
    p = argparse.ArgumentParser(
        prog="dynamo-tpu run",
        description="Run a dynamo-tpu serving graph",
    )
    p.add_argument("io", nargs="*", help="in=<http|text|stdin|batch:FILE> out=<echo|mocker|tpu>")
    p.add_argument("--model-path", help="local HF model dir (config/tokenizer/safetensors)")
    p.add_argument("--model-name", default=None, help="served model name")
    p.add_argument("--model-config", default=None,
                   help="canned config (tiny|llama3_1b|llama3_8b|"
                        "llama3_8b_int8|llama3_70b) for random-weight "
                        "serving")
    p.add_argument("--quantize", default=None, choices=["int8"],
                   help="weight quantization (w8a16 int8): quantizes "
                        "loaded checkpoints per-output-channel; an 8B "
                        "checkpoint on a 16 GB v5e requires it")
    p.add_argument("--http-host", default=cfg.http_host)
    p.add_argument("--http-port", type=int, default=cfg.http_port)
    p.add_argument("--prompt", default=None, help="prompt for in=text")
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--trace-speedup", type=float, default=0.0,
                   help="in=batch with a mooncake trace: replay arrival "
                        "timestamps at this speed multiple (0 = ignore "
                        "timestamps, submit all at once)")
    p.add_argument("--trace-block-size", type=int, default=64,
                   help="tokens represented by one trace hash id (must "
                        "match the datagen --block-size for the trace's "
                        "prefix sharing to replay faithfully)")
    p.add_argument("--tensor-parallel-size", type=int, default=1)
    p.add_argument("--num-pages", type=int, default=cfg.num_pages)
    p.add_argument("--page-size", type=int, default=cfg.page_size)
    p.add_argument("--max-decode-slots", type=int,
                   default=cfg.max_decode_slots)
    p.add_argument("--cache-dtype", default=cfg.cache_dtype)
    p.add_argument("--kv-quant", default=cfg.kv_quant,
                   choices=["none", "int8"],
                   help="KV quantization: int8 pool pages AND int8 "
                        "decode ctx with per-group scales — the "
                        "flash-decode kernel dequantizes each chunk "
                        "in VMEM, halving live-context HBM traffic, "
                        "pool residency, tier footprint and transfer "
                        "bytes; the write ring stays --cache-dtype")
    p.add_argument("--host-offload-pages", type=int,
                   default=cfg.host_offload_pages,
                   help="host-DRAM KV offload tier capacity in pages "
                        "(KVBM G2); 0 disables")
    p.add_argument("--disk-offload-pages", type=int,
                   default=cfg.disk_offload_pages,
                   help="mmap-backed disk KV tier capacity in pages "
                        "(KVBM G3, spill target of G2); 0 disables")
    p.add_argument("--disk-offload-path", default=cfg.disk_offload_path,
                   help="backing file for the G3 pool "
                        "(default: fresh tempfile); with a path the "
                        "tier journals a sidecar manifest and survives "
                        "engine restarts")
    p.add_argument("--scrub-on-start", action="store_true",
                   default=cfg.scrub_on_start,
                   help="eagerly re-checksum every G3 manifest entry at "
                        "attach, dropping torn/corrupt blocks as misses "
                        "(default: lazy verify at onboard gather)")
    # chunk-pipelined KV transfer plane (kv_transfer.py)
    p.add_argument("--kv-transfer-chunk-pages", type=int,
                   default=cfg.kv_transfer_chunk_pages,
                   help="pages per streamed KV-transfer chunk (disagg "
                        "remote prefill, G4 peer fetch, G2/G3 onboard); "
                        "0 = monolithic single-blob transfers")
    p.add_argument("--kv-transfer-inflight-chunks", type=int,
                   default=cfg.kv_transfer_inflight_chunks,
                   help="chunk gathers/D2H copies in flight per export "
                        "stream (double-buffer depth)")
    p.add_argument("--xfer-op-timeout", type=float,
                   default=cfg.xfer_op_timeout_s,
                   help="deadline in seconds for one queued page "
                        "export/import op (raise for multi-GiB chunked "
                        "imports on slow host links)")
    p.add_argument("--kv-transfer-stream-idle-timeout", type=float,
                   default=cfg.kv_transfer_stream_idle_timeout_s,
                   help="idle-timeout in seconds reclaiming a chunked "
                        "export stream whose receiver stalled (pinned "
                        "gather handles/page refs freed)")
    # overload plane (dynamo_tpu/overload/)
    p.add_argument("--max-waiting-requests", type=int,
                   default=cfg.max_waiting_requests,
                   help="bounded admission: waiting-queue depth budget; "
                        "intake past it is refused with a retriable "
                        "overload error (HTTP 429 + Retry-After at the "
                        "frontend). 0 = unbounded")
    p.add_argument("--max-waiting-prefill-tokens", type=int,
                   default=cfg.max_waiting_prefill_tokens,
                   help="bounded admission: prompt-token budget over "
                        "the waiting queue. 0 = unbounded")
    p.add_argument("--preempt-running",
                   default="on" if cfg.preempt_running else "off",
                   choices=["on", "off"],
                   help="allow a waiting HIGH-priority request to "
                        "force-migrate the lowest-priority RUNNING "
                        "stream (preemption-as-migration via the "
                        "resilience plane; exactly-once, greedy "
                        "token-identical)")
    p.add_argument("--round-pipeline",
                   default="on" if cfg.round_pipeline else "off",
                   choices=["on", "off"],
                   help="double-buffered round pipelining: dispatch "
                        "round N+1 before blocking on round N's token "
                        "fetch, hiding host bookkeeping under device "
                        "execution; off restores the serialized round "
                        "order (A/B + differential baseline)")
    # performance-attribution plane (telemetry/prof.py)
    p.add_argument("--prof-attribution",
                   default="on" if cfg.prof_attribution else "off",
                   choices=["on", "off"],
                   help="per-round host-segment attribution "
                        "(dynamo_host_round_seconds{segment} + "
                        "/debug/prof); near-zero overhead, off only "
                        "for A/B measurement")
    p.add_argument("--slo-ttft-target", type=float,
                   default=cfg.slo_ttft_target_s,
                   help="TTFT SLO target in seconds backing the "
                        "dynamo_slo_ttft_burn_rate gauge")
    p.add_argument("--slo-itl-target", type=float,
                   default=cfg.slo_itl_target_s,
                   help="ITL SLO target in seconds backing the "
                        "dynamo_slo_itl_burn_rate gauge")
    p.add_argument("--slo-objective", type=float,
                   default=cfg.slo_objective,
                   help="SLO objective (fraction of observations that "
                        "must meet the target, e.g. 0.99); burn rate = "
                        "frac-over-target / (1 - objective)")
    p.add_argument("--forensics-sample-rate", type=float,
                   default=cfg.forensics_sample_rate,
                   help="fraction of NON-breaching requests that still "
                        "get an SLO-breach-style dossier captured into "
                        "/debug/outliers (breaches are always captured; "
                        "0 disables the healthy-baseline sample)")
    # speculative decoding (dynamo_tpu/spec/)
    p.add_argument("--speculative", default=cfg.speculative,
                   choices=["off", "ngram", "draft"],
                   help="speculative decoding: ngram = model-free "
                        "prompt-lookup proposer; draft = small draft "
                        "model sharing the tokenizer (--draft-model-"
                        "config); eligible requests verify K proposed "
                        "tokens per target forward")
    p.add_argument("--num-speculative-tokens", type=int,
                   default=cfg.num_speculative_tokens,
                   help="K: proposed tokens per verify step (the cap "
                        "when --spec-adaptive is on)")
    p.add_argument("--spec-adaptive",
                   default="on" if cfg.spec_adaptive else "off",
                   choices=["on", "off"],
                   help="acceptance-adaptive K: each slot's effective K "
                        "walks within [--spec-min-k, K] on its rolling "
                        "acceptance rate, and slots whose rate collapses "
                        "de-speculate back to the fused decode round "
                        "(exported as dynamo_spec_effective_k)")
    p.add_argument("--spec-min-k", type=int, default=cfg.spec_min_k,
                   help="adaptive-K floor per slot")
    p.add_argument("--spec-tree",
                   default="on" if cfg.spec_tree else "off",
                   choices=["on", "off"],
                   help="tree speculation: draft up to --spec-branches "
                        "candidates per divergence point and verify the "
                        "whole tree in one forward under a tree-causal "
                        "mask; acceptance keeps the deepest surviving "
                        "root-to-leaf path")
    p.add_argument("--spec-branches", type=int, default=cfg.spec_branches,
                   help="branch fan per tree level (the cap when "
                        "--spec-adaptive walks the branches axis)")
    p.add_argument("--spec-tree-budget", type=int,
                   default=cfg.spec_tree_budget,
                   help="packed tree node budget incl. the root (one "
                        "compiled verify shape serves every tree); 0 = "
                        "auto: 1 + K * branches")
    p.add_argument("--spec-gate-acceptance", type=float,
                   default=cfg.spec_gate_acceptance,
                   help="de-speculate a stream whose live acceptance "
                        "EWMA stays below this for --spec-gate-window "
                        "consecutive verify steps (0 = no gate); gated "
                        "streams may re-arm after --spec-rearm-tokens "
                        "emitted tokens")
    p.add_argument("--spec-gate-window", type=int,
                   default=cfg.spec_gate_window,
                   help="consecutive below-gate verify steps before a "
                        "stream de-speculates")
    p.add_argument("--spec-rearm-tokens", type=int,
                   default=cfg.spec_rearm_tokens,
                   help="emitted tokens before a gated stream re-arms "
                        "speculation (doubles each time it re-gates; "
                        "0 = gated streams never re-arm)")
    p.add_argument("--draft-model-config", default=None,
                   help="canned ModelConfig name for the draft model "
                        "(speculative=draft; must share the target "
                        "vocab, e.g. tiny for --model-config tiny). "
                        "Drafting is batched across speculating slots "
                        "into one device program per round")
    # distributed mode (reference: etcd/NATS endpoints; here the dcp store).
    # --control-plane default stays None (it's the discovery-mode switch);
    # RuntimeConfig.control_plane is None unless the config file or
    # DYNTPU_CONTROL_PLANE opted in explicitly.
    p.add_argument("--control-plane", default=cfg.control_plane,
                   metavar="HOST:PORT",
                   help="control-plane store address; enables discovery")
    p.add_argument("--namespace", default=cfg.namespace)
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint-name", default="generate")
    p.add_argument("--router-mode", default="kv",
                   choices=["kv", "round_robin", "random"])
    p.add_argument("--record-kv-events", default=None, metavar="PATH",
                   help="record the frontend's kv_events stream to a JSONL "
                        "file for later replay (reference KvRecorder)")
    p.add_argument("--system-port", type=int, default=None,
                   help="per-process /metrics + /health server port "
                        "(reference http_server.rs); 0 = ephemeral")
    # resilience plane (dynamo_tpu/resilience/)
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="arm fault-injection points on the worker serving "
                        "path, e.g. 'kill_worker:p=0.1:after=3,delay:t=0.05'"
                        " (also via DYNAMO_CHAOS; tools/chaos.py arms a "
                        "running worker over HTTP)")
    p.add_argument("--trace-sample-rate", type=float, default=1.0,
                   help="fraction of requests fully traced by the frontend "
                        "(high-QPS deployments sample; migrated/failed "
                        "requests are always traced)")
    p.add_argument("--health-heartbeat-ttl", type=float, default=None,
                   help="frontend soft-lease TTL in seconds: a worker "
                        "whose load-metrics heartbeats go silent longer "
                        "than this stops receiving traffic before its "
                        "hard store lease expires (engines heartbeat on "
                        "idle ticks too; set well above ~1s). Default: "
                        "breaker-only health tracking")
    p.add_argument("--drain-timeout", type=float, default=60.0,
                   help="graceful-drain budget: in-flight requests get "
                        "this long to finish after SIGTERM or POST /drain "
                        "before the worker exits anyway")
    # multi-host single-engine bootstrap (reference MultiNodeConfig,
    # flags.rs:86-101 + leader_worker_barrier.rs)
    p.add_argument("--num-nodes", type=int, default=1)
    p.add_argument("--node-rank", type=int, default=0)
    p.add_argument("--leader-addr", default=None, metavar="HOST:PORT",
                   help="jax.distributed coordinator address (required on "
                        "the leader when --num-nodes > 1; workers discover "
                        "it via the barrier)")
    # disaggregated prefill/decode (reference flags.rs + disagg_router.rs)
    p.add_argument("--role", default="aggregated",
                   choices=["aggregated", "decode", "prefill"],
                   help="worker role for disaggregated serving")
    p.add_argument("--max-local-prefill-length", type=int, default=None,
                   help="prompts with more uncached tokens go to the "
                        "prefill queue (writes the store-watched conf)")
    p.add_argument("--max-prefill-queue-size", type=int, default=None)
    p.add_argument("--remote-kv", action="store_true",
                   help="KVBM G4: serve this worker's sealed KV pool to "
                        "peers and fall through the local tiers to peer "
                        "pools on prefix misses (requires --control-plane "
                        "and a G2 tier via --host-offload-pages)")
    # fleet prefix economy (kv_router/fleet.py + prefetch.py)
    p.add_argument("--kv-replication-target", type=int,
                   default=cfg.kv_replication_target,
                   help="desired fleet copies of a hot KV block: the "
                        "frontend's replication controller pushes "
                        "under-replicated hot prefix chains into workers' "
                        "G2 tiers ahead of demand and warm-starts cold "
                        "joiners from the fleet hot set (<= 1 disables "
                        "the controller)")
    p.add_argument("--kv-prefetch-hot-k", type=int,
                   default=cfg.kv_prefetch_hot_k,
                   help="hot prefix chains examined per controller tick "
                        "and pushed to a cold joiner")
    p.add_argument("--kv-prefetch-interval", type=float,
                   default=cfg.kv_prefetch_interval_s, metavar="SECONDS",
                   help="replication-controller tick period")
    p.add_argument("--kv-freq-halflife", type=float,
                   default=cfg.kv_freq_halflife_s, metavar="SECONDS",
                   help="KV indexer access-heat decay half-life (0 = raw "
                        "undecayed counters, the legacy behavior)")
    p.add_argument("--no-kv-dedup-admission", action="store_true",
                   help="disable dedup-by-hash admission hints: G4 "
                        "probes ignore the fleet holder digest")
    p.add_argument("--prefill-timeout", type=float, default=60.0,
                   help="decode-side wait for remote prefill before local "
                        "fallback")
    return p


def _parse_io(io: list[str]) -> tuple[str, str]:
    inp, out = "http", "echo"
    for item in io:
        if item.startswith("in="):
            inp = item[3:]
        elif item.startswith("out="):
            out = item[4:]
        else:
            raise SystemExit(f"unrecognized arg {item!r} (expected in=/out=)")
    return inp, out


def multi_host_bootstrap(args) -> None:
    """Bring up a multi-host single engine: rendezvous all nodes on a
    store barrier (leader distributes the jax coordinator address), then
    jax.distributed.initialize so the engine's mesh spans every host's
    chips (reference: LeaderBarrier/WorkerBarrier + vLLM's ray bootstrap).

    Liveness: each node's barrier lease is its group membership; after
    init, a dead node collapses the jax runtime itself, and the leader's
    registration lease (held by _serve_worker) deregisters the engine."""
    import json as _json

    import jax

    # honor JAX_PLATFORMS=cpu even when the axon TPU plugin force-registers
    # itself ahead of it (it rewrites the platform list to "axon,cpu" —
    # with jax.distributed, the spurious extra backend corrupts the
    # coordination-service topology exchange)
    if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
        jax.config.update("jax_platforms", "cpu")

    from dynamo_tpu.runtime.barrier import LeaderBarrier, WorkerBarrier
    from dynamo_tpu.runtime.client import KvClient

    host, port = _cp_addr(args)
    barrier_id = f"engine-{args.namespace}-{args.component}"

    async def rendezvous() -> str:
        kv = await KvClient(host, port).connect()
        try:
            if args.node_rank == 0:
                if not args.leader_addr:
                    raise SystemExit(
                        "--leader-addr required on node-rank 0"
                    )
                import uuid as _uuid

                args._mh_run_id = _uuid.uuid4().hex[:12]
                lb = LeaderBarrier(kv, barrier_id, args.num_nodes - 1)
                await lb.sync(_json.dumps({
                    "coordinator": args.leader_addr,
                    "num_nodes": args.num_nodes,
                    "run_id": args._mh_run_id,
                }))
                await lb.close()
                return args.leader_addr
            wb = WorkerBarrier(kv, barrier_id, f"node-{args.node_rank}")
            data = _json.loads(await wb.sync())
            await wb.close()
            args._mh_run_id = data.get("run_id", "r0")
            if data["num_nodes"] != args.num_nodes:
                raise SystemExit(
                    f"node count mismatch: leader says {data['num_nodes']}, "
                    f"this node was started with {args.num_nodes}"
                )
            return data["coordinator"]
        finally:
            await kv.close()

    coordinator = asyncio.run(rendezvous())
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=args.num_nodes,
        process_id=args.node_rank,
    )
    print(
        f"multi-host engine up: node {args.node_rank}/{args.num_nodes}, "
        f"{jax.device_count()} global devices"
    )


def _crosshost_prologue(args, cfg, ecfg, params):
    """Cross-host single-engine wiring. On rank 0, returns the dispatch
    sink (command broadcaster on its own background loop). On other ranks,
    builds the engine replica, REPLAYS the leader's commands forever, and
    exits the process when the stream stops — followers never serve."""
    import threading

    import jax

    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.engine.multihost import (
        CommandStream,
        Follower,
        make_dispatch_sink,
    )
    from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
    from dynamo_tpu.runtime.client import KvClient

    host, port = _cp_addr(args)
    engine_id = f"{args.component}"
    run_id = getattr(args, "_mh_run_id", "r0")
    mesh = make_mesh(MeshConfig(tp=args.tensor_parallel_size), jax.devices())

    if args.node_rank == 0:
        # dedicated loop thread: the engine thread emits commands without
        # touching the serving loop
        stream_loop = asyncio.new_event_loop()
        threading.Thread(
            target=stream_loop.run_forever, name="mh-cmd-stream", daemon=True
        ).start()
        kv = asyncio.run_coroutine_threadsafe(
            KvClient(host, port).connect(), stream_loop
        ).result(timeout=30)
        stream = CommandStream(
            kv, stream_loop, args.namespace, engine_id, run_id,
            args.num_nodes - 1,
        )
        # leader liveness key: followers exit when it expires
        asyncio.run_coroutine_threadsafe(
            stream.announce(), stream_loop
        ).result(timeout=30)

        def teardown() -> None:
            """Leader-exit discipline: tell followers to stop, then drop
            the liveness lease (close the kv client so its keep-alive
            dies). Without this the leader's atexit jax.distributed
            shutdown barrier waits on followers that are themselves
            waiting on the still-renewed liveness key — a deadlock that
            held the old CLI past test timeouts."""
            from dynamo_tpu.engine.multihost import stop_followers

            try:
                # pending batches must hit the wire before the stop
                # command, or followers see a seq gap
                asyncio.run_coroutine_threadsafe(
                    stream.drain(), stream_loop
                ).result(timeout=30)
                asyncio.run_coroutine_threadsafe(
                    stop_followers(
                        kv, args.namespace, engine_id, run_id,
                        args.num_nodes - 1, stream.seq,
                    ),
                    stream_loop,
                ).result(timeout=30)
            finally:
                # the lease revoke must happen even if the stop push
                # failed — followers fall back to liveness expiry
                try:
                    asyncio.run_coroutine_threadsafe(
                        stream.close(), stream_loop
                    ).result(timeout=10)
                finally:
                    stream_loop.call_soon_threadsafe(stream_loop.stop)

        args._mh_teardown = teardown
        return make_dispatch_sink(stream)

    async def follow() -> None:
        kv = await KvClient(host, port).connect()
        engine = TpuEngine(cfg, ecfg, params=params, mesh=mesh)
        print(
            f"cross-host follower rank {args.node_rank}: replaying "
            f"{args.namespace}/{engine_id} run {run_id} dispatch stream"
        )
        await Follower(
            engine, kv, args.namespace, engine_id, run_id, args.node_rank
        ).run()

    asyncio.run(follow())
    raise SystemExit(0)


def build_chain(args) -> "Any":
    """Construct the ModelChain for the selected engine."""
    from dynamo_tpu.backend import Backend
    from dynamo_tpu.frontend.model_manager import ModelChain
    from dynamo_tpu.preprocessor import OpenAIPreprocessor, PromptFormatter
    from dynamo_tpu.tokenizer import HfTokenizer, make_test_tokenizer

    inp, out = _parse_io(args.io)
    gguf_meta = None

    if args.model_path:
        # path | cached hub id | .gguf (reference local_model.rs:39; no
        # downloads — serving hosts have zero egress)
        from dynamo_tpu.model_resolver import resolve_model

        resolved = resolve_model(args.model_path)
        if resolved.kind == "gguf":
            # single-file serving: config + tokenizer + dequantized
            # weights all come out of the .gguf (reference gguf/ module +
            # llamacpp engine path)
            from dynamo_tpu.gguf import gguf_tokenizer, read_gguf

            gguf_meta, _ = read_gguf(resolved.path)
            tok = gguf_tokenizer(gguf_meta)
            fmt = PromptFormatter()
            name = args.model_name or os.path.basename(
                resolved.path).removesuffix(".gguf")
        else:
            tok = HfTokenizer.from_dir(resolved.path)
            fmt = PromptFormatter.from_dir(resolved.path)
            name = args.model_name or os.path.basename(
                resolved.path.rstrip("/"))
        args.model_path = resolved.path
    else:
        tok = make_test_tokenizer()
        fmt = PromptFormatter()
        name = args.model_name or "echo"

    if out == "echo":
        from dynamo_tpu.engines import EchoEngine

        engine: Any = EchoEngine()
    elif out == "mocker":
        from dynamo_tpu.mocker import MockerArgs, MockerEngine

        engine = MockerEngine(MockerArgs())
    elif out == "tpu":
        from dynamo_tpu.engine.config import EngineConfig
        from dynamo_tpu.engine.engine import TpuEngine
        from dynamo_tpu.models.config import ModelConfig
        from dynamo_tpu.parallel.mesh import MeshConfig

        local_devices = None
        cross_host = False
        if args.num_nodes > 1:
            if not args.control_plane:
                raise SystemExit("--num-nodes > 1 requires --control-plane")
            multi_host_bootstrap(args)
            import jax

            local_devices = jax.local_devices()
            # tp within one host's chips: each rank is an independent DP
            # replica (SURVEY §2.5 DP row). tp BEYOND the local chips: ONE
            # logical engine spans every host — rank 0 runs the scheduler
            # and broadcasts each dispatch, other ranks replay in lockstep
            # (engine/multihost.py; BASELINE config 4).
            cross_host = args.tensor_parallel_size > len(local_devices)
            if cross_host:
                if getattr(args, "role", None) in ("decode", "prefill"):
                    raise SystemExit(
                        "cross-host TP engines cannot join the disagg "
                        "data plane (the page transfer plane is "
                        "single-host); drop --role"
                    )
                if args.tensor_parallel_size > jax.device_count():
                    raise SystemExit(
                        f"--tensor-parallel-size {args.tensor_parallel_size}"
                        f" exceeds the {jax.device_count()} global chips"
                    )
                local_devices = None  # global mesh

        if args.model_path and gguf_meta is not None:
            from dynamo_tpu.gguf import config_from_gguf

            cfg = config_from_gguf(gguf_meta)
        elif args.model_path:
            cfg = ModelConfig.from_pretrained(args.model_path)
        elif args.model_config:
            cfg = getattr(ModelConfig, args.model_config)()
        else:
            raise SystemExit("out=tpu needs --model-path or --model-config")
        if args.quantize:
            from dataclasses import replace as _replace

            cfg = _replace(cfg, quant=args.quantize)
        ecfg = EngineConfig(
            num_pages=args.num_pages,
            page_size=args.page_size,
            max_decode_slots=args.max_decode_slots,
            cache_dtype=args.cache_dtype,
            kv_quant=args.kv_quant,
            host_offload_pages=args.host_offload_pages,
            disk_offload_pages=args.disk_offload_pages,
            disk_offload_path=args.disk_offload_path,
            scrub_on_start=args.scrub_on_start,
            speculative=args.speculative,
            num_speculative_tokens=args.num_speculative_tokens,
            spec_adaptive=args.spec_adaptive == "on",
            spec_min_k=args.spec_min_k,
            spec_tree=args.spec_tree == "on",
            spec_branches=args.spec_branches,
            spec_tree_budget=args.spec_tree_budget,
            spec_gate_acceptance=args.spec_gate_acceptance,
            spec_gate_window=args.spec_gate_window,
            spec_rearm_tokens=args.spec_rearm_tokens,
            kv_transfer_chunk_pages=args.kv_transfer_chunk_pages,
            kv_transfer_inflight_chunks=args.kv_transfer_inflight_chunks,
            xfer_op_timeout_s=args.xfer_op_timeout,
            kv_transfer_stream_idle_timeout_s=(
                args.kv_transfer_stream_idle_timeout
            ),
            max_waiting_requests=args.max_waiting_requests,
            max_waiting_prefill_tokens=args.max_waiting_prefill_tokens,
            preempt_running=args.preempt_running == "on",
            round_pipeline=args.round_pipeline == "on",
            prof_attribution=args.prof_attribution == "on",
            slo_ttft_target_s=args.slo_ttft_target,
            slo_itl_target_s=args.slo_itl_target,
            slo_objective=args.slo_objective,
            forensics_sample_rate=args.forensics_sample_rate,
            kv_dedup_admission=not getattr(
                args, "no_kv_dedup_admission", False
            ),
        )
        draft_cfg = None
        if args.speculative == "draft":
            if not args.draft_model_config:
                raise SystemExit(
                    "--speculative draft needs --draft-model-config"
                )
            draft_cfg = getattr(ModelConfig, args.draft_model_config)()
        params = None
        if args.model_path and gguf_meta is not None:
            from dynamo_tpu.gguf import load_gguf_params

            params = load_gguf_params(cfg, args.model_path)
        elif args.model_path:
            from dynamo_tpu.models import llama

            params = llama.load_hf_params(cfg, args.model_path)
        from dynamo_tpu.parallel.mesh import make_mesh

        on_dispatch = None
        if cross_host:
            on_dispatch = _crosshost_prologue(args, cfg, ecfg, params)
        engine = TpuEngine(
            cfg, ecfg, params=params,
            mesh=make_mesh(
                MeshConfig(tp=args.tensor_parallel_size), local_devices
            ) if local_devices is not None else None,
            mesh_config=MeshConfig(tp=args.tensor_parallel_size),
            on_dispatch=on_dispatch,
            draft_config=draft_cfg,
        )
    else:
        raise SystemExit(f"unknown engine out={out!r}")

    pre = OpenAIPreprocessor(tokenizer=tok, formatter=fmt, model_name=name)
    return inp, ModelChain(
        name=name, preprocessor=pre, engine=engine, backend=Backend(tok)
    )


async def _serve_http(args, chain) -> None:
    from dynamo_tpu.frontend import HttpService, ModelManager

    manager = ModelManager()
    manager.register(chain)
    svc = HttpService(manager, host=args.http_host, port=args.http_port,
                      trace_sample_rate=args.trace_sample_rate,
                      forensics_sample_rate=args.forensics_sample_rate)
    await svc.start()
    print(f"serving {chain.name!r} on http://{args.http_host}:{args.http_port}")
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await svc.stop()


async def _one_prompt(chain, prompt: str, max_tokens: int) -> str:
    from dynamo_tpu.protocols.openai import ChatCompletionRequest

    req = ChatCompletionRequest(
        model=chain.name,
        messages=[{"role": "user", "content": prompt}],
        max_tokens=max_tokens,
    )
    pre = chain.preprocess(req)
    parts = []
    async for out in chain.generate(pre):
        if out.text:
            parts.append(out.text)
    return "".join(parts)


async def _serve_text(args, chain) -> None:
    if args.prompt is not None:
        print(await _one_prompt(chain, args.prompt, args.max_tokens))
        return
    # interactive REPL
    while True:
        try:
            line = await asyncio.to_thread(input, "> ")
        except EOFError:
            return
        if line.strip():
            print(await _one_prompt(chain, line, args.max_tokens))


async def _serve_stdin(args, chain) -> None:
    for line in sys.stdin:
        if line.strip():
            print(await _one_prompt(chain, line.strip(), args.max_tokens))


async def _serve_batch(args, chain, path: str) -> None:
    """Batch mode doubles as the built-in benchmark (reference
    entrypoint/input/batch.rs:294): plain {"prompt": ...} JSONL runs
    through the chat chain; mooncake trace records (datagen output, with
    hash_ids/input_length/output_length) replay token-level with their
    prefix-sharing structure intact and timestamp pacing via
    --trace-speedup. Both print a summary line at the end."""
    with open(path) as f:
        recs = [json.loads(line) for line in f if line.strip()]
    is_trace = bool(recs) and "hash_ids" in recs[0]
    # submit concurrently so the continuous-batching engine actually batches
    sem = asyncio.Semaphore(64)
    ttfts: list[float] = []
    total_tokens = 0
    t0 = time.monotonic()

    async def one(rec):
        nonlocal total_tokens
        async with sem:
            t_sub = time.monotonic()
            first = None
            if is_trace:
                pre = _trace_request(rec, args.trace_block_size)
                n = 0
                async for out in chain.generate(pre):
                    if first is None and out.token_ids:
                        first = time.monotonic() - t_sub
                    n += len(out.token_ids)
                total_tokens += n
                if first is not None:
                    ttfts.append(first)
                return n
            text = await _one_prompt(
                chain, rec.get("prompt", ""),
                rec.get("max_tokens", args.max_tokens),
            )
            ttfts.append(time.monotonic() - t_sub)
            return text

    async def paced(rec, delay_s):
        if delay_s > 0:
            await asyncio.sleep(delay_s)
        return await one(rec)

    if is_trace and args.trace_speedup > 0:
        base_ms = recs[0].get("timestamp", 0)
        tasks = [
            paced(r, (r.get("timestamp", 0) - base_ms) / 1000.0
                  / args.trace_speedup)
            for r in recs
        ]
    else:
        tasks = [one(r) for r in recs]
    results = await asyncio.gather(*tasks)
    wall = time.monotonic() - t0
    if not is_trace:
        for rec, text in zip(recs, results):
            print(json.dumps({"prompt": rec.get("prompt", ""),
                              "text": text}))
    ttfts.sort()
    summary = {
        "requests": len(recs),
        "wall_s": round(wall, 3),
        "requests_per_s": round(len(recs) / wall, 2) if wall else None,
    }
    # trace mode measures a real first-token time; the prompt path only
    # observes whole-request latency — name the metrics honestly
    prefix = "ttft" if is_trace else "latency"
    summary[f"{prefix}_p50_s"] = (
        round(ttfts[len(ttfts) // 2], 4) if ttfts else None
    )
    summary[f"{prefix}_p99_s"] = (
        round(ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))], 4)
        if ttfts else None
    )
    if is_trace:  # token counts only exist on the token-level replay path
        summary["output_tok_s"] = round(total_tokens / wall, 2) \
            if wall else None
    print(json.dumps({"batch_summary": summary}), file=sys.stderr)


def _trace_request(rec: dict, block_size: int = 64) -> "Any":
    """Mooncake record -> PreprocessedRequest with DETERMINISTIC tokens
    per hash id, so equal hash prefixes produce equal token blocks and the
    prefix cache / KV router see the trace's sharing structure. The hash →
    tokens mapping uses a FIXED block_size (one hash = block_size tokens):
    a per-record size would make the same hash expand differently across
    records and destroy the sharing the replay exists to measure."""
    from dynamo_tpu.protocols.common import (
        PreprocessedRequest,
        StopConditions,
    )

    hash_ids = rec.get("hash_ids") or [0]
    isl = max(1, int(rec.get("input_length", 1)))
    tokens: list[int] = []
    for h in hash_ids:
        base = (int(h) * 2654435761) & 0x7FFFFFFF
        tokens.extend(
            (base + j * 40503) % 30000 + 10 for j in range(block_size)
        )
        if len(tokens) >= isl:
            break
    if len(tokens) < isl:  # trace lengths can exceed hash coverage
        tokens.extend(
            (len(tokens) + j) % 30000 + 10
            for j in range(isl - len(tokens))
        )
    return PreprocessedRequest(
        token_ids=tokens[:isl],
        stop_conditions=StopConditions(
            max_tokens=max(1, int(rec.get("output_length", 16))),
            ignore_eos=True,
        ),
    )


def _cp_addr(args) -> tuple[str, int]:
    host, _, port = args.control_plane.partition(":")
    return host or "127.0.0.1", int(port or 7111)


async def _serve_worker(args, chain) -> None:
    """in=endpoint: register the engine on the runtime and serve forever
    (reference Input::Endpoint, entrypoint/input.rs:43). --role decode adds
    the disagg wrapper + block-transfer data plane."""
    from dynamo_tpu.frontend.watcher import ModelEntry, register_llm
    from dynamo_tpu.runtime.component import DistributedRuntime

    host, port = _cp_addr(args)
    # resync: a store bounce must not unregister a serving worker — the
    # session re-grants the lease and re-puts registration keys
    rt = await DistributedRuntime.connect(host=host, port=port, resync=True)

    engine = chain.engine
    disagg_parts = []
    if args.role == "decode":
        import uuid

        from dynamo_tpu.disagg import (
            DisaggConfig,
            DisaggConfigWatcher,
            DisaggDecodeEngine,
            set_disagg_config,
        )

        if (args.max_local_prefill_length is not None
                or args.max_prefill_queue_size is not None):
            conf = DisaggConfig()
            if args.max_local_prefill_length is not None:
                conf.max_local_prefill_length = args.max_local_prefill_length
            if args.max_prefill_queue_size is not None:
                conf.max_prefill_queue_size = args.max_prefill_queue_size
            await set_disagg_config(rt.kv, args.namespace, conf)
        watcher = await DisaggConfigWatcher(rt.kv, args.namespace).start()
        engine = DisaggDecodeEngine(
            engine, rt, namespace=args.namespace, conf=watcher,
            prefill_timeout_s=args.prefill_timeout,
        )
        disagg_parts.append(watcher)
        # data plane + descriptor up BEFORE the endpoint serves: a request
        # landing in between would enqueue an unroutable prefill job (the
        # descriptor key is a fresh uuid, independent of the lease)
        served_xfer = await _attach_data_plane(
            args, rt, engine, uuid.uuid4().hex
        )
        disagg_parts.append(served_xfer)

    if getattr(args, "remote_kv", False) and args.role != "decode":
        # G4: aggregated workers also join the transfer plane (decode
        # workers already do) and fetch through it on prefix misses
        import uuid as _uuid

        inner = getattr(engine, "engine", engine)
        if getattr(inner, "offload", None) is None:
            raise SystemExit(
                "--remote-kv needs a G2 host tier "
                "(--host-offload-pages > 0)"
            )
        served_xfer = await _attach_data_plane(
            args, rt, engine, _uuid.uuid4().hex
        )
        disagg_parts.append(served_xfer)
    if getattr(args, "remote_kv", False):
        from dynamo_tpu.kv_transfer import RemoteKvFetcher

        inner = getattr(engine, "engine", engine)
        if getattr(inner, "offload", None) is not None:
            inner.remote_kv = RemoteKvFetcher(
                rt.kv, args.namespace, getattr(engine, "worker_id", ""),
                chunk_pages=args.kv_transfer_chunk_pages,
            )

    entry = ModelEntry(
        name=chain.name,
        namespace=args.namespace,
        component=args.component,
        endpoint=args.endpoint_name,
        block_size=args.page_size,
        router_mode=args.router_mode,
        model_path=args.model_path,
    )
    served = await register_llm(rt, engine, entry)

    # graceful drain (resilience/drain.py): SIGTERM (planner scale-down)
    # and POST /drain both stop admissions, let in-flight requests finish,
    # then exit — instead of killing warm KV and live streams
    import signal

    from dynamo_tpu.resilience.drain import DrainController

    drained_exit = asyncio.Event()
    drain = DrainController(
        engine,
        on_deregister=served.lease.revoke,
        on_drained=drained_exit.set,
        timeout_s=args.drain_timeout,
    )
    loop = asyncio.get_running_loop()
    try:
        loop.add_signal_handler(
            signal.SIGTERM,
            lambda: drain.request_drain(reason="SIGTERM"),
        )
    except (NotImplementedError, RuntimeError):
        pass  # platforms/loops without signal support: /drain still works

    if args.system_port is not None:
        from dynamo_tpu.runtime.system_server import SystemServer

        sysrv = await SystemServer(
            engine, port=args.system_port,
            worker_id=str(served.lease_id),
            drain=drain,
        ).start()
        disagg_parts.append(sysrv)  # stopped alongside disagg parts
        print(f"system server on :{sysrv.port}")
    print(
        f"worker {chain.name!r} instance {served.lease_id} "
        f"({args.role}) serving "
        f"{args.namespace}/{args.component}/{args.endpoint_name}"
    )
    try:
        # run until the control plane drops us OR a drain completes
        lost = asyncio.ensure_future(served.lease.lost.wait())
        drained = asyncio.ensure_future(drained_exit.wait())
        done, pending = await asyncio.wait(
            {lost, drained}, return_when=asyncio.FIRST_COMPLETED
        )
        for t in pending:
            t.cancel()
        if drained in done:
            print("drained; shutting down")
        else:
            print("lease lost; shutting down")
    finally:
        for part in disagg_parts:
            await part.stop()
        await served.shutdown()


async def _attach_data_plane(args, rt, engine, worker_id: str):
    """Serve the engine's KV pool on the block-transfer plane + publish the
    blockset descriptor (lease-less: rides the registration lease via the
    same worker id)."""
    from dynamo_tpu.kv_transfer import (
        BlocksetDescriptor,
        BlockTransferServer,
        KvCacheLayout,
        publish_descriptor,
    )

    inner = getattr(engine, "engine", engine)
    engine.worker_id = worker_id
    write_fn = getattr(engine, "guarded_import", None) or inner.import_pages
    srv = BlockTransferServer(
        read_fn=inner.export_pages, write_fn=write_fn,
        read_hashes_fn=getattr(inner, "export_pages_by_hash", None),
        # chunk-pipelined G4 serving: cheap probes + streamed hash reads
        count_hashes_fn=getattr(
            getattr(inner, "allocator", None), "cached_prefix_len", None
        ),
        read_hashes_stream_fn=getattr(inner, "export_hash_stream", None),
    )
    host, port = await srv.start()
    cfg, ecfg = inner.config, inner.ecfg
    await publish_descriptor(rt.kv, args.namespace, BlocksetDescriptor(
        worker_id=worker_id, host=host, port=port,
        layout=KvCacheLayout(
            num_layers=cfg.num_layers, num_kv_heads=cfg.num_kv_heads,
            page_size=ecfg.page_size, head_dim=cfg.head_dim,
            # what moves on the wire: int8 payloads (+ header scales)
            # for a quantized pool
            dtype=("int8" if ecfg.kv_quant == "int8"
                   else ecfg.cache_dtype),
        ),
    ))
    return srv


async def _serve_prefill_worker(args, chain) -> None:
    """--role prefill: consume the prefill queue; no model registration
    (reference prefill_worker.py)."""
    from dynamo_tpu.disagg import PrefillWorker
    from dynamo_tpu.runtime.component import DistributedRuntime

    host, port = _cp_addr(args)
    rt = await DistributedRuntime.connect(host=host, port=port, resync=True)
    worker = await PrefillWorker(
        rt, chain.engine, namespace=args.namespace
    ).start()
    print(f"prefill worker consuming {args.namespace}.prefill")
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await worker.stop()
        await rt.close()


async def _serve_http_dynamic(args) -> None:
    """in=http + --control-plane: discover models instead of building a
    local chain (reference EngineConfig::Dynamic, input/common.rs:55-90)."""
    from dynamo_tpu.frontend import HttpService, ModelManager
    from dynamo_tpu.frontend.watcher import ModelWatcher
    from dynamo_tpu.kv_router.prefetch import PrefetchConfig
    from dynamo_tpu.kv_router.scheduler import KvRouterConfig
    from dynamo_tpu.runtime.component import DistributedRuntime

    host, port = _cp_addr(args)
    # resync: the frontend serves from last-known state through an outage
    # (ModelWatcher freezes its health/load views) and resyncs after
    rt = await DistributedRuntime.connect(host=host, port=port, resync=True)
    manager = ModelManager()
    kv_recorder = None
    if args.record_kv_events:
        from dynamo_tpu.recorder import KvRecorder

        kv_recorder = KvRecorder(args.record_kv_events)
    router_config = KvRouterConfig(
        freq_halflife_s=(args.kv_freq_halflife or None),
    )
    # replication target <= 1 means "one copy is enough": no controller
    prefetch_config = None
    if args.kv_replication_target > 1:
        prefetch_config = PrefetchConfig(
            replication_target=args.kv_replication_target,
            hot_k=args.kv_prefetch_hot_k,
            interval_s=args.kv_prefetch_interval,
        )
    watcher = await ModelWatcher(
        rt, manager, namespace=args.namespace, kv_recorder=kv_recorder,
        heartbeat_ttl_s=args.health_heartbeat_ttl,
        router_config=router_config, prefetch_config=prefetch_config,
    ).start()
    svc = HttpService(manager, host=args.http_host, port=args.http_port,
                      trace_sample_rate=args.trace_sample_rate,
                      forensics_sample_rate=args.forensics_sample_rate)
    # /debug/kv_fleet serves the watcher's live per-model fleet views
    svc.fleet_views = watcher.fleet_views
    await svc.start()
    print(
        f"dynamic frontend on http://{args.http_host}:{args.http_port} "
        f"(namespace {args.namespace!r})"
    )
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await svc.stop()
        await watcher.stop()
        await rt.close()


def _shutdown_chain(args, chain) -> None:
    """Tear the engine + cross-host stream down BEFORE interpreter exit.

    Order matters: stop the engine first (so no further dispatches are
    broadcast), then the cross-host teardown (stop command + liveness
    lease drop). Skipping this leaves the engine's daemon thread racing
    jax's atexit distributed shutdown — the backend cache is cleared
    mid-round and the next jnp op re-initializes the cpu client, which
    re-publishes its coordination-service topology key and dies with
    ALREADY_EXISTS; the liveness lease then deadlocks the shutdown
    barrier (leader waits for followers; followers wait for the lease)."""
    try:
        if chain is not None:
            stop = getattr(chain.engine, "stop", None)
            if stop is not None:
                try:
                    asyncio.run(stop())
                except Exception as e:  # noqa: BLE001 - teardown proceeds
                    print(f"engine stop failed: {e}", file=sys.stderr)
    finally:
        # must run even if engine stop is interrupted (a second Ctrl-C):
        # skipping it reinstates the liveness-lease/atexit deadlock
        teardown = getattr(args, "_mh_teardown", None)
        if teardown is not None:
            try:
                teardown()
            except Exception as e:  # noqa: BLE001
                print(f"cross-host teardown failed: {e}", file=sys.stderr)


def run_cli(argv: list[str]) -> int:
    # intermixed: in=/out= positionals may appear between/after flags
    # (graph files and scripts compose argv in any order)
    args = build_parser().parse_intermixed_args(argv)
    chaos_spec = args.chaos or os.environ.get("DYNAMO_CHAOS")
    if chaos_spec:
        from dynamo_tpu.resilience.chaos import CHAOS

        CHAOS.configure(chaos_spec)
    inp, _ = _parse_io(args.io)
    chain = None
    try:
        if inp == "http" and args.control_plane:
            asyncio.run(_serve_http_dynamic(args))
            return 0
        if inp == "endpoint":
            if not args.control_plane:
                raise SystemExit("in=endpoint requires --control-plane")
            _, chain = build_chain(args)
            if args.role == "prefill":
                asyncio.run(_serve_prefill_worker(args, chain))
            else:
                asyncio.run(_serve_worker(args, chain))
            return 0
        inp, chain = build_chain(args)
        engine_start = getattr(chain.engine, "start", None)
        if engine_start is not None:
            engine_start()
        if inp == "http":
            asyncio.run(_serve_http(args, chain))
        elif inp == "text":
            asyncio.run(_serve_text(args, chain))
        elif inp == "stdin":
            asyncio.run(_serve_stdin(args, chain))
        elif inp.startswith("batch:"):
            asyncio.run(_serve_batch(args, chain, inp[len("batch:"):]))
        else:
            raise SystemExit(f"unknown input in={inp!r}")
    except KeyboardInterrupt:
        pass
    finally:
        _shutdown_chain(args, chain)
    return 0
