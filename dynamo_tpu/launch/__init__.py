"""Launcher: builds and runs serving graphs from CLI flags (reference
launch/dynamo-run)."""
