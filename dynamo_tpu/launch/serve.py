"""``dynamo-tpu serve graph.yaml`` — one-command serving-graph supervisor.

Parity: reference ``dynamo serve`` (deploy/sdk/src/dynamo/sdk/cli/
serving.py:66-152): a circus arbiter running one watcher per component —
here a small asyncio supervisor that launches the control-plane store,
worker fleets, and the HTTP frontend as child processes, restarts
unexpected exits with capped backoff, and drains gracefully on SIGTERM
(workers first so leases revoke, then frontend, then the store).

Graph file (YAML or JSON):

    namespace: dynamo
    control_plane:
      port: 7111            # omit `external: HOST:PORT` to self-host
    frontend:
      http_port: 8080
      args: []              # extra `run` args
    workers:
      - name: decode
        replicas: 2
        args: [out=tpu, --model-config, tiny, --model-name, m,
               --role, decode]
      - name: prefill
        replicas: 1
        args: [out=tpu, --model-config, tiny, --role, prefill]
"""
from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Optional

log = logging.getLogger(__name__)

MAX_RESTARTS = 5          # per child, within RESTART_WINDOW_S
RESTART_WINDOW_S = 300.0
BACKOFF_BASE_S = 1.0


def load_graph(path: str) -> dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    try:
        import yaml

        return yaml.safe_load(text) or {}
    except ImportError:
        return json.loads(text)


@dataclass
class _Child:
    name: str
    cmd: list[str]
    proc: Optional[subprocess.Popen] = None
    restarts: list[float] = field(default_factory=list)
    give_up: bool = False
    # restart scheduled for this deadline (0 = none); the monitor never
    # sleeps per-child, so one crash-looping child can't stall the others
    next_restart_at: float = 0.0

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class Supervisor:
    """Launch + babysit the graph's processes."""

    def __init__(self, graph: dict[str, Any], *, python: str = sys.executable):
        self.graph = graph
        self.python = python
        self.children: list[_Child] = []
        self.namespace = graph.get("namespace", "dynamo")
        cp = graph.get("control_plane", {}) or {}
        self.external_cp: Optional[str] = cp.get("external")
        self.cp_port: int = int(cp.get("port", 7111))
        self._stop = asyncio.Event()

    @property
    def cp_addr(self) -> str:
        return self.external_cp or f"127.0.0.1:{self.cp_port}"

    def _build_children(self) -> None:
        base = [self.python, "-m", "dynamo_tpu.cli"]
        if self.external_cp is None:
            self.children.append(_Child(
                name="control-plane",
                cmd=base + ["cp", "--port", str(self.cp_port)],
            ))
        for spec in self.graph.get("workers", []) or []:
            name = spec.get("name", "worker")
            replicas = int(spec.get("replicas", 1))
            args = [str(a) for a in (spec.get("args") or [])]
            for i in range(replicas):
                self.children.append(_Child(
                    name=f"{name}-{i}",
                    cmd=base + ["run", "in=endpoint",
                                "--control-plane", self.cp_addr,
                                "--namespace", self.namespace] + args,
                ))
        if "frontend" in self.graph:
            # a bare `frontend:` key (YAML null) means defaults, not absent
            fe = self.graph.get("frontend") or {}
            args = [str(a) for a in (fe.get("args") or [])]
            self.children.append(_Child(
                name="frontend",
                cmd=base + ["run", "in=http",
                            "--control-plane", self.cp_addr,
                            "--namespace", self.namespace,
                            "--http-port",
                            str(fe.get("http_port", 8080))] + args,
            ))

    def _spawn(self, child: _Child) -> None:
        log.info("serve: starting %s: %s", child.name, " ".join(child.cmd))
        child.proc = subprocess.Popen(
            child.cmd,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True,
            env=dict(os.environ),
        )

    async def start(self) -> "Supervisor":
        self._build_children()
        for child in self.children:
            self._spawn(child)
            if child.name == "control-plane":
                await asyncio.sleep(0.5)  # store up before dependents
        self._monitor_task = asyncio.get_running_loop().create_task(
            self._monitor()
        )
        return self

    async def _monitor(self) -> None:
        """Restart unexpected exits with capped per-child backoff (no
        inline sleeps: each child carries its own restart deadline)."""
        while not self._stop.is_set():
            await asyncio.sleep(0.5)
            now = time.monotonic()
            for child in self.children:
                if child.alive() or child.give_up:
                    continue
                if child.next_restart_at:
                    if now >= child.next_restart_at:
                        child.next_restart_at = 0.0
                        self._spawn(child)
                    continue
                child.restarts = [
                    t for t in child.restarts if now - t < RESTART_WINDOW_S
                ]
                if len(child.restarts) >= MAX_RESTARTS:
                    log.error("serve: %s exceeded %d restarts; giving up",
                              child.name, MAX_RESTARTS)
                    child.give_up = True
                    continue
                backoff = BACKOFF_BASE_S * (2 ** len(child.restarts))
                log.warning(
                    "serve: %s exited (rc=%s); restarting in %.1fs",
                    child.name,
                    child.proc.returncode if child.proc else "?",
                    backoff,
                )
                child.restarts.append(now)
                child.next_restart_at = now + backoff

    async def drain(self, timeout_s: float = 15.0) -> None:
        """Graceful stop: workers first (lease revocation deregisters
        them), then frontend, then the store."""
        self._stop.set()
        self._monitor_task.cancel()

        def group(pred):
            return [c for c in self.children if pred(c) and c.alive()]

        order = [
            group(lambda c: c.name not in ("frontend", "control-plane")),
            group(lambda c: c.name == "frontend"),
            group(lambda c: c.name == "control-plane"),
        ]
        for batch in order:
            for c in batch:
                c.proc.terminate()
            deadline = time.monotonic() + timeout_s
            for c in batch:
                while c.alive() and time.monotonic() < deadline:
                    await asyncio.sleep(0.1)
                if c.alive():
                    log.warning("serve: %s ignored SIGTERM; killing", c.name)
                    c.proc.kill()

    def status(self) -> dict[str, str]:
        return {
            c.name: ("up" if c.alive()
                     else "failed" if c.give_up else "down")
            for c in self.children
        }


async def serve_main(path: str) -> int:
    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s %(message)s"
    )
    sup = Supervisor(load_graph(path))
    await sup.start()
    names = ", ".join(c.name for c in sup.children)
    print(f"serving graph: {names} (control plane {sup.cp_addr})")

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()
    print("draining...")
    await sup.drain()
    return 0
