"""Autoscaling-plane observability: one registry, three surfaces.

The planner's observe->decide->scale loop increments counters here; the
frontend ``/metrics``, the per-worker system server and the aggregating
exporter all append ``render()``'s Prometheus text (zero-valued in
processes that run no planner), so a scaling storm — or a planner that
silently stopped deciding — is visible on every scrape surface.
"""
from __future__ import annotations

from dynamo_tpu.telemetry.metrics import CounterRegistry

# (name, type, help) — naming contract as in runtime/store_metrics.py:
# counters `*_total`, gauges plain names.
FAMILIES: tuple[tuple[str, str, str], ...] = (
    ("dynamo_planner_replicas", "gauge",
     "replica target of the planner's most recent decision"),
    ("dynamo_planner_decisions_total", "counter",
     "planner adjustment decisions taken (one per interval)"),
    ("dynamo_planner_scale_ups_total", "counter",
     "decisions that raised the replica target"),
    ("dynamo_planner_scale_downs_total", "counter",
     "decisions that lowered the replica target"),
    ("dynamo_planner_predicted_load", "gauge",
     "predictor forecast for the next interval (concurrent streams in "
     "predictive/SLA mode, mean KV usage in load mode)"),
    ("dynamo_planner_fleet_ttft_p99_seconds", "gauge",
     "p99 TTFT over the last decide interval from the fleet-merged "
     "latency feed (0 until the feed has data)"),
    ("dynamo_planner_fleet_queue_p99_seconds", "gauge",
     "p99 admission queue wait over the last decide interval from the "
     "fleet-merged latency feed (0 until the feed has data)"),
)

# process-wide registry shared by every planner in the process (parity
# with store_metrics.STORE)
PLANNER = CounterRegistry(FAMILIES, label="planner")
