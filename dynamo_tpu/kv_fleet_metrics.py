"""Fleet prefix-economy metrics: one process-wide registry, three scrape
surfaces.

The fleet-wide content-addressed KV planes — dedup-by-hash admission
(engine consults fleet hints before recomputing a prefix miss), the
router-driven replication/prefetch controller (kv_router/prefetch.py) and
replication-aware tier eviction (engine/offload.py) — all count here. The
frontend ``/metrics``, the per-worker system server and the aggregating
exporter each append ``render()``'s Prometheus text (same pattern as
kv_transfer_metrics.py), so the series exist on every surface, and every
family is documented in README's Observability section — the
metrics-contract lint (DTL005) enforces both.
"""
from __future__ import annotations

from dynamo_tpu.telemetry.metrics import CounterRegistry

# (name, type, help) — the fixed counter family set.
FAMILIES: tuple[tuple[str, str, str], ...] = (
    ("dynamo_kv_fleet_recompute_avoided_blocks_total", "counter",
     "prefix blocks pulled from a peer by hash instead of recomputed"),
    ("dynamo_kv_fleet_dedup_skipped_probes_total", "counter",
     "G4 fetch rounds skipped because fleet hints showed no peer holder"),
    ("dynamo_kv_fleet_prefetched_blocks_total", "counter",
     "blocks pushed into a worker host tier by the replication controller"),
    ("dynamo_kv_fleet_prefetch_rounds_total", "counter",
     "replication-controller passes that examined the fleet hot set"),
    ("dynamo_kv_fleet_warm_starts_total", "counter",
     "cold workers warm-started from the fleet top-K hot prefixes"),
    ("dynamo_kv_fleet_hint_pushes_total", "counter",
     "fleet replica/holder hint digests delivered to workers"),
    ("dynamo_kv_fleet_replicated_evictions_total", "counter",
     "tier evictions that chose a fleet-replicated block over unique ones"),
    ("dynamo_kv_fleet_last_copy_evictions_total", "counter",
     "tier evictions forced to drop the last known fleet copy of a block"),
)

# process-wide registry: the frontend controller and the worker-side
# admission/eviction hooks in one process share it
KV_FLEET = CounterRegistry(FAMILIES, label="kv-fleet")
