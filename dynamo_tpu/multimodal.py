"""Multimodal E/P/D serving graph (reference examples/multimodal:
encode worker + embedding transfer + prefill consumption,
components/encode_worker.py:148, disagg_router.py:48-66).

Three-stage flow, TPU-native:

  1. ``EncodeWorker`` — a runtime component serving an ``encode``
     endpoint: images in, language-model embedding rows out (the vision
     tower runs as its own worker so encoder and LLM scale
     independently, exactly the reference's E/P/D split).
  2. The embeddings travel back over the runtime's streamed push RPC
     (small: num_patches x hidden rows; the kv_transfer plane can carry
     them as raw arrays for big batches).
  3. ``MultimodalEngine`` — an AsyncEngine wrapper in front of a decode
     engine: resolves a request's images via the encode endpoint,
     attaches the embedding rows + content digest to
     ``PreprocessedRequest.multimodal``, and delegates. The TpuEngine
     injects the rows in place of the ``<image>`` placeholder tokens'
     embeddings during prefill (models/llama.py prefill `embeds`), and
     salts the request's block hashes with the digest so prefix caching
     never serves one image's KV for another.

The caller's prompt must already contain a run of placeholder tokens per
image; ``images[i]["pos"]`` marks where each run starts (the HTTP
preprocessor's image_url lowering produces this shape).
"""
from __future__ import annotations

import base64
import dataclasses
import hashlib
import logging
from typing import Any, AsyncIterator, Optional

import numpy as np

from dynamo_tpu.protocols.common import LLMEngineOutput, PreprocessedRequest

log = logging.getLogger(__name__)


def encode_image_payload(image: np.ndarray) -> dict[str, Any]:
    """Pack an [H, W, 3] float32 image for the encode endpoint."""
    arr = np.ascontiguousarray(image, np.float32)
    return {
        "data": base64.b64encode(arr.tobytes()).decode(),
        "shape": list(arr.shape),
    }


def decode_image_payload(payload: dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(payload["data"])
    return np.frombuffer(raw, np.float32).reshape(payload["shape"]).copy()


def images_digest(images: list[dict[str, Any]]) -> str:
    """Content digest over every image's bytes (prefix-cache salt)."""
    h = hashlib.sha256()
    for im in images:
        h.update(str(im.get("shape")).encode())
        h.update(base64.b64decode(im["data"]))
    return h.hexdigest()[:16]


class EncodeWorker:
    """Vision-encoder worker: serves ``encode`` on the runtime
    (reference encode_worker.py:148)."""

    def __init__(
        self,
        rt: Any,
        vision_cfg: Any = None,
        params: Any = None,
        namespace: str = "dynamo",
        component: str = "encoder",
        worker_id: str = "encoder-0",
    ):
        from dynamo_tpu.models.vision import VisionConfig, init_vision_params

        self.rt = rt
        self.cfg = vision_cfg or VisionConfig.tiny()
        self.params = params if params is not None else init_vision_params(
            self.cfg, 0
        )
        self.namespace = namespace
        self.component = component
        self.worker_id = worker_id
        self.images_encoded = 0
        self._served = None
        self._frames = None   # ArrayFrameServer (RPC transport only)

    async def encode_arrays(self, images: list[dict]) -> list[np.ndarray]:
        """Encode to raw [num_patches, out_hidden] f32 arrays (the
        in-process path — no transport)."""
        import asyncio

        from dynamo_tpu.models.vision import encode_image

        out = []
        for im in images:
            arr = decode_image_payload(im)
            emb = await asyncio.to_thread(
                lambda a=arr: np.asarray(
                    encode_image(self.cfg, self.params, a), np.float32
                )
            )
            self.images_encoded += 1
            out.append(emb)
        return out

    async def _handle(self, payload: dict) -> AsyncIterator[dict]:
        """RPC path: embeddings go as array-frame TICKETS, not JSON float
        lists — the peer collects the raw tensors over the frame2 side
        channel (reference moves them via NIXL, encode_worker.py:148).
        A LLaVA-scale image is ~9 MB of f32; JSON would 10x that."""
        embs = await self.encode_arrays(payload.get("images", []))
        out = []
        for emb in embs:
            out.append({
                "ticket": self._frames.park(emb),
                "host": self._frames.host, "port": self._frames.port,
                "shape": list(emb.shape),
            })
        yield {"embeddings": out}

    async def start(self) -> "EncodeWorker":
        from dynamo_tpu.kv_transfer import ArrayFrameServer

        self._frames = ArrayFrameServer()
        await self._frames.start()
        ep = self.rt.namespace(self.namespace).component(
            self.component
        ).endpoint("encode")
        self._served = await ep.serve(self._handle, worker_id=self.worker_id)
        return self

    async def stop(self) -> None:
        if self._served is not None:
            await self._served.shutdown()
            self._served = None
        if self._frames is not None:
            await self._frames.stop()
            self._frames = None


class MultimodalEngine:
    """AsyncEngine wrapper: encode stage -> embedding attach -> delegate
    (the reference's 3-stage disaggregation, orchestrated)."""

    def __init__(
        self,
        inner: Any,
        rt: Any = None,
        namespace: str = "dynamo",
        component: str = "encoder",
        local_encoder: Optional[Any] = None,  # EncodeWorker for in-process
    ):
        self.inner = inner
        self.rt = rt
        self.namespace = namespace
        self.component = component
        self.local_encoder = local_encoder
        self.images_resolved = 0
        self._client = None

    async def _encode(self, images: list[dict]) -> list[np.ndarray]:
        if self.local_encoder is not None:
            return await self.local_encoder.encode_arrays(images)
        if self._client is None:
            self._client = await self.rt.namespace(self.namespace).component(
                self.component
            ).endpoint("encode").client()
        async for item in self._client.generate({"images": images}):
            from dynamo_tpu.kv_transfer import take_remote_array

            out: list[np.ndarray] = []
            for ent in item["embeddings"]:
                if isinstance(ent, dict) and "ticket" in ent:
                    # array-frame transport: collect the raw tensor
                    out.append(await take_remote_array(
                        ent["host"], ent["port"], ent["ticket"]
                    ))
                else:  # legacy float-list responses stay readable
                    out.append(np.asarray(ent, np.float32))
            return out
        raise RuntimeError("encode endpoint returned no response")

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[LLMEngineOutput]:
        mm = request.multimodal or {}
        images = mm.get("images")
        if images:
            embs = await self._encode(images)
            entries = []
            for im, rows in zip(images, embs):
                entries.append({"pos": int(im["pos"]), "data": rows})
            self.images_resolved += len(entries)
            # resolved COPY: the caller's request keeps its raw images
            # (idempotent under frontend retry/failover re-dispatch)
            request = dataclasses.replace(request, multimodal={
                "embeddings": entries,
                "digest": images_digest(images),
            })
        async for out in self.inner.generate(request):
            yield out

    async def stop(self) -> None:
        stop = getattr(self.inner, "stop", None)
        if stop is not None:
            await stop()
