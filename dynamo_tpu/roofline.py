"""Per-step decode byte accounting + roofline attribution.

`roofline_frac` (bench.py, since r04) compares decode steps/s against
the WEIGHT-pass ceiling (peak HBM bandwidth / parameter bytes) — honest
for small-batch decode but a single opaque number: it says nothing
about where the other bytes go. This module decomposes the real
per-step HBM traffic of the fused decode round into its streams —
weights, live context KV (from actual per-slot context lengths, at the
kernel's chunk granularity), the int8 scale sidecar, the write ring,
and the logits row — so bench/profile lines can emit

  kv_bytes_per_step    KV-plane bytes per fused step (ctx + scales + ring)
  attn_roofline_frac   steps/s x total bytes-per-step / peak bandwidth
                       (fraction of the chip's bandwidth the measured
                       rate actually moves — the attributed roofline)

and the kv_quant=int8 claim ("live-KV HBM bytes <= 0.55x bf16") becomes
a reported ratio (`kv_ctx_bytes_vs_bf16`) instead of folklore.

All values are DERIVED from config + context lengths, not measured
counters — they are exact for the streams the fused round provably
moves (every weight byte, every live KV chunk the DMA-skip index map
admits) and they deliberately exclude second-order traffic (activation
spills, sampler temporaries). On CPU harnesses the byte fields stay
real (geometry is geometry) while utilization fractions should be
nulled by the caller per the PR 7 honesty rule — a CPU has no TPU peak
bandwidth to attribute against.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

# chip peak table (bf16 FLOP/s, HBM B/s); device_kind -> (flops, bw)
CHIP_PEAKS = {
    "TPU v5e": (197e12, 819e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v4": (275e12, 1228e9),
    "TPU v6e": (918e12, 1640e9),
}
DEFAULT_PEAK = (197e12, 819e9)  # assume v5e if unknown


def chip_info():
    """(device_kind, (peak_flops, peak_bw), on_accelerator)."""
    import jax

    dev = jax.devices()[0]
    kind = dev.device_kind
    on_accel = dev.platform != "cpu"
    for name, peak in CHIP_PEAKS.items():
        if name.lower() in kind.lower():
            return kind, peak, on_accel
    return kind, DEFAULT_PEAK, on_accel


def decode_byte_accounting(
    config,                      # models.config.ModelConfig
    ecfg,                        # engine.config.EngineConfig
    ctx_lens: Sequence[int],     # live per-slot context lengths
    param_bytes: int,
    steps_per_s: Optional[float] = None,
    peak_bw: Optional[float] = None,
) -> dict:
    """Decompose the fused decode round's per-step HBM bytes.

    Returns a dict with the per-stream breakdown (bytes/step), the
    aggregates (`kv_bytes_per_step`, `total_bytes_per_step`), the
    quantization ratio (`kv_ctx_bytes_vs_bf16` — live ctx + scale bytes
    vs the same geometry in bf16), and — when `steps_per_s`/`peak_bw`
    are given — `attn_roofline_frac`.
    """
    import jax.numpy as jnp

    from dynamo_tpu.ops.flash_decode import DEFAULT_CHUNK, _pick_chunk

    c, e = config, ecfg
    L, kvh, hd = c.num_layers, c.num_kv_heads, c.head_dim
    B, R = e.max_decode_slots, e.flush_every
    quant = e.kv_quant == "int8"
    compute_bytes = jnp.dtype(e.cache_dtype).itemsize
    kv_elem = 1 if quant else compute_bytes
    group = max(1, e.page_size)
    S = -(-e.max_context // group) * group if quant else e.max_context

    # live ctx stream: the kernel's DMA-skip admits whole CHUNKs up to
    # each lane's live context — round per lane to the chunk the kernel
    # would pick for this S (mirrors ops/flash_decode._pick_chunk)
    chunk = _pick_chunk(S, DEFAULT_CHUNK, group if quant else 1)
    lens = np.clip(np.asarray(list(ctx_lens), np.int64), 0, S)
    read_rows = np.ceil(lens / chunk).astype(np.int64) * chunk
    kv_ctx = int(2 * L * kvh * hd * read_rows.sum()) * kv_elem
    # int8 scale sidecar rides the same chunks: f32 per (layer, group),
    # no head axis
    kv_ctx_scales = (
        int(2 * L * (read_rows // group).sum()) * 4 if quant else 0
    )
    # write ring: read in full by every step's attention, one new row
    # written per lane per step; stays the compute dtype (it is tiny)
    ring_elems = 2 * L * kvh * B * R * hd
    kv_ring = (ring_elems + 2 * L * kvh * B * hd) * compute_bytes
    # logits row the sampler consumes (f32 accumulators)
    logits = B * c.vocab_size * 4

    bf16_equiv = int(2 * L * kvh * hd * read_rows.sum()) * 2
    kv_bytes = kv_ctx + kv_ctx_scales + kv_ring
    total = param_bytes + kv_bytes + logits
    out = {
        "bytes_per_step_breakdown": {
            "weights": param_bytes,
            "kv_ctx": kv_ctx,
            "kv_ctx_scales": kv_ctx_scales,
            "kv_ring": kv_ring,
            "logits": logits,
        },
        "kv_bytes_per_step": kv_bytes,
        "total_bytes_per_step": total,
        # live-context ratio vs the bf16 layout (the <= 0.55x pin):
        # int8 payload + f32-per-group sidecar over bf16 payload
        "kv_ctx_bytes_vs_bf16": (
            (kv_ctx + kv_ctx_scales) / bf16_equiv if bf16_equiv else None
        ),
        "attn_roofline_frac": (
            steps_per_s * total / peak_bw
            if steps_per_s and peak_bw else None
        ),
    }
    return out
