"""Int8 KV-block economy: host-side helpers + metrics for the quantized
paged prefix pool.

With ``kv_quant="int8"`` on EngineConfig the paged pool (G1 prefix-cache
STORAGE) holds int8 pages with per-block-per-layer absmax scales; the hot
decode path stays bf16 (the serving ctx region is untouched). The
quantize happens once, inside the fused ``seal_blocks`` gather (ctx ->
pool); the dequantize happens once, inside ``load_ctx_pages`` (pool ->
ctx at admission). Everything DOWNSTREAM of the pool — G2/G3 host/disk
tiers, disagg pushes, G4 peer fetches, export streams — moves the int8
bytes plus the small scale sidecar, so a 16 GB chip holds ~2x the
hittable prefix corpus and every transfer/offload path ships half the
payload bytes.

This module owns the HOST representation: a page bundle (int8 data +
f32 scales), host-side quantize/dequantize for tier/mode boundaries
(a bf16 peer pushing into an int8 pool, or vice versa), the wire-header
encoding (scales ride the JSON header of the existing two-part frames —
they are ~1/(2*kvh*ps*hd) of the payload), and the ``dynamo_kv_quant_*``
metric families rendered on all three scrape surfaces.

Device-side quantize/dequantize lives in models/llama.py
(seal_blocks/load_ctx_pages/gather_pages_q/scatter_pages_q) — fused into
the existing pool-boundary programs, never a separate dispatch.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from dynamo_tpu.telemetry.metrics import CounterRegistry

# scale floor: a block of exact zeros must not divide by zero, and the
# floor must be far below any real bf16 activation scale
SCALE_EPS = 1e-8

FAMILIES: tuple[tuple[str, str, str], ...] = (
    ("dynamo_kv_quant_pages_total", "counter",
     "KV pages quantized to int8 at a pool/transfer boundary"),
    ("dynamo_kv_quant_dequant_pages_total", "counter",
     "int8 KV pages dequantized back to the compute dtype"),
    ("dynamo_kv_quant_scale_bytes_total", "counter",
     "bytes of per-block scale sidecars shipped alongside int8 pages"),
    ("dynamo_kv_pool_capacity_blocks", "gauge",
     "paged prefix-pool capacity in blocks (usable pages; int8 pools "
     "fit ~2x the blocks of a bf16 pool in the same HBM)"),
)

_HISTOGRAMS: tuple[tuple[str, str], ...] = (
    ("dynamo_kv_quant_dequant_seconds",
     "wall time of one host-side dequantize (tier/mode boundary "
     "conversions; the pool->ctx dequant is fused on device)"),
)

KV_QUANT = CounterRegistry(FAMILIES, _HISTOGRAMS, label="kv-quant")


@dataclass
class QuantizedPages:
    """Host bundle of int8 KV pages + their per-block-per-layer scales.

    ``data`` is int8 ``[2(k/v), L, kvh, n, ps, hd]`` (the same axis
    order as llama.gather_pages); ``scales`` is f32 ``[2, L, n]`` —
    one absmax scale per (k/v, layer, page). Consumers that only need
    geometry (page counts, byte accounting) use ``shape``/``nbytes``
    without caring whether they hold a plain array or a bundle."""

    data: np.ndarray
    scales: np.ndarray

    @property
    def shape(self) -> tuple[int, ...]:
        return self.data.shape

    @property
    def dtype(self) -> np.dtype:
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return self.data.nbytes + self.scales.nbytes

    @property
    def n_pages(self) -> int:
        return int(self.data.shape[3])

    def slice_pages(self, lo: int, hi: int) -> "QuantizedPages":
        return QuantizedPages(
            self.data[:, :, :, lo:hi], self.scales[:, :, lo:hi]
        )

    def page(self, i: int) -> tuple[np.ndarray, np.ndarray]:
        """(page [2, L, kvh, ps, hd], scale [2, L]) for one page."""
        return self.data[:, :, :, i], self.scales[:, :, i]

    def dequantize(self, dtype) -> np.ndarray:
        """Back to a dense array in ``dtype`` (tier/mode boundaries
        only — the pool->ctx path dequantizes on device)."""
        t0 = time.monotonic()
        out = (
            self.data.astype(np.float32)
            * self.scales[:, :, None, :, None, None]
        ).astype(dtype)
        KV_QUANT.observe(
            "dynamo_kv_quant_dequant_seconds", time.monotonic() - t0
        )
        KV_QUANT.inc("dynamo_kv_quant_dequant_pages_total", self.n_pages)
        return out


def quantize_pages(data: np.ndarray) -> QuantizedPages:
    """Host-side symmetric int8 quantize of dense pages
    ``[2, L, kvh, n, ps, hd]`` with per-(k/v, layer, page) absmax scales
    — the mode boundary for bf16 payloads entering an int8 pool (the
    ctx->pool seal quantizes on device instead)."""
    f = np.asarray(data, np.float32)
    s = np.maximum(
        np.abs(f).max(axis=(2, 4, 5)) / 127.0, SCALE_EPS
    )  # [2, L, n]
    q = np.clip(
        np.rint(f / s[:, :, None, :, None, None]), -127, 127
    ).astype(np.int8)
    KV_QUANT.inc("dynamo_kv_quant_pages_total", q.shape[3])
    return QuantizedPages(q, s.astype(np.float32))


def is_quantized(data: Any) -> bool:
    return isinstance(data, QuantizedPages)


# ---------------------------------------------------------------------------
# wire form: int8 payload + scales in the frame header (kv_transfer.py
# two-part frames). The scale sidecar is small enough for the JSON
# header — [2, L, n] f32 vs [2, L, kvh, n, ps, hd] int8 payload.

def attach_wire_scales(header: dict, qp: QuantizedPages) -> None:
    """Add the scale sidecar to an outgoing frame header (shape/dtype
    fields must describe ``qp.data``, which is the payload)."""
    header["kv_scales"] = [float(x) for x in qp.scales.ravel()]
    header["kv_scales_shape"] = list(qp.scales.shape)
    KV_QUANT.inc("dynamo_kv_quant_scale_bytes_total", qp.scales.nbytes)


def from_wire(arr: np.ndarray, header: dict):
    """Rebuild the receive-side value: a QuantizedPages when the frame
    carried scales, the plain array otherwise."""
    if "kv_scales" not in header:
        return arr
    scales = np.asarray(header["kv_scales"], np.float32).reshape(
        header["kv_scales_shape"]
    )
    return QuantizedPages(arr, scales)


def to_pool_dtype(data: Any, quantized_pool: bool, dtype) -> Any:
    """Convert an incoming page payload to what the local pool stores:
    bundles for an int8 pool (quantizing dense payloads from bf16
    peers), dense ``dtype`` arrays otherwise (dequantizing bundles from
    int8 peers). Identity when the payload already matches."""
    if quantized_pool:
        return data if is_quantized(data) else quantize_pages(data)
    if is_quantized(data):
        return data.dequantize(dtype)
    return data
