"""Int8 KV-block economy: host-side helpers + metrics for the quantized
paged prefix pool.

With ``kv_quant="int8"`` on EngineConfig the paged pool (G1 prefix-cache
STORAGE) holds int8 pages with per-block-per-layer absmax scales, and the
serving ctx region is int8 too: decode attention dequantizes each KV
chunk in VMEM right after the DMA (ops/flash_decode.py), so live-context
HBM traffic per step is ~halved. Quantization points: prefill/span
writes quantize on store, the once-per-round ring flush requantizes the
touched scale groups (the ring itself stays the compute dtype — it is
tiny), and pool<->ctx copies at seal/admission are RAW int8 page moves
(the group size equals the page size, so the representations are
identical — no quant/dequant pass at the pool boundary at all).
Everything DOWNSTREAM of the pool — G2/G3 host/disk tiers, disagg
pushes, G4 peer fetches, export streams — moves the int8 bytes plus the
small scale sidecar, so a 16 GB chip holds ~2x the hittable prefix
corpus and every transfer/offload path ships half the payload bytes.

This module owns the HOST representation: a page bundle (int8 data +
f32 scales), host-side quantize/dequantize for tier/mode boundaries
(a bf16 peer pushing into an int8 pool, or vice versa), the wire-header
encoding (scales ride the JSON header of the existing two-part frames —
they are ~1/(2*kvh*ps*hd) of the payload), the shared DEVICE group-
quantization helpers (``dequantize_groups``/``requantize_groups`` — the
one absmax grid used by the ctx flush/span writes in models/llama.py and
by the flash-decode reference path), and the ``dynamo_kv_quant_*``
metric families rendered on all three scrape surfaces.

The remaining device-side pool-boundary conversions (mixed dense/int8
seal and load, gather_pages_q/scatter_pages_q) live in models/llama.py —
fused into the existing programs, never a separate dispatch.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

from dynamo_tpu.telemetry.metrics import CounterRegistry

# scale floor: a block of exact zeros must not divide by zero, and the
# floor must be far below any real bf16 activation scale
SCALE_EPS = 1e-8

FAMILIES: tuple[tuple[str, str, str], ...] = (
    ("dynamo_kv_quant_pages_total", "counter",
     "KV pages quantized to int8 at a pool/transfer boundary"),
    ("dynamo_kv_quant_dequant_pages_total", "counter",
     "int8 KV pages dequantized back to the compute dtype"),
    ("dynamo_kv_quant_scale_bytes_total", "counter",
     "bytes of per-block scale sidecars shipped alongside int8 pages"),
    ("dynamo_kv_pool_capacity_blocks", "gauge",
     "paged prefix-pool capacity in blocks (usable pages; int8 pools "
     "fit ~2x the blocks of a bf16 pool in the same HBM)"),
    ("dynamo_kv_quant_ctx_seal_raw_pages_total", "counter",
     "pages sealed ctx->pool as raw int8 copies (group size == page "
     "size, so no requantize pass at the seal boundary)"),
    ("dynamo_kv_quant_ctx_admit_raw_pages_total", "counter",
     "pages admitted pool->ctx as raw int8 copies (no dequantize pass "
     "at admission — the kernel dequantizes in VMEM per chunk)"),
    ("dynamo_kv_quant_ctx_flush_groups_total", "counter",
     "ctx scale groups covered by ring-flush requantize windows "
     "(lanes x window groups, once per decode round)"),
)

_HISTOGRAMS: tuple[tuple[str, str], ...] = (
    ("dynamo_kv_quant_dequant_seconds",
     "wall time of one host-side dequantize (tier/mode boundary "
     "conversions; the pool->ctx dequant is fused on device)"),
)

KV_QUANT = CounterRegistry(FAMILIES, _HISTOGRAMS, label="kv-quant")


@dataclass
class QuantizedPages:
    """Host bundle of int8 KV pages + their per-block-per-layer scales.

    ``data`` is int8 ``[2(k/v), L, kvh, n, ps, hd]`` (the same axis
    order as llama.gather_pages); ``scales`` is f32 ``[2, L, n]`` —
    one absmax scale per (k/v, layer, page). Consumers that only need
    geometry (page counts, byte accounting) use ``shape``/``nbytes``
    without caring whether they hold a plain array or a bundle."""

    data: np.ndarray
    scales: np.ndarray

    @property
    def shape(self) -> tuple[int, ...]:
        return self.data.shape

    @property
    def dtype(self) -> np.dtype:
        return self.data.dtype

    @property
    def nbytes(self) -> int:
        return self.data.nbytes + self.scales.nbytes

    @property
    def n_pages(self) -> int:
        return int(self.data.shape[3])

    def slice_pages(self, lo: int, hi: int) -> "QuantizedPages":
        return QuantizedPages(
            self.data[:, :, :, lo:hi], self.scales[:, :, lo:hi]
        )

    def page(self, i: int) -> tuple[np.ndarray, np.ndarray]:
        """(page [2, L, kvh, ps, hd], scale [2, L]) for one page."""
        return self.data[:, :, :, i], self.scales[:, :, i]

    def dequantize(self, dtype) -> np.ndarray:
        """Back to a dense array in ``dtype`` (tier/mode boundaries
        only — the pool->ctx path dequantizes on device)."""
        t0 = time.monotonic()
        out = (
            self.data.astype(np.float32)
            * self.scales[:, :, None, :, None, None]
        ).astype(dtype)
        KV_QUANT.observe(
            "dynamo_kv_quant_dequant_seconds", time.monotonic() - t0
        )
        KV_QUANT.inc("dynamo_kv_quant_dequant_pages_total", self.n_pages)
        return out


def quantize_pages(data: np.ndarray) -> QuantizedPages:
    """Host-side symmetric int8 quantize of dense pages
    ``[2, L, kvh, n, ps, hd]`` with per-(k/v, layer, page) absmax scales
    — the mode boundary for bf16 payloads entering an int8 pool (the
    ctx->pool seal quantizes on device instead)."""
    f = np.asarray(data, np.float32)
    s = np.maximum(
        np.abs(f).max(axis=(2, 4, 5)) / 127.0, SCALE_EPS
    )  # [2, L, n]
    q = np.clip(
        np.rint(f / s[:, :, None, :, None, None]), -127, 127
    ).astype(np.int8)
    KV_QUANT.inc("dynamo_kv_quant_pages_total", q.shape[3])
    return QuantizedPages(q, s.astype(np.float32))


def is_quantized(data: Any) -> bool:
    return isinstance(data, QuantizedPages)


# ---------------------------------------------------------------------------
# wire form: int8 payload + scales in the frame header (kv_transfer.py
# two-part frames). The scale sidecar is small enough for the JSON
# header — [2, L, n] f32 vs [2, L, kvh, n, ps, hd] int8 payload.

def attach_wire_scales(header: dict, qp: QuantizedPages) -> None:
    """Add the scale sidecar to an outgoing frame header (shape/dtype
    fields must describe ``qp.data``, which is the payload)."""
    header["kv_scales"] = [float(x) for x in qp.scales.ravel()]
    header["kv_scales_shape"] = list(qp.scales.shape)
    KV_QUANT.inc("dynamo_kv_quant_scale_bytes_total", qp.scales.nbytes)


def from_wire(arr: np.ndarray, header: dict):
    """Rebuild the receive-side value: a QuantizedPages when the frame
    carried scales, the plain array otherwise."""
    if "kv_scales" not in header:
        return arr
    scales = np.asarray(header["kv_scales"], np.float32).reshape(
        header["kv_scales_shape"]
    )
    return QuantizedPages(arr, scales)


# ---------------------------------------------------------------------------
# Device-side group quantization (jnp; traced inside the fused round /
# prefill programs — pure, no host effects).
#
# The int8 ctx region stores per-(layer, lane, position-group) absmax
# scales with group == page_size. That granularity is deliberately the
# POOL's granularity (one scale per [kvh, ps, hd] block, no head axis —
# pinned by the PR 7 tier/wire format), so ctx<->pool copies are
# representation-identical raw int8 moves. It is coarser than a
# per-head grid, but the PR 7 measurements (max logprob delta 0.005 at
# this exact grid) showed the quality budget is comfortable.
#
# Determinism rule: a write's scale depends ONLY on the request's own
# data. `written` marks the groups a write overlaps (their scale is
# recomputed); `valid` masks which window positions feed the absmax
# (current-request prefix + the new span — NEVER the stale suffix left
# by a previous slot occupant, which would make quantization depend on
# slot-reuse history). Untouched groups keep their scale bit-exactly,
# and dequant->requant with an unchanged scale is exact after rounding
# (|q| <= 127 in f32), so they never drift.

def dequantize_groups(
    q: jnp.ndarray,        # int8 [L, kvh, N, W, hd]
    scales: jnp.ndarray,   # f32 [L, N, W//group]
    group: int,
) -> jnp.ndarray:
    """Per-group dequantize of N windows back to f32."""
    L, kvh, N, W, hd = q.shape
    g = q.reshape(L, kvh, N, W // group, group, hd).astype(jnp.float32)
    out = g * scales[:, None, :, :, None, None]
    return out.reshape(L, kvh, N, W, hd)


def requantize_groups(
    wf: jnp.ndarray,       # f32 [L, kvh, N, W, hd] — dequantized windows
                           # with the new span already overlaid
    old_scale: jnp.ndarray,  # f32 [L, N, W//group]
    valid: jnp.ndarray,    # bool [N, W] — positions feeding the absmax
    written: jnp.ndarray,  # bool [N, W//group] — groups whose scale is
                           # recomputed (overlap the write)
    group: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Requantize N windows: written groups get a fresh absmax scale
    over their valid positions; untouched groups round-trip exactly
    through their old scale. Returns (int8 windows, new scales)."""
    L, kvh, N, W, hd = wf.shape
    nW = W // group
    gw = wf.reshape(L, kvh, N, nW, group, hd)
    vm = valid.reshape(N, nW, group)
    am = jnp.max(
        jnp.where(vm[None, None, :, :, :, None], jnp.abs(gw), 0.0),
        axis=(1, 4, 5),
    )  # [L, N, nW]
    fresh = jnp.maximum(am / 127.0, SCALE_EPS)
    new_scale = jnp.where(written[None], fresh, old_scale)
    div = jnp.maximum(new_scale, SCALE_EPS)[:, None, :, :, None, None]
    q = jnp.clip(jnp.round(gw / div), -127, 127).astype(jnp.int8)
    return q.reshape(L, kvh, N, W, hd), new_scale


def to_pool_dtype(data: Any, quantized_pool: bool, dtype) -> Any:
    """Convert an incoming page payload to what the local pool stores:
    bundles for an int8 pool (quantizing dense payloads from bf16
    peers), dense ``dtype`` arrays otherwise (dequantizing bundles from
    int8 peers). Identity when the payload already matches."""
    if quantized_pool:
        return data if is_quantized(data) else quantize_pages(data)
    if is_quantized(data):
        return data.dequantize(dtype)
    return data
