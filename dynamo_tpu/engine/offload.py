"""Host-DRAM KV offload tier (KVBM G2 — reference block_manager/offload.rs).

Reference shape (offload.rs:46-80, pool.rs:156): blocks leaving the device
pool's reuse set are offloaded down the tier hierarchy (G1 HBM -> G2 DRAM
-> G3 disk) through a priority queue with batched transfers; prefix hits
consult lower tiers and onboard blocks back up. This buys the BASELINE's
"40% TTFT from KV offload to CPU RAM" on multi-turn traffic whose working
set exceeds HBM.

TPU redesign: offload piggybacks on the engine's pipelined round loop —
candidates are pages PARKED in the allocator's LRU (committed, refcount 0);
once per round the engine validates them (hash still owns the page),
batch-gathers them in one fused jit, and fetches device->host
asynchronously behind compute (same copy_to_host_async pipeline as token
fetches). Nothing blocks the decode path. Onboard is the reverse: at
admission, a contiguous run of G2 blocks extends the G1 prefix match via
one scatter jit (async H2D upload; prefill follows in device order).

This module owns only the host pool + hash registry; the device side
(gather/scatter, validation, scheduling) lives in engine.py.
"""
from __future__ import annotations

import logging
from collections import OrderedDict
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)


class HostOffloadTier:
    """Fixed-capacity host pool of KV pages keyed by chained block hash.

    Slots hold [2(k/v), L, kvh, ps, hd] per page. LRU eviction on
    pressure. Single-owner (the engine loop) except for read-only counter
    access."""

    def __init__(self, num_pages: int, page_shape: tuple, dtype):
        # page_shape = (2, L, kvh, ps, hd); pool adds the page axis at 3
        self.num_pages = num_pages
        self.page_shape = tuple(page_shape)
        self.dtype = np.dtype(dtype)
        self._pool: Optional[np.ndarray] = None  # lazy: it can be GBs
        # hash -> (slot, parent_hash); insertion order = LRU order
        self._index: "OrderedDict[int, tuple[int, int]]" = OrderedDict()
        self._free: list[int] = list(range(num_pages))
        # counters
        self.pages_offloaded = 0
        self.onboard_hits = 0
        self.lookups = 0

    def _ensure_pool(self) -> np.ndarray:
        if self._pool is None:
            shape = (
                self.page_shape[0], self.page_shape[1], self.page_shape[2],
                self.num_pages, self.page_shape[3], self.page_shape[4],
            )
            self._pool = np.zeros(shape, self.dtype)
        return self._pool

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._index

    def __len__(self) -> int:
        return len(self._index)

    def put_batch(
        self, hashes: list[int], parents: list[int], data: np.ndarray
    ) -> int:
        """Store gathered pages (data [2, L, kvh, n, ps, hd], aligned with
        hashes). Existing entries are refreshed in LRU order. Returns the
        number of new pages stored."""
        pool = self._ensure_pool()
        stored = 0
        for i, (h, parent) in enumerate(zip(hashes, parents)):
            if h in self._index:
                self._index.move_to_end(h)
                continue
            if not self._free:
                old_h, (old_slot, _) = self._index.popitem(last=False)
                self._free.append(old_slot)
            slot = self._free.pop()
            pool[:, :, :, slot] = data[:, :, :, i]
            self._index[h] = (slot, parent)
            stored += 1
        self.pages_offloaded += stored
        return stored

    def lookup_run(self, hashes: list[int]) -> list[tuple[int, int]]:
        """Longest leading run of hashes present in the tier; returns
        [(hash, parent_hash), ...] and refreshes their LRU position."""
        self.lookups += len(hashes)
        run: list[tuple[int, int]] = []
        for h in hashes:
            ent = self._index.get(h)
            if ent is None:
                break
            self._index.move_to_end(h)
            run.append((h, ent[1]))
        self.onboard_hits += len(run)
        return run

    def gather(self, hashes: list[int]) -> np.ndarray:
        """Pages for the given (present) hashes: [2, L, kvh, n, ps, hd]."""
        pool = self._ensure_pool()
        slots = [self._index[h][0] for h in hashes]
        return pool[:, :, :, slots]

    def drop(self, block_hash: int) -> None:
        ent = self._index.pop(block_hash, None)
        if ent is not None:
            self._free.append(ent[0])

    def clear(self) -> int:
        n = len(self._index)
        for h in list(self._index):
            self.drop(h)
        return n
