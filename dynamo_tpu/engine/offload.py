"""Host-side KV offload tiers: G2 DRAM + G3 disk (KVBM — reference
block_manager/offload.rs, block_manager/storage/disk.rs:25).

Reference shape (offload.rs:46-80, pool.rs:156, block_manager.rs:69-82):
blocks leaving the device pool's reuse set are offloaded down the tier
hierarchy (G1 HBM -> G2 DRAM -> G3 disk) through a priority queue with
batched transfers; prefix hits consult lower tiers and onboard blocks back
up. This buys the BASELINE's "40% TTFT from KV offload to CPU RAM" on
multi-turn traffic whose working set exceeds HBM, and G3 extends the
reusable corpus past DRAM.

TPU redesign: offload piggybacks on the engine's pipelined round loop —
candidates are pages PARKED in the allocator's LRU (committed, refcount 0);
once per round the engine validates them (hash still owns the page),
batch-gathers them in one fused jit, and fetches device->host
asynchronously behind compute (same copy_to_host_async pipeline as token
fetches). Nothing blocks the decode path. Onboard is the reverse: at
admission, a contiguous run of G2/G3 blocks extends the G1 prefix match
via one scatter jit (async H2D upload; prefill follows in device order).

The G3 tier is an mmap-backed page pool: G2's LRU evictions spill DOWN
into it (instead of being dropped), and prefix lookups fall through G2
into G3 mid-run, so a run may be assembled from both tiers. Writes go
through the OS page cache (no fsync on the hot path) — G3 is a cache, not
durable state; its file is recreated at engine start.

This module owns only the host pools + hash registries; the device side
(gather/scatter, validation, scheduling) lives in engine.py.
"""
from __future__ import annotations

import logging
import os
import tempfile
from collections import OrderedDict
from typing import Optional

import numpy as np

log = logging.getLogger(__name__)


class _PageTier:
    """Fixed-capacity pool of KV pages keyed by chained block hash.

    Slots hold [2(k/v), L, kvh, ps, hd] per page; the pool array adds the
    page axis at 3. LRU eviction on pressure. Single-owner (the engine
    loop) except for read-only counter access. Subclasses provide the
    backing storage via ``_ensure_pool``."""

    def __init__(self, num_pages: int, page_shape: tuple, dtype,
                 scale_shape: tuple = ()):
        # page_shape = (2, L, kvh, ps, hd)
        self.num_pages = num_pages
        self.page_shape = tuple(page_shape)
        self.dtype = np.dtype(dtype)
        self._pool = None  # lazy: it can be GBs
        # int8 pools (kv_quant) carry a per-page scale sidecar of this
        # shape (typically (2, L)); scales are tiny and stay in RAM for
        # every tier — even the mmap-backed G3 (its file only holds page
        # payloads; the tier is a cache recreated at engine start)
        self.scale_shape = tuple(scale_shape)
        self._scale_pool: Optional[np.ndarray] = None
        # hash -> (slot, parent_hash); insertion order = LRU order
        self._index: "OrderedDict[int, tuple[int, int]]" = OrderedDict()
        self._free: list[int] = list(range(num_pages))
        # counters
        self.pages_offloaded = 0
        self.onboard_hits = 0
        self.lookups = 0

    @property
    def pool_shape(self) -> tuple:
        return (
            self.page_shape[0], self.page_shape[1], self.page_shape[2],
            self.num_pages, self.page_shape[3], self.page_shape[4],
        )

    def _ensure_pool(self) -> np.ndarray:
        raise NotImplementedError

    def _ensure_scales(self) -> np.ndarray:
        if self._scale_pool is None:
            self._scale_pool = np.zeros(
                self.scale_shape + (self.num_pages,), np.float32
            )
        return self._scale_pool

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._index

    def __len__(self) -> int:
        return len(self._index)

    def _evict_one(self) -> None:
        """Drop the LRU entry to free a slot (hook point for spill)."""
        old_h, (old_slot, _) = self._index.popitem(last=False)
        self._free.append(old_slot)

    def put_one(self, h: int, parent: int, page: np.ndarray,
                scale: Optional[np.ndarray] = None) -> bool:
        """Store one page ([2, L, kvh, ps, hd]); False if already held.
        ``scale`` ([*scale_shape]) rides along for int8 pools."""
        if h in self._index:
            self._index.move_to_end(h)
            return False
        pool = self._ensure_pool()
        if not self._free:
            self._evict_one()
        slot = self._free.pop()
        pool[:, :, :, slot] = page
        if self.scale_shape:
            self._ensure_scales()[..., slot] = (
                scale if scale is not None else 0.0
            )
        self._index[h] = (slot, parent)
        self.pages_offloaded += 1
        return True

    def put_batch(
        self, hashes: list[int], parents: list[int], data,
        scales: Optional[np.ndarray] = None,
    ) -> int:
        """Store gathered pages (data [2, L, kvh, n, ps, hd] — or a
        kv_quant.QuantizedPages bundle — aligned with hashes). Existing
        entries are refreshed in LRU order. Returns the number of new
        pages stored."""
        if scales is None and hasattr(data, "scales"):
            data, scales = data.data, data.scales
        stored = 0
        for i, (h, parent) in enumerate(zip(hashes, parents)):
            stored += bool(self.put_one(
                h, parent, data[:, :, :, i],
                scales[..., i] if scales is not None else None,
            ))
        return stored

    def lookup_run(self, hashes: list[int]) -> list[tuple[int, int]]:
        """Longest leading run of hashes present in the tier; returns
        [(hash, parent_hash), ...] and refreshes their LRU position."""
        self.lookups += len(hashes)
        run: list[tuple[int, int]] = []
        for h in hashes:
            ent = self._index.get(h)
            if ent is None:
                break
            self._index.move_to_end(h)
            run.append((h, ent[1]))
        self.onboard_hits += len(run)
        return run

    def gather(self, hashes: list[int]) -> np.ndarray:
        """Pages for the given (present) hashes: [2, L, kvh, n, ps, hd]."""
        pool = self._ensure_pool()
        slots = [self._index[h][0] for h in hashes]
        return pool[:, :, :, slots]

    def gather_scales(self, hashes: list[int]) -> Optional[np.ndarray]:
        """Scale sidecar aligned with ``gather`` ([*scale_shape, n]);
        None for unquantized tiers."""
        if not self.scale_shape:
            return None
        scales = self._ensure_scales()
        slots = [self._index[h][0] for h in hashes]
        return scales[..., slots]

    def read_page(self, block_hash: int) -> np.ndarray:
        """One page [2, L, kvh, ps, hd] (must be present)."""
        pool = self._ensure_pool()
        return pool[:, :, :, self._index[block_hash][0]]

    def read_scale(self, block_hash: int) -> Optional[np.ndarray]:
        if not self.scale_shape:
            return None
        return self._ensure_scales()[..., self._index[block_hash][0]]

    def drop(self, block_hash: int) -> None:
        ent = self._index.pop(block_hash, None)
        if ent is not None:
            self._free.append(ent[0])

    def clear(self) -> int:
        n = len(self._index)
        for h in list(self._index):
            self.drop(h)
        return n


class DiskOffloadTier(_PageTier):
    """G3: mmap-backed page pool (reference storage/disk.rs:25,
    block_manager.rs:69-82 CacheLevel::G3). The file is a plain dense
    array; the OS page cache absorbs write bursts and serves hot reads,
    so spill/onboard never issue synchronous IO on the engine loop."""

    def __init__(self, num_pages: int, page_shape: tuple, dtype,
                 path: Optional[str] = None, scale_shape: tuple = ()):
        super().__init__(num_pages, page_shape, dtype,
                         scale_shape=scale_shape)
        self.path = path
        self._owns_file = path is None

    def _ensure_pool(self) -> np.ndarray:
        if self._pool is None:
            if self.path is None:
                fd, self.path = tempfile.mkstemp(
                    prefix="dynamo-tpu-kv-g3-", suffix=".mmap"
                )
                os.close(fd)
            self._pool = np.memmap(
                self.path, dtype=self.dtype, mode="w+",
                shape=self.pool_shape,
            )
            log.info(
                "G3 disk tier: %d pages (%.1f MB) at %s", self.num_pages,
                np.prod(self.pool_shape) * self.dtype.itemsize / 1e6,
                self.path,
            )
        return self._pool

    def close(self) -> None:
        if self._pool is not None:
            self._pool._mmap.close()
            self._pool = None
        if self._owns_file and self.path and os.path.exists(self.path):
            os.unlink(self.path)
            self.path = None


class HostOffloadTier(_PageTier):
    """G2: host-DRAM pool. With a ``spill`` tier attached, LRU evictions
    cascade DOWN into it (G2 -> G3) instead of being dropped, and
    ``lookup_run``/``gather`` fall through to it mid-run, so one onboard
    can be assembled from both tiers (reference offload.rs tier walk)."""

    def __init__(self, num_pages: int, page_shape: tuple, dtype,
                 spill: Optional[_PageTier] = None,
                 scale_shape: tuple = ()):
        super().__init__(num_pages, page_shape, dtype,
                         scale_shape=scale_shape)
        self.spill = spill

    def _ensure_pool(self) -> np.ndarray:
        if self._pool is None:
            self._pool = np.zeros(self.pool_shape, self.dtype)
        return self._pool

    def _evict_one(self) -> None:
        old_h, (old_slot, old_parent) = self._index.popitem(last=False)
        if self.spill is not None:
            self.spill.put_one(
                old_h, old_parent, self._ensure_pool()[:, :, :, old_slot],
                (self._ensure_scales()[..., old_slot]
                 if self.scale_shape else None),
            )
        self._free.append(old_slot)

    def lookup_run(self, hashes: list[int]) -> list[tuple[int, int]]:
        self.lookups += len(hashes)
        run: list[tuple[int, int]] = []
        for h in hashes:
            ent = self._index.get(h)
            if ent is not None:
                self._index.move_to_end(h)
                run.append((h, ent[1]))
                continue
            if self.spill is not None:
                sub = self.spill.lookup_run([h])
                if sub:
                    run.append(sub[0])
                    continue
            break
        self.onboard_hits += len(run)
        return run

    def gather(self, hashes: list[int]) -> np.ndarray:
        out = np.empty(
            self.page_shape[:3] + (len(hashes),) + self.page_shape[3:],
            self.dtype,
        )
        for i, h in enumerate(hashes):
            if h in self._index:
                out[:, :, :, i] = self.read_page(h)
            else:
                out[:, :, :, i] = self.spill.read_page(h)
        return out

    def gather_scales(self, hashes: list[int]) -> Optional[np.ndarray]:
        if not self.scale_shape:
            return None
        out = np.empty(self.scale_shape + (len(hashes),), np.float32)
        for i, h in enumerate(hashes):
            if h in self._index:
                out[..., i] = self.read_scale(h)
            else:
                out[..., i] = self.spill.read_scale(h)
        return out

    def clear(self) -> int:
        n = super().clear()
        if self.spill is not None:
            n += self.spill.clear()
        return n
