"""Host-side KV offload tiers: G2 DRAM + G3 disk (KVBM — reference
block_manager/offload.rs, block_manager/storage/disk.rs:25).

Reference shape (offload.rs:46-80, pool.rs:156, block_manager.rs:69-82):
blocks leaving the device pool's reuse set are offloaded down the tier
hierarchy (G1 HBM -> G2 DRAM -> G3 disk) through a priority queue with
batched transfers; prefix hits consult lower tiers and onboard blocks back
up. This buys the BASELINE's "40% TTFT from KV offload to CPU RAM" on
multi-turn traffic whose working set exceeds HBM, and G3 extends the
reusable corpus past DRAM.

TPU redesign: offload piggybacks on the engine's pipelined round loop —
candidates are pages PARKED in the allocator's LRU (committed, refcount 0);
once per round the engine validates them (hash still owns the page),
batch-gathers them in one fused jit, and fetches device->host
asynchronously behind compute (same copy_to_host_async pipeline as token
fetches). Nothing blocks the decode path. Onboard is the reverse: at
admission, a contiguous run of G2/G3 blocks extends the G1 prefix match
via one scatter jit (async H2D upload; prefill follows in device order).

The G3 tier is an mmap-backed page pool: G2's LRU evictions spill DOWN
into it (instead of being dropped), and prefix lookups fall through G2
into G3 mid-run, so a run may be assembled from both tiers. Writes go
through the OS page cache (no fsync on the hot path).

Integrity plane (kv_integrity.py): every index entry carries the block's
content crc, minted at first host materialization; ``verify_pages``
checks gathered bytes against it at onboard admission, and a shared
``KvQuarantine`` makes tier puts refuse hashes that ever failed.

Crash consistency (G3): when the tier has an operator-provided ``path``
it journals a sidecar manifest (``<path>.manifest``, JSON lines:
slot -> hash/parent/crc/scale, compacted via atomic rename) and replays
it at attach, so the disk corpus survives an engine restart. A startup
scrub (lazy by default, eager with ``scrub_on_start``) verifies or drops
entries — torn writes come back as plain cache misses.

This module owns only the host pools + hash registries; the device side
(gather/scatter, validation, scheduling) lives in engine.py.
"""
from __future__ import annotations

import itertools
import json
import logging
import os
import tempfile
from collections import OrderedDict
from typing import Callable, Optional

import numpy as np

from dynamo_tpu.kv_fleet_metrics import KV_FLEET
from dynamo_tpu.kv_integrity import (
    KV_INTEGRITY,
    KvQuarantine,
    page_checksum,
)

log = logging.getLogger(__name__)

# journal compaction threshold: rewrite the manifest once the journal
# carries this many times more lines than live entries could need
_JOURNAL_SLACK = 4

# replication-aware eviction scans this many LRU-oldest entries for a
# well-replicated victim before falling back to the plain LRU head —
# bounded so eviction stays O(1)-ish under pressure
_EVICT_SCAN = 8


def _chaos():
    # lazy: resilience.chaos imports metrics/overload; keep the tier
    # importable standalone and pay one module-dict lookup per gather
    from dynamo_tpu.resilience.chaos import CHAOS

    return CHAOS


class _PageTier:
    """Fixed-capacity pool of KV pages keyed by chained block hash.

    Slots hold [2(k/v), L, kvh, ps, hd] per page; the pool array adds the
    page axis at 3. LRU eviction on pressure. Single-owner (the engine
    loop) except for read-only counter access. Subclasses provide the
    backing storage via ``_ensure_pool``."""

    def __init__(self, num_pages: int, page_shape: tuple, dtype,
                 scale_shape: tuple = (),
                 quarantine: Optional[KvQuarantine] = None):
        # page_shape = (2, L, kvh, ps, hd)
        self.num_pages = num_pages
        self.page_shape = tuple(page_shape)
        self.dtype = np.dtype(dtype)
        self._pool = None  # lazy: it can be GBs
        # int8 pools (kv_quant) carry a per-page scale sidecar of this
        # shape (typically (2, L)); scales are tiny and stay in RAM for
        # every tier — the G3 manifest additionally journals them so a
        # restored disk tier can still dequantize
        self.scale_shape = tuple(scale_shape)
        self._scale_pool: Optional[np.ndarray] = None
        # hash -> (slot, parent_hash, crc); insertion order = LRU order
        self._index: "OrderedDict[int, tuple[int, int, int]]" = (
            OrderedDict()
        )
        self._free: list[int] = list(range(num_pages))
        # shared deny-list: hashes that failed verification are refused
        # (puts no-op, lookups miss) until their quarantine TTL lapses
        self.quarantine = quarantine
        # fleet prefix economy: when wired (engine.apply_fleet_hints),
        # maps hash -> known fleet replica count (None = unknown) and
        # eviction prefers well-replicated blocks over the last copy
        self.fleet_replicas: Optional[Callable[[int], Optional[int]]] = None
        # counters
        self.pages_offloaded = 0
        self.onboard_hits = 0
        self.lookups = 0

    @property
    def pool_shape(self) -> tuple:
        return (
            self.page_shape[0], self.page_shape[1], self.page_shape[2],
            self.num_pages, self.page_shape[3], self.page_shape[4],
        )

    def _ensure_pool(self) -> np.ndarray:
        raise NotImplementedError

    def _ensure_scales(self) -> np.ndarray:
        if self._scale_pool is None:
            self._scale_pool = np.zeros(
                self.scale_shape + (self.num_pages,), np.float32
            )
        return self._scale_pool

    def __contains__(self, block_hash: int) -> bool:
        return block_hash in self._index

    def __len__(self) -> int:
        return len(self._index)

    # -- journal hooks (no-ops except for the manifest-backed G3) --

    def _on_put(self, h: int, parent: int, slot: int, crc: int,
                scale: Optional[np.ndarray]) -> None:
        pass

    def _on_drop(self, h: int) -> None:
        pass

    def _pick_victim(self) -> int:
        """Choose the hash to evict. Plain LRU head unless the fleet
        replica hook is wired: then scan the ``_EVICT_SCAN`` oldest
        entries and evict the best-replicated one (>= 2 known fleet
        copies, oldest wins ties), so the fleet's LAST copy of a warm
        block outlives the eighth copy of the same system prompt."""
        head = next(iter(self._index))
        fn = self.fleet_replicas
        if fn is None:
            return head
        best_h = None
        best_r = 1
        for h in itertools.islice(self._index, _EVICT_SCAN):
            try:
                r = fn(h)
            except Exception:  # noqa: BLE001 — stale hints must not block eviction
                log.debug("fleet replica lookup failed for %#x", h,
                          exc_info=True)
                r = None
            if r is not None and r > best_r:
                best_h, best_r = h, r
        if best_h is not None:
            KV_FLEET.inc("dynamo_kv_fleet_replicated_evictions_total")
            return best_h
        try:
            head_r = fn(head)
        except Exception:  # noqa: BLE001 — stale hints must not block eviction
            log.debug("fleet replica lookup failed for %#x", head,
                      exc_info=True)
            head_r = None
        if head_r is not None and head_r <= 1:
            KV_FLEET.inc("dynamo_kv_fleet_last_copy_evictions_total")
        return head

    def _evict_one(self) -> None:
        """Drop one entry to free a slot (hook point for spill)."""
        old_h = self._pick_victim()
        old_slot, _, _ = self._index.pop(old_h)
        self._free.append(old_slot)
        self._on_drop(old_h)

    def put_one(self, h: int, parent: int, page: np.ndarray,
                scale: Optional[np.ndarray] = None,
                checksum: Optional[int] = None) -> bool:
        """Store one page ([2, L, kvh, ps, hd]); False if already held
        or quarantined. ``scale`` ([*scale_shape]) rides along for int8
        pools. ``checksum`` is the block's content crc — minted here
        (first materialization) when the caller doesn't carry one."""
        if self.quarantine is not None and h in self.quarantine:
            return False
        if h in self._index:
            self._index.move_to_end(h)
            return False
        pool = self._ensure_pool()
        if not self._free:
            self._evict_one()
        slot = self._free.pop()
        pool[:, :, :, slot] = page
        if self.scale_shape:
            self._ensure_scales()[..., slot] = (
                scale if scale is not None else 0.0
            )
        if checksum is None:
            checksum = page_checksum(
                pool[:, :, :, slot],
                self._ensure_scales()[..., slot]
                if self.scale_shape else None,
            )
        self._index[h] = (slot, parent, checksum)
        self.pages_offloaded += 1
        self._on_put(h, parent, slot, checksum,
                     scale if self.scale_shape else None)
        return True

    def put_batch(
        self, hashes: list[int], parents: list[int], data,
        scales: Optional[np.ndarray] = None,
        checksums: Optional[list[int]] = None,
    ) -> int:
        """Store gathered pages (data [2, L, kvh, n, ps, hd] — or a
        kv_quant.QuantizedPages bundle — aligned with hashes). Existing
        entries are refreshed in LRU order. Returns the number of new
        pages stored."""
        if scales is None and hasattr(data, "scales"):
            data, scales = data.data, data.scales
        stored = 0
        for i, (h, parent) in enumerate(zip(hashes, parents)):
            stored += bool(self.put_one(
                h, parent, data[:, :, :, i],
                scales[..., i] if scales is not None else None,
                checksums[i] if checksums is not None else None,
            ))
        return stored

    def lookup_run(self, hashes: list[int]) -> list[tuple[int, int]]:
        """Longest leading run of hashes present in the tier; returns
        [(hash, parent_hash), ...] and refreshes their LRU position."""
        self.lookups += len(hashes)
        run: list[tuple[int, int]] = []
        for h in hashes:
            ent = self._index.get(h)
            if ent is None:
                break
            self._index.move_to_end(h)
            run.append((h, ent[1]))
        self.onboard_hits += len(run)
        return run

    def checksum_of(self, block_hash: int) -> Optional[int]:
        ent = self._index.get(block_hash)
        return None if ent is None else ent[2]

    def verify_pages(self, hashes: list[int], data,
                     scales: Optional[np.ndarray] = None) -> list[int]:
        """Check gathered bytes against the stored content crcs; returns
        the indices of mismatching pages (counters updated here)."""
        if scales is None and hasattr(data, "scales"):
            data, scales = data.data, data.scales
        bad: list[int] = []
        for i, h in enumerate(hashes):
            want = self.checksum_of(h)
            if want is None:
                continue
            got = page_checksum(
                data[:, :, :, i],
                scales[..., i] if scales is not None else None,
            )
            if got != want:
                bad.append(i)
        if bad:
            KV_INTEGRITY.inc("dynamo_kv_integrity_failed_total",
                             len(bad))
        KV_INTEGRITY.inc("dynamo_kv_integrity_verified_total",
                         len(hashes) - len(bad))
        return bad

    def gather(self, hashes: list[int]) -> np.ndarray:
        """Pages for the given (present) hashes: [2, L, kvh, n, ps, hd].
        The result is always a copy — chaos bit-flips mutate it without
        touching the pool (a *detectable* in-flight corruption)."""
        pool = self._ensure_pool()
        slots = [self._index[h][0] for h in hashes]
        out = pool[:, :, :, slots]
        _chaos().maybe_flip_bits(out)
        return out

    def gather_scales(self, hashes: list[int]) -> Optional[np.ndarray]:
        """Scale sidecar aligned with ``gather`` ([*scale_shape, n]);
        None for unquantized tiers."""
        if not self.scale_shape:
            return None
        scales = self._ensure_scales()
        slots = [self._index[h][0] for h in hashes]
        return scales[..., slots]

    def read_page(self, block_hash: int) -> np.ndarray:
        """One page [2, L, kvh, ps, hd] (must be present)."""
        pool = self._ensure_pool()
        return pool[:, :, :, self._index[block_hash][0]]

    def read_scale(self, block_hash: int) -> Optional[np.ndarray]:
        if not self.scale_shape:
            return None
        return self._ensure_scales()[..., self._index[block_hash][0]]

    def rot_page(self, block_hash: int) -> bool:
        """Flip one byte of the POOL-RESIDENT copy of a page WITHOUT
        touching its sealed crc — models silent post-seal rot (DRAM
        flip, torn disk write). The next gather+verify_pages over the
        block fails closed: this is what the ``corrupt_prefetch`` chaos
        point fires on fleet-prefetched pages."""
        ent = self._index.get(block_hash)
        if ent is None:
            return False
        pool = self._ensure_pool()
        view = pool[:, :, :, ent[0]]
        idx = (0,) * view.ndim
        raw = bytearray(np.asarray(view[idx]).tobytes())
        raw[0] ^= 0x01
        view[idx] = np.frombuffer(bytes(raw), dtype=self.dtype)[0]
        return True

    def drop(self, block_hash: int) -> None:
        ent = self._index.pop(block_hash, None)
        if ent is not None:
            self._free.append(ent[0])
            self._on_drop(block_hash)

    def drop_everywhere(self, block_hash: int) -> None:
        """Quarantine support: purge the hash from this tier (and any
        lower tier — see HostOffloadTier)."""
        self.drop(block_hash)

    def clear(self) -> int:
        n = len(self._index)
        for h in list(self._index):
            self.drop(h)
        return n


class DiskOffloadTier(_PageTier):
    """G3: mmap-backed page pool (reference storage/disk.rs:25,
    block_manager.rs:69-82 CacheLevel::G3). The file is a plain dense
    array; the OS page cache absorbs write bursts and serves hot reads,
    so spill/onboard never issue synchronous IO on the engine loop.

    With an operator-provided ``path`` the tier is restart-survivable: a
    sidecar manifest (``<path>.manifest``) journals every put/drop and is
    replayed at attach. Pages are written to the mmap BEFORE their
    journal line, so a crash can leave an orphaned page (harmless — the
    slot is reused) but never a journal entry pointing at unwritten
    bytes that would verify; torn journal tails are skipped line-wise."""

    def __init__(self, num_pages: int, page_shape: tuple, dtype,
                 path: Optional[str] = None, scale_shape: tuple = (),
                 quarantine: Optional[KvQuarantine] = None,
                 scrub_on_start: bool = False):
        super().__init__(num_pages, page_shape, dtype,
                         scale_shape=scale_shape, quarantine=quarantine)
        self.path = path
        self._owns_file = path is None
        self.scrub_on_start = bool(scrub_on_start)
        self._journal = None  # open append handle to the manifest
        self._journal_lines = 0
        self.scrub_recovered = 0
        self.scrub_dropped = 0
        if path is not None and os.path.exists(path):
            self._attach()
        elif (self.manifest_path is not None
              and os.path.exists(self.manifest_path)):
            # manifest without its pool file: stale — entries would
            # point into fresh zeros; start clean instead
            os.unlink(self.manifest_path)

    # -- backing file --

    @property
    def manifest_path(self) -> Optional[str]:
        return None if self.path is None else self.path + ".manifest"

    def _ensure_pool(self) -> np.ndarray:
        if self._pool is None:
            if self.path is None:
                fd, self.path = tempfile.mkstemp(
                    prefix="dynamo-tpu-kv-g3-", suffix=".mmap"
                )
                os.close(fd)
            nbytes = int(np.prod(self.pool_shape)) * self.dtype.itemsize
            exists = os.path.exists(self.path)
            size = os.path.getsize(self.path) if exists else 0
            if exists and 0 < size < nbytes:
                # truncated mid-growth (crash) or short operator file:
                # extend sparsely — the zero tail fails crc at scrub and
                # its blocks come back as misses instead of SIGBUS
                os.truncate(self.path, nbytes)
                size = nbytes
            # pre-existing files attach with "r+" (a "w+" open would
            # zero a restart-survivable corpus or an operator's file)
            mode = "r+" if exists and size >= nbytes else "w+"
            self._pool = np.memmap(
                self.path, dtype=self.dtype, mode=mode,
                shape=self.pool_shape,
            )
            log.info(
                "G3 disk tier: %d pages (%.1f MB) at %s (%s)",
                self.num_pages,
                np.prod(self.pool_shape) * self.dtype.itemsize / 1e6,
                self.path, "attached" if mode == "r+" else "created",
            )
        return self._pool

    # -- manifest journal --

    def _meta(self) -> dict:
        return {
            "g3_manifest": 1,
            "num_pages": self.num_pages,
            "page_shape": list(self.page_shape),
            "dtype": self.dtype.name,
            "scale_shape": list(self.scale_shape),
        }

    def _ensure_journal(self):
        if self._journal is None and self.manifest_path is not None:
            fresh = (
                not os.path.exists(self.manifest_path)
                or os.path.getsize(self.manifest_path) == 0
            )
            self._journal = open(self.manifest_path, "a")
            if fresh:
                self._journal.write(json.dumps(self._meta()) + "\n")
                self._journal.flush()
        return self._journal

    def _journal_write(self, rec: dict) -> None:
        j = self._ensure_journal()
        if j is None:
            return
        j.write(json.dumps(rec) + "\n")
        j.flush()
        self._journal_lines += 1
        if self._journal_lines > max(
            _JOURNAL_SLACK * self.num_pages, 256
        ):
            self.compact_manifest()

    def _on_put(self, h: int, parent: int, slot: int, crc: int,
                scale: Optional[np.ndarray]) -> None:
        if self.manifest_path is None or self._owns_file:
            return
        self._journal_write({
            "put": int(h), "parent": int(parent), "slot": int(slot),
            "crc": int(crc),
            "scale": (
                [float(x) for x in np.asarray(scale, np.float32).ravel()]
                if scale is not None else None
            ),
        })

    def _on_drop(self, h: int) -> None:
        if self.manifest_path is None or self._owns_file:
            return
        self._journal_write({"drop": int(h)})

    def compact_manifest(self) -> None:
        """Rewrite the journal as one line per live entry via tmp-file +
        atomic rename — a crash mid-compaction leaves either the old or
        the new manifest, never a half state."""
        if self.manifest_path is None or self._owns_file:
            return
        if self._journal is not None:
            self._journal.close()
            self._journal = None
        tmp = self.manifest_path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(self._meta()) + "\n")
            for h, (slot, parent, crc) in self._index.items():
                scale = (
                    self._ensure_scales()[..., slot]
                    if self.scale_shape else None
                )
                f.write(json.dumps({
                    "put": int(h), "parent": int(parent),
                    "slot": int(slot), "crc": int(crc),
                    "scale": (
                        [float(x) for x in scale.ravel()]
                        if scale is not None else None
                    ),
                }) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.manifest_path)
        self._journal_lines = len(self._index)

    @staticmethod
    def load_manifest(manifest_path: str):
        """Replay a manifest journal: (meta, live entries {hash: (slot,
        parent, crc, scale-list|None)}, torn/invalid line count). Used
        by attach and by tools/scrub_kv.py."""
        meta = None
        live: "OrderedDict[int, tuple]" = OrderedDict()
        torn = 0
        with open(manifest_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    torn += 1  # torn tail / partial write
                    continue
                if "g3_manifest" in rec:
                    meta = rec
                elif "drop" in rec:
                    live.pop(int(rec["drop"]), None)
                elif "put" in rec:
                    try:
                        ent = (int(rec["slot"]), int(rec["parent"]),
                               int(rec["crc"]), rec.get("scale"))
                    except (KeyError, TypeError, ValueError):
                        torn += 1
                        continue
                    h = int(rec["put"])
                    live.pop(h, None)  # re-put: newest slot wins
                    live[h] = ent
                else:
                    torn += 1
        return meta, live, torn

    def _attach(self) -> None:
        """Restart survival: replay the manifest against the existing
        backing file, scrubbing entries back into the index."""
        mpath = self.manifest_path
        if mpath is None or self._owns_file:
            return
        if not os.path.exists(mpath):
            return  # operator file with no manifest: attach empty
        try:
            meta, live, torn = self.load_manifest(mpath)
        except OSError as e:
            log.warning("G3 manifest unreadable (%s); starting empty", e)
            return
        dropped = torn
        if meta is not None and (
            meta.get("num_pages") != self.num_pages
            or list(meta.get("page_shape", [])) != list(self.page_shape)
            or meta.get("dtype") != self.dtype.name
            or list(meta.get("scale_shape", []))
            != list(self.scale_shape)
        ):
            log.warning(
                "G3 manifest geometry mismatch at %s; dropping %d "
                "entries", mpath, len(live),
            )
            dropped += len(live)
            live.clear()
        pool = self._ensure_pool()
        used: set[int] = set()
        for h, (slot, parent, crc, scale) in live.items():
            scale_arr = None
            if self.scale_shape:
                want_n = int(np.prod(self.scale_shape))
                if scale is None or len(scale) != want_n:
                    dropped += 1
                    continue
                scale_arr = np.asarray(scale, np.float32).reshape(
                    self.scale_shape
                )
            if not (0 <= slot < self.num_pages) or slot in used:
                dropped += 1
                continue
            if self.scrub_on_start and page_checksum(
                pool[:, :, :, slot], scale_arr
            ) != crc:
                dropped += 1
                KV_INTEGRITY.inc("dynamo_kv_integrity_failed_total")
                continue
            used.add(slot)
            self._index[h] = (slot, parent, crc)
            if self.scale_shape:
                self._ensure_scales()[..., slot] = scale_arr
        self._free = [
            s for s in range(self.num_pages) if s not in used
        ]
        self.scrub_recovered = len(self._index)
        self.scrub_dropped = dropped
        KV_INTEGRITY.inc(
            "dynamo_kv_integrity_g3_scrub_recovered_total",
            self.scrub_recovered,
        )
        KV_INTEGRITY.inc(
            "dynamo_kv_integrity_g3_scrub_dropped_total", dropped
        )
        if self.scrub_on_start:
            KV_INTEGRITY.inc(
                "dynamo_kv_integrity_verified_total",
                self.scrub_recovered,
            )
        log.info(
            "G3 attach: %d blocks recovered, %d dropped (%s scrub) "
            "from %s", self.scrub_recovered, dropped,
            "eager" if self.scrub_on_start else "lazy", mpath,
        )
        # start the journal from a compact state so replayed drops/puts
        # from the previous life don't accrete forever
        self.compact_manifest()

    def _maybe_chaos_truncate(self) -> None:
        # chaos truncate_g3: simulate the backing file losing its tail
        # region (dropped writes) — live-safe (ftruncate under an active
        # mmap would SIGBUS), and detectable by the crc verify
        if _chaos().fire("truncate_g3"):
            self._ensure_pool()[:, :, :, self.num_pages // 2:] = 0

    def gather(self, hashes: list[int]) -> np.ndarray:
        self._maybe_chaos_truncate()
        return super().gather(hashes)

    def read_page(self, block_hash: int) -> np.ndarray:
        # the G2 tier's fall-through gather reads G3 page-wise
        self._maybe_chaos_truncate()
        return super().read_page(block_hash)

    def close(self) -> None:
        if not self._owns_file:
            self.compact_manifest()
        if self._journal is not None:
            self._journal.close()
            self._journal = None
        if self._pool is not None:
            self._pool._mmap.close()
            self._pool = None
        if self._owns_file and self.path and os.path.exists(self.path):
            os.unlink(self.path)
            self.path = None


class HostOffloadTier(_PageTier):
    """G2: host-DRAM pool. With a ``spill`` tier attached, LRU evictions
    cascade DOWN into it (G2 -> G3) instead of being dropped, and
    ``lookup_run``/``gather`` fall through to it mid-run, so one onboard
    can be assembled from both tiers (reference offload.rs tier walk)."""

    def __init__(self, num_pages: int, page_shape: tuple, dtype,
                 spill: Optional[_PageTier] = None,
                 scale_shape: tuple = (),
                 quarantine: Optional[KvQuarantine] = None):
        super().__init__(num_pages, page_shape, dtype,
                         scale_shape=scale_shape, quarantine=quarantine)
        self.spill = spill

    def _ensure_pool(self) -> np.ndarray:
        if self._pool is None:
            self._pool = np.zeros(self.pool_shape, self.dtype)
        return self._pool

    def _evict_one(self) -> None:
        old_h = self._pick_victim()
        old_slot, old_parent, old_crc = self._index.pop(old_h)
        if self.spill is not None:
            # the crc travels with the block down the spill: G3 inherits
            # G2's seal-time checksum instead of re-minting over bytes
            # that may already have rotted in DRAM
            self.spill.put_one(
                old_h, old_parent, self._ensure_pool()[:, :, :, old_slot],
                (self._ensure_scales()[..., old_slot]
                 if self.scale_shape else None),
                checksum=old_crc,
            )
        self._free.append(old_slot)
        self._on_drop(old_h)

    def lookup_run(self, hashes: list[int]) -> list[tuple[int, int]]:
        self.lookups += len(hashes)
        run: list[tuple[int, int]] = []
        for h in hashes:
            ent = self._index.get(h)
            if ent is not None:
                self._index.move_to_end(h)
                run.append((h, ent[1]))
                continue
            if self.spill is not None:
                sub = self.spill.lookup_run([h])
                if sub:
                    run.append(sub[0])
                    continue
            break
        self.onboard_hits += len(run)
        return run

    def checksum_of(self, block_hash: int) -> Optional[int]:
        ent = self._index.get(block_hash)
        if ent is not None:
            return ent[2]
        if self.spill is not None:
            return self.spill.checksum_of(block_hash)
        return None

    def gather(self, hashes: list[int]) -> np.ndarray:
        out = np.empty(
            self.page_shape[:3] + (len(hashes),) + self.page_shape[3:],
            self.dtype,
        )
        for i, h in enumerate(hashes):
            if h in self._index:
                out[:, :, :, i] = self.read_page(h)
            else:
                out[:, :, :, i] = self.spill.read_page(h)
        _chaos().maybe_flip_bits(out)
        return out

    def gather_scales(self, hashes: list[int]) -> Optional[np.ndarray]:
        if not self.scale_shape:
            return None
        out = np.empty(self.scale_shape + (len(hashes),), np.float32)
        for i, h in enumerate(hashes):
            if h in self._index:
                out[..., i] = self.read_scale(h)
            else:
                out[..., i] = self.spill.read_scale(h)
        return out

    def drop_everywhere(self, block_hash: int) -> None:
        self.drop(block_hash)
        if self.spill is not None:
            self.spill.drop(block_hash)

    def clear(self) -> int:
        n = super().clear()
        if self.spill is not None:
            n += self.spill.clear()
        return n
