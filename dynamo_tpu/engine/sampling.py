"""On-device batched sampling: greedy / temperature / top-k / top-p plus
frequency, presence, and repetition penalties.

All slots sample in one fused jit alongside the decode step — logits never
leave HBM (contrast: the reference's engines sample inside vLLM; SURVEY.md
§7 "sampling on-device"). Static shapes: top-k truncates to the engine-wide
``max_top_k`` lanes, per-slot effective k/p mask within them.

State is per decode slot and lives on device:
  - ``keys``: per-slot PRNG keys (split per step -> reproducible per-request
    streams from a request seed);
  - ``counts``: per-slot output-token histograms for the penalty terms.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

NEG_INF = -1e30


class SamplerState(NamedTuple):
    keys: jnp.ndarray    # [B, 2] uint32 per-slot PRNG keys
    counts: jnp.ndarray  # [B, V] int32 output-token histogram


class SamplingParams(NamedTuple):
    """Per-slot sampling knobs as device arrays (set on slot assignment)."""

    temperature: jnp.ndarray          # [B] f32; <=0 means greedy
    top_k: jnp.ndarray                # [B] i32; 0/negative disables
    top_p: jnp.ndarray                # [B] f32; 1.0 disables
    frequency_penalty: jnp.ndarray    # [B] f32
    presence_penalty: jnp.ndarray     # [B] f32
    repetition_penalty: jnp.ndarray   # [B] f32; 1.0 disables


def init_state(batch: int, vocab: int, seed: int = 0) -> SamplerState:
    base = jax.random.PRNGKey(seed)
    keys = jax.random.split(base, batch)
    return SamplerState(
        keys=jnp.asarray(keys, jnp.uint32),
        counts=jnp.zeros((batch, vocab), jnp.int32),
    )


def default_params(batch: int) -> SamplingParams:
    return SamplingParams(
        temperature=jnp.zeros(batch, jnp.float32),
        top_k=jnp.zeros(batch, jnp.int32),
        top_p=jnp.ones(batch, jnp.float32),
        frequency_penalty=jnp.zeros(batch, jnp.float32),
        presence_penalty=jnp.zeros(batch, jnp.float32),
        repetition_penalty=jnp.ones(batch, jnp.float32),
    )


def apply_penalties(
    logits: jnp.ndarray, counts: jnp.ndarray, p: SamplingParams
) -> jnp.ndarray:
    """OpenAI-style frequency/presence penalties + HF repetition penalty."""
    seen = (counts > 0)
    logits = logits - p.frequency_penalty[:, None] * counts.astype(jnp.float32)
    logits = logits - p.presence_penalty[:, None] * seen.astype(jnp.float32)
    rep = p.repetition_penalty[:, None]
    penalized = jnp.where(logits > 0, logits / rep, logits * rep)
    logits = jnp.where(seen, penalized, logits)
    return logits


def sample_step_impl(
    logits: jnp.ndarray,      # [B, V] f32
    state: SamplerState,
    params: SamplingParams,
    max_top_k: int,
) -> tuple[jnp.ndarray, SamplerState]:
    """Sample one token per slot; returns (tokens [B] i32, new state)."""
    B, V = logits.shape
    logits = apply_penalties(logits, state.counts, params)

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    temps = jnp.maximum(params.temperature, 1e-6)[:, None]
    vals, idxs = jax.lax.top_k(logits, max_top_k)     # [B, K]
    scaled = vals / temps
    pos = jnp.arange(max_top_k)[None, :]
    k_eff = jnp.where(params.top_k <= 0, max_top_k, params.top_k)
    mask_k = pos < jnp.minimum(k_eff, max_top_k)[:, None]
    probs = jax.nn.softmax(jnp.where(mask_k, scaled, NEG_INF), axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # nucleus: keep lanes whose cumulative prob (exclusive) is < top_p
    mask_p = (cum - probs) < params.top_p[:, None]
    final = jnp.where(mask_k & mask_p, scaled, NEG_INF)

    def row(key, logit_row):
        new_key, sub = jax.random.split(jax.random.wrap_key_data(key, impl="threefry2x32"))
        choice = jax.random.categorical(sub, logit_row)
        return jax.random.key_data(new_key), choice

    new_keys, choice = jax.vmap(row)(state.keys, final)
    sampled = jnp.take_along_axis(idxs, choice[:, None], axis=-1)[:, 0].astype(jnp.int32)

    tokens = jnp.where(params.temperature <= 0.0, greedy, sampled)
    counts = state.counts.at[jnp.arange(B), tokens].add(1)
    return tokens, SamplerState(keys=new_keys, counts=counts)


sample_step = jax.jit(
    sample_step_impl, static_argnums=(3,), donate_argnums=(1,)
)


def compute_logprobs(
    logits: jnp.ndarray,   # [B, V] f32 RAW model logits (pre-penalty)
    tokens: jnp.ndarray,   # [B] i32 chosen tokens
    k: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """OpenAI-style logprobs: the MODEL's log-softmax (before sampling
    transforms), for the chosen token plus the top-k alternatives.
    Returns (chosen_lp [B], top_ids [B, k], top_lps [B, k])."""
    logp = logits - jax.nn.logsumexp(logits, axis=-1, keepdims=True)
    chosen = jnp.take_along_axis(logp, tokens[:, None], axis=-1)[:, 0]
    top_lps, top_ids = jax.lax.top_k(logp, k)
    return chosen, top_ids.astype(jnp.int32), top_lps


def reset_slot(state: SamplerState, slot: int, seed: int) -> SamplerState:
    """Host-side slot (re)initialization on request assignment."""
    key = jax.random.key_data(jax.random.PRNGKey(seed))
    return SamplerState(
        keys=state.keys.at[slot].set(key),
        counts=state.counts.at[slot].set(0),
    )
