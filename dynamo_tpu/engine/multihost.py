"""Cross-host single-engine controller (BASELINE config 4; reference
MultiNodeConfig launch/dynamo-run/src/flags.rs:86-101 +
leader_worker_barrier.rs:137,230 — vLLM uses ray, TRT-LLM uses MPI; the
TPU-native answer is jax.distributed + SPMD lockstep).

One logical worker backed by N host processes over a single
``jax.distributed`` mesh:

  - Every host builds the SAME engine state (params from the same seed or
    checkpoint, ctx/ring/pool) sharded over the GLOBAL mesh.
  - The LEADER runs the full host scheduler (admission, rounds, seals) and
    broadcasts every device dispatch as a compact JSON command over the
    control-plane store's durable per-follower FIFO queues BEFORE issuing
    it locally.
  - FOLLOWERS replay the commands in order, issuing the identical jits.
    XLA's collectives inside the programs (tp/ep shardings span hosts)
    form the actual lockstep: the leader's device work blocks until every
    follower dispatches the matching program, so followers can lag on the
    host side without correctness impact.
  - Only the leader fetches results / talks to clients — follower hosts
    never read device data (their shards' contribution flows through the
    collectives).

Round pipelining (EngineConfig.round_pipeline) needs no follower-side
change: the leader's _round may now EMIT round N+1's command before it
has finished round N's host bookkeeping (fetch/emit), but commands are
still broadcast in device-dispatch order — which is the only order a
follower ever sees. The replay loop below is the completion-free
"dispatch half" by construction (followers never fetch), so the
pipelined leader simply narrows the host-side lag between itself and
its followers; the lag bound stays flush_every * (max_inflight_rounds
+ 1) steps either way.

Scope: the multihost engine serves the dense/MoE decode+prefill paths,
batched prefill, and the sp ring prefill (its own broadcast command);
host-offload tiers, the page transfer plane, and multimodal injection
remain single-host (asserted at init) — they materialize host copies of
device arrays, which a multi-process mesh shards across hosts.

Bring-up uses the store-backed leader/worker barrier (runtime/barrier.py)
so all hosts enter the replay loop only after every process has built its
engine state.
"""
from __future__ import annotations

import asyncio
import json
import logging
import threading
from typing import Any, Optional

import jax.numpy as jnp
import numpy as np

log = logging.getLogger(__name__)


def cmd_queue(namespace: str, engine_id: str, run_id: str,
              host: int) -> str:
    # run_id (a fresh uuid per leader incarnation, distributed through the
    # bring-up barrier) scopes the durable queues: a restarted follower
    # must never replay a DEAD run's leftover commands onto a fresh engine
    return f"{namespace}.mh.{engine_id}.{run_id}.cmds.{host}"


def leader_key(namespace: str, engine_id: str, run_id: str) -> str:
    return f"dynamo://{namespace}/mh/{engine_id}/{run_id}/leader"


class CommandStream:
    """Leader-side dispatch broadcaster: thread-safe (the engine loop is a
    plain thread), pumped onto the runtime's asyncio loop."""

    def __init__(self, kv: Any, loop: asyncio.AbstractEventLoop,
                 namespace: str, engine_id: str, run_id: str,
                 n_followers: int):
        self.kv = kv
        self.loop = loop
        self.namespace = namespace
        self.engine_id = engine_id
        self.run_id = run_id
        self.queues = [
            cmd_queue(namespace, engine_id, run_id, h + 1)
            for h in range(n_followers)
        ]
        self.seq = 0
        self.lease: Optional[Any] = None
        self._err: Optional[BaseException] = None
        self._pending: list[str] = []
        self._lock = threading.Lock()
        self._flushing = False

    async def announce(self, ttl_s: float = 5.0) -> None:
        """Publish the leader liveness key (lease-bound): followers poll
        it while idle and exit when the leader is gone."""
        self.lease = await self.kv.lease_grant(ttl_s)
        await self.kv.put(
            leader_key(self.namespace, self.engine_id, self.run_id),
            "up", lease=self.lease.id,
        )

    async def drain(self) -> None:
        """Wait until every emitted command is on the wire (call before
        pushing an out-of-band stop: a stop overtaking a pending batch
        would open a seq gap on the followers)."""
        while True:
            with self._lock:
                idle = not self._pending and not self._flushing
            if idle or self._err is not None:
                return
            await asyncio.sleep(0.005)

    async def close(self) -> None:
        """Revoke the liveness key (followers see the leader as gone
        immediately) and stop the keep-alive task."""
        if self.lease is not None:
            await self.lease.revoke()
            self.lease = None
        await self.kv.close()

    def emit(self, op: str, payload: dict) -> None:
        """Thread-safe. Commands are COALESCED: every emit appends to a
        pending batch, and one flush task per wakeup of the stream loop
        drains the whole batch as a single array frame per follower,
        pushed to all followers CONCURRENTLY — per round the leader pays
        one store round-trip, not #commands x #followers (the v5p-64
        scaling concern: 31 followers, several commands per round)."""
        with self._lock:
            self.seq += 1
            raw = json.dumps({"seq": self.seq, "op": op, **payload})
            self._pending.append(raw)
        self.loop.call_soon_threadsafe(self._schedule_flush)
        if self._err is not None:
            raise RuntimeError(f"command broadcast failed: {self._err}")

    def _schedule_flush(self) -> None:
        # stream-loop thread: one flush task at a time keeps per-queue
        # FIFO order (batches are drained in emit order)
        if self._flushing:
            return
        self._flushing = True
        asyncio.ensure_future(self._flush(), loop=self.loop)

    async def _flush(self) -> None:
        try:
            while True:
                with self._lock:
                    batch = self._pending
                    self._pending = []
                if not batch:
                    return
                frame = (
                    batch[0] if len(batch) == 1
                    else "[" + ",".join(batch) + "]"
                )
                try:
                    await asyncio.gather(*[
                        self.kv.qpush(q, frame) for q in self.queues
                    ])
                except BaseException as e:  # noqa: BLE001
                    # surfaced on the NEXT emit; if the leader's device
                    # work is already blocked on a follower that never got
                    # this batch, recovery is the liveness teardown
                    # (leader key expiry -> followers exit -> jax runtime
                    # collapse)
                    log.exception("multihost command broadcast failed")
                    self._err = e
                    return
        finally:
            self._flushing = False
            with self._lock:
                if self._pending and self._err is None:
                    self._schedule_flush()


def make_dispatch_sink(stream: CommandStream):
    """The TpuEngine on_dispatch hook."""

    def sink(op: str, payload: dict) -> None:
        stream.emit(op, payload)

    return sink


class Follower:
    """Replays the leader's dispatch stream on this host's engine replica.

    The engine must be constructed with the same configs/params/mesh as
    the leader's and NEVER started (its host loop stays off); this class
    drives its jits directly.
    """

    def __init__(self, engine: Any, kv: Any, namespace: str,
                 engine_id: str, run_id: str, host_index: int):
        self.engine = engine
        self.kv = kv
        self.queue = cmd_queue(namespace, engine_id, run_id, host_index)
        self.leader_key = leader_key(namespace, engine_id, run_id)
        self.commands_applied = 0
        self._expected_seq = 1

    async def run(self) -> None:
        """Replay until a `stop` command or leader death (liveness key
        expiry — a crashed leader must not leave followers holding the
        jax runtime forever)."""
        while True:
            raw = await self.kv.qpop(self.queue, timeout_s=10.0)
            if raw is None:
                if await self.kv.get(self.leader_key) is None:
                    log.warning("multihost leader gone; follower exiting")
                    return
                continue
            decoded = json.loads(raw)
            # the leader coalesces a round's commands into one frame
            batch = decoded if isinstance(decoded, list) else [decoded]
            for cmd in batch:
                seq = cmd.get("seq", -1)
                if seq != self._expected_seq:
                    raise RuntimeError(
                        f"command stream gap: expected "
                        f"{self._expected_seq}, got {seq} — follower "
                        f"state is no longer lockstep"
                    )
                self._expected_seq += 1
                if cmd["op"] == "stop":
                    return
                self.apply(cmd)
                self.commands_applied += 1

    # replayed op -> the follower's dispatch_counts bucket (parity with
    # the leader's accounting at its own dispatch sites; "round" picks
    # round/round_seal below and "patch" counts inside _dispatch_patch)
    _OP_BUCKETS = {
        "prefill": "prefill", "prefill_batch": "prefill_batch",
        "sample_first": "sample_first", "sp_prefill": "sp_prefill",
        "load_ctx": "load_ctx", "seal": "seal",
    }

    def apply(self, cmd: dict) -> None:
        eng = self.engine
        op = cmd["op"]
        bucket = self._OP_BUCKETS.get(op)
        if bucket is not None:
            eng.dispatch_counts[bucket] += 1
        if op == "round":
            eng.dispatch_counts[
                "round_seal" if cmd.get("seal") else "round"] += 1
            seal = cmd.get("seal")
            if seal:
                # leader fused the round's seal batch into the program
                out = eng._engine_round_seal(
                    eng.params, eng.ctx, eng.ring, eng._dev, eng.cache,
                    jnp.asarray(np.asarray(seal["slots"], np.int32)),
                    jnp.asarray(np.asarray(seal["starts"], np.int32)),
                    jnp.asarray(np.asarray(seal["pages"], np.int32)),
                    cmd["n_steps"], cmd["want_lp"], cmd["want_sample"],
                )
                eng.ctx, eng.ring, eng._dev, eng.cache = (
                    out[0], out[1], out[2], out[3]
                )
            else:
                out = eng._engine_round(
                    eng.params, eng.ctx, eng.ring, eng._dev,
                    cmd["n_steps"], cmd["want_lp"], cmd["want_sample"],
                )
                eng.ctx, eng.ring, eng._dev = out[0], out[1], out[2]
        elif op == "patch":
            admit = dict(cmd.get("admit") or {})
            if admit:
                # the admitted first token is this host's own sample_first
                # replay result (same program + key -> same token)
                admit["tok"] = eng._mh_last_first_tok
                admit["keys"] = np.asarray(admit["keys"], np.uint32)
            eng._dispatch_patch(
                clear_slots=cmd.get("clear_slots") or [],
                admit=admit or None,
            )
        elif op == "prefill":
            from dynamo_tpu.models import llama

            eng.ctx, eng._mh_last_logits = llama.prefill(
                eng.config, eng.params, eng.ctx,
                jnp.asarray(np.asarray(cmd["tokens"], np.int32)),
                jnp.int32(cmd["slot"]),
                jnp.int32(cmd["start"]), jnp.int32(cmd["end"]),
                None, None, jnp.int32(cmd.get("adapter", 0)),
            )
        elif op == "prefill_batch":
            from dynamo_tpu.models import llama

            k = len(cmd["slots"])
            eng.ctx, eng._mh_last_logits = llama.batch_prefill(
                eng.config, eng.params, eng.ctx,
                jnp.asarray(np.asarray(cmd["tokens"], np.int32)),
                jnp.asarray(np.asarray(cmd["slots"], np.int32)),
                jnp.asarray(np.asarray(cmd["q_starts"], np.int32)),
                jnp.asarray(np.asarray(cmd["seq_lens"], np.int32)),
                int(cmd["ctx_span"]),
                jnp.asarray(np.asarray(
                    cmd.get("adapter_ids", [0] * k), np.int32)),
            )
        elif op == "sample_first":
            logits = eng._mh_last_logits
            if cmd.get("index") is not None:
                logits = logits[cmd["index"]]
            toks, _lp = eng._sample_first(
                logits,
                jnp.asarray(np.asarray(cmd["key"], np.uint32)),
                jnp.float32(cmd["temp"]),
                jnp.int32(cmd["top_k"]),
                jnp.float32(cmd["top_p"]),
                eng.config.vocab_size,
                cmd["want_lp"],
            )
            eng._mh_last_first_tok = toks
        elif op == "sp_prefill":
            from dynamo_tpu.models import llama
            from dynamo_tpu.ops.ring_attention import sp_shard

            toks = jnp.asarray(np.asarray(cmd["tokens"], np.int32))
            kv, logits = llama.sp_prefill(
                eng.config, eng.params, sp_shard(toks, eng.mesh),
                jnp.int32(cmd["n"]), eng.mesh,
            )
            eng.ctx = llama.write_ctx_span(
                eng.ctx, jnp.int32(cmd["slot"]), kv
            )
            eng._mh_last_logits = logits
        elif op == "load_ctx":
            from dynamo_tpu.models import llama

            eng.ctx = llama.load_ctx_pages(
                eng.ctx, eng.cache, jnp.int32(cmd["slot"]),
                jnp.asarray(np.asarray(cmd["pages"], np.int32)),
            )
        elif op == "seal":
            from dynamo_tpu.models import llama

            eng.cache = llama.seal_blocks(
                eng.cache, eng.ctx,
                jnp.asarray(np.asarray(cmd["slots"], np.int32)),
                jnp.asarray(np.asarray(cmd["starts"], np.int32)),
                jnp.asarray(np.asarray(cmd["pages"], np.int32)),
                page_size=eng.ecfg.page_size,
            )
        else:
            raise RuntimeError(f"unknown multihost command {op!r}")


async def stop_followers(kv: Any, namespace: str, engine_id: str,
                         run_id: str, n_followers: int, seq: int) -> None:
    raw = json.dumps({"seq": seq + 1, "op": "stop"})
    for h in range(n_followers):
        await kv.qpush(cmd_queue(namespace, engine_id, run_id, h + 1), raw)
