"""Host-side paged-KV page allocator with prefix reuse and LRU eviction.

The device holds the page pool tensors (models/llama.py init_cache); this
module owns which page holds what:

  - a free list of never/no-longer-used pages (page 0 reserved as scratch);
  - a registry mapping chained block hash -> committed page, enabling
    radix-style prefix reuse across requests (equal chained hash == equal
    prefix, dynamo_tpu.tokens);
  - per-page refcounts; unreferenced committed pages park in an LRU from
    which they can be revived (prefix hit) or evicted (allocation pressure);
  - stored/removed/cleared event emission for the KV-router plane.

Parity: this is the engine-side half of what the reference gets from vLLM's
prefix caching plus its own BlockPool (block_manager/pool.rs:156, sequence-
hash registry block/registry.rs:490) and KvEventPublisher (publisher.rs:99).

Representation-agnostic by design: with ``kv_quant=int8`` the device pages
this allocator hands out hold int8 payloads + per-page scales, and since
PR 14 the serving ctx shares that representation (group == page_size), so
seal/admission copies are raw page moves — nothing here changes; a page is
a page regardless of its element dtype.
"""
from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Optional

from dynamo_tpu.kv_router.protocols import KvCacheEvent, KvEventKind, StoredBlock

EventSink = Callable[[KvCacheEvent], None]


@dataclass
class PageRecord:
    page: int
    block_hash: int
    parent_hash: int


class PageAllocator:
    """Allocates/reuses device pages. Thread-safe: the engine scheduler is
    the main user, but the disagg decode path (asyncio thread) allocates
    and commits remote-prefilled pages concurrently — a single lock covers
    every public mutation."""

    def __init__(
        self,
        num_pages: int,
        page_size: int,
        worker_id: str = "",
        on_event: Optional[EventSink] = None,
        enable_prefix_caching: bool = True,
    ):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is reserved)")
        self.num_pages = num_pages
        self.page_size = page_size
        self.worker_id = worker_id
        self.on_event = on_event
        self.enable_prefix_caching = enable_prefix_caching
        # offload hook: called (page, block_hash, parent_hash) when a
        # committed page parks in the LRU — the engine queues it as a G2
        # offload candidate. Called under the allocator lock: must be cheap
        # and non-blocking.
        self.on_park: Optional[Callable[[int, int, int], None]] = None

        self._lock = threading.RLock()
        self._free: deque[int] = deque(range(1, num_pages))
        self._registry: dict[int, PageRecord] = {}   # block_hash -> record
        self._page_hash: dict[int, int] = {}         # page -> committed hash
        self._ref: dict[int, int] = {}               # page -> refcount
        self._lru: OrderedDict[int, None] = OrderedDict()  # block_hash -> None
        self._event_id = 0
        # counters for metrics
        self.hit_blocks = 0
        self.lookup_blocks = 0

    # ---- introspection ----

    @property
    def total_pages(self) -> int:
        return self.num_pages - 1

    @property
    def active_pages(self) -> int:
        return self.total_pages - len(self._free) - len(self._lru)

    @property
    def available_pages(self) -> int:
        """Pages obtainable right now (free + evictable)."""
        return len(self._free) + len(self._lru)

    def usage(self) -> float:
        return self.active_pages / max(self.total_pages, 1)

    def hit_rate(self) -> float:
        return self.hit_blocks / max(self.lookup_blocks, 1)

    # ---- allocation ----

    def match_prefix(self, block_hashes: list[int]) -> list[int]:
        """Longest cached prefix of the given chained hashes; returned pages
        are referenced (caller must free). Revives LRU-parked pages."""
        pages: list[int] = []
        if not self.enable_prefix_caching:
            return pages
        with self._lock:
            self.lookup_blocks += len(block_hashes)
            for h in block_hashes:
                rec = self._registry.get(h)
                if rec is None:
                    break
                self._ref_page(rec.page, h)
                pages.append(rec.page)
            self.hit_blocks += len(pages)
            return pages

    def page_for_hash(self, block_hash: int) -> Optional[int]:
        """Which page currently holds this committed block (None if
        evicted) — offload-candidate validation."""
        with self._lock:
            rec = self._registry.get(block_hash)
            return None if rec is None else rec.page

    def cached_prefix_len(self, block_hashes: list[int]) -> int:
        """How many leading blocks are cached, WITHOUT taking references or
        touching hit-rate counters — a stat-neutral peek for routing/disagg
        decisions."""
        if not self.enable_prefix_caching:
            return 0
        with self._lock:
            n = 0
            for h in block_hashes:
                if h not in self._registry:
                    break
                n += 1
            return n

    def allocate(self, n: int) -> Optional[list[int]]:
        """n fresh pages (refcount 1 each), evicting LRU-parked committed
        pages if needed. None if not satisfiable (caller queues/preempts)."""
        with self._lock:
            if n > self.available_pages:
                return None
            while len(self._free) < n:
                self._evict_one()
            pages = [self._free.popleft() for _ in range(n)]
            for p in pages:
                self._ref[p] = 1
            return pages

    def commit(self, page: int, block_hash: int, parent_hash: int) -> bool:
        """Mark `page` as holding the sealed block `block_hash` (chained on
        parent_hash), making it reusable by other requests. Returns False on
        duplicate hash (page stays private to its request)."""
        if not self.enable_prefix_caching:
            return False
        with self._lock:
            if block_hash in self._registry:
                return False
            self._registry[block_hash] = PageRecord(page, block_hash, parent_hash)
            self._page_hash[page] = block_hash
            self._emit(
                KvCacheEvent(
                    kind=KvEventKind.STORED,
                    parent_hash=parent_hash,
                    blocks=[StoredBlock(block_hash=block_hash)],
                )
            )
            return True

    def free(self, pages: list[int]) -> None:
        """Release one reference on each page. Unreferenced committed pages
        park in the LRU (still prefix-hittable); uncommitted ones return to
        the free list."""
        with self._lock:
            for p in pages:
                r = self._ref.get(p, 0) - 1
                if r > 0:
                    self._ref[p] = r
                    continue
                self._ref.pop(p, None)
                h = self._page_hash.get(p)
                if h is not None:
                    self._lru[h] = None
                    self._lru.move_to_end(h)
                    if self.on_park is not None:
                        self.on_park(p, h, self._registry[h].parent_hash)
                else:
                    self._free.append(p)

    def snapshot_stored_events(
        self, batch: int = 256
    ) -> list[KvCacheEvent]:
        """Authoritative cache state as an event stream: one CLEARED
        followed by STORED events covering every committed block. Routers
        that missed events (dropped on the lossy pub/sub plane) converge
        by applying a periodic resync of this snapshot — the event plane's
        answer to 'a dropped STORED permanently skews routing'."""
        with self._lock:
            records = list(self._registry.values())
        # events are returned UNSTAMPED: the publisher sink sets worker_id
        # (same path as live events); event_id stays 0 — stamping here
        # would race _emit's counter outside the lock
        events: list[KvCacheEvent] = [KvCacheEvent(kind=KvEventKind.CLEARED)]
        for i in range(0, len(records), batch):
            chunk = records[i : i + batch]
            events.append(KvCacheEvent(
                kind=KvEventKind.STORED,
                blocks=[StoredBlock(block_hash=r.block_hash)
                        for r in chunk],
            ))
        return events

    def clear(self) -> int:
        """Drop all reusable cached pages (the /clear_kv_blocks operation,
        reference http/service/clear_kv_blocks.rs). In-use pages survive.
        Returns number of pages cleared."""
        with self._lock:
            n = len(self._lru)
            while self._lru:
                self._evict_one()
            self._emit(KvCacheEvent(kind=KvEventKind.CLEARED))
            return n

    # ---- internals ----

    def _ref_page(self, page: int, block_hash: int) -> None:
        r = self._ref.get(page, 0)
        if r == 0:
            self._lru.pop(block_hash, None)
        self._ref[page] = r + 1

    def _evict_one(self) -> None:
        h, _ = self._lru.popitem(last=False)
        rec = self._registry.pop(h)
        self._page_hash.pop(rec.page, None)
        self._free.append(rec.page)
        self._emit(
            KvCacheEvent(kind=KvEventKind.REMOVED, removed_hashes=[h])
        )

    def _emit(self, ev: KvCacheEvent) -> None:
        if self.on_event is None:
            return
        self._event_id += 1
        ev.event_id = self._event_id
        ev.worker_id = self.worker_id
        self.on_event(ev)
