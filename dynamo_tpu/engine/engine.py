"""TpuEngine: pipelined continuous batching over contiguous per-slot KV.

Architecture (TPU-first redesign of what the reference delegates to vLLM —
SURVEY.md §7 step 3; round-4 layout, see models/llama.py module doc). The
defining constraints: device→host reads have high latency (µs on PCIe TPU
VMs, ~80ms through a tunneled dev chip) while dispatches and host→device
uploads are cheap and asynchronous, and paged gathers/scatters in the
per-step program waste bandwidth. The engine therefore NEVER blocks a
decode step on host data, and keeps PAGING OUT of the hot path:

  - Serving context is contiguous per slot (``ctx_kv``); the paged pool is
    prefix-cache storage, copied in at admission (load_ctx_pages) and out
    at block seal (seal_blocks). Decode attention streams dense slabs
    (ops/flash_decode.py).
  - All decode state lives on device: last tokens, context lengths, write
    destinations, sampler keys/counts, per-slot sampling params. One fused
    jit (decode + sample + state advance) steps every slot;
    all-greedy rounds skip the full sampler (static want_sample gate).
  - The host loop dispatches steps ahead in rounds of ``flush_every``; each
    round's sampled tokens are stacked on device ([F, B]) and fetched with
    ``copy_to_host_async`` — fetches pipeline behind compute, so results
    arrive a bounded LAG behind dispatch without ever stalling the device.
  - Host processing (token emission, stop detection, block sealing,
    admission) runs on lagged results. State changes are applied via a
    patch jit dispatched between rounds — device-order semantics make this
    race-free: a step dispatched before a patch sees pre-patch state, and
    a seal copy dispatched before a lane's re-prefill reads the pre-reuse
    content.
  - Slots finished on host keep garbage-decoding until their release patch
    lands (≤ pipeline lag steps). Safety: the release patch redirects the
    lane's writes to the scratch lane (dest), so a lane being prefilled
    for its next request is never corrupted; before release, garbage
    writes advance monotonically past every sealed position.
  - Prefill runs per request at bucketed padded lengths into the slot's
    region; the first token is sampled on device and patched into the slot
    without a host round trip. Admission needs only a free lane — active
    requests can never run out of KV space, so there is no preemption.

The engine implements the AsyncEngine contract: ``generate(request)`` yields
LLMEngineOutput deltas; dropping the iterator cancels (reference
engine.rs:124-140 AsyncEngineContext::stop_generating).
"""
from __future__ import annotations

import asyncio
import functools
import logging
import os
from collections import deque
import queue as queue_mod
import threading
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.cache import PageAllocator
from dynamo_tpu.engine.config import EngineConfig, pow2_cover  # noqa: F401
# (pow2_cover re-exported: engine.engine was its historical home)
from dynamo_tpu.engine import sampling
from dynamo_tpu.kv_fleet_metrics import KV_FLEET
from dynamo_tpu.kv_integrity import KV_INTEGRITY, KvQuarantine
from dynamo_tpu.kv_quant import KV_QUANT, QuantizedPages, to_pool_dtype
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.overload import (
    OVERLOAD,
    PRIORITY_HIGH,
    AdmissionController,
    EngineOverloadedError,
    PreemptedError,
)
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.spec.metrics import SPEC
from dynamo_tpu.spec.proposer import comb_parents
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.telemetry import (
    TRACES,
    FlightRecorder,
    TelemetryRegistry,
    request_histograms,
)
from dynamo_tpu.telemetry import metrics as tmetrics
from dynamo_tpu.telemetry import prof as tprof
from dynamo_tpu.telemetry.prof import PROF, RoundProf
from dynamo_tpu.telemetry.trace import Span, span_now
from dynamo_tpu.tenancy.metrics import TENANT
from dynamo_tpu.tenancy.quotas import TenantQuotas
from dynamo_tpu.tokens import TokenBlockSequence

log = logging.getLogger(__name__)

_FIRST_TOKEN_KEY_TAG = 0x46697273  # distinct PRNG stream for first tokens

# per-request trace spans shipped back in the finishing annotation are
# capped (a 10k-token generation must not grow a 10k-entry span list);
# the total decode-round count still travels in the timing annotation
_MAX_ROUND_SPANS = 24
# requests flagged "trace_detail" by the frontend (forensics candidates —
# every request, since breach status is only known at finish) keep a much
# deeper round-span ring so a late promotion yields a complete dossier
_MAX_ROUND_SPANS_DETAIL = 256


def _span_dict(name: str, t0_monotonic: float, **attrs) -> dict:
    """Span ending now that began at monotonic ``t0_monotonic`` — the
    annotation-ready wire form (telemetry.trace.span_now)."""
    return span_now(name, t0_monotonic, **attrs).to_dict()


# attribution-segment indices (telemetry/prof.py SEGMENTS), bound once so
# the round loop's enter() calls pass ints, not strings
_SEG_INTAKE = tprof.SEGMENTS.index("intake")
_SEG_SLOT_SCAN = tprof.SEGMENTS.index("slot_scan")
_SEG_FETCH = tprof.SEGMENTS.index("fetch")
_SEG_ANNOTATE = tprof.SEGMENTS.index("annotate")
_SEG_RELEASES = tprof.SEGMENTS.index("releases")
_SEG_TRANSFER = tprof.SEGMENTS.index("transfer")
_SEG_OFFLOAD = tprof.SEGMENTS.index("offload")
_SEG_ADMIT = tprof.SEGMENTS.index("admit")
_SEG_SEAL_ASM = tprof.SEGMENTS.index("seal_assembly")
_SEG_DISPATCH = tprof.SEGMENTS.index("dispatch")
_SEG_SPEC = tprof.SEGMENTS.index("spec_dispatch")
_SEG_SEAL_FLUSH = tprof.SEGMENTS.index("seal_flush")
_SEG_METRICS = tprof.SEGMENTS.index("metrics_fold")




@dataclass
class _Request:
    req: PreprocessedRequest
    seq: TokenBlockSequence
    out: asyncio.Queue
    loop: asyncio.AbstractEventLoop
    # the prompt — kept separate from req.token_ids so engine-side state
    # never mutates the caller's request object
    tokens: list[int] = field(default_factory=list)
    matched_blocks: int = 0
    # prompt blocks already copy-committed into the prefix cache; chunked
    # prefill seals complete blocks INCREMENTALLY (each chunk's full pages
    # become prefix-hittable while later chunks still compute — what lets
    # the disagg prefill worker stream them mid-prefill)
    sealed_prefix: int = 0
    # chunked-prefill progress: tokens already in cache (-1 = not started).
    # Prefill runs ONE chunk per scheduling round so decode rounds
    # interleave with long prompts instead of stalling behind them.
    prefill_pos: int = -1
    slot: int = -1
    produced: int = 0
    last_token: int = -1          # newest processed token, not yet in seq
    cancelled: bool = False
    finished: bool = False
    # overload plane: this request's prompt tokens are counted in the
    # engine's waiting-prefill-token backlog (set at intake, cleared
    # exactly once when the request gets a lane or leaves the queue)
    counted: bool = False
    enqueue_time: float = field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None
    # telemetry: worker-side span dicts (queue/prefill/decode rounds —
    # telemetry/trace.py), round-batched inter-token gaps as (gap_s, n),
    # and timestamps backing them
    trace_spans: list[dict] = field(default_factory=list)
    # per-round decode/spec spans accumulate as raw tuples
    # (kind, t0_monotonic, duration_s, n_tokens, spec_host) and are
    # materialized into span dicts ONCE at finish (_final_annotations) —
    # the per-round dict/round() churn was measurable annotate tax on
    # the hot loop, paid even for requests whose trace nobody reads
    round_spans: list[tuple] = field(default_factory=list)
    itl_gaps: list[tuple] = field(default_factory=list)
    t_prefill_start: Optional[float] = None
    t_last_emit: Optional[float] = None
    decode_rounds: int = 0
    # speculative decoding (spec/): a speculating slot's device lane
    # stays PARKED (dest=scratch) — its real state lives here on the
    # host and in the ctx region, driven by verify dispatches instead of
    # the fused decode round.
    spec: bool = False
    spec_ready: bool = False       # host knows the pending token
    spec_inflight: bool = False    # a verify dispatch is outstanding
    # full sequence incl. the pending token (region holds KV for all but
    # the last element) — the proposers' lookup corpus
    spec_tokens: list[int] = field(default_factory=list)
    spec_keys: Optional[np.ndarray] = None  # [2] uint32 PRNG key
    # host mirror of the sampler's output-token counts histogram [V] —
    # allocated only for penalized requests (the verifier's penalized
    # accept path consumes it; despec restores it onto the device state)
    spec_counts: Optional[np.ndarray] = None
    spec_proposed: int = 0
    spec_accepted: int = 0
    # acceptance gating: a gated stream runs on the fused round
    # (spec=False) but keeps mirroring its sequence/counts through
    # _spec_gated_advance so speculation can re-arm mid-stream
    spec_gated: bool = False
    spec_rearm_left: int = 0     # fused tokens until a re-arm attempt
    spec_gate_backoff: int = 1   # re-arm budget multiplier (doubles)
    # two-phase re-arm drain: in-flight round entries whose dispatch-time
    # snapshot still steps this lane (the clear patch lands after them
    # in program order; their tokens are real and must be mirrored)
    spec_rearm_wait: int = 0
    # forensics: frontend marks candidates with a "trace_detail"
    # annotation — lifts the round-span cap so late (finish-time) trace
    # promotion still sees the full decode path
    trace_detail: bool = False
    # tenancy plane: SFQ virtual finish-time stamp minted at enqueue
    # (tenant virtual clock + prompt cost / weight) — orders
    # same-priority waiting entries so a storming tenant self-paces
    # behind its own stamps (see _enqueue_waiting)
    vft: float = 0.0

    @property
    def tenant(self) -> str:
        return getattr(self.req, "tenant", "") or "default"

    @property
    def adapter_id(self) -> int:
        return int(getattr(self.req, "adapter_id", 0) or 0)

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    def max_new_tokens(self, max_context: int) -> int:
        mt = self.req.stop_conditions.max_tokens
        cap = max_context - self.prompt_len
        return min(mt, cap) if mt is not None else cap

    def emit(self, item: LLMEngineOutput | Exception) -> None:
        # the client's event loop can be gone by the time the engine
        # thread flushes (interpreter/test teardown, _fail_all during
        # shutdown) — a raise here would mask the ORIGINAL engine
        # failure with "RuntimeError: Event loop is closed"
        try:
            self.loop.call_soon_threadsafe(self.out.put_nowait, item)
        except RuntimeError:
            log.debug("dropped emit to a closed event loop (shutdown)")


@dataclass
class _Entry:
    """One in-flight fetch: either a round of stacked step tokens or a
    request's prefill first-token."""

    kind: str                      # "round" | "first"
    handle: Any                    # device array being copied to host
    # round:
    slots: list[Optional[_Request]] = field(default_factory=list)  # snapshot
    n_steps: int = 0
    # first:
    request: Optional[_Request] = None
    # offload: hashes/parents aligned with the gathered pages
    hashes: list[int] = field(default_factory=list)
    parents: list[int] = field(default_factory=list)
    # logprobs: ONE packed f32 handle — [F, B, 1+2K] for rounds,
    # [1, 1+2K] for "first" entries (chosen | top ids as f32 | top lps;
    # see _build_jits.pack_lp / _unpack_lp)
    lp_handle: Optional[Any] = None
    # spec verify: (slot, request, history-length-at-dispatch) per live
    # row, aligned with the leading rows of the fetched arrays
    rows: list[tuple] = field(default_factory=list)
    # spec verify: (n_out [B], new_keys [B, 2]) device handles fetched
    # alongside `handle` (the [B, K+1] accepted-token array)
    aux: Any = None
    # telemetry: dispatch time, for dynamo_engine_round_seconds
    t_dispatch: float = 0.0
    # spec verify: (draft_s, verify_s) host dispatch walls — become the
    # spec_draft / spec_verify child spans under the round span
    spec_host: Any = None


# sentinel closing an export stream's chunk queue (engine loop -> consumer)
_STREAM_EOS = object()


@dataclass
class _ExportStream:
    """One in-flight chunked page export: the engine loop advances it a
    little every round (dispatch up to ``inflight`` padded gathers with
    copy_to_host_async, convert ready heads, feed the consumer queue) —
    the loop never blocks on the consumer, and the D2H of chunk i
    overlaps the gather/compute behind chunk i+1."""

    ids: list[int]
    chunk_pages: int
    inflight: int
    out_q: queue_mod.Queue
    pos: int = 0                      # next page index to gather
    # (n_real_pages, data handle, scales handle|None) per dispatched,
    # unconsumed chunk
    pending: deque = field(default_factory=deque)
    # hash-addressed exports pin their matched refs until every gather
    # is dispatched (device order then protects the reads)
    free_pages: Optional[list[int]] = None
    # last time this stream moved (dispatch/convert), seeded with the
    # registration time: a stream whose consumer vanished mid-pull (or
    # before pulling anything) parks with a full queue forever, which
    # would leak its pinned pages — the loop reclaims it after the
    # transfer deadline of inactivity
    last_progress: float = field(default_factory=time.monotonic)


class TpuEngine:
    """Pipelined continuous-batching paged-KV engine on a jax mesh."""

    def __init__(
        self,
        model_config: ModelConfig,
        engine_config: Optional[EngineConfig] = None,
        *,
        params: Any = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        mesh_config: Optional[MeshConfig] = None,
        rng_seed: int = 0,
        on_kv_event: Optional[Callable[[KvCacheEvent], None]] = None,
        on_metrics: Optional[Callable[[ForwardPassMetrics], None]] = None,
        on_dispatch: Optional[Callable[[str, dict], None]] = None,
        draft_config: Any = None,
        draft_params: Any = None,
    ):
        self.config = model_config
        self.ecfg = engine_config or EngineConfig()
        self.mesh = mesh or make_mesh(mesh_config)
        self.on_metrics = on_metrics
        # multihost leader hook: every device dispatch is broadcast to the
        # follower hosts BEFORE being issued locally (engine/multihost.py).
        # Followers replay the identical jit sequence (incl. the sp ring
        # prefill, its own command); host-offload tiers, the page-transfer
        # plane and multimodal injection are single-host features and are
        # rejected below/at their call sites.
        self.on_dispatch = on_dispatch
        if on_dispatch is not None:
            if (self.ecfg.host_offload_pages > 0
                    or self.ecfg.disk_offload_pages > 0):
                raise ValueError(
                    "multihost engine: host/disk offload tiers are "
                    "single-host features"
                )
            if self.ecfg.speculative != "off":
                raise ValueError(
                    "multihost engine: speculative decoding is a "
                    "single-host feature (the verify/propose dispatch "
                    "sequence is data-dependent on fetched results)"
                )

        c, e = self.config, self.ecfg
        cache_dtype = jnp.dtype(e.cache_dtype)
        # int8 KV-block economy: the paged pool (and every tier/transfer
        # consumer downstream of it) stores int8 pages + per-block
        # scales; the serving ctx region stays cache_dtype
        self.kv_quant = e.kv_quant == "int8"
        # ctx region quantized too (in-kernel dequant decode hot path);
        # one flag so mixed-precision experiments can split them later
        self.ctx_quant = self.kv_quant
        # ring-flush requantize geometry: every lane rewrites the same
        # window of scale groups once per round (see llama._flush_ctx_quant)
        _g = max(1, e.page_size)
        _nG = -(-e.max_context // _g)
        self._flush_groups_per_round = e.max_decode_slots * min(
            -(-e.flush_every // _g) + 1, _nG)
        p_sh = llama.param_shardings(c, self.mesh)
        if params is None:
            params = llama.init_params(c, rng_seed)
        self.params = jax.tree.map(lambda x, s: jax.device_put(x, s), params, p_sh)
        # resident LoRA adapter bank (tenancy plane): rides INSIDE the
        # params pytree so every jitted program carries it with zero
        # signature churn — the model fns look it up via
        # params.get("adapters") (a trace-time presence check; engines
        # without a bank trace the identical pre-tenancy programs). Row
        # 0 is the all-zeros identity = the base model, exactly.
        self.n_adapters = max(0, e.lora_adapters)
        if self.n_adapters > 0:
            from dynamo_tpu.tenancy.adapters import (
                init_adapter_bank,
                replicate_bank,
            )

            self.params = dict(
                self.params,
                adapters=replicate_bank(
                    init_adapter_bank(c, self.n_adapters, e.lora_rank),
                    self.mesh,
                ),
            )
        # paged pool: prefix-cache STORAGE (sealed blocks copied in,
        # admission prefixes copied out — models/llama.py module doc)
        self.cache = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            llama.init_cache(c, e.num_pages, e.page_size, cache_dtype,
                             kv_quant=e.kv_quant),
            llama.cache_shardings(c, self.mesh, kv_quant=e.kv_quant),
        )
        # contiguous per-slot serving context (+1 scratch lane for freed
        # slots' in-flight garbage steps). Under kv_quant=int8 the ctx
        # region is int8 too (group == page_size scale grid), so the
        # decode kernel streams half the live-KV bytes and pool<->ctx
        # copies at seal/admission are raw int8 moves.
        self.ctx = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            llama.init_ctx(c, e.max_decode_slots, e.max_context, cache_dtype,
                           kv_quant=e.kv_quant, group=e.page_size),
            llama.ctx_shardings(c, self.mesh, kv_quant=e.kv_quant),
        )
        # decode write ring: the round's steps write here; flush_ctx
        # scatters it into the ctx region once per round (keeping the
        # GB-scale region read-only inside the round — see llama.init_ring)
        self.ring = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            llama.init_ring(c, e.max_decode_slots, e.flush_every, cache_dtype),
            llama.ring_shardings(c, self.mesh),
        )
        self.allocator = PageAllocator(
            e.num_pages, e.page_size,
            worker_id=e.worker_id,
            on_event=on_kv_event,
            enable_prefix_caching=e.enable_prefix_caching,
        )
        # host-DRAM offload tier (KVBM G2): parked pages are batch-gathered
        # once per round and fetched to host behind compute. A deque:
        # on_park appends from BOTH the engine loop and the disagg asyncio
        # thread; the dispatcher drains with popleft (both thread-safe),
        # never a swap that could drop a concurrent append.
        self.offload = None
        self._offload_cands: deque = deque()
        # KV integrity plane (kv_integrity.py): one quarantine shared by
        # every host tier — a block that ever failed verification is
        # dropped everywhere and refused re-admission until its TTL
        # lapses, so the stream recomputes it instead of re-serving rot
        self.kv_quarantine = KvQuarantine()
        if e.disk_offload_pages > 0 and e.host_offload_pages <= 0:
            raise ValueError(
                "disk_offload_pages (G3) requires host_offload_pages (G2): "
                "the tier hierarchy is strict (block_manager.rs:69-82)"
            )
        if e.host_offload_pages > 0:
            from dynamo_tpu.engine.offload import (
                DiskOffloadTier,
                HostOffloadTier,
            )

            page_shape = (
                2, c.num_layers, c.num_kv_heads, e.page_size, c.head_dim
            )
            # tiers store what the pool stores: int8 pages + per-page
            # scale sidecars under kv_quant, so G2/G3 hold ~2x the
            # blocks per byte too
            tier_dtype = np.int8 if self.kv_quant else cache_dtype
            scale_shape = (2, c.num_layers) if self.kv_quant else ()
            spill = None
            if e.disk_offload_pages > 0:
                spill = DiskOffloadTier(
                    e.disk_offload_pages, page_shape, tier_dtype,
                    path=e.disk_offload_path, scale_shape=scale_shape,
                    quarantine=self.kv_quarantine,
                    scrub_on_start=e.scrub_on_start,
                )
            self.offload = HostOffloadTier(
                e.host_offload_pages, page_shape, tier_dtype, spill=spill,
                scale_shape=scale_shape, quarantine=self.kv_quarantine,
            )
            self.allocator.on_park = (
                lambda p, h, par: self._offload_cands.append((p, h, par))
            )

        # speculative decoding (dynamo_tpu/spec/): proposers, the fused
        # verifier, acceptance counters. Eligible slots bypass the fused
        # decode round entirely — see _dispatch_spec.
        self.spec = None
        if e.speculative != "off":
            from dynamo_tpu.spec import SpecDecoder

            self.spec = SpecDecoder(
                c, e, mesh=self.mesh,
                draft_config=draft_config, draft_params=draft_params,
                rng_seed=rng_seed,
            )

        # telemetry: latency histograms (scraped by the system server,
        # shipped to the exporter inside ForwardPassMetrics) + the
        # flight-recorder ring of recent dispatches
        self.telemetry = request_histograms(TelemetryRegistry(), engine=True)
        self._h_ttft = self.telemetry.get(tmetrics.TTFT[0])
        self._h_itl = self.telemetry.get(tmetrics.ITL[0])
        self._h_e2e = self.telemetry.get(tmetrics.E2E[0])
        self._h_queue = self.telemetry.get(tmetrics.QUEUE[0])
        self._h_round = self.telemetry.get(tmetrics.ROUND[0])
        # histogram snapshots are built per metrics() call, which the
        # engine loop makes EVERY round via on_metrics while the
        # publisher throttles to ~4 Hz — cache at the publish cadence so
        # the per-round cost is a timestamp compare, not 5 locked walks
        self._hist_snap: tuple[float, dict] = (0.0, {})
        self.flight = FlightRecorder(e.flight_recorder_events)
        # performance-attribution plane (telemetry/prof.py): per-round
        # host-segment switch timers, folded into the process-global
        # PROF registry at the metrics-publish cadence and served at
        # /debug/prof
        self.prof = RoundProf(enabled=e.prof_attribution)
        PROF.configure(e.slo_ttft_target_s, e.slo_itl_target_s,
                       e.slo_objective)
        # tail-latency forensics (telemetry/forensics.py): worker-side
        # breach capture for remote-worker mode — dossiers assembled
        # straight from this engine's prof/flight rings into OUTLIERS
        from dynamo_tpu.telemetry.forensics import ForensicsCapture
        self._forensics = ForensicsCapture(
            sample_rate=e.forensics_sample_rate,
            ttft_target_s=e.slo_ttft_target_s,
            itl_target_s=e.slo_itl_target_s,
            engines_fn=lambda: [self],
        )

        B = e.max_decode_slots
        self._B = B
        self._slots: list[Optional[_Request]] = [None] * B
        # slots reserved by an in-progress (multi-chunk) prefill: occupied
        # but NOT decoding — their dev lane stays parked on scratch until
        # the admission patch
        self._prefilling: dict[int, _Request] = {}
        # host mirror of dispatch-time context lengths
        self._ctx_disp = np.ones(B, np.int32)
        # numpy-backed slot-state mirrors (the slot_scan diet): updated
        # incrementally at every slot transition (_slot_on/_slot_off —
        # admission, despeculation, finish, release, fail_all) so the
        # per-round scheduling decisions are O(1) numpy reductions over
        # these instead of per-slot Python attribute walks.
        #   _slot_active: occupied AND not finished AND not speculating
        #   _slot_spec:   speculating (lane parked, verify-driven)
        #   _slot_lp / _slot_sampler: the slot's contribution to the
        #       round's want_lp / want_sample flags when active
        self._slot_active = np.zeros(B, bool)
        self._slot_spec = np.zeros(B, bool)
        self._slot_lp = np.zeros(B, bool)
        self._slot_sampler = np.zeros(B, bool)
        # cached (active list, want_lp, want_sample); invalidated on any
        # slot transition — steady decode recomputes it zero times/round
        self._active_cache: Optional[tuple[list[int], bool, bool]] = None

        # device state dict
        self._dev = {
            "tokens": jnp.zeros(B, jnp.int32),
            "ctx": jnp.ones(B, jnp.int32),
            # live slots write their own ctx lane; freed slots write the
            # scratch lane B (protects lanes being re-prefilled)
            "dest": jnp.full((B,), B, jnp.int32),
            "keys": jnp.zeros((B, 2), jnp.uint32),
            "counts": jnp.zeros((B, c.vocab_size), jnp.int32),
            "temp": jnp.zeros(B, jnp.float32),
            "top_k": jnp.zeros(B, jnp.int32),
            "top_p": jnp.ones(B, jnp.float32),
            "freq": jnp.zeros(B, jnp.float32),
            "pres": jnp.zeros(B, jnp.float32),
            "rep": jnp.ones(B, jnp.float32),
            # per-slot resident LoRA bank row (0 = identity base model);
            # gathered inside the fused round program — mixed adapters
            # in one decode batch cost zero extra dispatches
            "adapter": jnp.zeros(B, jnp.int32),
        }

        self._build_jits()

        self._intake: queue_mod.Queue = queue_mod.Queue()
        self._xfer: queue_mod.Queue = queue_mod.Queue()  # page export/import
        # idle-loop doorbell: producers (submit intake, _xfer_op page
        # ops) set it after enqueueing so the idle sleep in _run_loop
        # wakes immediately instead of finishing its 20 ms nap — the
        # decode-side import latency that capped disagg chunk streaming
        self._wake_evt = threading.Event()
        # chunked page exports in flight (kv_transfer chunk pipeline):
        # advanced a little every round, never blocking the loop
        self._xfer_streams: list[_ExportStream] = []
        # G4 remote tier: pages fetched from peer pools land here (from
        # the serving asyncio thread) and drain into the G2 host tier on
        # the engine loop before admission (kv_transfer.RemoteKvFetcher)
        self.remote_kv: Any = None
        self._host_ingest: queue_mod.Queue = queue_mod.Queue()
        self.remote_onboard_blocks = 0
        # fleet prefix economy: the frontend's replica/holder hint digest
        # (kv_router/fleet.py FleetHints), applied via apply_fleet_hints;
        # consulted by dedup admission and tier eviction. None until the
        # first hint push arrives.
        self.fleet_hints: Any = None
        self._waiting: list[_Request] = []
        # overload plane (dynamo_tpu/overload/): bounded admission over
        # the not-yet-prefilling backlog. The token counter is updated
        # from BOTH the asyncio intake side and the engine thread, so it
        # takes the lock; reads for budget checks are advisory.
        self.admission = AdmissionController(
            e.max_waiting_requests,
            e.max_waiting_prefill_tokens,
            queue_wait_s=lambda: self._h_queue.percentile(0.5),
        )
        self._waiting_tokens = 0
        self._wt_lock = threading.Lock()
        # tenancy plane (dynamo_tpu/tenancy/): per-tenant slices of the
        # backlog budgets + SFQ fair-share state. The per-tenant
        # counters ride the same `counted` flag / _wt_lock as
        # _waiting_tokens (inc at intake, dec exactly once at lane
        # acquisition or queue exit).
        self.tenant_quotas = TenantQuotas(
            e.tenant_max_waiting_requests,
            e.tenant_max_waiting_prefill_tokens,
            weights=e.tenant_weights,
        )
        self._tenant_waiting: dict[str, int] = {}
        self._tenant_tokens: dict[str, int] = {}
        # SFQ virtual clocks: per-tenant virtual finish time of the last
        # enqueued request, and the global clock advanced as requests
        # start service — a light tenant's fresh arrival stamps near the
        # global clock, i.e. near the queue head (engine thread only)
        self._tenant_vnow: dict[str, float] = {}
        self._vclock = 0.0
        self.sheds = 0                # deadline-expired waiting requests
        self.waiting_preemptions = 0  # waiting entries evicted by priority
        self.preempt_migrations = 0   # running streams force-migrated
        self._entries: list[_Entry] = []
        # sealed blocks awaiting the batched ctx->pool copy:
        # (slot, start_pos, pool_page)
        self._seal_queue: list[tuple[int, int, int]] = []
        self._to_release: list[_Request] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = False
        # graceful drain (resilience/drain.py): begin_drain() stops
        # admissions; drained() flips once in-flight work finishes
        self._draining = False
        self._drained_evt = threading.Event()
        self.step_count = 0
        self.tokens_generated = 0
        self.sp_prefills = 0
        self.batch_prefills = 0     # batched-prefill dispatches (K >= 2)
        # dispatch-budget accounting (tools/profile_round.py
        # --dispatch-budget, the bench dispatches_per_round field, and
        # the tier-1 regression pin): every host->device program launch
        # or async D2H fetch initiation increments its bucket
        self.dispatch_counts: dict[str, int] = {
            "round": 0, "round_seal": 0, "seal": 0, "patch": 0,
            "prefill": 0, "prefill_batch": 0, "sp_prefill": 0,
            "load_ctx": 0, "sample_first": 0, "fetch": 0, "encode": 0,
            "offload_gather": 0, "xfer_gather": 0, "xfer_scatter": 0,
            # speculative path: the fused batch-draft and verify
            # programs (the legacy PER-SLOT draft loop's dispatches are
            # accounted by spec.stats()['spec_draft_dispatch_total'])
            "spec_draft": 0, "spec_verify": 0,
        }
        # prefix-commit event plane: subscribers (the disagg streaming
        # export, offload candidacy, future replication) are notified
        # when a seal batch's pool copy is DISPATCHED — exporting after
        # the callback is device-order safe — instead of polling the
        # allocator on a fixed cadence (the PR 5 2 ms poll)
        self._commit_cbs: list[Callable[[], None]] = []
        self._commit_lock = threading.Lock()
        self._last_metrics_pub = 0.0
        # round-pipeline accounting (ecfg.round_pipeline): early-dispatch
        # counters behind pipeline_stats() — pipeline_depth is the mean
        # rounds in flight right after an early dispatch, overlap_ratio
        # the fraction of pipelined-round host time spent in the
        # completion half (i.e. running WHILE the early dispatch executes
        # on device). pipe_flushes counts why the pipeline fell back to
        # the strict order, per flush point.
        self._pipe_dispatches = 0
        self._pipe_depth_sum = 0
        self._pipe_hidden_s = 0.0
        self._pipe_host_s = 0.0
        self.pipe_flushes: dict[str, int] = {
            "drain": 0, "admission": 0, "release": 0,
            "seal_overflow": 0, "spec": 0,
        }

    # ------------------------------------------------------------------
    # jitted programs

    def _build_jits(self) -> None:
        c, e = self.config, self.ecfg
        max_top_k = e.max_top_k
        max_context = e.max_context
        # fused-seal width: ONE static shape so the fused round program
        # compiles exactly once per (n_steps, lp, sample) combo — a
        # pow2-per-batch width would compile the whole round program per
        # width bucket (measured +40% on the CPU test suite). Sized for
        # a full aligned burst (every slot completing blocks the same
        # round); larger admission-time bursts overflow to the
        # standalone seal_blocks path.
        self._seal_fuse_w = pow2_cover(max(
            e.max_decode_slots,
            e.max_decode_slots * e.flush_every // max(e.page_size, 1),
            1,
        ))

        max_logprobs = e.max_logprobs

        def pack_lp(chosen, ids, lps):
            """One f32 row [..., 1+2K] per step: chosen logprob, top ids
            (exact in f32 — vocab << 2^24), top logprobs. Packing means
            ONE stacked fetch per lp round instead of three separate
            copy_to_host_async pipelines (the dispatch diet)."""
            return jnp.concatenate(
                [chosen[..., None], ids.astype(jnp.float32), lps], axis=-1
            )

        def round_body(params, ctx_kv, ring, dev, n_steps, want_lp,
                       want_sample):
            """A FULL scheduling round in one program: n_steps fused
            decode+sample steps via lax.fori_loop (body compiles once) and
            the ring->ctx flush — one dispatch + one result fetch per
            round instead of n_steps+2, the single biggest lever on
            per-step host overhead. The ctx region is READ-ONLY until the
            tail flush (write/read interleave on it forces XLA copies —
            llama.init_ring). `want_lp` adds the logprob computation only
            for rounds that asked for it; `want_sample` gates the full
            sampler — all-greedy rounds (the common serving case) take a
            bare argmax instead of top-k over the vocab."""
            B = dev["tokens"].shape[0]
            ring_base = jnp.maximum(dev["ctx"] - 1, 0)
            toks_out = jnp.zeros((n_steps, B), jnp.int32)
            lp_out = (
                jnp.zeros((n_steps, B, 1 + 2 * max_logprobs), jnp.float32)
                if want_lp else None
            )
            sp = sampling.SamplingParams(
                temperature=dev["temp"], top_k=dev["top_k"], top_p=dev["top_p"],
                frequency_penalty=dev["freq"], presence_penalty=dev["pres"],
                repetition_penalty=dev["rep"],
            )

            # MoE models: freed/garbage lanes must not claim expert
            # capacity (and masking keeps outputs batch-independent)
            live = (dev["dest"] != B) if c.moe is not None else None

            def body(s, carry):
                ring, dev, toks_out, lp_out = carry
                ring, logits = llama.decode_step_impl(
                    c, params, ctx_kv, ring, dev["tokens"], dev["ctx"],
                    ring_base, s, live, dev["adapter"],
                )
                if want_sample:
                    toks, st = sampling.sample_step_impl(
                        logits,
                        sampling.SamplerState(dev["keys"], dev["counts"]),
                        sp, max_top_k,
                    )
                    keys, counts = st.keys, st.counts
                else:
                    toks = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    keys, counts = dev["keys"], dev["counts"]
                toks_out = jax.lax.dynamic_update_index_in_dim(
                    toks_out, toks, s, 0
                )
                if want_lp:
                    chosen, ids, lps = sampling.compute_logprobs(
                        logits, toks, max_logprobs
                    )
                    lp_out = jax.lax.dynamic_update_index_in_dim(
                        lp_out, pack_lp(chosen, ids, lps), s, 0
                    )
                dev = dict(
                    dev,
                    tokens=toks,
                    ctx=jnp.minimum(dev["ctx"] + 1, max_context),
                    keys=keys,
                    counts=counts,
                )
                return ring, dev, toks_out, lp_out

            ring, dev, toks_out, lp_out = jax.lax.fori_loop(
                0, n_steps, body, (ring, dev, toks_out, lp_out)
            )
            # round boundary: scatter the ring into the ctx region
            # (single write, after every read — aliases in place)
            valid = jnp.minimum(jnp.int32(n_steps), max_context - ring_base)
            ctx_kv = llama.flush_ctx_impl(
                ctx_kv, ring, dev["dest"], ring_base, valid
            )
            return ctx_kv, ring, dev, toks_out, lp_out

        engine_round = functools.partial(
            jax.jit, donate_argnums=(1, 2, 3), static_argnums=(4, 5, 6)
        )(round_body)

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3, 4),
                           static_argnums=(8, 9, 10))
        def engine_round_seal(params, ctx_kv, ring, dev, cache,
                              seal_slots, seal_starts, seal_pages,
                              n_steps, want_lp, want_sample):
            """engine_round with the round's pending ctx->pool seal batch
            FUSED onto the tail — in steady decode a block completes
            nearly every round, so the previously separate seal_blocks
            program was a per-round straggler dispatch. The seal runs
            after the flush and reads positions written by already-
            dispatched programs (the host only queues a seal for
            positions whose results it has processed, which lag the
            dispatch front by at least a round)."""
            ctx_kv, ring, dev, toks_out, lp_out = round_body(
                params, ctx_kv, ring, dev, n_steps, want_lp, want_sample
            )
            cache = llama.seal_blocks_impl(
                cache, ctx_kv, seal_slots, seal_starts, seal_pages,
                e.page_size,
            )
            return ctx_kv, ring, dev, cache, toks_out, lp_out

        @functools.partial(jax.jit, donate_argnums=(0,))
        def patch(dev, clear_mask, admit_meta, admit_tok, admit_keys,
                  admit_counts):
            """State patch (releases + one admission). ``admit_meta`` is
            ONE packed f32[9] row — [slot, ctx, temp, top_k, top_p, freq,
            pres, rep, adapter] — instead of eleven scalar device_puts
            per admission (every int here is exact in f32; ctx and
            adapter ids < 2^24). slot == B is the no-admission sentinel:
            every .at[] update is dropped."""
            B = dev["tokens"].shape[0]
            dev = dict(dev)
            dev["ctx"] = jnp.where(clear_mask, 1, dev["ctx"])
            dev["tokens"] = jnp.where(clear_mask, 0, dev["tokens"])
            dev["temp"] = jnp.where(clear_mask, 0.0, dev["temp"])
            dev["counts"] = jnp.where(clear_mask[:, None], 0, dev["counts"])
            # freed slots park on the scratch lane so their in-flight
            # garbage steps can't touch a lane being re-prefilled
            dev["dest"] = jnp.where(
                clear_mask, B, dev["dest"]
            ).astype(jnp.int32)
            dev["adapter"] = jnp.where(clear_mask, 0, dev["adapter"])
            s = admit_meta[0].astype(jnp.int32)
            dev["tokens"] = dev["tokens"].at[s].set(admit_tok[0])
            dev["ctx"] = dev["ctx"].at[s].set(admit_meta[1].astype(jnp.int32))
            dev["dest"] = dev["dest"].at[s].set(s)
            dev["keys"] = dev["keys"].at[s].set(admit_keys)
            # fresh admissions pass the cached zero row; a penalized slot
            # despeculating back to the fused round restores its histogram
            dev["counts"] = dev["counts"].at[s].set(admit_counts)
            dev["temp"] = dev["temp"].at[s].set(admit_meta[2])
            dev["top_k"] = dev["top_k"].at[s].set(
                admit_meta[3].astype(jnp.int32)
            )
            dev["top_p"] = dev["top_p"].at[s].set(admit_meta[4])
            dev["freq"] = dev["freq"].at[s].set(admit_meta[5])
            dev["pres"] = dev["pres"].at[s].set(admit_meta[6])
            dev["rep"] = dev["rep"].at[s].set(admit_meta[7])
            dev["adapter"] = dev["adapter"].at[s].set(
                admit_meta[8].astype(jnp.int32)
            )
            return dev

        @functools.partial(jax.jit, static_argnums=(5, 6))
        def sample_first(logits, key, temp, top_k, top_p, vocab, want_lp):
            st = sampling.SamplerState(
                keys=key[None], counts=jnp.zeros((1, vocab), jnp.int32)
            )
            sp = sampling.SamplingParams(
                temperature=temp[None], top_k=top_k[None], top_p=top_p[None],
                frequency_penalty=jnp.zeros(1), presence_penalty=jnp.zeros(1),
                repetition_penalty=jnp.ones(1),
            )
            toks, _ = sampling.sample_step_impl(logits[None], st, sp, max_top_k)
            lp = (pack_lp(*sampling.compute_logprobs(
                      logits[None], toks, max_logprobs))
                  if want_lp else None)
            return toks, lp  # [1] i32, optional packed [1, 1+2K] f32

        self._engine_round = engine_round
        self._engine_round_seal = engine_round_seal
        self._patch = patch
        self._sample_first = sample_first
        # reusable zero counts row for ordinary admissions (no per-patch
        # [V]-sized H2D upload) + the no-admission token placeholder
        self._zero_counts = jnp.zeros(c.vocab_size, jnp.int32)
        self._zero_tok = jnp.zeros(1, jnp.int32)
        # cached all-scratch dummy seal batch: seal-less rounds reuse it
        # so the fused round costs ZERO extra H2D uploads
        z = jnp.zeros(self._seal_fuse_w, jnp.int32)
        self._zero_seal = (z, z, z)

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._thread = threading.Thread(
            target=self._run_loop, name="tpu-engine-loop", daemon=True
        )
        self._thread.start()

    async def stop(self) -> None:
        self._stop.set()
        if self._thread:
            await asyncio.to_thread(self._thread.join, 30.0)
        # items raced in after the loop's own exit drain
        self._drain_xfer_queue()
        if self.offload is not None and self.offload.spill is not None:
            self.offload.spill.close()

    # ---- graceful drain (resilience/drain.py DrainController contract) --

    def begin_drain(self) -> None:
        """Stop admitting: subsequent generate() calls raise the retriable
        WorkerDrainingError; requests already accepted run to completion."""
        self._draining = True
        if not self._started:
            # the loop never ran: nothing can be in flight
            self._drained_evt.set()

    def drained(self) -> bool:
        return self._drained_evt.is_set()

    # ---- prefix-commit event plane ----

    def subscribe_commits(self, cb: Callable[[], None]) -> None:
        """Register a callback fired (from the engine thread) whenever
        the committed prefix grew: sealed blocks became MATCHABLE
        (_queue_seal) or a seal batch's pool copies were dispatched.
        Exporting on this signal is device-order safe because every
        engine-loop export path flushes queued seal copies before its
        pool read. Replaces fixed-cadence allocator polling for
        streaming export / offload candidacy / replication consumers;
        callbacks must be cheap and non-blocking (bounce to your own
        loop/queue)."""
        with self._commit_lock:
            if cb not in self._commit_cbs:
                self._commit_cbs.append(cb)

    def unsubscribe_commits(self, cb: Callable[[], None]) -> None:
        with self._commit_lock:
            if cb in self._commit_cbs:
                self._commit_cbs.remove(cb)

    def _notify_commits(self) -> None:
        with self._commit_lock:
            cbs = list(self._commit_cbs)
        for cb in cbs:
            try:
                cb()
            except Exception:  # noqa: BLE001 — never kill the loop
                log.exception("commit listener failed")

    # ------------------------------------------------------------------
    # tenancy plane: resident adapters

    def install_adapter(self, adapter_id: int, weights: dict) -> None:
        """Install one fine-tune variant's LoRA factors into the
        resident bank (site -> {"a": [L, d_in, r], "b": [L, r, d_out]}).
        Swapping the bank is a pure buffer replacement — shapes/dtypes
        are unchanged, so no jitted program retraces."""
        from dynamo_tpu.tenancy.adapters import replicate_bank, set_adapter

        bank = (self.params or {}).get("adapters")
        if bank is None:
            raise ValueError(
                "engine has no adapter bank (EngineConfig.lora_adapters=0)"
            )
        self.params = dict(
            self.params,
            adapters=replicate_bank(
                set_adapter(bank, adapter_id, weights), self.mesh
            ),
        )

    # ------------------------------------------------------------------
    # AsyncEngine surface

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[LLMEngineOutput]:
        """Stream engine outputs (token-id deltas) for one request."""
        if self._draining:
            from dynamo_tpu.resilience.drain import WorkerDrainingError

            raise WorkerDrainingError(
                "worker draining: not admitting new requests"
            )
        if not self._started:
            self.start()
        if len(request.token_ids) == 0:
            raise ValueError("empty prompt")
        if len(request.token_ids) >= self.ecfg.max_context:
            raise ValueError(
                f"prompt length {len(request.token_ids)} exceeds max context "
                f"{self.ecfg.max_context}"
            )
        tenant = getattr(request, "tenant", "") or "default"
        adapter_id = int(getattr(request, "adapter_id", 0) or 0)
        if adapter_id and not (0 < adapter_id < max(1, self.n_adapters)):
            raise ValueError(
                f"adapter_id {adapter_id} out of range: engine bank has "
                f"{self.n_adapters} adapter slots"
            )
        # overload plane: a deadline that expired before intake is shed
        # immediately — zero tokens, the DEADLINE finish reason, never an
        # error (the client's budget ran out, nothing failed)
        if (request.deadline is not None
                and time.time() > request.deadline):
            self.sheds += 1
            OVERLOAD.inc("dynamo_overload_shed_total")
            yield LLMEngineOutput(
                token_ids=[], finish_reason=FinishReason.DEADLINE,
                annotations={"shed": {"reason": "deadline",
                                      "queued_s": 0.0}},
            )
            return
        # bounded admission: a full waiting queue refuses intake with the
        # retriable overload error (router spills to a peer, frontend
        # answers 429 + Retry-After). A HIGH-priority arrival is admitted
        # anyway — the engine loop restores the budget by preempting the
        # lowest-priority waiting entry (_enforce_bounds).
        if self.admission.bounded:
            waiting = (sum(1 for w in self._waiting if w.slot < 0)
                       + self._intake.qsize())
            with self._wt_lock:
                tokens = self._waiting_tokens
            try:
                self.admission.check(waiting, tokens)
            except EngineOverloadedError:
                if request.priority < PRIORITY_HIGH:
                    OVERLOAD.inc("dynamo_overload_rejected_total")
                    TENANT.inc("dynamo_tenant_rejected_total", tenant)
                    raise
        # per-tenant admission slice: one tenant's storm exhausts its
        # OWN budget (429 + Retry-After derived from that tenant's own
        # queue waits) before it can crowd the global queue. HIGH
        # priority is force-admitted like the global check —
        # _enforce_bounds restores the budget from the same tenant.
        if self.tenant_quotas.bounded:
            with self._wt_lock:
                t_waiting = self._tenant_waiting.get(tenant, 0)
                t_tokens = self._tenant_tokens.get(tenant, 0)
            try:
                self.tenant_quotas.check(tenant, t_waiting, t_tokens)
            except EngineOverloadedError:
                if request.priority < PRIORITY_HIGH:
                    OVERLOAD.inc("dynamo_overload_rejected_total")
                    TENANT.inc("dynamo_tenant_rejected_total", tenant)
                    raise
        TENANT.inc("dynamo_tenant_admitted_total", tenant)
        # multimodal requests salt their block hashes with the image digest:
        # placeholder tokens are identical across different images, and a
        # prefix-cache hit keyed on tokens alone would serve the wrong
        # image's KV
        salt = request.model
        if request.multimodal and request.multimodal.get("digest"):
            salt = f"{salt}|mm:{request.multimodal['digest']}"
        r = _Request(
            req=request,
            seq=TokenBlockSequence.from_tokens(
                request.token_ids, self.ecfg.page_size, salt=salt
            ),
            out=asyncio.Queue(),
            loop=asyncio.get_running_loop(),
            tokens=list(request.token_ids),
            trace_detail="trace_detail" in (request.annotations or []),
        )
        if self.remote_kv is not None and self.offload is not None:
            await self._remote_prefetch(r)
        r.counted = True
        with self._wt_lock:
            self._waiting_tokens += len(r.tokens)
            self._tenant_waiting[tenant] = (
                self._tenant_waiting.get(tenant, 0) + 1
            )
            self._tenant_tokens[tenant] = (
                self._tenant_tokens.get(tenant, 0) + len(r.tokens)
            )
        self._intake.put(r)
        self._wake_evt.set()
        try:
            while True:
                item = await r.out.get()
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            r.cancelled = True

    # ------------------------------------------------------------------
    # padded page I/O (shared by transfers, offload, onboard): page lists
    # are pow2-bucketed for compile-cache reuse; padding targets scratch
    # page 0 (garbage by contract)

    def _gather_padded(self, pages: list[int]):
        """Device gather of whole pages; returns DEVICE arrays
        ``(data [2, L, kvh, pow2(n), ps, hd], scales|None)`` — callers
        slice [:len(pages)] on the page axis after fetching. Quantized
        pools return the int8 payload plus its [2, L, pow2(n)] scale
        sidecar."""
        w = pow2_cover(len(pages))
        padded = np.zeros(w, np.int32)
        padded[: len(pages)] = pages
        if self.kv_quant:
            return llama.gather_pages_q(self.cache, jnp.asarray(padded))
        return llama.gather_pages(self.cache, jnp.asarray(padded)), None

    def _host_pages(self, data_h, scales_h, n: int):
        """Fetch a padded device gather to host and trim the padding:
        a QuantizedPages bundle for int8 pools, a dense array else."""
        data = np.asarray(data_h)[:, :, :, :n]
        if scales_h is None:
            return data
        return QuantizedPages(data, np.asarray(scales_h)[:, :, :n])

    def _scatter_padded(self, pages: list[int], data) -> None:
        """Scatter host pages [2, L, kvh, n, ps, hd] into the pool.
        ``data`` may be a dense array or a QuantizedPages bundle; either
        is converted to what THIS pool stores at the boundary (a bf16
        peer's push quantizes on the way in; an int8 bundle landing in a
        bf16 pool dequantizes)."""
        data = to_pool_dtype(
            data, self.kv_quant, np.dtype(self.cache["k"].dtype)
        )
        n = len(pages)
        w = pow2_cover(n)
        padded = np.zeros(w, np.int32)
        padded[:n] = pages
        self.dispatch_counts["xfer_scatter"] += 1
        if self.kv_quant:
            d, s = data.data, data.scales
            if w > n:
                d = np.concatenate(
                    [d, np.zeros(d.shape[:3] + (w - n,) + d.shape[4:],
                                 d.dtype)], axis=3,
                )
                s = np.concatenate(
                    [s, np.zeros(s.shape[:2] + (w - n,), s.dtype)], axis=2,
                )
            self.cache = llama.scatter_pages_q(
                self.cache, jnp.asarray(padded),
                jnp.asarray(d), jnp.asarray(s),
            )
            return
        if w > n:
            pad_shape = list(data.shape)
            pad_shape[3] = w - n
            data = np.concatenate(
                [data, np.zeros(pad_shape, data.dtype)], axis=3
            )
        self.cache = llama.scatter_pages(
            self.cache, jnp.asarray(padded), jnp.asarray(data)
        )

    # ------------------------------------------------------------------
    # KV page export/import (block-transfer data plane hooks;
    # kv_transfer.py BlockTransferServer read_fn/write_fn)

    def export_pages(self, page_ids: list[int]) -> np.ndarray:
        """Gather whole pages to host: [2, L, kvh, n, ps, hd] (a
        kv_quant.QuantizedPages bundle — int8 + scales — for quantized
        pools). Thread-safe — blocks the CALLER until the engine loop
        services it at a round boundary (device-order safe w.r.t.
        in-flight steps)."""
        return self._xfer_op("export", page_ids, None)

    def import_pages(self, page_ids: list[int], data: np.ndarray) -> None:
        """Scatter host pages into the pool (inverse of export_pages)."""
        self._xfer_op("import", page_ids, data)

    def export_pages_by_hash(
        self, hashes: list[int]
    ) -> tuple[int, Optional[np.ndarray]]:
        """G4 serving side: the longest committed run of the chained-hash
        prefix this pool holds, as (found, pages [2, L, kvh, found, ps,
        hd]). Thread-safe (serviced by the engine loop like
        export_pages)."""
        return self._xfer_op("export_hash", [int(h) for h in hashes], None)

    # ---- chunked export streams (kv_transfer chunk pipeline) ----

    def export_pages_stream(
        self, page_ids: list[int], chunk_pages: int = 0, inflight: int = 0,
    ):
        """Chunked thread-safe export: an iterator of host arrays
        [2, L, kvh, <=chunk_pages, ps, hd] covering ``page_ids`` in
        order. The engine loop double-buffers the per-chunk gathers
        (``kv_transfer_inflight_chunks`` D2H copies in flight) and keeps
        serving between chunks — peak host staging is O(chunk), and a
        consumer streaming chunks over TCP overlaps the wire time with
        the next chunk's gather."""
        out_q = self._start_stream("export_stream", list(page_ids),
                                   chunk_pages, inflight)
        return self._consume_stream(out_q)

    def export_hash_stream(
        self, hashes: list[int], chunk_pages: int = 0, inflight: int = 0,
    ) -> tuple[int, Any]:
        """G4 serving side, chunked: resolve the longest committed run of
        the chained-hash prefix and export it as (found, chunk iterator)
        — the streaming analogue of export_pages_by_hash, without ever
        staging the whole run on host."""
        out_q = self._start_stream(
            "export_hash_stream", [int(h) for h in hashes],
            chunk_pages, inflight,
        )
        first = self._next_stream_item(out_q)  # ("found", k) | Exception
        if isinstance(first, Exception):
            raise first
        found = int(first[1])
        return found, self._consume_stream(out_q)

    def _start_stream(
        self, kind: str, ids: list[int], chunk_pages: int, inflight: int,
    ) -> queue_mod.Queue:
        if self.on_dispatch is not None:
            raise RuntimeError(
                "multihost engine: the page transfer plane is single-host"
            )
        if self._stop.is_set():
            raise RuntimeError("engine stopped")
        if not self._started:
            self.start()
        e = self.ecfg
        chunk_pages = int(chunk_pages or e.kv_transfer_chunk_pages
                          or max(len(ids), 1))
        inflight = max(1, int(inflight or e.kv_transfer_inflight_chunks))
        out_q: queue_mod.Queue = queue_mod.Queue()
        self._xfer.put((kind, ids, (chunk_pages, inflight, out_q),
                        threading.Event(), {}))
        self._wake_evt.set()
        return out_q

    def _next_stream_item(self, out_q: queue_mod.Queue) -> Any:
        """One queue item with the same stop/deadline discipline as
        _xfer_op (the engine may stop or wedge mid-stream)."""
        deadline = time.monotonic() + self.ecfg.xfer_op_timeout_s
        stop_grace: Optional[float] = None
        while True:
            try:
                item = out_q.get(timeout=1.0)
                # the consumer pull just freed an inflight slot — ring
                # the doorbell so a throttled export stream dispatches
                # its next chunk now, not after the idle sleep
                self._wake_evt.set()
                return item
            except queue_mod.Empty:
                now = time.monotonic()
                if self._stop.is_set():
                    if stop_grace is None:
                        stop_grace = now + 10.0
                    elif now > stop_grace:
                        raise RuntimeError(
                            "engine stopped during page export stream"
                        )
                elif now > deadline:
                    raise TimeoutError("page export stream timed out")

    def _consume_stream(self, out_q: queue_mod.Queue):
        while True:
            item = self._next_stream_item(out_q)
            if item is _STREAM_EOS:
                return
            if isinstance(item, Exception):
                raise item
            yield item

    def _service_export_streams(self) -> bool:
        """Advance every in-flight chunked export a little (called once
        per round): convert ready head chunks for the consumer, dispatch
        new gathers up to the double-buffer depth. Returns True if any
        stream made progress (keeps the loop cycling while exports
        drain)."""
        if not self._xfer_streams:
            return False
        if self._seal_queue:
            # stream gathers read the pool: queued seal copies first
            self._flush_seals()
        now = time.monotonic()
        keep: list[_ExportStream] = []
        progressed = False
        for st in self._xfer_streams:
            try:
                moved = self._advance_stream(st)
            except Exception as e:  # noqa: BLE001 — surface to the consumer
                if st.free_pages is not None:
                    self.allocator.free(st.free_pages)
                    st.free_pages = None
                st.out_q.put(e)
                st.out_q.put(_STREAM_EOS)
                progressed = True
                continue
            if moved:
                st.last_progress = now
                progressed = True
            if st.pos >= len(st.ids) and not st.pending:
                st.out_q.put(_STREAM_EOS)
                progressed = True
            elif (not moved and now - st.last_progress
                    > self.ecfg.kv_transfer_stream_idle_timeout_s):
                # consumer vanished mid-stream (dead peer connection /
                # stalled receiver): reclaim the pinned gather handles
                # and page refs instead of leaking them for the full
                # xfer-op deadline — an export stream that moved nothing
                # for the idle window is abandoned, however long a
                # HEALTHY transfer is allowed to take
                if st.free_pages is not None:
                    self.allocator.free(st.free_pages)
                    st.free_pages = None
                st.out_q.put(RuntimeError("export stream abandoned"))
                st.out_q.put(_STREAM_EOS)
                progressed = True
            else:
                keep.append(st)
        self._xfer_streams = keep
        return progressed

    def _advance_stream(self, st: _ExportStream) -> bool:
        progressed = False
        # convert ready heads — bounded by consumer pull so a stalled
        # peer can't grow unbounded host staging (BOTH handles must be
        # ready: np.asarray on a pending scale copy would block the loop)
        while (st.pending and st.pending[0][1].is_ready()
               and (st.pending[0][2] is None
                    or st.pending[0][2].is_ready())
               and st.out_q.qsize() < st.inflight):
            n, handle, scales_h = st.pending.popleft()
            st.out_q.put(self._host_pages(handle, scales_h, n))
            progressed = True
        # dispatch the next gathers (async D2H behind compute)
        while (st.pos < len(st.ids) and len(st.pending) < st.inflight
               and st.out_q.qsize() < st.inflight):
            chunk = st.ids[st.pos: st.pos + st.chunk_pages]
            self.dispatch_counts["xfer_gather"] += 1
            out, scales = self._gather_padded(chunk)
            out.copy_to_host_async()
            self.dispatch_counts["fetch"] += 1
            if scales is not None:
                scales.copy_to_host_async()
            st.pending.append((len(chunk), out, scales))
            st.pos += len(chunk)
            progressed = True
        if st.pos >= len(st.ids) and st.free_pages is not None:
            # every gather is dispatched: device order protects the
            # reads, drop the pins now (same contract as export_hash)
            self.allocator.free(st.free_pages)
            st.free_pages = None
        return progressed

    def _xfer_op(self, kind: str, page_ids: list[int], data) -> Any:
        if self.on_dispatch is not None and kind in (
            "export", "import", "export_hash",
        ):
            raise RuntimeError(
                "multihost engine: the page transfer plane is single-host"
            )
        if self._stop.is_set():
            raise RuntimeError("engine stopped")
        if not self._started:
            self.start()
        done = threading.Event()
        box: dict[str, Any] = {}
        self._xfer.put((kind, list(page_ids), data, done, box))
        self._wake_evt.set()
        # wait in slices. On stop, the loop-exit drain (or stop()'s final
        # drain) errors still-queued items; an in-flight op completes and
        # reports its real result — we only bound the wait, never clobber
        # the box ourselves (that would misreport a completed transfer).
        deadline = time.monotonic() + self.ecfg.xfer_op_timeout_s
        stop_grace: Optional[float] = None
        while not done.wait(timeout=1.0):
            now = time.monotonic()
            if self._stop.is_set():
                if stop_grace is None:
                    stop_grace = now + 10.0
                elif now > stop_grace:
                    raise RuntimeError(f"engine stopped during page {kind}")
            elif now > deadline:
                raise TimeoutError(f"page {kind} timed out")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _process_transfers(self) -> bool:
        """Service queued page-transfer ops. Returns True when at least
        one op was processed — transfer traffic IS work, and counting it
        keeps the loop hot while a disagg import stream is chunking
        pages in (otherwise each chunk eats an idle-path sleep)."""
        processed = False
        while True:
            try:
                kind, ids, data, done, box = self._xfer.get_nowait()
            except queue_mod.Empty:
                return processed
            processed = True
            if kind != "import" and self._seal_queue:
                # pool reads (exports, hash matches, clears) must see
                # queued seal copies dispatched first — commits are
                # matchable the moment _queue_seal runs, but with seals
                # riding the fused round their device copy may still be
                # pending this round
                self._flush_seals()
            try:
                if kind == "export":
                    self.dispatch_counts["xfer_gather"] += 1
                    out, scales = self._gather_padded(ids)
                    box["result"] = self._host_pages(out, scales, len(ids))
                elif kind == "export_stream":
                    chunk_pages, inflight, out_q = data
                    self._xfer_streams.append(_ExportStream(
                        ids=ids, chunk_pages=chunk_pages,
                        inflight=inflight, out_q=out_q,
                    ))
                elif kind == "export_hash_stream":
                    # resolve + pin on the engine loop; the stream frees
                    # the pins once every gather is dispatched
                    chunk_pages, inflight, out_q = data
                    pages = self.allocator.match_prefix(ids)
                    out_q.put(("found", len(pages)))
                    if not pages:
                        out_q.put(_STREAM_EOS)
                    else:
                        self._xfer_streams.append(_ExportStream(
                            ids=pages, chunk_pages=chunk_pages,
                            inflight=inflight, out_q=out_q,
                            free_pages=pages,
                        ))
                elif kind == "export_hash":
                    # G4 peer-serving side: ids are chained block hashes;
                    # resolve the longest committed run, export it, drop
                    # the refs the match pinned
                    pages = self.allocator.match_prefix(ids)
                    if not pages:
                        box["result"] = (0, None)
                    else:
                        self.dispatch_counts["xfer_gather"] += 1
                        out, scales = self._gather_padded(pages)
                        data = self._host_pages(out, scales, len(pages))
                        self.allocator.free(pages)
                        box["result"] = (len(pages), data)
                elif kind == "clear":
                    n = self.allocator.clear()
                    self._offload_cands.clear()  # parked refs now stale
                    if self.offload is not None:
                        n += self.offload.clear()
                        # in-flight D2H offload batches would repopulate
                        # the tiers after the clear — drop them (their
                        # fetches complete harmlessly, results unused)
                        self._entries = [
                            en for en in self._entries
                            if en.kind != "offload"
                        ]
                    box["result"] = n
                else:
                    self._scatter_padded(ids, data)
                    box["result"] = None
            except Exception as e:  # noqa: BLE001 — surface to the caller
                box["error"] = e
                if kind in ("export_stream", "export_hash_stream"):
                    # stream consumers wait on the chunk queue, not the box
                    data[2].put(e)
                    data[2].put(_STREAM_EOS)
            finally:
                done.set()

    def clear_kv_blocks(self) -> int:
        """Drop all reusable cached pages across every tier (G1 HBM LRU +
        G2 DRAM + G3 disk) — the /clear_kv_blocks operation (reference
        http/service/clear_kv_blocks.rs). In-use pages survive. Thread-safe:
        serviced by the engine loop at a round boundary."""
        return self._xfer_op("clear", [], None)

    def embed(self, token_ids: list[int]) -> list[float]:
        """Mean-pooled normalized embedding of a prompt (the /v1/embeddings
        surface). Cache-free encoder pass over read-only params — safe to
        call from any thread, concurrent with serving. Bounded by
        max_context: the O(T^2) one-shot attention would otherwise let one
        long input OOM the device serving everyone."""
        if self.on_dispatch is not None:
            # llama.encode is an SPMD program over the global mesh; it is
            # not in the broadcast command set, so dispatching it on the
            # leader alone would deadlock the cross-host collectives
            raise RuntimeError(
                "multihost engine: embeddings are a single-host feature"
            )
        if not token_ids:
            raise ValueError("empty input")
        if len(token_ids) > self.ecfg.max_context:
            raise ValueError(
                f"input length {len(token_ids)} exceeds max context "
                f"{self.ecfg.max_context}"
            )
        T = pow2_cover(max(len(token_ids), 8))
        toks = np.zeros(T, np.int32)
        toks[: len(token_ids)] = token_ids
        self.dispatch_counts["encode"] += 1
        out = llama.encode(
            self.config, self.params, jnp.asarray(toks),
            jnp.int32(len(token_ids)),
        )
        return np.asarray(out, np.float32).tolist()

    def _histograms_snapshot(self) -> dict:
        """Telemetry snapshot refreshed at most every 0.25 s (the
        publisher's own throttle) — metrics() runs every round."""
        now = time.monotonic()
        t, snap = self._hist_snap
        if now - t >= 0.25:
            snap = self.telemetry.snapshot()
            self._hist_snap = (now, snap)
        return snap

    def metrics(self) -> ForwardPassMetrics:
        a = self.allocator
        # "gpu cache usage" must reflect LIVE serving occupancy, not the
        # pool: in the contiguous-ctx design the paged pool holds parked
        # (refcount-0, reclaimable) prefix blocks, so a.usage() reads ~0
        # under full decode load and the planner would never scale up.
        # The live analogue of vLLM's metric is ctx-region token
        # occupancy, floored by pool pressure.
        live_tokens = sum(
            int(self._ctx_disp[i])
            for i, s in enumerate(self._slots) if s is not None
        )
        ctx_usage = live_tokens / float(self._B * self.ecfg.max_context)
        e = self.ecfg
        num_waiting = (sum(1 for r in self._waiting if r.slot < 0)
                       + self._intake.qsize())
        with self._wt_lock:
            waiting_tokens = self._waiting_tokens
        # process-level overload gauges (all three scrape surfaces)
        OVERLOAD.set("dynamo_overload_queue_depth", num_waiting)
        OVERLOAD.set("dynamo_overload_queue_tokens", waiting_tokens)
        # tenant-sliced backlog gauges
        with self._wt_lock:
            t_waiting = dict(self._tenant_waiting)
            t_tokens = dict(self._tenant_tokens)
        for t in set(t_waiting) | set(t_tokens):
            TENANT.set("dynamo_tenant_queue_depth", t,
                       t_waiting.get(t, 0))
            TENANT.set("dynamo_tenant_queue_tokens", t,
                       t_tokens.get(t, 0))
        # pool capacity in blocks: the kv_quant=int8 headline — the same
        # HBM budget holds ~2x the blocks of a bf16 pool
        KV_QUANT.set("dynamo_kv_pool_capacity_blocks", a.total_pages)
        spec_k_mean = spec_k_p50 = spec_k_p95 = 0.0
        if self.spec is not None:
            # per-slot adaptive-K distribution over currently-speculating
            # slots: the mean alone hid bimodal fleets (half the slots
            # collapsed to min_k, half pinned at the cap)
            spec_k_mean, spec_k_p50, spec_k_p95 = self.spec.effective_k_dist(
                np.flatnonzero(self._slot_spec).tolist()
            )
            SPEC.set(
                "dynamo_spec_accept_rate", self.spec.acceptance_rate()
            )
        return ForwardPassMetrics(
            worker_id=self.ecfg.worker_id,
            worker_stats=WorkerStats(
                request_active_slots=(
                    sum(s is not None for s in self._slots)
                    + len(self._prefilling)
                ),
                request_total_slots=self._B,
                # in-prefill requests count as active (they hold a lane),
                # not waiting
                num_requests_waiting=num_waiting,
                # overload plane: backlog + budgets, so routers spill
                # away from a saturating worker before its bound sheds
                num_waiting_prefill_tokens=waiting_tokens,
                max_waiting_requests=e.max_waiting_requests,
                max_waiting_prefill_tokens=e.max_waiting_prefill_tokens,
                spec_proposed_total=(
                    self.spec.proposed_total if self.spec else 0
                ),
                spec_accepted_total=(
                    self.spec.accepted_total if self.spec else 0
                ),
                spec_acceptance_rate=(
                    self.spec.acceptance_rate() if self.spec else 0.0
                ),
                # adaptive-K distribution over currently-speculating
                # slots — the planner-facing signal for how deep
                # speculation actually runs (0 when off / idle)
                spec_effective_k=spec_k_mean,
                spec_effective_k_p50=spec_k_p50,
                spec_effective_k_p95=spec_k_p95,
                spec_tree_nodes_total=(
                    self.spec.tree_nodes_total if self.spec else 0
                ),
                spec_tree_accepted_path_len_total=(
                    self.spec.tree_path_len_total if self.spec else 0
                ),
                spec_gated_despecs_total=(
                    self.spec.gated_despec_total if self.spec else 0
                ),
            ),
            histograms=self._histograms_snapshot(),
            kv_stats=KvStats(
                kv_active_blocks=a.active_pages,
                kv_total_blocks=a.total_pages,
                gpu_cache_usage_perc=max(a.usage(), ctx_usage),
                gpu_prefix_cache_hit_rate=a.hit_rate(),
                host_blocks=len(self.offload) if self.offload else 0,
                host_total_blocks=(
                    self.offload.num_pages if self.offload else 0
                ),
                host_onboard_hits=(
                    self.offload.onboard_hits if self.offload else 0
                ),
                disk_blocks=(
                    len(self.offload.spill)
                    if self.offload and self.offload.spill else 0
                ),
                disk_total_blocks=(
                    self.offload.spill.num_pages
                    if self.offload and self.offload.spill else 0
                ),
            ),
        )

    def tenant_debug(self) -> dict:
        """Per-tenant quota/backlog/metric view — the engine half of the
        /debug/tenants surface (runtime/system_server.py; the frontend
        merges its own HTTP-side slice)."""
        with self._wt_lock:
            t_waiting = dict(self._tenant_waiting)
            t_tokens = dict(self._tenant_tokens)
        quotas = self.tenant_quotas.snapshot()
        metrics_snap = TENANT.snapshot()
        tenants: dict[str, dict[str, Any]] = {}
        for t in (set(t_waiting) | set(t_tokens) | set(quotas)
                  | set(metrics_snap)):
            tenants[t] = {
                "waiting_requests": t_waiting.get(t, 0),
                "waiting_prefill_tokens": t_tokens.get(t, 0),
                **(quotas.get(t) or {}),
                "metrics": metrics_snap.get(t, {}),
            }
        return {
            "bounded": self.tenant_quotas.bounded,
            "max_waiting_requests": (
                self.ecfg.tenant_max_waiting_requests
            ),
            "max_waiting_prefill_tokens": (
                self.ecfg.tenant_max_waiting_prefill_tokens
            ),
            "n_adapters": self.n_adapters,
            "tenants": tenants,
        }

    # ------------------------------------------------------------------
    # engine loop

    def _run_loop(self) -> None:
        last_idle_beat = 0.0
        while not self._stop.is_set():
            try:
                did_work = self._round()
            except Exception as exc:  # noqa: BLE001 — engine loop must survive
                log.exception("engine round failed")
                # the last N dispatches before the failure are the
                # postmortem; logs alone never have them
                self.flight.dump(log, reason=repr(exc))
                try:
                    self._fail_all(
                        RuntimeError("engine step failed; see logs")
                    )
                except Exception:  # noqa: BLE001 — never mask the root cause
                    log.exception("fail_all cleanup itself failed")
                did_work = False
            if not did_work:
                # idle heartbeat: busy rounds publish metrics themselves;
                # an IDLE engine must keep heartbeating too, or the
                # health plane's soft leases (resilience/health.py
                # heartbeat_ttl_s) would read silence as wedged
                now = time.monotonic()
                if self.on_metrics is not None and now - last_idle_beat >= 0.5:
                    last_idle_beat = now
                    try:
                        self.on_metrics(self.metrics())
                    except Exception:  # noqa: BLE001 — never kill the loop
                        log.exception("idle metrics publish failed")
                # wait on the doorbell, not intake alone: _xfer_op page
                # imports (disagg decode side) and intake both ring it,
                # so either wakes the loop immediately. Clear BEFORE the
                # non-blocking drain — a set racing the clear is seen on
                # the next wait.
                self._wake_evt.wait(timeout=0.02)
                self._wake_evt.clear()
                try:
                    self._waiting.append(self._intake.get_nowait())
                except queue_mod.Empty:
                    pass
        self._drain_xfer_queue()

    def _drain_xfer_queue(self) -> None:
        """Abandon queued transfer ops with an error, not a long stall.
        Only touches items still IN the queue — an in-flight op finishes
        normally and reports its real result."""
        while True:
            try:
                kind, _ids, data, done, box = self._xfer.get_nowait()
            except queue_mod.Empty:
                break
            box["error"] = RuntimeError("engine stopped")
            if kind in ("export_stream", "export_hash_stream"):
                data[2].put(box["error"])
                data[2].put(_STREAM_EOS)
            done.set()
        # in-flight chunk streams: close their consumer queues too
        for st in self._xfer_streams:
            st.out_q.put(RuntimeError("engine stopped"))
            st.out_q.put(_STREAM_EOS)
        self._xfer_streams = []

    def _round(self) -> bool:
        """One scheduling round: process ready results, flush seal copies,
        apply patches (releases, admissions), dispatch a round of steps.

        With ``ecfg.round_pipeline`` the round runs double-buffered: when
        the pipeline is clear (_pipeline_clear — nothing would mutate
        slot state under an in-flight program) the NEXT fused program is
        dispatched BEFORE this round's packed fetch is consumed, so the
        completion half (fetch, emit, releases, transfer/offload
        servicing) overlaps device execution and steady-state wall
        approaches max(host, device) instead of host + device. Any flush
        condition falls back to the exact pre-pipelining
        process-then-dispatch order (counted in pipe_flushes)."""
        e = self.ecfg
        prof = self.prof
        prof.begin_round()
        t_round = time.monotonic()
        prof.enter(_SEG_INTAKE)
        self._drain_intake()
        prof.enter(_SEG_SLOT_SCAN)
        self._enforce_bounds()
        rounds_in_flight = sum(1 for en in self._entries if en.kind == "round")
        dispatched = False
        t_pipe = 0.0
        if (e.round_pipeline
                and rounds_in_flight <= e.max_inflight_rounds
                and self._pipeline_clear()):
            # dispatch half FIRST (round pipelining): launch round N+1
            # before consuming round N's fetch — everything below the
            # dispatch runs while the device executes. The seal batch
            # taken here is last round's (this round's completions queue
            # theirs for the NEXT dispatch: one extra round of commit
            # latency, still device-order safe).
            active, want_lp, want_sample = self._active_slots()
            if active:
                prof.enter(_SEG_DISPATCH)
                self._dispatch_round(active, want_lp, want_sample)
                dispatched = True
                rounds_in_flight += 1
                self._pipe_dispatches += 1
                self._pipe_depth_sum += rounds_in_flight
                t_pipe = time.monotonic()
        prof.enter(_SEG_FETCH)
        self._process_entries(block=rounds_in_flight > e.max_inflight_rounds)
        # seals queued by result processing are NOT flushed here: they
        # ride the next fused dispatch (_dispatch_round). Pool
        # readers below (transfers, streams, offload, prefill_begin)
        # flush standalone first themselves.
        prof.enter(_SEG_RELEASES)
        self._apply_releases()
        prof.enter(_SEG_TRANSFER)
        xfer_work = self._process_transfers()
        stream_work = self._service_export_streams()
        prof.enter(_SEG_OFFLOAD)
        self._dispatch_offloads()
        self._drain_host_ingest()  # G4 pages land before admission
        prof.enter(_SEG_ADMIT)
        self._admit()
        prof.enter(_SEG_SLOT_SCAN)

        # mid-flight prefills ARE work: without this a multi-chunk
        # (disagg-shaped) prefill pays the idle-path intake sleep between
        # every chunk — the r07 chunked-TTFT regression
        did_work = (dispatched or bool(self._entries) or stream_work
                    or xfer_work or bool(self._prefilling))
        rounds_in_flight = sum(1 for en in self._entries if en.kind == "round")
        if not dispatched and rounds_in_flight <= e.max_inflight_rounds:
            # flushed / disabled pipeline: dispatch at the legacy
            # position — after every patch above, the exact
            # pre-pipelining order (what `round_pipeline=False` pins
            # in the differential tests). Dispatch only for LIVE
            # requests: a round for finished-awaiting-release slots is
            # pure garbage work that also queues ahead of the next
            # arrival's prefill. Speculating slots are excluded — their
            # lanes are parked and they advance through verify
            # dispatches instead.
            active, want_lp, want_sample = self._active_slots()
            if active:
                prof.enter(_SEG_DISPATCH)
                self._dispatch_round(active, want_lp, want_sample)
                did_work = dispatched = True
        if self.spec is not None and bool(self._slot_spec.any()):
            prof.enter(_SEG_SPEC)
            if self._dispatch_spec():
                did_work = dispatched = True
        if self._seal_queue and (
                not e.round_pipeline or not dispatched
                or len(self._seal_queue) > self._seal_fuse_w):
            # no fused ride is coming (nothing dispatched / pipelining
            # off leaves no next-round ride guarantee) or the queue
            # outgrew the fused width (admission burst): dispatch
            # standalone rather than letting commits sit
            prof.enter(_SEG_SEAL_FLUSH)
            self._flush_seals()
            did_work = True
        if t_pipe:
            now = time.monotonic()
            # completion-half host time that ran with the early dispatch
            # in flight on device (the overlap_ratio numerator) vs the
            # pipelined round's total host time
            self._pipe_hidden_s += now - t_pipe
            self._pipe_host_s += now - t_round
        # fold prof + refresh the SLO burn-rate gauges at the publish
        # cadence, not once per round — building ForwardPassMetrics every
        # round was measurable host tax and the pub/sub plane throttles
        # to ~4 Hz anyway
        now = time.monotonic()
        if now - self._last_metrics_pub >= 0.1:
            self._last_metrics_pub = now
            prof.enter(_SEG_METRICS)
            PROF.fold(prof)
            PROF.fold_burn_rates(
                self._h_ttft.snapshot(), self._h_itl.snapshot(),
                e.slo_ttft_target_s, e.slo_itl_target_s,
                e.slo_objective,
            )
            if self.on_metrics is not None:
                self.on_metrics(self.metrics())
        if (not dispatched and self._entries
                and self._intake.empty() and not self._waiting):
            # nothing to overlap with the in-flight fetches (e.g. every
            # live slot is waiting on its verify result) — block on the
            # head entry instead of spinning the loop
            prof.enter(_SEG_FETCH)
            self._process_entries(block=True)
        if (self._draining
                and not self._entries and not self._waiting
                and not self._prefilling and self._intake.empty()
                and all(s is None for s in self._slots)):
            self._drained_evt.set()
        prof.end_round(record=did_work)
        return did_work

    def _pipeline_clear(self) -> bool:
        """True when the dispatch half may run BEFORE the completion half
        (round pipelining): nothing pending may mutate slot state under
        the in-flight program. Each False increments its pipe_flushes
        bucket — the explicit flush points: drain, admissions
        (waiting / mid-prefill / fresh intake), pending release patches,
        seal-queue overflow past the fused width, and speculating slots
        (their verify results re-shape the next round)."""
        if self._draining:
            self.pipe_flushes["drain"] += 1
            return False
        if self._waiting or self._prefilling or not self._intake.empty():
            self.pipe_flushes["admission"] += 1
            return False
        if self._to_release:
            self.pipe_flushes["release"] += 1
            return False
        if len(self._seal_queue) > self._seal_fuse_w:
            self.pipe_flushes["seal_overflow"] += 1
            return False
        if self.spec is not None and bool(self._slot_spec.any()):
            self.pipe_flushes["spec"] += 1
            return False
        return True

    def _active_slots(self) -> tuple[list[int], bool, bool]:
        """(active slot list, want_lp, want_sample) reduced from the
        numpy slot-state mirrors; cached until the next slot transition
        — steady decode pays zero per-slot Python scans per round."""
        cached = self._active_cache
        if cached is None:
            idx = np.flatnonzero(self._slot_active)
            cached = (
                idx.tolist(),
                bool(self._slot_lp[idx].any()),
                bool(self._slot_sampler[idx].any()),
            )
            self._active_cache = cached
        return cached

    def _slot_on(self, slot: int, r: _Request) -> None:
        """Mirror a slot becoming LIVE (fused-decode driven) into the
        slot-state arrays. A slot needs the sampler if it samples OR
        carries penalties — penalties apply to greedy decoding too, and
        the counts histogram must advance for them to be correct."""
        so = r.req.sampling_options
        self._slot_active[slot] = True
        self._slot_spec[slot] = False
        self._slot_lp[slot] = r.req.output_options.logprobs is not None
        self._slot_sampler[slot] = (
            (so.temperature or 0.0) > 0.0
            or (so.frequency_penalty or 0.0) != 0.0
            or (so.presence_penalty or 0.0) != 0.0
            or (so.repetition_penalty or 1.0) != 1.0
        )
        self._active_cache = None

    def _slot_off(self, slot: int, spec: bool = False) -> None:
        """Mirror a slot leaving the fused decode round (finish, release,
        or — with ``spec`` — speculative admission/parking)."""
        self._slot_active[slot] = False
        self._slot_spec[slot] = spec
        self._slot_lp[slot] = False
        self._slot_sampler[slot] = False
        self._active_cache = None

    def pipeline_stats(self) -> dict:
        """Round-pipeline effectiveness counters (profile_round
        --dispatch-budget / bench): mean in-flight depth right after an
        early dispatch, the fraction of pipelined-round host time spent
        in the completion half (running under device execution), and the
        per-reason flush counts."""
        n = self._pipe_dispatches
        return {
            "round_pipeline": bool(self.ecfg.round_pipeline),
            "pipelined_dispatches": n,
            "pipeline_depth": round(self._pipe_depth_sum / n, 4) if n else 0.0,
            "overlap_ratio": (
                round(self._pipe_hidden_s / self._pipe_host_s, 4)
                if self._pipe_host_s > 0 else 0.0
            ),
            "pipe_flushes": dict(self.pipe_flushes),
        }

    def _drain_intake(self) -> None:
        if self._intake.empty():
            return  # steady decode: skip the Empty-exception round trip
        while True:
            try:
                self._enqueue_waiting(self._intake.get_nowait())
            except queue_mod.Empty:
                return

    def _enqueue_waiting(self, r: _Request) -> None:
        """Weighted fair share (SFQ) within a priority class; a
        high-priority arrival still queues ahead of every lower-priority
        entry that has NOT started prefill (entries holding a lane are
        active work, never jumped).

        Each request is stamped with a virtual finish time — the
        tenant's virtual clock advanced by prompt-cost / weight — and
        inserts before the first not-started same-priority entry with a
        LARGER stamp. A storming tenant's backlog carries ever-growing
        stamps while a light tenant's fresh arrival starts at the global
        virtual clock (advanced at service start, _note_queue_wait), so
        it lands near the head. Single-tenant traffic degrades to exact
        FIFO: one tenant's stamps are monotonic by construction."""
        t = r.tenant
        vstart = max(self._tenant_vnow.get(t, 0.0), self._vclock)
        r.vft = vstart + max(1, len(r.tokens)) / self.tenant_quotas.weight(t)
        self._tenant_vnow[t] = r.vft
        if r.req.priority > 0:
            for i, w in enumerate(self._waiting):
                if w.prefill_pos < 0 and w.req.priority < r.req.priority:
                    self._waiting.insert(i, r)
                    return
            self._waiting.append(r)
            return
        for i, w in enumerate(self._waiting):
            if (w.prefill_pos < 0 and w.req.priority == r.req.priority
                    and w.vft > r.vft):
                self._waiting.insert(i, r)
                return
        self._waiting.append(r)

    # ---- overload plane: budgets, deadline shedding, preemption ----

    def _uncount_waiting(self, r: _Request) -> None:
        """Drop a request's prompt from the waiting-token backlog
        (idempotent — first lane acquisition or queue exit wins)."""
        if not r.counted:
            return
        r.counted = False
        t = r.tenant
        with self._wt_lock:
            self._waiting_tokens -= len(r.tokens)
            self._tenant_waiting[t] = max(
                0, self._tenant_waiting.get(t, 0) - 1
            )
            self._tenant_tokens[t] = max(
                0, self._tenant_tokens.get(t, 0) - len(r.tokens)
            )

    def _shed_waiting(self, r: _Request, reason: str) -> None:
        """Drop a still-WAITING request from the queue. ``deadline``
        sheds finish cleanly (zero tokens, DEADLINE reason — the budget
        ran out, nothing failed); preemption/bound sheds surface the
        retriable overload error so the router re-routes them."""
        self._uncount_waiting(r)
        r.finished = True
        if reason == "deadline":
            self.sheds += 1
            OVERLOAD.inc("dynamo_overload_shed_total")
            r.emit(LLMEngineOutput(
                token_ids=[], finish_reason=FinishReason.DEADLINE,
                annotations={"shed": {
                    "reason": "deadline",
                    "queued_s": round(
                        time.monotonic() - r.enqueue_time, 3),
                }},
            ))
        else:
            t = r.tenant
            TENANT.inc("dynamo_tenant_shed_total", t)
            if self.tenant_quotas.bounded:
                # pressure is tenant-confined, so the hint is too: this
                # tenant's own queue-wait p50 x its own backlog depth
                with self._wt_lock:
                    t_waiting = self._tenant_waiting.get(t, 0)
                retry = self.tenant_quotas.retry_after_s(t, t_waiting)
            else:
                retry = self.admission.retry_after_s(
                    sum(1 for w in self._waiting if w.slot < 0)
                )
            r.emit(EngineOverloadedError(
                f"request shed while waiting ({reason})",
                retry_after_s=retry,
                tenant=t,
            ))

    def _enforce_bounds(self) -> None:
        """Restore the admission budgets after a HIGH-priority arrival
        was force-admitted past them: evict the lowest-priority, newest
        waiting entry until the backlog fits. When every candidate has
        the same priority there is no one to preempt FOR — the newest
        arrival bounces instead (the budget stays honest either way)."""
        self._enforce_tenant_bounds()
        adm = self.admission
        if not adm.bounded:
            return
        while True:
            cands = [r for r in self._waiting
                     if r.prefill_pos < 0 and not r.cancelled
                     and not r.finished]
            n = len(cands)
            with self._wt_lock:
                tokens = self._waiting_tokens
            over = ((adm.max_waiting_requests
                     and n > adm.max_waiting_requests)
                    or (adm.max_waiting_prefill_tokens
                        and tokens > adm.max_waiting_prefill_tokens))
            if not over or not cands:
                return
            lo = min(r.req.priority for r in cands)
            hi = max(r.req.priority for r in cands)
            victim = max(
                (r for r in cands if r.req.priority == lo),
                key=lambda r: r.enqueue_time,
            )
            if lo < hi:
                self.waiting_preemptions += 1
                OVERLOAD.inc("dynamo_overload_preempted_total")
                self._shed_waiting(victim, "preempted by priority")
            else:
                OVERLOAD.inc("dynamo_overload_rejected_total")
                self._shed_waiting(victim, "queue budget exceeded")
            self._waiting.remove(victim)

    def _enforce_tenant_bounds(self) -> None:
        """Per-tenant half of _enforce_bounds: a HIGH-priority arrival
        force-admitted past its tenant's budget is paid for WITHIN that
        tenant — the victim is always the offending tenant's own
        lowest-priority, newest waiting entry, never another tenant's
        work."""
        tq = self.tenant_quotas
        if not tq.bounded:
            return
        while True:
            by_tenant: dict[str, list[_Request]] = {}
            for r in self._waiting:
                if r.prefill_pos < 0 and not r.cancelled and not r.finished:
                    by_tenant.setdefault(r.tenant, []).append(r)
            victim = None
            for t, rs in by_tenant.items():
                toks = sum(len(r.tokens) for r in rs)
                over = ((tq.max_waiting_requests
                         and len(rs) > tq.max_waiting_requests)
                        or (tq.max_waiting_prefill_tokens
                            and toks > tq.max_waiting_prefill_tokens))
                if not over:
                    continue
                lo = min(r.req.priority for r in rs)
                hi = max(r.req.priority for r in rs)
                victim = max(
                    (r for r in rs if r.req.priority == lo),
                    key=lambda r: r.enqueue_time,
                )
                if lo < hi:
                    self.waiting_preemptions += 1
                    OVERLOAD.inc("dynamo_overload_preempted_total")
                    self._shed_waiting(victim, "preempted by priority "
                                               "(tenant budget)")
                else:
                    OVERLOAD.inc("dynamo_overload_rejected_total")
                    self._shed_waiting(victim, "tenant budget exceeded")
                self._waiting.remove(victim)
                break
            if victim is None:
                return

    def _maybe_preempt_running(self) -> None:
        """Running half of priority preemption (behind
        ``preempt_running``): a HIGH-priority request blocked on a lane
        force-migrates the lowest-priority RUNNING stream — its client
        stream fails with the retriable PreemptedError, the router
        replays it on a peer (exactly-once, greedy token-identical, the
        PR-4 migration plane), and the freed lane admits the
        high-priority request at the next round. At most one victim per
        round; lanes mid-prefill are never preempted (their replay
        would waste the whole prefill for no freed decode capacity
        yet)."""
        if not self.ecfg.preempt_running:
            return
        hp = next(
            (r for r in self._waiting
             if r.prefill_pos < 0 and not r.cancelled
             and r.req.priority > 0),
            None,
        )
        if hp is None or self._free_slot() is not None:
            return
        victims = [
            s for s in self._slots
            if s is not None and not s.finished and not s.cancelled
            and s.req.priority < hp.req.priority
        ]
        if not victims:
            return
        # tenant-confined preference: when tenant budgets are set, a
        # victim is drawn from a tenant that is OVER its own budget
        # whenever one is running — an innocent tenant's stream is only
        # preempted when no over-budget tenant holds a lane
        if self.tenant_quotas.bounded:
            with self._wt_lock:
                tw = dict(self._tenant_waiting)
                tt = dict(self._tenant_tokens)
            over = [
                v for v in victims
                if self.tenant_quotas.over_budget(
                    tw.get(v.tenant, 0), tt.get(v.tenant, 0))
            ]
            if over:
                victims = over
        lo = min(v.req.priority for v in victims)
        victim = max(
            (v for v in victims if v.req.priority == lo),
            key=lambda v: v.enqueue_time,
        )
        self.preempt_migrations += 1
        OVERLOAD.inc("dynamo_overload_preempt_migrations_total")
        log.warning(
            "preempting running request %s (priority %d) for "
            "high-priority arrival %s",
            victim.req.request_id, victim.req.priority,
            hp.req.request_id,
        )
        victim.emit(PreemptedError(
            "preempted by a higher-priority request; stream migrates"
        ))
        self._finish(victim, None)

    # ---- dispatch side ----

    def _dispatch_round(
        self, active: list[int], want_lp: bool, want_sample: bool
    ) -> None:
        """Dispatch flush_every fused steps + one stacked-token fetch.
        ``active``/``want_lp``/``want_sample`` come precomputed from the
        slot-state mirrors (_active_slots) — plain-greedy rounds skip
        the full sampler (argmax only), lp-free rounds skip the packed
        logprob pipeline."""
        e = self.ecfg
        n = e.flush_every
        # the round's pending seal batch rides the SAME program (the
        # dispatch diet: in steady decode a block completes nearly every
        # round, and the separate seal_blocks program was a per-round
        # straggler dispatch). Fixed width = one compiled variant;
        # admission-burst overflow drains via the standalone flush at
        # the end of _round.
        prev_seg = self.prof.push(_SEG_SEAL_ASM)
        seal = self._take_seal_batch(width=self._seal_fuse_w)
        self.prof.enter(prev_seg)
        if self.on_dispatch is not None:
            # followers must replay the identical (fused) program, so
            # the seal arrays always travel — zeros for seal-less rounds
            w = self._seal_fuse_w
            payload = {
                "n_steps": n, "want_lp": want_lp,
                "want_sample": want_sample,
                "seal": ({
                    "slots": seal[0].tolist(),
                    "starts": seal[1].tolist(),
                    "pages": seal[2].tolist(),
                } if seal is not None else {
                    "slots": [0] * w, "starts": [0] * w,
                    "pages": [0] * w,
                }),
            }
            self.on_dispatch("round", payload)
        # one fused program: n decode+sample steps + flush + seal. The
        # SAME program runs whether the round has seals or not (seal-
        # less rounds pass the cached all-scratch dummy batch — page 0
        # is garbage by contract) — one compiled variant per engine, not
        # one per seal-width plus a plain variant, which is what keeps
        # the fusion free at compile time too.
        t_disp = time.monotonic()
        if seal is not None:
            self.dispatch_counts["round_seal"] += 1
            seal_dev = (jnp.asarray(seal[0]), jnp.asarray(seal[1]),
                        jnp.asarray(seal[2]))
        else:
            self.dispatch_counts["round"] += 1
            seal_dev = self._zero_seal
        (self.ctx, self.ring, self._dev, self.cache, stacked,
         lp_stacked) = self._engine_round_seal(
            self.params, self.ctx, self.ring, self._dev, self.cache,
            *seal_dev, n, want_lp, want_sample,
        )
        if seal is not None:
            if self.kv_quant:
                if self.ctx_quant:
                    # ctx and pool share the int8 representation: the
                    # fused seal moved raw pages, nothing requantized
                    KV_QUANT.inc(
                        "dynamo_kv_quant_ctx_seal_raw_pages_total",
                        seal[3])
                else:
                    KV_QUANT.inc("dynamo_kv_quant_pages_total", seal[3])
            self._notify_commits()
        if self.ctx_quant:
            # ring flush requantized its per-lane window groups inside
            # the same fused program (deterministic geometry: every lane
            # touches the same window width each round)
            KV_QUANT.inc("dynamo_kv_quant_ctx_flush_groups_total",
                         self._flush_groups_per_round)
        self.flight.record(
            "round", slots=list(active), n_steps=n,
            # post-PR 7 round shape: seals ride the fused program
            # (seal_w = real seal-batch width, 0 on seal-less rounds)
            # and token fetches are packed (1 stacked + 1 packed-logprob
            # pipeline, never 3) — recorded so /debug/flight matches
            # dispatch_counts
            seal_w=int(seal[3]) if seal is not None else 0,
            fetches=1 + (1 if lp_stacked is not None else 0),
            spec_slots=np.flatnonzero(self._slot_spec).tolist(),
            dispatch_ms=round((time.monotonic() - t_disp) * 1e3, 3),
        )
        # only dispatched lanes advance (spec slots track their own
        # lengths through verify processing)
        self._ctx_disp[active] = np.minimum(
            self._ctx_disp[active] + n, e.max_context
        )
        if self.n_adapters:
            # adapter-switch-overhead observability: which tenants'
            # rounds gathered a non-base bank row (the row gather is
            # fused into this same program — zero extra dispatches)
            seen: set[str] = set()
            for i in active:
                r = self._slots[i]
                if r is not None and r.adapter_id and r.tenant not in seen:
                    seen.add(r.tenant)
                    TENANT.inc("dynamo_tenant_adapter_rounds_total",
                               r.tenant)
        self.step_count += n
        stacked.copy_to_host_async()
        self.dispatch_counts["fetch"] += 1
        if lp_stacked is not None:
            # packed: ONE extra fetch pipeline, not three
            lp_stacked.copy_to_host_async()
            self.dispatch_counts["fetch"] += 1
        self._entries.append(
            _Entry(
                kind="round",
                t_dispatch=t_disp,
                handle=stacked,
                # snapshot EXCLUDES speculating slots: their device lanes
                # are parked, so their columns in this round's stacked
                # tokens are garbage — advancing them from here would
                # corrupt the verify-driven history (the slot's spec flag
                # may flip by the time the fetch lands, so the filter
                # must happen at dispatch time, not at processing)
                slots=[
                    (r if r is None or not r.spec else None)
                    for r in self._slots
                ],
                n_steps=n,
                lp_handle=lp_stacked,
            )
        )

    def _dispatch_patch(
        self,
        clear_slots: list[int] = (),
        admit: Optional[dict[str, Any]] = None,
    ) -> None:
        if self.on_dispatch is not None:
            a = dict(admit or {})
            a.pop("tok", None)  # followers use their own sample_first result
            a.pop("counts", None)  # spec-only (spec is rejected multihost)
            if "keys" in a:
                a["keys"] = np.asarray(a["keys"]).tolist()
            self.on_dispatch("patch", {
                "clear_slots": list(clear_slots), "admit": a,
            })
        B = self._B
        clear = np.zeros(B, bool)
        for s in clear_slots:
            clear[s] = True
        a = admit or {}
        counts = a.get("counts")
        # one packed f32 row instead of ten scalar uploads (the patch
        # jit unpacks; see _build_jits.patch)
        meta = np.array([
            a.get("slot", B), a.get("ctx", 1),
            a.get("temp", 0.0), a.get("top_k", 0), a.get("top_p", 1.0),
            a.get("freq", 0.0), a.get("pres", 0.0), a.get("rep", 1.0),
            a.get("adapter", 0),
        ], np.float32)
        self.dispatch_counts["patch"] += 1
        self._dev = self._patch(
            self._dev,
            jnp.asarray(clear),
            jnp.asarray(meta),
            a.get("tok", self._zero_tok),
            jnp.asarray(a.get("keys", np.zeros(2, np.uint32))),
            self._zero_counts if counts is None
            else jnp.asarray(counts, jnp.int32),
        )

    # ---- speculative decoding (spec/): propose -> fused verify ----

    def _dispatch_spec(self) -> bool:
        """Collect spec-ready slots, draft K tokens for ALL of them in at
        most ONE device dispatch (llama.batch_draft / host n-gram lookup),
        and dispatch ONE fused score+accept program (static width B; dummy
        rows target the scratch lane) — O(1) device dispatches per round
        in the number of speculating slots AND in K (the draft steps run
        inside a fori_loop). The verify optimistically writes K+1 KV rows
        per slot; the host later commits only the accepted prefix —
        rollback is pointer truncation because attention masks by
        sequence length and the next write over the lane overwrites the
        dead span.

        K here is the ROUND width: the bucketed max of the participants'
        per-slot effective K (acceptance-adaptive; spec/decoder.py) —
        when every participant's acceptance sags, the whole round
        shrinks. Returns True if anything was dispatched.
        """
        if self.spec.tree:
            return self._dispatch_spec_tree()
        e = self.ecfg
        K_cap = self.spec.k
        ready = [
            (i, r) for i, r in enumerate(self._slots)
            if r is not None and r.spec and r.spec_ready
            and not r.finished and not r.cancelled and not r.spec_inflight
        ]
        if not ready:
            return False
        rows: list[tuple[int, _Request, int, int]] = []
        dispatched = False
        for slot, r in ready:
            n_hist = len(r.spec_tokens)
            # the verify writes up to K_cap+1 rows at [N, N+K+1); when
            # that no longer fits the region, hand the slot back to the
            # fused decode round for its final tokens (checked against
            # the CAP, not the round K — the round width isn't known yet)
            if (n_hist - 1) + K_cap + 1 > e.max_context:
                self._despeculate(slot, r)
                dispatched = True
                continue
            rows.append((slot, r, n_hist, self.spec.k_for(slot)))
        if not rows:
            return dispatched
        K = self.spec.round_k([k for *_, k in rows])
        B = self._B
        toks = np.zeros((B, K + 1), np.int32)
        slots_a = np.full(B, B, np.int32)     # dummies -> scratch lane
        q_starts = np.zeros(B, np.int32)
        seq_lens = np.zeros(B, np.int32)      # 0: dummy rows fully masked
        keys = np.zeros((B, 2), np.uint32)
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)
        # penalties: built only when some row carries them — the [B, V]
        # counts upload (and the verifier's histogram-advancing scan
        # variant) costs nothing on penalty-free rounds
        penalties = None
        if any(r.spec_counts is not None for _, r, _, _ in rows):
            penalties = (
                np.zeros((B, self.config.vocab_size), np.int32),
                np.zeros(B, np.float32),          # freq
                np.zeros(B, np.float32),          # pres
                np.ones(B, np.float32),           # rep
            )
        for j, (slot, r, n_hist, _k) in enumerate(rows):
            toks[j, 0] = r.spec_tokens[-1]    # pending token
            slots_a[j] = slot
            q_starts[j] = n_hist - 1
            seq_lens[j] = n_hist + K
            keys[j] = r.spec_keys
            so = r.req.sampling_options
            temps[j] = so.temperature or 0.0
            top_ks[j] = so.top_k or 0
            top_ps[j] = so.top_p if so.top_p is not None else 1.0
            if penalties is not None and r.spec_counts is not None:
                penalties[0][j] = r.spec_counts
                penalties[1][j] = so.frequency_penalty or 0.0
                penalties[2][j] = so.presence_penalty or 0.0
                penalties[3][j] = so.repetition_penalty or 1.0
        t_disp = time.monotonic()
        drafted = None
        if self.spec.draft is not None and e.spec_batch_draft:
            # ONE multi-slot multi-token draft program; the [B, K] device
            # result splices into the verify tokens INSIDE the verify jit
            self.dispatch_counts["spec_draft"] += 1
            drafted = self.spec.propose_batch(
                [(slot, r.spec_tokens) for slot, r, _, _ in rows], B, K,
            )
        else:
            for j, (slot, r, _n, _k) in enumerate(rows):
                proposal = self.spec.propose(slot, r.spec_tokens, K)
                if isinstance(proposal, list):    # n-gram: host tokens
                    toks[j, 1:] = proposal
                else:          # legacy per-slot draft: device [K], no sync
                    if drafted is None:
                        drafted = jnp.zeros((B, K), jnp.int32)
                    drafted = drafted.at[j].set(proposal)
        t_draft_end = time.monotonic()
        self.dispatch_counts["spec_verify"] += 1
        self.ctx, out_toks, n_out, new_keys = self.spec.verify(
            self.params, self.ctx, jnp.asarray(toks), drafted, slots_a,
            q_starts, seq_lens, keys, temps, top_ks, top_ps,
            penalties=penalties,
        )
        for arr in (out_toks, n_out, new_keys):
            arr.copy_to_host_async()
            self.dispatch_counts["fetch"] += 1
        t_verify_end = time.monotonic()
        self.flight.record(
            "spec_verify", slots=[slot for slot, *_ in rows], k=K,
            fetches=3,
            dispatch_ms=round((t_verify_end - t_disp) * 1e3, 3),
        )
        for slot, r, _, _ in rows:
            r.spec_ready = False
            r.spec_inflight = True
        self._entries.append(_Entry(
            kind="spec", handle=out_toks, rows=rows,
            aux=(n_out, new_keys), n_steps=K, t_dispatch=t_disp,
            spec_host=(t_draft_end - t_disp, t_verify_end - t_draft_end),
        ))
        return True

    def _dispatch_spec_tree(self) -> bool:
        """Tree-speculation round (--spec-tree): same dispatch budget as
        the linear path — at most ONE draft program + ONE fused verify —
        but the fetch count IMPROVES to ONE packed [B, 2D+4] handle
        (tokens | accepted path | n_out | keys; see spec_verify_tree)
        instead of three.

        Each row carries a packed token tree (flat tokens + parent
        pointers, node 0 = pending token): the n-gram proposer merges
        its top-M continuations into a trie on the host; the draft model
        emits a comb (M branches per depth off a greedy spine) from the
        SAME fused batch_draft program. The verify scores every node
        under a tree-causal ancestor mask in one q_start>0 forward,
        walks the deepest surviving root-to-leaf path on device, and
        commits only that path's KV rows — sibling rows are never
        written, so rollback stays pointer truncation.

        Round shape (D depths x M branches) is the bucketed max of the
        per-slot adaptive controller's (k, m) votes — the branch axis
        moves OPPOSITE to depth (high acceptance -> deep + narrow; low
        -> shallow + wide hedging), see AdaptiveKController.observe.
        """
        e = self.ecfg
        T_cap = self.spec.tree_budget
        ready = [
            (i, r) for i, r in enumerate(self._slots)
            if r is not None and r.spec and r.spec_ready
            and not r.finished and not r.cancelled and not r.spec_inflight
        ]
        if not ready:
            return False
        rows: list[tuple[int, _Request, int, int, int]] = []
        dispatched = False
        for slot, r in ready:
            n_hist = len(r.spec_tokens)
            # the dense-mode commit spans up to T_cap rows at [N, N+T);
            # when that no longer fits the region, hand the slot back
            # (checked against the BUDGET — the round T isn't known yet)
            if (n_hist - 1) + T_cap > e.max_context:
                self._despeculate(slot, r)
                dispatched = True
                continue
            rows.append((
                slot, r, n_hist,
                self.spec.k_for(slot), self.spec.m_for(slot),
            ))
        if not rows:
            return dispatched
        K = self.spec.round_k([k for *_, k, _m in rows])
        M = self.spec.round_m([m for *_, m in rows])
        draft_mode = self.spec.draft is not None
        # comb drafts pack exactly 1 + K*M nodes (the [B, K*M] device
        # draft splices in verbatim), so M clamps to the budget; n-gram
        # tries use the full budget so every trie shape compiles to ONE
        # program shape
        while draft_mode and 1 + K * M > T_cap and M > 1:
            M //= 2
        T = 1 + K * M if draft_mode else T_cap
        B = self._B
        toks = np.zeros((B, T), np.int32)
        parents = np.full((B, T), -2, np.int32)   # -2 = padding node
        parents[:, 0] = -1                        # node 0 = root
        slots_a = np.full(B, B, np.int32)         # dummies -> scratch
        q_starts = np.zeros(B, np.int32)
        seq_lens = np.zeros(B, np.int32)          # 0: dummy rows masked
        keys = np.zeros((B, 2), np.uint32)
        temps = np.zeros(B, np.float32)
        top_ks = np.zeros(B, np.int32)
        top_ps = np.ones(B, np.float32)
        nodes_used = np.zeros(B, np.int32)
        penalties = None
        if any(r.spec_counts is not None for _, r, *_ in rows):
            penalties = (
                np.zeros((B, self.config.vocab_size), np.int32),
                np.zeros(B, np.float32),          # freq
                np.zeros(B, np.float32),          # pres
                np.ones(B, np.float32),           # rep
            )
        comb = (
            np.asarray(comb_parents(K, M), np.int32)
            if draft_mode else None
        )
        for j, (slot, r, n_hist, _k, _m) in enumerate(rows):
            toks[j, 0] = r.spec_tokens[-1]        # pending token
            slots_a[j] = slot
            q_starts[j] = n_hist - 1
            seq_lens[j] = (n_hist - 1) + T
            keys[j] = r.spec_keys
            so = r.req.sampling_options
            temps[j] = so.temperature or 0.0
            top_ks[j] = so.top_k or 0
            top_ps[j] = so.top_p if so.top_p is not None else 1.0
            if draft_mode:
                parents[j] = comb
                nodes_used[j] = T - 1
            if penalties is not None and r.spec_counts is not None:
                penalties[0][j] = r.spec_counts
                penalties[1][j] = so.frequency_penalty or 0.0
                penalties[2][j] = so.presence_penalty or 0.0
                penalties[3][j] = so.repetition_penalty or 1.0
        t_disp = time.monotonic()
        drafted = None
        if draft_mode:
            # tree drafting always takes the fused batch path: the comb
            # shape IS a batch_draft output (spine + per-depth top-M)
            self.dispatch_counts["spec_draft"] += 1
            drafted = self.spec.propose_batch_tree(
                [(slot, r.spec_tokens) for slot, r, *_ in rows], B, K, M,
            )
        else:
            for j, (slot, r, _n, _k, _m) in enumerate(rows):
                tks, prs = self.spec.propose_tree(r.spec_tokens, K, M)
                n = min(len(tks), T - 1)
                toks[j, 1:1 + n] = tks[:n]
                parents[j, 1:1 + n] = prs[:n]
                nodes_used[j] = n
        t_draft_end = time.monotonic()
        self.dispatch_counts["spec_verify"] += 1
        self.ctx, packed = self.spec.verify_tree(
            self.params, self.ctx, jnp.asarray(toks), drafted,
            jnp.asarray(parents), slots_a, q_starts, seq_lens, keys,
            temps, top_ks, top_ps, K, penalties=penalties,
        )
        packed.copy_to_host_async()
        self.dispatch_counts["fetch"] += 1
        t_verify_end = time.monotonic()
        self.flight.record(
            "spec_verify_tree", slots=[slot for slot, *_ in rows],
            k=K, m=M, nodes=T - 1, fetches=1,
            dispatch_ms=round((t_verify_end - t_disp) * 1e3, 3),
        )
        for slot, r, *_ in rows:
            r.spec_ready = False
            r.spec_inflight = True
        self._entries.append(_Entry(
            kind="spec_tree", handle=packed, rows=rows,
            aux=(M, parents, nodes_used), n_steps=K, t_dispatch=t_disp,
            spec_host=(t_draft_end - t_disp, t_verify_end - t_draft_end),
        ))
        return True

    def _despeculate(
        self, slot: int, r: _Request, gated: bool = False
    ) -> None:
        """Hand a speculating slot back to the fused decode round: the
        admit patch restores the exact device state the non-spec path
        would carry (pending token, ctx length, PRNG keys) — the
        continuation is token-identical.

        ``gated=True`` marks an acceptance-gate despec (--spec-gate-
        acceptance): the stream keeps mirroring its sequence on the
        fused round (_spec_gated_advance) and re-arms speculation after
        --spec-rearm-tokens fused tokens; each re-gate doubles that
        budget so a persistently incompressible stream converges to the
        plain fused round."""
        so = r.req.sampling_options
        r.spec = False
        r.spec_ready = False
        if gated:
            r.spec_gated = True
            r.spec_rearm_left = (
                self.ecfg.spec_rearm_tokens * r.spec_gate_backoff
            )
            r.spec_gate_backoff *= 2
            SPEC.inc("dynamo_spec_tree_gated_despecs_total")
            self.spec.on_gated_despec(slot)
        else:
            self.spec.on_despec(slot)
        self._slot_on(slot, r)  # back into the fused round's active set
        self._ctx_disp[slot] = len(r.spec_tokens)
        self._dispatch_patch(admit=dict(
            slot=slot,
            ctx=len(r.spec_tokens),
            tok=jnp.asarray([r.spec_tokens[-1]], jnp.int32),
            keys=np.asarray(r.spec_keys, np.uint32),
            temp=so.temperature or 0.0,
            top_k=so.top_k or 0,
            top_p=so.top_p if so.top_p is not None else 1.0,
            # penalized slots restore their sampler state in full: the
            # fused continuation must see the same histogram the verify
            # loop advanced, or penalties would reset mid-request
            freq=so.frequency_penalty or 0.0,
            pres=so.presence_penalty or 0.0,
            rep=so.repetition_penalty or 1.0,
            counts=r.spec_counts,
        ))

    def _process_spec(self, entry: _Entry) -> None:
        """Consume one verify result: emit the accepted prefix + bonus
        token per slot, advance host history and PRNG keys, roll the
        draft model's KV pointer back to the accepted length.

        Adaptive K lands here: every verified token is emitted (the
        round already paid the forward for the full bucketed-max width —
        discarding accepted tokens would waste exactly the mixed-K
        rounds the controller creates), acceptance is accounted at the
        ROUND width, the rolling rate updates, and a slot whose rate
        collapsed is handed back to the fused decode round instead of
        re-arming. Per-slot effective K shapes the NEXT round's width
        vote, not this round's emission."""
        out = np.asarray(entry.handle)          # [B, K+1]
        n_out_arr = np.asarray(entry.aux[0])    # [B]
        new_keys = np.asarray(entry.aux[1])     # [B, 2]
        k_round = entry.n_steps
        for j, (slot, r, hist_len, _k_eff) in enumerate(entry.rows):
            r.spec_inflight = False
            if r.finished or self._slots[slot] is not r:
                continue
            if r.cancelled:
                self._finish(r, None)
                continue
            n = int(n_out_arr[j])
            accepted = n - 1
            self.spec.on_result(slot, hist_len, accepted, k_round)
            r.spec_proposed += k_round
            r.spec_accepted += accepted
            toks = [int(t) for t in out[j, :n]]
            batch: list[int] = []
            finish: Optional[FinishReason] = None
            for tok in toks:
                finish = self._advance_token(r, tok)
                if finish is FinishReason.EOS:
                    break  # stop token itself is not emitted
                batch.append(tok)
                if finish is not None:
                    break
            if batch:
                self._note_emit(r, len(batch), entry, "spec_verify_round")
            if batch or finish is not None:
                extra = (
                    {"annotations": self._final_annotations(r)}
                    if finish is not None else {}
                )
                r.emit(LLMEngineOutput(
                    token_ids=batch, finish_reason=finish, **extra
                ))
            self.tokens_generated += len(batch)
            if finish is not None:
                self._finish(r, None)
                continue
            if r.spec_counts is not None:
                # host mirror of the penalty histogram: every emitted
                # token counts (matching the fused sampler's per-token
                # advance; the request's first-ever token is excluded
                # there too — see _process_first)
                for t in toks:
                    r.spec_counts[t] += 1
            r.spec_tokens.extend(toks)  # accepted + bonus, all emitted
            r.spec_keys = new_keys[j]
            if self.spec.should_gate(slot):
                # acceptance EWMA pinned under the gate for a full
                # window: this workload isn't speculation-shaped right
                # now — run it fused, revisit after the re-arm budget
                self._despeculate(slot, r, gated=True)
                continue
            if self.spec.should_despec(slot):
                # acceptance collapsed: every verify here costs a full
                # forward for ~1 emitted token — strictly worse than the
                # fused round. Token-identical continuation, like the
                # context-limit despec.
                self._despeculate(slot, r)
                continue
            r.spec_ready = True
            self._ctx_disp[slot] = len(r.spec_tokens)

    def _process_spec_tree(self, entry: _Entry) -> None:
        """Consume one tree-verify result: the single packed [B, 2D+4]
        fetch carries, per row, the accepted-path tokens + bonus
        (cols [0, D]), the accepted node indices (cols [D+1, 2D]), the
        emitted count n_out (col 2D+1) and the advanced PRNG key
        bitcast to i32 (cols 2D+2, 2D+3) — see spec_verify_tree.

        Emission is identical to the linear path; the extra tree
        bookkeeping is the per-branch acceptance histogram + draft-KV
        spine rollback (on_result_tree), the tree counters on the SPEC
        scrape registry, and the acceptance gate (a stream whose EWMA
        pins under --spec-gate-acceptance de-speculates with re-arm
        armed instead of permanently)."""
        packed = np.asarray(entry.handle)       # [B, 2D+4] i32
        D = entry.n_steps                       # round depth (d_max)
        m_round, parents, nodes_used = entry.aux
        for j, (slot, r, hist_len, _k, _m) in enumerate(entry.rows):
            r.spec_inflight = False
            if r.finished or self._slots[slot] is not r:
                continue
            if r.cancelled:
                self._finish(r, None)
                continue
            n = int(packed[j, 2 * D + 1])
            accepted = n - 1
            self.spec.on_result_tree(
                slot, hist_len, accepted, D, m_round,
                int(nodes_used[j]),
                [int(x) for x in packed[j, D + 1:D + 1 + accepted]],
                [int(x) for x in parents[j]],
            )
            SPEC.inc("dynamo_spec_tree_nodes_total", int(nodes_used[j]))
            SPEC.inc("dynamo_spec_tree_accepted_path_len_total", accepted)
            # acceptance stays tokens-per-depth — directly comparable
            # to the linear chain at the same K
            r.spec_proposed += D
            r.spec_accepted += accepted
            toks = [int(t) for t in packed[j, :n]]
            batch: list[int] = []
            finish: Optional[FinishReason] = None
            for tok in toks:
                finish = self._advance_token(r, tok)
                if finish is FinishReason.EOS:
                    break  # stop token itself is not emitted
                batch.append(tok)
                if finish is not None:
                    break
            if batch:
                self._note_emit(r, len(batch), entry, "spec_verify_round")
            if batch or finish is not None:
                extra = (
                    {"annotations": self._final_annotations(r)}
                    if finish is not None else {}
                )
                r.emit(LLMEngineOutput(
                    token_ids=batch, finish_reason=finish, **extra
                ))
            self.tokens_generated += len(batch)
            if finish is not None:
                self._finish(r, None)
                continue
            if r.spec_counts is not None:
                for t in toks:
                    r.spec_counts[t] += 1
            r.spec_tokens.extend(toks)  # accepted path + bonus
            r.spec_keys = np.ascontiguousarray(
                packed[j, 2 * D + 2:2 * D + 4]
            ).view(np.uint32)
            if self.spec.should_gate(slot):
                self._despeculate(slot, r, gated=True)
                continue
            if self.spec.should_despec(slot):
                self._despeculate(slot, r)
                continue
            r.spec_ready = True
            self._ctx_disp[slot] = len(r.spec_tokens)

    def _note_emit(
        self, r: _Request, n_tokens: int, entry: _Entry, kind: str
    ) -> None:
        """Telemetry for one round's emitted batch: per-token gaps into
        the ITL histogram (the batch arrives together — its gap is the
        round wall split over the tokens) and a capped round span."""
        now = time.monotonic()
        if r.t_last_emit is not None:
            gap = (now - r.t_last_emit) / n_tokens
            self._h_itl.observe(gap, n_tokens,
                                exemplar_id=r.req.request_id or None)
            if len(r.itl_gaps) < 4096:
                r.itl_gaps.append((gap, n_tokens))
        r.t_last_emit = now
        r.decode_rounds += 1
        cap = (_MAX_ROUND_SPANS_DETAIL if r.trace_detail
               else _MAX_ROUND_SPANS)
        if (len(r.trace_spans) + len(r.round_spans) < cap
                and entry.t_dispatch):
            # annotate diet: the hot loop records one raw tuple; the
            # span dicts (and spec draft/verify children) are built
            # lazily at finish, when something actually reads the trace
            r.round_spans.append((
                kind, entry.t_dispatch, now - entry.t_dispatch,
                n_tokens, entry.spec_host,
            ))

    def _final_annotations(self, r: _Request) -> dict:
        """Annotations for the FINISHING output: speculation counters,
        per-request timing (TTFT / ITL p50/p95 / queue / E2E — what
        sdk.request_stats folds), and the worker-side trace spans the
        frontend merges into its span tree. Called exactly once per
        normally-finished request; also registers the spans in the
        worker-local trace store when no frontend owns the trace in this
        process (remote-worker mode)."""
        prev_seg = self.prof.push(_SEG_ANNOTATE)
        try:
            return self._final_annotations_inner(r)
        finally:
            self.prof.enter(prev_seg)

    def _final_annotations_inner(self, r: _Request) -> dict:
        ann = self._spec_annotations(r)
        now = time.monotonic()
        e2e = now - r.enqueue_time
        self._h_e2e.observe(e2e, exemplar_id=r.req.request_id or None)
        timing: dict[str, Any] = {
            "e2e_s": round(e2e, 6),
            "output_tokens": r.produced,
            "decode_rounds": r.decode_rounds,
        }
        if r.first_token_time is not None:
            timing["ttft_s"] = round(
                r.first_token_time - r.enqueue_time, 6
            )
        if r.t_prefill_start is not None:
            timing["queue_s"] = round(
                r.t_prefill_start - r.enqueue_time, 6
            )
        for key, q in (("itl_p50_s", 0.50), ("itl_p95_s", 0.95)):
            v = tmetrics.weighted_percentile(r.itl_gaps, q)
            if v is not None:
                timing[key] = round(v, 6)
        ann["timing"] = timing
        if r.round_spans:
            # materialize the lazily-accumulated round spans (same wire
            # form _span_dict produced per round before the diet: the
            # unix start is anchored off the shared monotonic clock)
            wall_now = time.time()
            mono_now = time.monotonic()
            for kind, t0, dur, n_toks, spec_host in r.round_spans:
                start = wall_now - (mono_now - t0)
                sp: dict[str, Any] = {
                    "name": kind, "start_s": round(start, 6),
                    "duration_s": round(dur, 6),
                    "attrs": {"tokens": n_toks},
                }
                if spec_host is not None:
                    # spec rounds carry draft/verify child spans so the
                    # speculation cost shows up inside timelines, not
                    # just as one opaque round span
                    draft_s, verify_s = spec_host
                    t0_w = sp["start_s"]
                    sp["children"] = [
                        Span("spec_draft", t0_w, draft_s).to_dict(),
                        Span("spec_verify", t0_w + draft_s,
                             verify_s).to_dict(),
                    ]
                r.trace_spans.append(sp)
            r.round_spans = []
        if r.trace_spans:
            ann["trace"] = {"spans": list(r.trace_spans)}
            rid = r.req.request_id
            if rid and not TRACES.has_active(rid):
                TRACES.record_remote(rid, r.trace_spans)
                # worker-side forensics: in remote-worker mode no
                # in-process frontend sees this finish, so the breach /
                # sample decision runs here and the dossier is assembled
                # directly from the engine's own rings
                self._forensics.worker_finish(
                    rid, timing=timing,
                    worker_id=str(self.ecfg.worker_id),
                    trace_spans=r.trace_spans,
                )
        return ann

    def _spec_annotations(self, r: _Request) -> dict:
        """Per-request speculation stats for the finishing output — the
        SDK reads these back as request stats (sdk.request_stats), which
        is what lets a planner gate speculation on observed acceptance."""
        if r.spec_proposed <= 0:
            return {}
        return {"spec": {
            "proposed": r.spec_proposed,
            "accepted": r.spec_accepted,
            "acceptance_rate": r.spec_accepted / r.spec_proposed,
        }}

    # ---- block sealing (ctx -> pool prefix-cache copies) ----

    def _queue_seal(self, r: _Request, position: int,
                    block_hash: int, parent_hash: int) -> None:
        """Copy-commit one sealed block into the prefix cache. Best-effort:
        a full pool (no free/evictable page) skips the commit — the prefix
        cache is a cache, not required state."""
        got = self.allocator.allocate(1)
        if got is None:
            return
        page = got[0]
        if not self.allocator.commit(page, block_hash, parent_hash):
            self.allocator.free([page])  # duplicate hash: already cached
            return
        self._seal_queue.append((r.slot, position * self.ecfg.page_size, page))
        # release our reference: the page parks in the LRU (prefix-hittable,
        # offload-candidate) once the seal copy below is dispatched
        self.allocator.free([page])

    def _seal_prefilled(self, r: _Request, limit: Optional[int] = None) -> None:
        """Copy-commit the prompt blocks fully covered by prefill so far
        (beyond what was prefix-matched). Called after EVERY prefill
        chunk, not only at prompt completion: complete prefix blocks
        become prefix-hittable while later chunks still compute — local
        concurrent duplicates hit them, and the disagg prefill worker
        streams them to the decode pool mid-prefill (the chunk-pipelined
        transfer plane's unit of overlap)."""
        ps = self.ecfg.page_size
        done_blocks = min(
            r.prefill_pos // ps if limit is None else limit,
            len(r.seq.blocks),
        )
        for blk in r.seq.blocks[r.sealed_prefix:done_blocks]:
            self._queue_seal(r, blk.position, blk.block_hash, blk.parent_hash)
        if done_blocks > r.sealed_prefix:
            # blocks are MATCHABLE the moment _queue_seal commits them —
            # notify now, not when their pool copy dispatches. On a
            # prefill-only engine (disagg prefill worker) nothing else
            # dispatches seals between export runs, so the deferred
            # notification left the export stream riding its 10 ms
            # safety timeout once per chunk; every engine-loop export
            # path flushes queued seals before any pool read, so the
            # earlier wake stays device-order safe.
            self._notify_commits()
        r.sealed_prefix = max(r.sealed_prefix, done_blocks)

    def _take_seal_batch(self, width: Optional[int] = None):
        """Pop + pad the pending seal queue as (slots, starts, pages)
        int32 arrays (padding rows -> scratch page 0), or None.

        With ``width`` (the fused-round path) at most ``width`` entries
        are taken and the arrays are padded to EXACTLY that width — one
        static shape, one compile. Without it (standalone flush) the
        whole queue is taken at a pow2-bucketed width."""
        if not self._seal_queue:
            return None
        if width is None:
            batch = self._seal_queue
            self._seal_queue = []
            w = pow2_cover(len(batch))
        else:
            batch = self._seal_queue[:width]
            self._seal_queue = self._seal_queue[width:]
            w = width
        slots = np.zeros(w, np.int32)
        starts = np.zeros(w, np.int32)
        pages = np.zeros(w, np.int32)  # padding -> scratch page 0
        for i, (s, st, pg) in enumerate(batch):
            slots[i], starts[i], pages[i] = s, st, pg
        return slots, starts, pages, len(batch)

    def _flush_seals(self) -> None:
        """Dispatch the batched ctx->pool seal copy standalone (pow2-
        padded). Device order makes this safe: the sealed positions were
        written by already-dispatched programs, and any admission/
        offload/export that READS these pool pages is dispatched after
        this. The steady-decode path doesn't come here — its seals ride
        the fused round program (_dispatch_round); this covers admission
        boundaries and rounds that read the pool before dispatching."""
        batch = self._take_seal_batch()
        if batch is None:
            return
        slots, starts, pages, n_real = batch
        if self.on_dispatch is not None:
            self.on_dispatch("seal", {
                "slots": slots.tolist(), "starts": starts.tolist(),
                "pages": pages.tolist(),
            })
        self.dispatch_counts["seal"] += 1
        self.cache = llama.seal_blocks(
            self.cache, self.ctx,
            jnp.asarray(slots), jnp.asarray(starts), jnp.asarray(pages),
            page_size=self.ecfg.page_size,
        )
        if self.kv_quant:
            if self.ctx_quant:
                KV_QUANT.inc(
                    "dynamo_kv_quant_ctx_seal_raw_pages_total", n_real)
            else:
                KV_QUANT.inc("dynamo_kv_quant_pages_total", n_real)
        self._notify_commits()

    # ---- offload (G2 tier) ----

    def _dispatch_offloads(self) -> None:
        """Batch-gather validated park candidates and fetch them to host
        behind compute. Runs BEFORE admission so same-round allocations
        cannot recycle a candidate page between validation and the gather
        dispatch (device-order then guarantees the gather reads the
        pre-recycle content anyway; validation just avoids wasted work)."""
        if self.offload is None or not self._offload_cands:
            return
        batch: list[tuple[int, int, int]] = []
        while len(batch) < self.ecfg.offload_batch:
            try:
                cand = self._offload_cands.popleft()
            except IndexError:
                break
            page, h, _parent = cand
            if h in self.offload:
                continue
            if self.allocator.page_for_hash(h) != page:
                continue  # evicted/recycled since parking
            batch.append(cand)
        if not batch:
            return
        if self._seal_queue:
            # the gather reads the pool: queued seal copies first
            self._flush_seals()
        t_disp = time.monotonic()
        self.dispatch_counts["offload_gather"] += 1
        out, scales = self._gather_padded([p for p, _, _ in batch])
        out.copy_to_host_async()
        self.dispatch_counts["fetch"] += 1
        if scales is not None:
            scales.copy_to_host_async()
        self.flight.record(
            "g2_offload", pages=len(batch),
            dispatch_ms=round((time.monotonic() - t_disp) * 1e3, 3),
        )
        self._entries.append(_Entry(
            kind="offload", handle=out, n_steps=len(batch),
            hashes=[h for _, h, _ in batch],
            parents=[par for _, _, par in batch],
            aux=scales,
        ))

    def _onboard_from_host(
        self, hashes: list[int], matched_pages: list[int]
    ) -> list[int]:
        """Extend a G1 prefix match with a contiguous run held in the G2
        host tier: allocate pages, scatter (async H2D — prefill follows in
        device order), commit under the same chained hashes."""
        if self.offload is None:
            return matched_pages
        m = len(matched_pages)
        run = self.offload.lookup_run(hashes[m:])
        if not run:
            return matched_pages
        pages = self.allocator.allocate(len(run))
        if pages is None:
            return matched_pages
        # chunked H2D: gather+scatter kv_transfer_chunk_pages at a time —
        # peak host staging is O(chunk) instead of O(run), and the
        # uniform chunk width reuses one compiled scatter shape
        cp = self.ecfg.kv_transfer_chunk_pages or len(pages)
        good = len(run)
        for i in range(0, len(pages), cp):
            chunk = run[i:i + cp]
            hs = [h for h, _ in chunk]
            data = self.offload.gather(hs)
            scales = self.offload.gather_scales(hs)
            # admission verify: gathered G2/G3 bytes are checked against
            # their seal-time crcs BEFORE the scatter — corrupt tier
            # content must never reach the device pool
            bad = self.offload.verify_pages(hs, data, scales)
            k = bad[0] if bad else len(chunk)
            if k:
                self._scatter_padded(
                    pages[i:i + k],
                    QuantizedPages(data[:, :, :, :k], scales[..., :k])
                    if scales is not None else data[:, :, :, :k],
                )
            if bad:
                # quarantine the failed blocks (drop from every tier,
                # refuse re-admission); the chained run must stay
                # contiguous, so everything past the first bad block is
                # surrendered and recomputed as prefill — corruption
                # costs latency, never wrong tokens
                for j in bad:
                    self.kv_quarantine.add(hs[j])
                    self.offload.drop_everywhere(hs[j])
                good = i + k
                KV_INTEGRITY.inc(
                    "dynamo_kv_integrity_recomputed_total",
                    len(run) - good,
                )
                log.warning(
                    "KV integrity: %d corrupt block(s) in onboard run "
                    "quarantined; %d of %d blocks recomputed as prefill",
                    len(bad), len(run) - good, len(run),
                )
                break
        if good < len(run):
            self.allocator.free(pages[good:])
            pages, run = pages[:good], run[:good]
        for pg, (h, parent) in zip(pages, run):
            self.allocator.commit(pg, h, parent)
        log.debug("onboarded %d blocks from host tier", len(pages))
        return matched_pages + pages

    # ---- G4 remote tier (kv_transfer.RemoteKvFetcher) ----

    async def _remote_prefetch(self, r: _Request) -> None:
        """Before admission: if the prompt's block-hash run is uncovered
        by G1/G2/G3, ask peer workers for it (G4). Fetched pages are
        queued for the engine loop to land in the G2 host tier, where the
        normal onboard path (_onboard_from_host) picks them up — the
        remote tier needs no scatter path of its own. Coverage checks
        here are read-only hints from another thread; a stale answer
        costs one wasted fetch or one recompute, never correctness."""
        ps = self.ecfg.page_size
        blocks = r.seq.blocks
        matchable = blocks[: max(0, (len(r.tokens) - 1) // ps)]
        if not matchable:
            return
        covered = self.allocator.cached_prefix_len(
            [b.block_hash for b in matchable]
        )
        off = self.offload
        i = covered
        while i < len(matchable) and (
            matchable[i].block_hash in off
            or (off.spill is not None and matchable[i].block_hash in off.spill)
        ):
            i += 1
        missing = matchable[i:]
        if not missing:
            return
        # dedup-by-hash admission: consult the fleet hint digest before
        # probing. Fleet-known holders are probed first; a miss whose
        # blocks the fleet hot set doesn't know at all skips the probe
        # round (recomputing a fleet-unique prefix is the right call —
        # probing every peer for it is pure wasted wire).
        holder_hint: Optional[list[str]] = None
        hints = self.fleet_hints
        if (self.ecfg.kv_dedup_admission and hints is not None
                and hints.applied):
            known = [h for b in missing
                     for h in hints.holders(b.block_hash)]
            if known:
                # dedupe, first-seen order (leading blocks first)
                holder_hint = list(dict.fromkeys(known))
            elif all(hints.replicas(b.block_hash) is None
                     for b in missing):
                KV_FLEET.inc("dynamo_kv_fleet_dedup_skipped_probes_total")
                return
        t_fetch = time.monotonic()
        chunk_spans: list[dict] = []
        t_prev = t_fetch

        def land(offset: int, arr: np.ndarray) -> None:
            # one streamed chunk: into the host-ingest queue immediately
            # (the G2 tier fills while later chunks are still on the
            # wire) + a child span under g4_fetch
            nonlocal t_prev
            n = int(arr.shape[3])
            sub = missing[offset:offset + n]
            # mode boundary: an int8 peer's bundle lands as-is in an
            # int8 tier; cross-mode payloads convert here
            payload = to_pool_dtype(arr, self.kv_quant, off.dtype)
            if not isinstance(payload, QuantizedPages):
                payload = np.asarray(payload, dtype=off.dtype)
            self._host_ingest.put((
                [b.block_hash for b in sub],
                [b.parent_hash for b in sub],
                payload,
            ))
            self._wake_evt.set()
            chunk_spans.append(_span_dict(
                "g4_chunk", t_prev, blocks=n, offset=offset,
            ))
            t_prev = time.monotonic()

        try:
            # every fetch path (chunk-streamed, probe full reply, legacy
            # monolithic race) delivers pages through `land` — data is
            # always None here
            found, _ = await self.remote_kv.fetch(
                [b.block_hash for b in missing], on_chunk=land,
                holders=holder_hint,
            )
        except Exception:  # noqa: BLE001 — G4 is best-effort
            log.exception("G4 remote fetch failed")
            return
        if not found:
            return
        # every fetched block is a prefill block this worker did NOT
        # recompute — the dedup economy's headline counter
        KV_FLEET.inc(
            "dynamo_kv_fleet_recompute_avoided_blocks_total", int(found)
        )
        # trace the peer-pool fetch (with its chunk children): rides the
        # request's worker-side span list so migration replays / disagg
        # flows show the G4 hop end-to-end in /debug/trace/{request_id}
        sp = _span_dict(
            "g4_fetch", t_fetch,
            blocks=int(found), requested=len(missing),
            chunks=max(len(chunk_spans), 1),
        )
        if chunk_spans:
            sp["children"] = chunk_spans
        r.trace_spans.append(sp)

    def _drain_host_ingest(self) -> None:
        from dynamo_tpu.resilience.chaos import CHAOS

        while True:
            try:
                hashes, parents, data = self._host_ingest.get_nowait()
            except queue_mod.Empty:
                return
            if self.offload is None:
                return
            n = self.offload.put_batch(hashes, parents, data)
            self.remote_onboard_blocks += n
            if n and CHAOS.fire("corrupt_prefetch"):
                # rot a just-landed page AFTER its crc was sealed at put
                # (silent DRAM corruption of prefetched content): the
                # onboard-admission verify must quarantine it before it
                # can reach the device pool
                self.offload.rot_page(hashes[0])

    def apply_fleet_hints(self, digest: dict) -> None:
        """Frontend hint push (kv_router/prefetch.py): retain the fleet
        replica/holder digest for dedup admission and wire replica counts
        into G2/G3 eviction. Hint maps are swapped wholesale, so the
        engine thread racing a push sees the old or the new digest, never
        a torn one."""
        from dynamo_tpu.kv_router.fleet import FleetHints

        if self.fleet_hints is None:
            self.fleet_hints = FleetHints(digest)
        else:
            self.fleet_hints.apply(digest)
        if self.offload is not None:
            self.offload.fleet_replicas = self.fleet_hints.replicas
            if getattr(self.offload, "spill", None) is not None:
                self.offload.spill.fleet_replicas = (
                    self.fleet_hints.replicas
                )

    async def prefetch_hashes(
        self, hashes: list[int], parents: Optional[list[int]] = None
    ) -> int:
        """Fleet replication push (kv_router/prefetch.py): pull the given
        chained-hash run from peer pools into the G2 host tier AHEAD of
        demand. Blocks already held in G1/G2/G3 are skipped; fetched
        pages ride the same host-ingest queue as demand G4 fetches.
        Returns blocks landed."""
        off = self.offload
        if self.remote_kv is None or off is None or not hashes:
            return 0
        if parents is None:
            # best-effort chain: within the run each block's parent is
            # its predecessor; the head's true parent is unknown here
            parents = [0, *hashes[:-1]]
        par = dict(zip(hashes, parents))
        missing = [
            h for h in hashes
            if h not in off
            and (off.spill is None or h not in off.spill)
            and self.allocator.page_for_hash(h) is None
        ]
        if not missing:
            return 0

        def land(offset: int, arr: np.ndarray) -> None:
            n = int(arr.shape[3])
            sub = missing[offset:offset + n]
            payload = to_pool_dtype(arr, self.kv_quant, off.dtype)
            if not isinstance(payload, QuantizedPages):
                payload = np.asarray(payload, dtype=off.dtype)
            self._host_ingest.put((sub, [par[h] for h in sub], payload))
            self._wake_evt.set()

        try:
            found, _ = await self.remote_kv.fetch(missing, on_chunk=land)
        except Exception:  # noqa: BLE001 — prefetch is best-effort
            log.exception("fleet prefetch fetch failed")
            return 0
        found = int(found or 0)
        if found:
            KV_FLEET.inc("dynamo_kv_fleet_prefetched_blocks_total", found)
        return found

    # ---- admission / prefill ----

    def _admit(self) -> None:
        now = time.time()
        kept = []
        for r in self._waiting:
            if r.cancelled:
                self._abort_prefill(r)
            elif (r.prefill_pos < 0 and r.req.deadline is not None
                    and now > r.req.deadline):
                # deadline-aware shedding: a still-WAITING request whose
                # deadline passed would only prefill dead work. Never a
                # request that already started (mid-prefill/mid-stream
                # work is delivered, not discarded).
                self._shed_waiting(r, "deadline")
            else:
                kept.append(r)
        self._waiting = kept
        self._maybe_preempt_running()
        # bounded prefill budget per round: a long prompt advances one
        # chunk at a time with decode rounds in between (ITL isolation,
        # the local form of what disagg provides globally). Concurrent
        # same-bucket chunks batch into ONE [K, T] program (batch_prefill)
        # — the TTFT lever under bursty arrivals.
        budget = max(1, self.ecfg.prefill_chunks_per_round)
        while budget > 0 and self._waiting:
            group, width = self._collect_prefill_group(budget)
            if not group:
                return  # head is blocked on a free lane
            if len(group) == 1:
                r = group[0]
                status = self._prefill_step(r)
                budget -= 1
                if status in ("done", "failed"):
                    self._waiting.remove(r)
            else:
                budget -= len(group)
                for r in self._batch_prefill_group(group, width):
                    self._waiting.remove(r)

    def _needs_solo_prefill(self, r: _Request) -> bool:
        """Paths the batched program doesn't carry: multimodal embedding
        injection and the sequence-parallel ring prefill."""
        if (r.req.multimodal or {}).get("embeddings"):
            return True
        e = self.ecfg
        if (r.prefill_pos < 0
                and e.sp_prefill_threshold is not None
                and r.adapter_id == 0
                and self.mesh.shape.get("sp", 1) > 1):
            ps = e.page_size
            hashes = r.seq.block_hashes()
            matchable = hashes[: max(0, (len(r.tokens) - 1) // ps)]
            cached = self.allocator.cached_prefix_len(matchable)
            if len(r.tokens) - cached * ps >= e.sp_prefill_threshold:
                return True
        return False

    def _chunk_width(self, remaining: int) -> int:
        """Padded (bucketed, page-aligned) width of the next chunk for a
        request with `remaining` unprefilled tokens — mirrors
        _prefill_step's chunk shape exactly."""
        e = self.ecfg
        ps = e.page_size
        max_chunk = ((e.prefill_buckets[-1] + ps - 1) // ps) * ps
        pad_t = e.bucket_for(min(remaining, max_chunk)) or max_chunk
        return ((pad_t + ps - 1) // ps) * ps

    def _collect_prefill_group(
        self, budget: int
    ) -> tuple[list[_Request], int]:
        """Walk the waiting queue head and collect a FIFO prefix of
        requests whose next chunks share one bucket width (one compiled
        [K, T] shape). Requests are *begun* (lane + prefix match) as they
        are considered — a member whose bucket diverges stays begun and
        leads the next group. Returns (group, T); a solo group routes
        through the per-request path."""
        e = self.ecfg
        group: list[_Request] = []
        width = 0
        cap = min(budget, max(1, e.prefill_batch_max))
        for r in self._waiting:
            if len(group) >= cap:
                break
            if self._needs_solo_prefill(r):
                break
            if r.prefill_pos < 0:
                if self._free_slot() is None:
                    break
                self._prefill_begin(r)
            t = self._chunk_width(len(r.tokens) - r.prefill_pos)
            if not group:
                width = t
                cap = min(cap, max(1, e.prefill_token_budget // t))
            elif t != width:
                break
            group.append(r)
        if not group and self._waiting:
            head = self._waiting[0]
            if self._needs_solo_prefill(head) and (
                head.prefill_pos >= 0 or self._free_slot() is not None
            ):
                return [head], 0
        return group, width

    def _batch_prefill_group(
        self, group: list[_Request], width: int
    ) -> list[_Request]:
        """Dispatch one batched prefill for the group's next chunks and
        finish the requests whose prompts complete. The compiled batch
        width is the CAP for this bucket (not the group size): short
        groups pad with scratch-lane dummies so each (T, ctx_span) shape
        compiles once."""
        e = self.ecfg
        K = max(len(group),
                min(e.prefill_batch_max,
                    max(1, e.prefill_token_budget // width)))
        toks = np.zeros((K, width), np.int32)
        slots = np.full(K, self._B, np.int32)   # dummies -> scratch lane
        q_starts = np.zeros(K, np.int32)
        seq_lens = np.zeros(K, np.int32)        # dummy seq_len 0: all
        chunk_lens = []                         # tokens masked out
        adapter_ids = np.zeros(K, np.int32)     # dummies -> identity row
        for i, r in enumerate(group):
            start = r.prefill_pos
            chunk = r.tokens[start : start + width]
            toks[i, : len(chunk)] = chunk
            slots[i] = r.slot
            q_starts[i] = start
            seq_lens[i] = start + len(chunk)
            chunk_lens.append(len(chunk))
            adapter_ids[i] = r.adapter_id
        # ctx_span is binary — 0 (fresh) or the FULL region: each distinct
        # value is its own ~30 s XLA compile on the dev chip, and the
        # masked flash scan over dead context is a rounding error next to
        # the parameter matmuls
        ctx_span = e.max_context if int(q_starts.max()) > 0 else 0
        self.batch_prefills += 1
        if self.on_dispatch is not None:
            self.on_dispatch("prefill_batch", {
                "tokens": toks.tolist(), "slots": slots.tolist(),
                "q_starts": q_starts.tolist(),
                "seq_lens": seq_lens.tolist(), "ctx_span": ctx_span,
                "adapter_ids": adapter_ids.tolist(),
            })
        t_disp = time.monotonic()
        self.dispatch_counts["prefill_batch"] += 1
        self.ctx, logits = llama.batch_prefill(
            self.config, self.params, self.ctx, jnp.asarray(toks),
            jnp.asarray(slots), jnp.asarray(q_starts),
            jnp.asarray(seq_lens), ctx_span, jnp.asarray(adapter_ids),
        )
        self.flight.record(
            "prefill_batch", slots=[r.slot for r in group], width=width,
            dispatch_ms=round((time.monotonic() - t_disp) * 1e3, 3),
        )
        done: list[_Request] = []
        for i, r in enumerate(group):
            r.prefill_pos = int(q_starts[i]) + chunk_lens[i]
            if r.prefill_pos < len(r.tokens):
                self._seal_prefilled(r)  # mid-prompt blocks seal per chunk
                continue  # multi-chunk: next chunk in a later round
            if self._finish_prefill(r, logits[i], index=i) == "done":
                done.append(r)
        return done

    def _free_slot(self) -> Optional[int]:
        for i, s in enumerate(self._slots):
            if s is None and i not in self._prefilling:
                return i
        return None

    def _abort_prefill(self, r: _Request) -> None:
        """Release a half-prefilled request's lane reservation."""
        self._uncount_waiting(r)
        if r.slot >= 0 and self._prefilling.get(r.slot) is r:
            del self._prefilling[r.slot]
        r.slot = -1
        r.prefill_pos = -1

    def _note_queue_wait(self, r: _Request) -> None:
        """Account the admission queue wait once, when the request first
        gets a lane (multi-chunk continuations keep the original mark).
        The request also leaves the waiting-token backlog here — it is
        active prefill work now, not queued work."""
        self._uncount_waiting(r)
        # SFQ: service starting advances the global virtual clock to
        # this request's stamp, so later light-tenant arrivals start
        # from here rather than from zero
        self._vclock = max(self._vclock, r.vft)
        if r.t_prefill_start is not None:
            return
        now = time.monotonic()
        wait = now - r.enqueue_time
        self._h_queue.observe(wait, exemplar_id=r.req.request_id or None)
        t = r.tenant
        self.tenant_quotas.note_queue_wait(t, wait)
        TENANT.observe("dynamo_tenant_request_queue_seconds", t, wait,
                       exemplar_id=r.req.request_id or None)
        r.trace_spans.append(_span_dict("queue", r.enqueue_time))
        r.t_prefill_start = now

    def _prefill_begin(self, r: _Request) -> None:
        """Start a request's prefill: reserve a lane, prefix-match (HBM,
        then host tiers) and copy the matched run pool -> ctx. Seals
        queued by other requests must be flushed first — their pool pages
        are matchable but the copy may not be dispatched yet."""
        ps = self.ecfg.page_size
        prompt = r.tokens
        self._flush_seals()
        slot = self._free_slot()
        assert slot is not None, "caller checks slot availability"
        r.slot = slot
        self._prefilling[slot] = r
        self._note_queue_wait(r)
        hashes = r.seq.block_hashes()
        matchable = hashes[: max(0, (len(prompt) - 1) // ps)]
        matched_pages = self.allocator.match_prefix(matchable)
        t_onboard = time.monotonic()
        g1_matched = len(matched_pages)
        matched_pages = self._onboard_from_host(matchable, matched_pages)
        if len(matched_pages) > g1_matched:
            r.trace_spans.append(_span_dict(
                "g2_onboard", t_onboard,
                blocks=len(matched_pages) - g1_matched,
            ))
        # a matched/onboarded run longer than the ctx region cannot be
        # loaded (and the pow2 PADDING below can overflow the region even
        # when the real run fits — load_ctx_pages clamps that statically;
        # BENCH_r05: 46 matched pages padded to 64 vs a 52-page region).
        # Drop overflow pages rather than failing the engine round; their
        # refs are released with the rest after the load dispatch.
        max_blocks = self.ecfg.max_context // ps
        usable_pages = matched_pages[:max_blocks]
        if len(matched_pages) > max_blocks:
            log.warning(
                "matched prefix run (%d pages) exceeds the ctx region "
                "(%d pages); dropping overflow",
                len(matched_pages), max_blocks,
            )
        r.matched_blocks = len(usable_pages)
        if usable_pages:
            w = pow2_cover(len(usable_pages))
            padded = np.zeros(w, np.int32)  # padding -> scratch page 0
            padded[: len(usable_pages)] = usable_pages
            if self.on_dispatch is not None:
                self.on_dispatch("load_ctx", {
                    "slot": slot, "pages": padded.tolist(),
                })
            self.dispatch_counts["load_ctx"] += 1
            self.ctx = llama.load_ctx_pages(
                self.ctx, self.cache, jnp.int32(slot),
                jnp.asarray(padded),
            )
            if self.kv_quant and self.ctx_quant:
                # admission moved raw int8 pages + scales; the kernel
                # dequantizes them in VMEM per chunk (no fused dequant)
                KV_QUANT.inc("dynamo_kv_quant_ctx_admit_raw_pages_total",
                             len(usable_pages))
        if matched_pages:
            # copy dispatched (if any) — device order lets us drop the
            # refs now (all matched refs, including dropped overflow)
            self.allocator.free(matched_pages)
        r.prefill_pos = len(usable_pages) * ps
        r.sealed_prefix = len(usable_pages)  # matched blocks: already cached

    def _prefill_step(self, r: _Request) -> str:
        """Advance one prefill chunk; on the final chunk, sample the first
        token on device and activate the slot. Returns progress | done |
        failed. Long prompts route through the sequence-parallel ring
        prefill when the mesh has an sp axis (EngineConfig
        sp_prefill_threshold)."""
        e = self.ecfg
        ps = e.page_size
        prompt = r.tokens

        if (r.prefill_pos < 0
                and e.sp_prefill_threshold is not None
                and not (r.req.multimodal or {}).get("embeddings")
                and r.adapter_id == 0  # sp ring path serves the base model
                and self.mesh.shape.get("sp", 1) > 1):
            # threshold applies to the UNCACHED suffix: a mostly-cached
            # long prompt is cheaper on the chunked local path (which
            # reuses the prefix) than on a full ring recompute
            hashes = r.seq.block_hashes()
            matchable = hashes[: max(0, (len(prompt) - 1) // ps)]
            cached = self.allocator.cached_prefix_len(matchable)
            if len(prompt) - cached * ps >= e.sp_prefill_threshold:
                return self._sp_prefill_full(r)

        if r.prefill_pos < 0:
            self._prefill_begin(r)

        # one page-aligned continuation chunk (q_start advances); only the
        # final chunk's logits matter
        max_chunk = ((e.prefill_buckets[-1] + ps - 1) // ps) * ps
        start = r.prefill_pos
        chunk = prompt[start : start + max_chunk]
        pad_t = e.bucket_for(len(chunk)) or max_chunk
        pad_t = ((pad_t + ps - 1) // ps) * ps
        toks = np.zeros(pad_t, np.int32)
        toks[: len(chunk)] = chunk
        embeds = embeds_mask = None
        mm = r.req.multimodal or {}
        if mm.get("embeddings"):
            # override rows for image-token positions in this chunk
            # (vision-encoder outputs injected in place of the token
            # embedding — reference examples/multimodal E/P/D flow)
            ov = np.zeros((pad_t, self.config.hidden_size), np.float32)
            msk = np.zeros(pad_t, bool)
            for ent in mm["embeddings"]:
                data = np.asarray(ent["data"], np.float32)
                p0 = int(ent["pos"])
                lo = max(p0, start)
                hi = min(p0 + len(data), start + len(chunk))
                if lo < hi:
                    ov[lo - start: hi - start] = data[lo - p0: hi - p0]
                    msk[lo - start: hi - start] = True
            if msk.any():
                embeds = jnp.asarray(ov)
                embeds_mask = jnp.asarray(msk)
        if self.on_dispatch is not None:
            if embeds is not None:
                r.emit(ValueError(
                    "multimodal requests are single-host only"))
                self._abort_prefill(r)
                return "failed"
            self.on_dispatch("prefill", {
                "tokens": toks.tolist(), "slot": r.slot,
                "start": start, "end": start + len(chunk),
                "adapter": r.adapter_id,
            })
        t_disp = time.monotonic()
        self.dispatch_counts["prefill"] += 1
        self.ctx, logits = llama.prefill(
            self.config, self.params, self.ctx,
            jnp.asarray(toks), jnp.int32(r.slot),
            jnp.int32(start), jnp.int32(start + len(chunk)),
            embeds, embeds_mask, jnp.int32(r.adapter_id),
        )
        self.flight.record(
            "prefill", slots=[r.slot], tokens=len(chunk), start=start,
            dispatch_ms=round((time.monotonic() - t_disp) * 1e3, 3),
        )
        r.prefill_pos = start + len(chunk)
        if r.prefill_pos < len(prompt):
            # commit the chunk's complete blocks now (prefix-hittable /
            # streamable while the next chunks compute)
            self._seal_prefilled(r)
            return "progress"  # decode rounds run before the next chunk

        return self._finish_prefill(r, logits)

    def _sp_prefill_full(self, r: _Request) -> str:
        """Whole-prompt sequence-parallel ring prefill (ops/
        ring_attention.py): ONE pass with the prompt sharded over the sp
        mesh axis — per-device KV is O(T/sp), KV blocks rotate over ICI.
        The computed span enters the slot's ctx region via write_ctx_span;
        block sealing/commit then proceeds exactly like local prefill.
        (Recomputes the full prompt — no prefix-match integration; the sp
        path exists for prompts too long to prefill locally at all.)"""
        from dynamo_tpu.ops.ring_attention import sp_shard

        e = self.ecfg
        prompt = r.tokens
        self._flush_seals()
        slot = self._free_slot()
        assert slot is not None, "caller checks slot availability"
        r.slot = slot
        self._prefilling[slot] = r
        self._note_queue_wait(r)
        sp_n = self.mesh.shape["sp"]
        pad = -len(prompt) % sp_n
        toks = np.zeros(len(prompt) + pad, np.int32)
        toks[: len(prompt)] = prompt
        if self.on_dispatch is not None:
            self.on_dispatch("sp_prefill", {
                "tokens": toks.tolist(), "slot": slot, "n": len(prompt),
            })
        t_disp = time.monotonic()
        self.dispatch_counts["sp_prefill"] += 1
        kv, logits = llama.sp_prefill(
            self.config, self.params,
            sp_shard(jnp.asarray(toks), self.mesh),
            jnp.int32(len(prompt)), self.mesh,
        )
        self.flight.record(
            "sp_prefill", slots=[slot], tokens=len(prompt),
            dispatch_ms=round((time.monotonic() - t_disp) * 1e3, 3),
        )
        self.ctx = llama.write_ctx_span(self.ctx, jnp.int32(slot), kv)
        r.prefill_pos = len(prompt)
        r.matched_blocks = 0
        self.sp_prefills += 1
        return self._finish_prefill(r, logits)

    def _finish_prefill(self, r: _Request, logits, index: int = None) -> str:
        """Shared prefill tail: commit prompt blocks, sample the first
        token on device, activate the slot. `index` is the request's row
        when `logits` was sliced from a batched prefill — broadcast so
        followers slice their own replayed [K, V] logits identically."""
        prompt = r.tokens
        if r.t_prefill_start is not None:
            r.trace_spans.append(_span_dict(
                "prefill", r.t_prefill_start,
                prompt_tokens=len(prompt), matched_blocks=r.matched_blocks,
                slot=r.slot,
            ))
        # copy-commit the remaining complete prompt blocks into the
        # prefix cache (earlier chunks sealed theirs incrementally)
        self._seal_prefilled(r, limit=len(r.seq.blocks))

        so = r.req.sampling_options
        if so.seed is not None:
            # seeded: fully reproducible keys derived from the seed alone
            first_key = np.array([_FIRST_TOKEN_KEY_TAG, so.seed], np.uint32)
            step_keys = np.array([0, so.seed], np.uint32)
        else:
            # unseeded: fresh entropy per request — two identical prompts
            # must NOT produce identical outputs (landing on the same slot
            # previously reused the [0, slot+1] key stream)
            nonce = np.frombuffer(os.urandom(8), np.uint32).copy()
            first_key = np.array(
                [_FIRST_TOKEN_KEY_TAG ^ int(nonce[0]), int(nonce[1])], np.uint32
            )
            step_keys = nonce
        want_lp = r.req.output_options.logprobs is not None
        if self.on_dispatch is not None:
            self.on_dispatch("sample_first", {
                "key": first_key.tolist(),
                "temp": float(so.temperature or 0.0),
                "top_k": int(so.top_k or 0),
                "top_p": float(so.top_p if so.top_p is not None else 1.0),
                "want_lp": want_lp,
                "index": index,
            })
        self.dispatch_counts["sample_first"] += 1
        first_tok, first_lp = self._sample_first(
            logits,
            jnp.asarray(first_key),
            jnp.float32(so.temperature or 0.0),
            jnp.int32(so.top_k or 0),
            jnp.float32(so.top_p if so.top_p is not None else 1.0),
            self.config.vocab_size,
            want_lp,
        )

        slot = r.slot
        del self._prefilling[slot]
        self._slots[slot] = r
        self._ctx_disp[slot] = len(prompt) + 1
        # speculation is confined to the base model (adapter 0): the
        # draft/verify programs have no adapter plumbing, and a draft
        # proposing from base-model logits against a variant's target
        # distribution would crater acceptance anyway
        if (self.spec is not None and r.adapter_id == 0
                and self.spec.eligible(r.req)):
            # speculative admission: the device lane stays PARKED on the
            # scratch lane (exactly like a freed slot) — the slot's real
            # state lives host-side and it advances through verify
            # dispatches once the first token's fetch lands
            # (_process_first marks it spec-ready)
            r.spec = True
            self._slot_off(slot, spec=True)
            r.spec_keys = np.asarray(step_keys, np.uint32)
            if self.spec.penalized(r.req):
                # penalized slots carry the sampler's output-token
                # histogram host-side; the verifier's penalized accept
                # path advances it per accepted token
                r.spec_counts = np.zeros(
                    self.config.vocab_size, np.int32
                )
        else:
            self._slot_on(slot, r)
            self._dispatch_patch(
                admit=dict(
                    slot=slot,
                    ctx=len(prompt) + 1,
                    tok=first_tok,
                    keys=step_keys,
                    temp=so.temperature or 0.0,
                    top_k=so.top_k or 0,
                    top_p=so.top_p if so.top_p is not None else 1.0,
                    freq=so.frequency_penalty or 0.0,
                    pres=so.presence_penalty or 0.0,
                    rep=so.repetition_penalty or 1.0,
                    adapter=r.adapter_id,
                ),
            )
        # first token reaches the client via the async fetch pipeline
        first_tok.copy_to_host_async()
        self.dispatch_counts["fetch"] += 1
        if first_lp is not None:
            first_lp.copy_to_host_async()  # packed: one fetch
            self.dispatch_counts["fetch"] += 1
        self._entries.append(_Entry(
            kind="first", handle=first_tok, request=r, lp_handle=first_lp
        ))
        return "done"

    # ---- processing side (lagged results) ----

    def _process_entries(self, block: bool = False) -> None:
        # first-token / offload entries are independent of round ordering
        # (a round dispatched before an admission doesn't contain the
        # request; one dispatched after is behind it in the queue) —
        # process them as soon as their fetch lands instead of behind up
        # to max_inflight_rounds stacked round fetches. This is the TTFT
        # lever: the first token no longer waits out the decode pipeline.
        remaining = []
        for entry in self._entries:
            if entry.kind != "round" and entry.handle.is_ready():
                self._consume_entry(entry)
            else:
                remaining.append(entry)
        self._entries = remaining
        while self._entries:
            entry = self._entries[0]
            if not block and not entry.handle.is_ready():
                return
            self._entries.pop(0)
            self._consume_entry(entry)
            block = False  # only force at most one blocking wait

    def _unpack_lp(self, packed: np.ndarray):
        """Split one packed logprob row/stack [..., 1+2K] back into
        (chosen, top_ids, top_lps) — inverse of the jit-side pack_lp."""
        K = self.ecfg.max_logprobs
        return (packed[..., 0], packed[..., 1:1 + K].astype(np.int32),
                packed[..., 1 + K:])

    def _consume_entry(self, entry: _Entry) -> None:
        if entry.kind in ("round", "spec", "spec_tree") and entry.t_dispatch:
            self._h_round.observe(time.monotonic() - entry.t_dispatch)
        data = np.asarray(entry.handle)
        if entry.kind == "first":
            lp = None
            if entry.lp_handle is not None:
                chosen, ids, lps = self._unpack_lp(
                    np.asarray(entry.lp_handle)[0]
                )
                lp = (float(chosen), ids, lps)
            self._process_first(entry.request, int(data[0]), lp)
        elif entry.kind == "offload":
            scales = (
                np.asarray(entry.aux)[:, :, : entry.n_steps]
                if entry.aux is not None else None
            )
            self.offload.put_batch(
                entry.hashes, entry.parents,
                data[:, :, :, : entry.n_steps], scales,
            )
        elif entry.kind == "spec":
            self._process_spec(entry)
        elif entry.kind == "spec_tree":
            self._process_spec_tree(entry)
        else:
            self._process_round(entry, data)

    def _lp_payload(self, r: _Request, lp) -> dict:
        """LLMEngineOutput logprob fields for one emitted token."""
        n_req = r.req.output_options.logprobs
        if lp is None or n_req is None:
            return {}
        chosen, ids, lps = lp
        n = min(int(n_req), self.ecfg.max_logprobs)
        pairs = [[int(i), float(v)] for i, v in zip(ids[:n], lps[:n])]
        return {"log_probs": [float(chosen)], "top_logprobs": [pairs]}

    def _process_first(self, r: _Request, tok: int, lp=None) -> None:
        if r.cancelled or r.finished:
            self._finish(r, None)
            return
        if r.first_token_time is None:
            r.first_token_time = time.monotonic()
            r.t_last_emit = r.first_token_time
            ttft = r.first_token_time - r.enqueue_time
            self._h_ttft.observe(ttft,
                                 exemplar_id=r.req.request_id or None)
            TENANT.observe("dynamo_tenant_request_ttft_seconds",
                           r.tenant, ttft,
                           exemplar_id=r.req.request_id or None)
        sc = r.req.stop_conditions
        if not sc.ignore_eos and tok in (sc.stop_token_ids or []) and (
            sc.min_tokens is None or r.produced >= sc.min_tokens
        ):
            self._finish(r, FinishReason.EOS)
            return
        r.last_token = tok
        r.produced += 1  # may continue a preempted request's count
        r.emit(LLMEngineOutput(token_ids=[tok], **self._lp_payload(r, lp)))
        if r.produced >= r.max_new_tokens(self.ecfg.max_context):
            self._finish(r, FinishReason.LENGTH, emit_empty=True)
        elif r.spec:
            # the host now knows the pending token — speculation can start
            r.spec_tokens = list(r.tokens) + [tok]
            r.spec_ready = True

    def _process_round(self, entry: _Entry, toks: np.ndarray) -> None:
        """Consume one round's stacked tokens. Emission is BATCHED per
        request per round (tokens of a round arrive together in one fetch;
        per-token emits through the asyncio machinery are pure host
        overhead — on a 1-core box they, not the device, capped
        throughput)."""
        lp_arrs = None
        if entry.lp_handle is not None:
            lp_arrs = self._unpack_lp(np.asarray(entry.lp_handle))
        for slot, r in enumerate(entry.slots):
            # identity check doubles as the epoch: a recycled slot holds
            # a different _Request object than the snapshot
            if r is None or r.finished or self._slots[slot] is not r:
                continue
            if r.cancelled:
                self._finish(r, None)
                continue
            batch: list[int] = []
            lp_chosen: list[float] = []
            lp_top: list[list] = []
            n_lp = r.req.output_options.logprobs
            finish: Optional[FinishReason] = None
            for step in range(entry.n_steps):
                tok = int(toks[step, slot])
                finish = self._advance_token(r, tok)
                if finish is FinishReason.EOS:
                    break  # stop token itself is not emitted
                batch.append(tok)
                if lp_arrs is not None and n_lp is not None:
                    k = min(int(n_lp), self.ecfg.max_logprobs)
                    lp_chosen.append(float(lp_arrs[0][step, slot]))
                    lp_top.append(
                        [[int(i), float(v)] for i, v in zip(
                            lp_arrs[1][step, slot][:k],
                            lp_arrs[2][step, slot][:k])]
                    )
                if finish is not None:
                    break
            if batch:
                self._note_emit(r, len(batch), entry, "decode_round")
            if batch or finish is not None:
                extra = {}
                if lp_chosen:
                    extra = {"log_probs": lp_chosen, "top_logprobs": lp_top}
                if finish is not None:
                    extra["annotations"] = self._final_annotations(r)
                r.emit(LLMEngineOutput(
                    token_ids=batch, finish_reason=finish, **extra
                ))
            if finish is not None:
                self._finish(r, None)
                continue
            if r.spec_gated or r.spec_rearm_wait > 0:
                self._spec_gated_advance(slot, r, batch)
        self.tokens_generated += int(
            sum(1 for s in entry.slots if s is not None) * entry.n_steps
        )

    def _spec_gated_advance(
        self, slot: int, r: _Request, batch: list[int]
    ) -> None:
        """Fused-round bookkeeping for a gated (or re-arming) stream:
        keep the host sequence/penalty mirrors current so speculation
        can resume exactly where the fused round leaves off — the
        proposers' lookup corpus and the despec/re-arm patches all read
        ``spec_tokens``."""
        if batch:
            r.spec_tokens.extend(batch)
            if r.spec_counts is not None:
                for t in batch:
                    r.spec_counts[t] += 1
        if r.spec_rearm_wait > 0:
            # phase 2 of the re-arm drain: one in-flight round entry
            # whose snapshot still stepped this lane has been consumed
            # (its tokens were real — mirrored above); once the last one
            # lands, the clear patch has taken effect in program order
            # and the first verify can dispatch
            r.spec_rearm_wait -= 1
            if r.spec_rearm_wait == 0:
                r.spec_ready = True
            return
        if self.ecfg.spec_rearm_tokens <= 0:
            return  # gate is permanent: no re-arm budget configured
        r.spec_rearm_left -= len(batch)
        if r.spec_rearm_left <= 0:
            self._rearm_spec(slot, r)

    def _rearm_spec(self, slot: int, r: _Request) -> None:
        """Re-arm speculation on a gated stream (two-phase drain).

        Phase 1 (here): flip the request back to spec mode and PARK the
        device lane. Unlike spec admission (_process_first), the lane is
        LIVE mid-stream — rounds already dispatched keep stepping it
        until the clear patch lands in program order — so count the
        in-flight round entries whose snapshot still contains this lane.
        Phase 2 (_spec_gated_advance): each such entry's consumption
        decrements the counter while still mirroring its emitted tokens;
        at zero the device has drained and spec_ready arms the first
        verify. Without the drain, that verify's commit would race the
        in-flight rounds' writes over the same ctx rows.
        """
        r.spec = True
        r.spec_gated = False
        r.spec_ready = False
        self.spec.on_rearm(slot)
        self._slot_off(slot, spec=True)
        self._dispatch_patch(clear_slots=[slot])
        self._ctx_disp[slot] = len(r.spec_tokens)
        # the device PRNG key advanced privately while the stream ran on
        # the fused round — the host cannot recover it without an extra
        # fetch. Reseed deterministically from the stale key and the
        # produced count: greedy streams are unaffected (keys unused),
        # sampled streams keep seeded reproducibility (same request +
        # schedule -> same fold) though the draw sequence diverges from
        # an ungated run's.
        stale = np.asarray(r.spec_keys, np.uint32)
        fold = (r.produced * 2654435761 + 0x9E3779B9) & 0xFFFFFFFF
        r.spec_keys = np.asarray(
            [int(stale[0]) ^ fold, int(stale[1]) ^ (fold >> 1)],
            np.uint32,
        )
        r.spec_rearm_wait = sum(
            1 for en in self._entries
            if en.kind == "round" and slot < len(en.slots)
            and en.slots[slot] is r
        )
        if r.spec_rearm_wait == 0:
            r.spec_ready = True

    def _advance_token(
        self, r: _Request, tok: int
    ) -> Optional[FinishReason]:
        """Per-token state advance (sealing, stop detection, budget).
        Returns the finish reason when this token ENDS the request (EOS:
        token not emitted; LENGTH: token emitted as the last one)."""
        sc = r.req.stop_conditions
        # copy-commit the block completed by the previous token into the
        # prefix cache (device order: those positions were written by
        # already-dispatched steps)
        if r.last_token >= 0:
            for blk in r.seq.extend([r.last_token]):
                self._queue_seal(
                    r, blk.position, blk.block_hash, blk.parent_hash
                )
        if not sc.ignore_eos and tok in (sc.stop_token_ids or []) and (
            sc.min_tokens is None or r.produced >= sc.min_tokens
        ):
            return FinishReason.EOS
        r.last_token = tok
        r.produced += 1
        if r.produced >= r.max_new_tokens(self.ecfg.max_context):
            return FinishReason.LENGTH
        return None

    def _finish(
        self,
        r: _Request,
        reason: Optional[FinishReason],
        emit_empty: bool = False,
    ) -> None:
        """Mark finished on host; the slot is reclaimed via a release patch
        at the next round boundary (in-flight garbage steps are redirected
        to the scratch lane by the patch's dest update)."""
        if r.finished:
            return
        r.finished = True
        if r.slot >= 0 and self._slots[r.slot] is r:
            self._slot_off(r.slot)  # out of the dispatch set immediately
        if reason is not None:
            r.emit(LLMEngineOutput(
                token_ids=[], finish_reason=reason,
                annotations=self._final_annotations(r),
            ))
        self._to_release.append(r)

    def _apply_releases(self) -> None:
        # also sweep cancelled requests that never got a finish event
        for slot, r in enumerate(self._slots):
            if r is not None and r.cancelled and not r.finished:
                r.finished = True
                self._slot_off(slot)
                self._to_release.append(r)
        if not self._to_release:
            return
        clear_slots = []
        for r in self._to_release:
            if r.slot >= 0 and self._slots[r.slot] is r:
                clear_slots.append(r.slot)
                self._slots[r.slot] = None
                self._slot_off(r.slot)
                self._ctx_disp[r.slot] = 1
                if self.spec is not None and r.spec:
                    self.spec.release(r.slot)  # drop stale draft KV state
            r.slot = -1
        self._to_release = []
        if clear_slots:
            self._dispatch_patch(clear_slots=clear_slots)

    def _fail_all(self, err: Exception) -> None:
        for r in list(self._slots):
            if r is not None:
                r.emit(err)
                r.finished = True
        self._slots = [None] * self._B
        self._slot_active[:] = False
        self._slot_spec[:] = False
        self._slot_lp[:] = False
        self._slot_sampler[:] = False
        self._active_cache = None
        if self.spec is not None:
            for i in range(self._B):
                self.spec.release(i)
        for r in self._waiting:
            r.emit(err)
            self._abort_prefill(r)  # also drops its waiting-token count
        self._waiting = []
        self._prefilling = {}
        self._entries = []
        self._seal_queue = []

