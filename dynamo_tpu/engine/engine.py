"""TpuEngine: continuous batching over the paged-KV JAX model.

Architecture (TPU-first redesign of what the reference delegates to vLLM —
SURVEY.md §7 step 3):

  - One fixed-width decode batch of ``max_decode_slots`` slots steps every
    iteration; each slot is one in-flight request. Static shapes — exactly
    one compiled decode program.
  - Prefill runs per request at one of a few bucketed padded lengths (one
    compiled program per bucket), writing prompt KV straight into pages,
    reusing any cached prefix pages (chained-hash match).
  - A host-side step loop (dedicated thread — JAX dispatch is async, the
    loop only blocks on the sampled-token transfer) drives admission,
    page growth, block commit/publish, stop conditions, and preemption.
  - Sampling is fused on device; only sampled token ids cross to host.

The engine implements the AsyncEngine contract: ``generate(request)`` yields
LLMEngineOutput deltas; cancellation propagates via the iterator being
dropped (reference engine.rs:124-140 AsyncEngineContext::stop_generating).
"""
from __future__ import annotations

import asyncio
import logging
import queue as queue_mod
import threading
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.cache import PageAllocator
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine import sampling
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.tokens import TokenBlockSequence

log = logging.getLogger(__name__)


@dataclass
class _Request:
    req: PreprocessedRequest
    seq: TokenBlockSequence
    out: asyncio.Queue
    loop: asyncio.AbstractEventLoop
    pages: list[int] = field(default_factory=list)
    matched_blocks: int = 0       # prefix-cache hit depth (blocks)
    slot: int = -1
    produced: int = 0
    last_token: int = 0
    cancelled: bool = False
    prefill_done: bool = False
    enqueue_time: float = field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return len(self.req.token_ids)

    def max_new_tokens(self, max_context: int) -> int:
        mt = self.req.stop_conditions.max_tokens
        cap = max_context - self.prompt_len
        return min(mt, cap) if mt is not None else cap

    def emit(self, item: LLMEngineOutput | Exception) -> None:
        self.loop.call_soon_threadsafe(self.out.put_nowait, item)


class TpuEngine:
    """Continuous-batching paged-KV engine on a jax mesh."""

    def __init__(
        self,
        model_config: ModelConfig,
        engine_config: Optional[EngineConfig] = None,
        *,
        params: Any = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        mesh_config: Optional[MeshConfig] = None,
        rng_seed: int = 0,
        on_kv_event: Optional[Callable[[KvCacheEvent], None]] = None,
        on_metrics: Optional[Callable[[ForwardPassMetrics], None]] = None,
    ):
        self.config = model_config
        self.ecfg = engine_config or EngineConfig()
        self.mesh = mesh or make_mesh(mesh_config)
        self.on_metrics = on_metrics

        c, e = self.config, self.ecfg
        cache_dtype = jnp.dtype(e.cache_dtype)
        p_sh = llama.param_shardings(c, self.mesh)
        if params is None:
            params = llama.init_params(c, rng_seed)
        self.params = jax.tree.map(lambda x, s: jax.device_put(x, s), params, p_sh)
        self.cache = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            llama.init_cache(c, e.num_pages, e.page_size, cache_dtype),
            llama.cache_shardings(c, self.mesh),
        )
        self.allocator = PageAllocator(
            e.num_pages,
            e.page_size,
            worker_id=e.worker_id,
            on_event=on_kv_event,
            enable_prefix_caching=e.enable_prefix_caching,
        )

        B = e.max_decode_slots
        self._slots: list[Optional[_Request]] = [None] * B
        # host mirrors of decode-state device inputs
        self._page_tables = np.zeros((B, e.max_pages_per_seq), np.int32)
        self._ctx_lens = np.ones(B, np.int32)
        self._tokens = np.zeros(B, np.int32)
        # host mirrors of per-slot sampling params
        self._samp = {
            "temperature": np.zeros(B, np.float32),
            "top_k": np.zeros(B, np.int32),
            "top_p": np.ones(B, np.float32),
            "frequency_penalty": np.zeros(B, np.float32),
            "presence_penalty": np.zeros(B, np.float32),
            "repetition_penalty": np.ones(B, np.float32),
        }
        self._samp_dirty = True
        self._samp_dev: Optional[sampling.SamplingParams] = None
        self._sampler_state = sampling.init_state(B, c.vocab_size, rng_seed)

        self._intake: queue_mod.Queue = queue_mod.Queue()
        self._waiting: list[_Request] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = False
        # stats
        self.step_count = 0
        self.tokens_generated = 0

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._thread = threading.Thread(
            target=self._run_loop, name="tpu-engine-loop", daemon=True
        )
        self._thread.start()

    async def stop(self) -> None:
        self._stop.set()
        if self._thread:
            await asyncio.to_thread(self._thread.join, 10.0)

    # ------------------------------------------------------------------
    # AsyncEngine surface

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[LLMEngineOutput]:
        """Stream engine outputs (token-id deltas) for one request."""
        if not self._started:
            self.start()
        if len(request.token_ids) == 0:
            raise ValueError("empty prompt")
        if len(request.token_ids) >= self.ecfg.max_context:
            raise ValueError(
                f"prompt length {len(request.token_ids)} exceeds max context "
                f"{self.ecfg.max_context}"
            )
        r = _Request(
            req=request,
            seq=TokenBlockSequence.from_tokens(
                request.token_ids, self.ecfg.page_size, salt=request.model
            ),
            out=asyncio.Queue(),
            loop=asyncio.get_running_loop(),
        )
        self._intake.put(r)
        try:
            while True:
                item = await r.out.get()
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            r.cancelled = True

    def metrics(self) -> ForwardPassMetrics:
        a = self.allocator
        return ForwardPassMetrics(
            worker_id=self.ecfg.worker_id,
            worker_stats=WorkerStats(
                request_active_slots=sum(s is not None for s in self._slots),
                request_total_slots=len(self._slots),
                num_requests_waiting=len(self._waiting) + self._intake.qsize(),
            ),
            kv_stats=KvStats(
                kv_active_blocks=a.active_pages,
                kv_total_blocks=a.total_pages,
                gpu_cache_usage_perc=a.usage(),
                gpu_prefix_cache_hit_rate=a.hit_rate(),
            ),
        )

    # ------------------------------------------------------------------
    # step loop (engine thread)

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            try:
                did_work = self._step()
            except Exception:  # noqa: BLE001 — engine loop must survive
                log.exception("engine step failed")
                self._fail_all(RuntimeError("engine step failed; see logs"))
                did_work = False
            if not did_work:
                try:
                    r = self._intake.get(timeout=0.02)
                    self._waiting.append(r)
                except queue_mod.Empty:
                    pass

    def _step(self) -> bool:
        self._drain_intake()
        self._admit()
        active = [s for s in self._slots if s is not None]
        if not active:
            return False
        self._reap_cancelled()
        active = [s for s in self._slots if s is not None]
        if not active:
            return False
        self._decode_once()
        if self.on_metrics is not None:
            self.on_metrics(self.metrics())
        return True

    def _drain_intake(self) -> None:
        while True:
            try:
                self._waiting.append(self._intake.get_nowait())
            except queue_mod.Empty:
                return

    def _reap_cancelled(self) -> None:
        for i, r in enumerate(self._slots):
            if r is not None and r.cancelled:
                self._release(r)
        self._waiting = [r for r in self._waiting if not r.cancelled]

    # ---- admission / prefill ----

    def _admit(self) -> None:
        while self._waiting and None in self._slots:
            r = self._waiting[0]
            if r.cancelled:
                self._waiting.pop(0)
                continue
            if not self._try_prefill(r):
                return  # head-of-line blocks until pages free up
            self._waiting.pop(0)

    def _try_prefill(self, r: _Request) -> bool:
        e = self.ecfg
        ps = e.page_size
        prompt = r.req.token_ids
        bucket = e.bucket_for(max(len(prompt), 1))
        if bucket is None:
            r.emit(ValueError(f"prompt longer than max bucket {e.prefill_buckets[-1]}"))
            return True  # consumed (failed)

        # prefix-cache match over complete prompt blocks; never match the
        # whole prompt (the last block must be recomputed to get logits)
        hashes = r.seq.block_hashes()
        matched_pages = self.allocator.match_prefix(
            hashes[: max(0, (len(prompt) - 1) // ps)]
        )
        n_cached = len(matched_pages) * ps
        n_total_pages = (len(prompt) + ps - 1) // ps
        fresh = self.allocator.allocate(n_total_pages - len(matched_pages))
        if fresh is None:
            self.allocator.free(matched_pages)
            return False
        r.pages = matched_pages + fresh
        r.matched_blocks = len(matched_pages)

        # pad the uncached suffix to a bucket (rounded to a page multiple)
        suffix = prompt[n_cached:]
        pad_t = e.bucket_for(max(len(suffix), 1))
        if pad_t is not None:
            pad_t = ((pad_t + ps - 1) // ps) * ps
        if pad_t is None or n_cached // ps + pad_t // ps > e.max_pages_per_seq:
            self.allocator.free(r.pages)
            r.pages = []
            r.emit(ValueError("prompt does not fit page table"))
            return True
        toks = np.zeros(pad_t, np.int32)
        toks[: len(suffix)] = suffix
        table = np.zeros(e.max_pages_per_seq, np.int32)
        table[: len(r.pages)] = r.pages

        self.cache, logits = llama.prefill(
            self.config,
            self.params,
            self.cache,
            jnp.asarray(toks),
            jnp.asarray(table),
            jnp.int32(n_cached),
            jnp.int32(len(prompt)),
        )
        # commit complete prompt blocks beyond the matched prefix
        for blk in r.seq.blocks[r.matched_blocks:]:
            self.allocator.commit(
                r.pages[blk.position], blk.block_hash, blk.parent_hash
            )

        first = self._sample_host(r, np.asarray(logits))
        r.first_token_time = time.monotonic()
        stop_ids = set(r.req.stop_conditions.stop_token_ids or [])
        if not r.req.stop_conditions.ignore_eos and first in stop_ids:
            self.allocator.free(r.pages)
            r.pages = []
            r.emit(LLMEngineOutput(token_ids=[], finish_reason=FinishReason.EOS))
            return True
        self._emit_token(r, first)
        if r.produced >= r.max_new_tokens(e.max_context):
            self.allocator.free(r.pages)
            r.pages = []
            r.emit(LLMEngineOutput(token_ids=[], finish_reason=FinishReason.LENGTH))
            return True
        self._assign_slot(r, first, table)
        return True

    def _assign_slot(self, r: _Request, first_token: int, table: np.ndarray) -> None:
        slot = self._slots.index(None)
        r.slot = slot
        r.prefill_done = True
        r.last_token = first_token
        self._slots[slot] = r
        self._page_tables[slot] = table
        # context includes the pending first token (position prompt_len)
        self._ctx_lens[slot] = r.seq.total_tokens + 1
        self._tokens[slot] = first_token
        so = r.req.sampling_options
        self._samp["temperature"][slot] = so.temperature or 0.0
        self._samp["top_k"][slot] = so.top_k or 0
        self._samp["top_p"][slot] = so.top_p if so.top_p is not None else 1.0
        self._samp["frequency_penalty"][slot] = so.frequency_penalty or 0.0
        self._samp["presence_penalty"][slot] = so.presence_penalty or 0.0
        self._samp["repetition_penalty"][slot] = so.repetition_penalty or 1.0
        self._samp_dirty = True
        self._sampler_state = sampling.reset_slot(
            self._sampler_state, slot, so.seed if so.seed is not None else slot + 1
        )

    def _sample_host(self, r: _Request, logits: np.ndarray) -> int:
        """First token after prefill — sampled host-side (once per request)."""
        so = r.req.sampling_options
        t = so.temperature or 0.0
        if t <= 0.0:
            return int(np.argmax(logits))
        x = logits.astype(np.float64) / t
        if so.top_k:
            kth = np.partition(x, -so.top_k)[-so.top_k]
            x = np.where(x < kth, -np.inf, x)
        p = np.exp(x - np.max(x))
        p /= p.sum()
        if so.top_p is not None and so.top_p < 1.0:
            order = np.argsort(-p)
            cum = np.cumsum(p[order])
            keep = np.zeros_like(p, bool)
            keep[order[: max(1, int(np.searchsorted(cum, so.top_p) + 1))]] = True
            p = np.where(keep, p, 0.0)
            p /= p.sum()
        rng = np.random.RandomState(so.seed if so.seed is not None else None)
        return int(rng.choice(len(p), p=p))

    # ---- decode ----

    def _decode_once(self) -> None:
        e = self.ecfg
        ps = e.page_size
        # grow page tables: slots whose NEXT written position opens a page.
        # _ctx_lens already includes the pending token; its position is
        # ctx_len-1 and must have a page before the step writes its KV.
        for slot, r in enumerate(self._slots):
            if r is None:
                continue
            pos = int(self._ctx_lens[slot]) - 1
            if pos // ps >= len(r.pages):
                pages = None
                while pages is None:
                    pages = self.allocator.allocate(1)
                    if pages is None:
                        self._preempt_lowest()  # may preempt r itself
                        if self._slots[slot] is None:
                            break
                if self._slots[slot] is None or pages is None:
                    continue
                r.pages.extend(pages)
                self._page_tables[slot, len(r.pages) - 1] = pages[0]

        active_idx = [i for i, s in enumerate(self._slots) if s is not None]
        if not active_idx:
            return

        if self._samp_dirty:
            self._samp_dev = sampling.SamplingParams(
                temperature=jnp.asarray(self._samp["temperature"]),
                top_k=jnp.asarray(self._samp["top_k"]),
                top_p=jnp.asarray(self._samp["top_p"]),
                frequency_penalty=jnp.asarray(self._samp["frequency_penalty"]),
                presence_penalty=jnp.asarray(self._samp["presence_penalty"]),
                repetition_penalty=jnp.asarray(self._samp["repetition_penalty"]),
            )
            self._samp_dirty = False

        self.cache, logits = llama.decode_step(
            self.config,
            self.params,
            self.cache,
            jnp.asarray(self._tokens),
            jnp.asarray(self._page_tables),
            jnp.asarray(self._ctx_lens),
        )
        tokens_dev, self._sampler_state = sampling.sample_step(
            logits.astype(jnp.float32),
            self._sampler_state,
            self._samp_dev,
            self.ecfg.max_top_k,
        )
        tokens = np.asarray(tokens_dev)
        self.step_count += 1

        for slot in active_idx:
            r = self._slots[slot]
            if r is None:
                continue
            # the token just processed was r.last_token at position ctx-1;
            # seal/commit any block it completed
            new_blocks = r.seq.extend([r.last_token]) if r.prefill_done else []
            for blk in new_blocks:
                if blk.position < len(r.pages):
                    self.allocator.commit(
                        r.pages[blk.position], blk.block_hash, blk.parent_hash
                    )
            tok = int(tokens[slot])
            self.tokens_generated += 1
            self._finish_or_continue(r, slot, tok)

    def _emit_token(self, r: _Request, tok: int) -> None:
        r.produced += 1
        r.emit(LLMEngineOutput(token_ids=[tok]))

    def _finish_or_continue(self, r: _Request, slot: int, tok: int) -> None:
        sc = r.req.stop_conditions
        stop_ids = set(sc.stop_token_ids or [])
        if not sc.ignore_eos and tok in stop_ids and (
            sc.min_tokens is None or r.produced >= sc.min_tokens
        ):
            r.emit(LLMEngineOutput(token_ids=[], finish_reason=FinishReason.EOS))
            self._release(r)
            return
        r.produced += 1
        if r.produced >= r.max_new_tokens(self.ecfg.max_context):
            r.emit(
                LLMEngineOutput(token_ids=[tok], finish_reason=FinishReason.LENGTH)
            )
            self._release(r)
            return
        r.emit(LLMEngineOutput(token_ids=[tok]))
        r.last_token = tok
        self._ctx_lens[slot] += 1
        self._tokens[slot] = tok

    # ---- preemption / release ----

    def _preempt_lowest(self) -> None:
        """Preempt the most recently admitted request (LIFO keeps older
        requests making progress — mirrors vLLM recompute preemption)."""
        victims = [s for s in self._slots if s is not None]
        if not victims:
            return
        victim = max(victims, key=lambda r: r.enqueue_time)
        self._preempt(victim)

    def _preempt(self, r: _Request) -> None:
        slot = r.slot
        self.allocator.free(r.pages)
        r.pages = []
        r.prefill_done = False
        # Restart with everything processed so far plus the pending token as
        # the new prompt; re-prefill recomputes (matching any still-cached
        # prefix pages) and resumes sampling where we left off. Emitted
        # tokens are never re-emitted (prefill emits the NEXT token).
        r.req.token_ids = r.seq.tokens + [r.last_token]
        r.seq = TokenBlockSequence.from_tokens(
            r.req.token_ids, self.ecfg.page_size, salt=r.req.model
        )
        self._clear_slot(slot)
        r.slot = -1
        self._waiting.insert(0, r)
        log.info("preempted request %s", r.req.request_id)

    def _release(self, r: _Request) -> None:
        self.allocator.free(r.pages)
        r.pages = []
        if r.slot >= 0:
            self._clear_slot(r.slot)
        r.slot = -1

    def _clear_slot(self, slot: int) -> None:
        self._slots[slot] = None
        self._page_tables[slot] = 0
        self._ctx_lens[slot] = 1
        self._tokens[slot] = 0

    def _fail_all(self, err: Exception) -> None:
        for r in list(self._slots):
            if r is not None:
                r.emit(err)
                self._release(r)
        for r in self._waiting:
            r.emit(err)
        self._waiting = []
