"""TpuEngine: pipelined continuous batching over the paged-KV JAX model.

Architecture (TPU-first redesign of what the reference delegates to vLLM —
SURVEY.md §7 step 3). The defining constraint is that device→host reads
have high latency (µs on PCIe TPU VMs, ~80ms through a tunneled dev chip)
while dispatches and host→device uploads are cheap and asynchronous. The
engine therefore NEVER blocks a decode step on host data:

  - All decode state lives on device: last tokens, context lengths, page
    tables, context caps, sampler keys/counts, per-slot sampling params.
    One fused jit (decode + sample + state advance) steps every slot.
  - The host loop dispatches steps ahead in rounds of ``flush_every``; each
    round's sampled tokens are stacked on device ([F, B]) and fetched with
    ``copy_to_host_async`` — fetches pipeline behind compute, so results
    arrive a bounded LAG behind dispatch without ever stalling the device.
  - Host processing (token emission, stop detection, block sealing/commit,
    page growth, admission, preemption) runs on lagged results. State
    changes are applied via a patch jit dispatched between rounds —
    device-order semantics make this race-free: a step dispatched before a
    patch sees pre-patch state, and page writes it performs land before
    any later prefill that reuses those pages.
  - Slots finished on host keep garbage-decoding until their release patch
    lands (≤ pipeline lag steps). Safety: garbage writes only ever touch
    the slot's own uncommitted tail pages, pre-allocated private pages, or
    the reserved scratch page 0 — a finished request's final sealed block
    is deliberately NOT committed to the prefix cache (see _finish).
  - Prefill runs per request at bucketed padded lengths; the first token is
    sampled on device and patched into the slot without a host round trip.

The engine implements the AsyncEngine contract: ``generate(request)`` yields
LLMEngineOutput deltas; dropping the iterator cancels (reference
engine.rs:124-140 AsyncEngineContext::stop_generating).
"""
from __future__ import annotations

import asyncio
import functools
import logging
import os
from collections import deque
import queue as queue_mod
import threading
import time
from dataclasses import dataclass, field
from typing import Any, AsyncIterator, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.cache import PageAllocator
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine import sampling
from dynamo_tpu.kv_router.protocols import (
    ForwardPassMetrics,
    KvCacheEvent,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.models import llama
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.parallel.mesh import MeshConfig, make_mesh
from dynamo_tpu.protocols.common import (
    FinishReason,
    LLMEngineOutput,
    PreprocessedRequest,
)
from dynamo_tpu.tokens import TokenBlockSequence

log = logging.getLogger(__name__)

_FIRST_TOKEN_KEY_TAG = 0x46697273  # distinct PRNG stream for first tokens


def pow2_cover(n: int, lo: int = 1) -> int:
    """Smallest power of two >= max(n, lo) — the compile-cache bucketing
    used for page-table widths and transfer sizes (padding always targets
    scratch page 0)."""
    w = lo
    while w < n:
        w *= 2
    return w


@dataclass
class _Request:
    req: PreprocessedRequest
    seq: TokenBlockSequence
    out: asyncio.Queue
    loop: asyncio.AbstractEventLoop
    # current (possibly restart-extended) prompt — kept separate from
    # req.token_ids so preemption never mutates the caller's request object
    tokens: list[int] = field(default_factory=list)
    pages: list[int] = field(default_factory=list)
    matched_blocks: int = 0
    # chunked-prefill progress: tokens already in cache (-1 = not started).
    # Prefill runs ONE chunk per scheduling round so decode rounds
    # interleave with long prompts instead of stalling behind them.
    prefill_pos: int = -1
    slot: int = -1
    produced: int = 0
    last_token: int = -1          # newest processed token, not yet in seq
    cancelled: bool = False
    finished: bool = False
    enqueue_time: float = field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None

    @property
    def prompt_len(self) -> int:
        return len(self.tokens)

    def max_new_tokens(self, max_context: int) -> int:
        mt = self.req.stop_conditions.max_tokens
        cap = max_context - self.prompt_len
        return min(mt, cap) if mt is not None else cap

    def emit(self, item: LLMEngineOutput | Exception) -> None:
        self.loop.call_soon_threadsafe(self.out.put_nowait, item)


@dataclass
class _Entry:
    """One in-flight fetch: either a round of stacked step tokens or a
    request's prefill first-token."""

    kind: str                      # "round" | "first"
    handle: Any                    # device array being copied to host
    # round:
    slots: list[Optional[_Request]] = field(default_factory=list)  # snapshot
    n_steps: int = 0
    # first:
    request: Optional[_Request] = None
    # offload: hashes/parents aligned with the gathered pages
    hashes: list[int] = field(default_factory=list)
    parents: list[int] = field(default_factory=list)
    # logprobs: stacked (chosen [F,B], top_ids [F,B,K], top_lps [F,B,K])
    # for rounds, or the single-step tuple for "first" entries
    lp_handle: Optional[tuple] = None


class TpuEngine:
    """Pipelined continuous-batching paged-KV engine on a jax mesh."""

    def __init__(
        self,
        model_config: ModelConfig,
        engine_config: Optional[EngineConfig] = None,
        *,
        params: Any = None,
        mesh: Optional[jax.sharding.Mesh] = None,
        mesh_config: Optional[MeshConfig] = None,
        rng_seed: int = 0,
        on_kv_event: Optional[Callable[[KvCacheEvent], None]] = None,
        on_metrics: Optional[Callable[[ForwardPassMetrics], None]] = None,
    ):
        self.config = model_config
        self.ecfg = engine_config or EngineConfig()
        self.mesh = mesh or make_mesh(mesh_config)
        self.on_metrics = on_metrics

        c, e = self.config, self.ecfg
        cache_dtype = jnp.dtype(e.cache_dtype)
        p_sh = llama.param_shardings(c, self.mesh)
        if params is None:
            params = llama.init_params(c, rng_seed)
        self.params = jax.tree.map(lambda x, s: jax.device_put(x, s), params, p_sh)
        self.cache = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            llama.init_cache(c, e.num_pages, e.page_size, cache_dtype),
            llama.cache_shardings(c, self.mesh),
        )
        # decode write ring: one lane per slot, flush_every entries deep —
        # decode steps write here; llama.flush scatters a full ring into the
        # page pool once per round (see models/llama.py init_ring)
        self.ring = jax.tree.map(
            lambda x, s: jax.device_put(x, s),
            llama.init_ring(c, e.max_decode_slots, e.flush_every, cache_dtype),
            llama.ring_shardings(c, self.mesh),
        )
        self.allocator = PageAllocator(
            e.num_pages, e.page_size,
            worker_id=e.worker_id,
            on_event=on_kv_event,
            enable_prefix_caching=e.enable_prefix_caching,
        )
        # host-DRAM offload tier (KVBM G2): parked pages are batch-gathered
        # once per round and fetched to host behind compute. A deque:
        # on_park appends from BOTH the engine loop and the disagg asyncio
        # thread; the dispatcher drains with popleft (both thread-safe),
        # never a swap that could drop a concurrent append.
        self.offload = None
        self._offload_cands: deque = deque()
        if e.disk_offload_pages > 0 and e.host_offload_pages <= 0:
            raise ValueError(
                "disk_offload_pages (G3) requires host_offload_pages (G2): "
                "the tier hierarchy is strict (block_manager.rs:69-82)"
            )
        if e.host_offload_pages > 0:
            from dynamo_tpu.engine.offload import (
                DiskOffloadTier,
                HostOffloadTier,
            )

            page_shape = (
                2, c.num_layers, c.num_kv_heads, e.page_size, c.head_dim
            )
            spill = None
            if e.disk_offload_pages > 0:
                spill = DiskOffloadTier(
                    e.disk_offload_pages, page_shape, cache_dtype,
                    path=e.disk_offload_path,
                )
            self.offload = HostOffloadTier(
                e.host_offload_pages, page_shape, cache_dtype, spill=spill,
            )
            self.allocator.on_park = (
                lambda p, h, par: self._offload_cands.append((p, h, par))
            )

        B = e.max_decode_slots
        self._B = B
        self._slots: list[Optional[_Request]] = [None] * B
        # host mirrors of dispatch-time state (exactly track device values)
        self._pt_disp = np.zeros((B, e.max_pages_per_seq), np.int32)
        self._ctx_disp = np.ones(B, np.int32)
        self._cap_disp = np.full(B, e.page_size, np.int32)

        # device state dict (page tables stay host-side — uploaded
        # width-bucketed per round, so the attention grid tracks actual use)
        self._dev = {
            "tokens": jnp.zeros(B, jnp.int32),
            "ctx": jnp.ones(B, jnp.int32),
            "cap": jnp.full((B,), e.page_size, jnp.int32),
            "keys": jnp.zeros((B, 2), jnp.uint32),
            "counts": jnp.zeros((B, c.vocab_size), jnp.int32),
            "temp": jnp.zeros(B, jnp.float32),
            "top_k": jnp.zeros(B, jnp.int32),
            "top_p": jnp.ones(B, jnp.float32),
            "freq": jnp.zeros(B, jnp.float32),
            "pres": jnp.zeros(B, jnp.float32),
            "rep": jnp.ones(B, jnp.float32),
        }

        self._build_jits()

        self._intake: queue_mod.Queue = queue_mod.Queue()
        self._xfer: queue_mod.Queue = queue_mod.Queue()  # page export/import
        self._waiting: list[_Request] = []
        self._entries: list[_Entry] = []
        self._grow_dirty: set[int] = set()
        self._to_release: list[_Request] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started = False
        self.step_count = 0
        self.tokens_generated = 0

    # ------------------------------------------------------------------
    # jitted programs

    def _build_jits(self) -> None:
        c, e = self.config, self.ecfg
        max_top_k = e.max_top_k

        max_logprobs = e.max_logprobs

        @functools.partial(jax.jit, donate_argnums=(1, 2, 3),
                           static_argnums=(6, 7))
        def engine_round(params, cache, ring, dev, pt, ring_base,
                         n_steps, want_lp):
            """A FULL scheduling round in one program: n_steps fused
            decode+sample steps via lax.fori_loop (body compiles once) and
            the ring->pool flush — one dispatch + one result fetch per
            round instead of n_steps+2, the single biggest lever on
            per-step host overhead. pt is width-bucketed [B, W] (one
            compile per (W, n_steps, want_lp)); `want_lp` adds the logprob
            computation only for rounds that asked for it.

            Flush contract: pt must cover every position written this
            round (the scheduler's _ensure_coverage guarantees it), so the
            bucketed table doubles as the flush table."""
            B = dev["tokens"].shape[0]
            toks_out = jnp.zeros((n_steps, B), jnp.int32)
            lp_out = (
                (jnp.zeros((n_steps, B), jnp.float32),
                 jnp.zeros((n_steps, B, max_logprobs), jnp.int32),
                 jnp.zeros((n_steps, B, max_logprobs), jnp.float32))
                if want_lp else None
            )
            sp = sampling.SamplingParams(
                temperature=dev["temp"], top_k=dev["top_k"], top_p=dev["top_p"],
                frequency_penalty=dev["freq"], presence_penalty=dev["pres"],
                repetition_penalty=dev["rep"],
            )

            def body(s, carry):
                ring, dev, toks_out, lp_out = carry
                ring, logits = llama.decode_step_impl(
                    c, params, cache, ring, dev["tokens"], pt, dev["ctx"],
                    ring_base, s,
                )
                toks, st = sampling.sample_step_impl(
                    logits, sampling.SamplerState(dev["keys"], dev["counts"]),
                    sp, max_top_k,
                )
                toks_out = jax.lax.dynamic_update_index_in_dim(
                    toks_out, toks, s, 0
                )
                if want_lp:
                    chosen, ids, lps = sampling.compute_logprobs(
                        logits, toks, max_logprobs
                    )
                    lp_out = (
                        jax.lax.dynamic_update_index_in_dim(
                            lp_out[0], chosen, s, 0),
                        jax.lax.dynamic_update_index_in_dim(
                            lp_out[1], ids, s, 0),
                        jax.lax.dynamic_update_index_in_dim(
                            lp_out[2], lps, s, 0),
                    )
                dev = dict(
                    dev,
                    tokens=toks,
                    ctx=jnp.minimum(dev["ctx"] + 1, dev["cap"]),
                    keys=st.keys,
                    counts=st.counts,
                )
                return ring, dev, toks_out, lp_out

            ring, dev, toks_out, lp_out = jax.lax.fori_loop(
                0, n_steps, body, (ring, dev, toks_out, lp_out)
            )
            # round boundary: scatter the ring into the pool in-program
            valid = jnp.minimum(
                jnp.int32(n_steps), dev["cap"] - ring_base
            )
            cache = llama.flush_impl(c, cache, ring, pt, ring_base, valid)
            return cache, ring, dev, toks_out, lp_out

        @functools.partial(jax.jit, donate_argnums=(0,))
        def patch(
            dev, clear_mask, grow_mask, cap_new,
            admit_slot, admit_ctx, admit_tok, admit_keys,
            admit_temp, admit_top_k, admit_top_p,
            admit_freq, admit_pres, admit_rep,
        ):
            dev = dict(dev)
            dev["cap"] = jnp.where(grow_mask | clear_mask, cap_new, dev["cap"])
            dev["ctx"] = jnp.where(clear_mask, 1, dev["ctx"])
            dev["tokens"] = jnp.where(clear_mask, 0, dev["tokens"])
            dev["temp"] = jnp.where(clear_mask, 0.0, dev["temp"])
            dev["counts"] = jnp.where(clear_mask[:, None], 0, dev["counts"])
            # single admission (admit_slot == B sentinel -> all .at[] dropped)
            s = admit_slot
            dev["tokens"] = dev["tokens"].at[s].set(admit_tok[0])
            dev["ctx"] = dev["ctx"].at[s].set(admit_ctx)
            dev["keys"] = dev["keys"].at[s].set(admit_keys)
            dev["counts"] = dev["counts"].at[s].set(0)
            dev["temp"] = dev["temp"].at[s].set(admit_temp)
            dev["top_k"] = dev["top_k"].at[s].set(admit_top_k)
            dev["top_p"] = dev["top_p"].at[s].set(admit_top_p)
            dev["freq"] = dev["freq"].at[s].set(admit_freq)
            dev["pres"] = dev["pres"].at[s].set(admit_pres)
            dev["rep"] = dev["rep"].at[s].set(admit_rep)
            return dev

        @functools.partial(jax.jit, static_argnums=(5, 6))
        def sample_first(logits, key, temp, top_k, top_p, vocab, want_lp):
            st = sampling.SamplerState(
                keys=key[None], counts=jnp.zeros((1, vocab), jnp.int32)
            )
            sp = sampling.SamplingParams(
                temperature=temp[None], top_k=top_k[None], top_p=top_p[None],
                frequency_penalty=jnp.zeros(1), presence_penalty=jnp.zeros(1),
                repetition_penalty=jnp.ones(1),
            )
            toks, _ = sampling.sample_step_impl(logits[None], st, sp, max_top_k)
            lp = (sampling.compute_logprobs(logits[None], toks, max_logprobs)
                  if want_lp else None)
            return toks, lp  # [1] i32, optional ([1], [1,K], [1,K])

        self._engine_round = engine_round
        self._patch = patch
        self._sample_first = sample_first

    # ------------------------------------------------------------------
    # lifecycle

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        self._thread = threading.Thread(
            target=self._run_loop, name="tpu-engine-loop", daemon=True
        )
        self._thread.start()

    async def stop(self) -> None:
        self._stop.set()
        if self._thread:
            await asyncio.to_thread(self._thread.join, 30.0)
        # items raced in after the loop's own exit drain
        self._drain_xfer_queue()
        if self.offload is not None and self.offload.spill is not None:
            self.offload.spill.close()

    # ------------------------------------------------------------------
    # AsyncEngine surface

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[LLMEngineOutput]:
        """Stream engine outputs (token-id deltas) for one request."""
        if not self._started:
            self.start()
        if len(request.token_ids) == 0:
            raise ValueError("empty prompt")
        if len(request.token_ids) >= self.ecfg.max_context:
            raise ValueError(
                f"prompt length {len(request.token_ids)} exceeds max context "
                f"{self.ecfg.max_context}"
            )
        r = _Request(
            req=request,
            seq=TokenBlockSequence.from_tokens(
                request.token_ids, self.ecfg.page_size, salt=request.model
            ),
            out=asyncio.Queue(),
            loop=asyncio.get_running_loop(),
            tokens=list(request.token_ids),
        )
        self._intake.put(r)
        try:
            while True:
                item = await r.out.get()
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            r.cancelled = True

    # ------------------------------------------------------------------
    # padded page I/O (shared by transfers, offload, onboard): page lists
    # are pow2-bucketed for compile-cache reuse; padding targets scratch
    # page 0 (garbage by contract)

    def _gather_padded(self, pages: list[int]):
        """Device gather of whole pages; returns the DEVICE array
        [2, L, kvh, pow2(n), ps, hd] — callers slice [:len(pages)] on the
        page axis after fetching."""
        w = pow2_cover(len(pages))
        padded = np.zeros(w, np.int32)
        padded[: len(pages)] = pages
        return llama.gather_pages(self.cache, jnp.asarray(padded))

    def _scatter_padded(self, pages: list[int], data: np.ndarray) -> None:
        """Scatter host pages [2, L, kvh, n, ps, hd] into the pool."""
        n = len(pages)
        w = pow2_cover(n)
        padded = np.zeros(w, np.int32)
        padded[:n] = pages
        if w > n:
            pad_shape = list(data.shape)
            pad_shape[3] = w - n
            data = np.concatenate(
                [data, np.zeros(pad_shape, data.dtype)], axis=3
            )
        self.cache = llama.scatter_pages(
            self.cache, jnp.asarray(padded), jnp.asarray(data)
        )

    # ------------------------------------------------------------------
    # KV page export/import (block-transfer data plane hooks;
    # kv_transfer.py BlockTransferServer read_fn/write_fn)

    def export_pages(self, page_ids: list[int]) -> np.ndarray:
        """Gather whole pages to host: [2, L, kvh, n, ps, hd]. Thread-safe —
        blocks the CALLER until the engine loop services it at a round
        boundary (device-order safe w.r.t. in-flight steps)."""
        return self._xfer_op("export", page_ids, None)

    def import_pages(self, page_ids: list[int], data: np.ndarray) -> None:
        """Scatter host pages into the pool (inverse of export_pages)."""
        self._xfer_op("import", page_ids, data)

    def _xfer_op(self, kind: str, page_ids: list[int], data) -> Any:
        if self._stop.is_set():
            raise RuntimeError("engine stopped")
        if not self._started:
            self.start()
        done = threading.Event()
        box: dict[str, Any] = {}
        self._xfer.put((kind, list(page_ids), data, done, box))
        # wait in slices. On stop, the loop-exit drain (or stop()'s final
        # drain) errors still-queued items; an in-flight op completes and
        # reports its real result — we only bound the wait, never clobber
        # the box ourselves (that would misreport a completed transfer).
        deadline = time.monotonic() + 120.0
        stop_grace: Optional[float] = None
        while not done.wait(timeout=1.0):
            now = time.monotonic()
            if self._stop.is_set():
                if stop_grace is None:
                    stop_grace = now + 10.0
                elif now > stop_grace:
                    raise RuntimeError(f"engine stopped during page {kind}")
            elif now > deadline:
                raise TimeoutError(f"page {kind} timed out")
        if "error" in box:
            raise box["error"]
        return box.get("result")

    def _process_transfers(self) -> None:
        while True:
            try:
                kind, ids, data, done, box = self._xfer.get_nowait()
            except queue_mod.Empty:
                return
            try:
                if kind == "export":
                    out = self._gather_padded(ids)
                    box["result"] = np.asarray(out)[:, :, :, : len(ids)]
                elif kind == "clear":
                    n = self.allocator.clear()
                    self._offload_cands.clear()  # parked refs now stale
                    if self.offload is not None:
                        n += self.offload.clear()
                        # in-flight D2H offload batches would repopulate
                        # the tiers after the clear — drop them (their
                        # fetches complete harmlessly, results unused)
                        self._entries = [
                            en for en in self._entries
                            if en.kind != "offload"
                        ]
                    box["result"] = n
                else:
                    self._scatter_padded(ids, data)
                    box["result"] = None
            except Exception as e:  # noqa: BLE001 — surface to the caller
                box["error"] = e
            finally:
                done.set()

    def clear_kv_blocks(self) -> int:
        """Drop all reusable cached pages across every tier (G1 HBM LRU +
        G2 DRAM + G3 disk) — the /clear_kv_blocks operation (reference
        http/service/clear_kv_blocks.rs). In-use pages survive. Thread-safe:
        serviced by the engine loop at a round boundary."""
        return self._xfer_op("clear", [], None)

    def embed(self, token_ids: list[int]) -> list[float]:
        """Mean-pooled normalized embedding of a prompt (the /v1/embeddings
        surface). Cache-free encoder pass over read-only params — safe to
        call from any thread, concurrent with serving. Bounded by
        max_context: the O(T^2) one-shot attention would otherwise let one
        long input OOM the device serving everyone."""
        if not token_ids:
            raise ValueError("empty input")
        if len(token_ids) > self.ecfg.max_context:
            raise ValueError(
                f"input length {len(token_ids)} exceeds max context "
                f"{self.ecfg.max_context}"
            )
        T = pow2_cover(max(len(token_ids), 8))
        toks = np.zeros(T, np.int32)
        toks[: len(token_ids)] = token_ids
        out = llama.encode(
            self.config, self.params, jnp.asarray(toks),
            jnp.int32(len(token_ids)),
        )
        return np.asarray(out, np.float32).tolist()

    def metrics(self) -> ForwardPassMetrics:
        a = self.allocator
        return ForwardPassMetrics(
            worker_id=self.ecfg.worker_id,
            worker_stats=WorkerStats(
                request_active_slots=sum(s is not None for s in self._slots),
                request_total_slots=self._B,
                num_requests_waiting=len(self._waiting) + self._intake.qsize(),
            ),
            kv_stats=KvStats(
                kv_active_blocks=a.active_pages,
                kv_total_blocks=a.total_pages,
                gpu_cache_usage_perc=a.usage(),
                gpu_prefix_cache_hit_rate=a.hit_rate(),
                host_blocks=len(self.offload) if self.offload else 0,
                host_total_blocks=(
                    self.offload.num_pages if self.offload else 0
                ),
                host_onboard_hits=(
                    self.offload.onboard_hits if self.offload else 0
                ),
                disk_blocks=(
                    len(self.offload.spill)
                    if self.offload and self.offload.spill else 0
                ),
                disk_total_blocks=(
                    self.offload.spill.num_pages
                    if self.offload and self.offload.spill else 0
                ),
            ),
        )

    # ------------------------------------------------------------------
    # engine loop

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            try:
                did_work = self._round()
            except Exception:  # noqa: BLE001 — engine loop must survive
                log.exception("engine round failed")
                self._fail_all(RuntimeError("engine step failed; see logs"))
                did_work = False
            if not did_work:
                try:
                    self._waiting.append(self._intake.get(timeout=0.02))
                except queue_mod.Empty:
                    pass
        self._drain_xfer_queue()

    def _drain_xfer_queue(self) -> None:
        """Abandon queued transfer ops with an error, not a 120s stall.
        Only touches items still IN the queue — an in-flight op finishes
        normally and reports its real result."""
        while True:
            try:
                *_ignored, done, box = self._xfer.get_nowait()
            except queue_mod.Empty:
                break
            box["error"] = RuntimeError("engine stopped")
            done.set()

    def _round(self) -> bool:
        """One scheduling round: process ready results, apply patches
        (releases, admissions, page growth), dispatch a round of steps."""
        e = self.ecfg
        self._drain_intake()
        rounds_in_flight = sum(1 for en in self._entries if en.kind == "round")
        self._process_entries(block=rounds_in_flight > e.max_inflight_rounds)
        self._apply_releases()
        self._process_transfers()
        self._dispatch_offloads()
        self._admit()

        active = [i for i, s in enumerate(self._slots) if s is not None]
        did_work = bool(self._entries)
        rounds_in_flight = sum(1 for en in self._entries if en.kind == "round")
        if active and rounds_in_flight <= e.max_inflight_rounds:
            self._dispatch_round(active)
            did_work = True
        if self.on_metrics is not None:
            self.on_metrics(self.metrics())
        return did_work

    def _drain_intake(self) -> None:
        while True:
            try:
                self._waiting.append(self._intake.get_nowait())
            except queue_mod.Empty:
                return

    # ---- dispatch side ----

    def _dispatch_round(self, active: list[int]) -> None:
        """Dispatch flush_every fused steps + one stacked-token fetch."""
        e = self.ecfg
        n = e.flush_every
        if not self._ensure_coverage(active, n):
            active = [i for i, s in enumerate(self._slots) if s is not None]
            if not active:
                return
        # width-bucketed page-table upload (uploads are cheap/async)
        widest = max(
            (len(self._slots[i].pages) for i in active), default=1
        )
        w = min(pow2_cover(widest, lo=2), e.max_pages_per_seq)
        pt_dev = jnp.asarray(self._pt_disp[:, :w])
        # ring slot 0 holds the position decoded by this round's first step
        ring_base_np = np.maximum(self._ctx_disp - 1, 0)
        ring_base = jnp.asarray(ring_base_np)
        want_lp = any(
            self._slots[i] is not None
            and not self._slots[i].finished
            and self._slots[i].req.output_options.logprobs is not None
            for i in active
        )
        # one fused program: n decode+sample steps + flush (see engine_round)
        self.cache, self.ring, self._dev, stacked, lp_stacked = (
            self._engine_round(
                self.params, self.cache, self.ring, self._dev, pt_dev,
                ring_base, n, want_lp,
            )
        )
        self._ctx_disp = np.minimum(self._ctx_disp + n, self._cap_disp)
        self.step_count += n
        stacked.copy_to_host_async()
        if lp_stacked is not None:
            for arr in lp_stacked:
                arr.copy_to_host_async()
        self._entries.append(
            _Entry(
                kind="round",
                handle=stacked,
                slots=list(self._slots),
                n_steps=n,
                lp_handle=lp_stacked,
            )
        )

    def _ensure_coverage(self, active: list[int], n_steps: int) -> bool:
        """Make every active slot's page table cover the positions the next
        n_steps will write; allocate/preempt as needed. Returns False if any
        preemption happened (caller must recompute the active set)."""
        e = self.ecfg
        ps = e.page_size
        clean = True
        for slot in list(active):
            r = self._slots[slot]
            if r is None or r.finished:
                continue  # finished slots garbage-write within their cap
            # last position written in this round = ctx_disp - 1 + n_steps
            need_pos = min(int(self._ctx_disp[slot]) - 1 + n_steps,
                           e.max_context - 1)
            need_pages = need_pos // ps + 1
            while len(r.pages) < need_pages:
                got = self.allocator.allocate(1)
                if got is None:
                    self._preempt_for_space(slot)
                    clean = False
                    if self._slots[slot] is None:
                        break
                    continue
                r.pages.extend(got)
                self._pt_disp[slot, len(r.pages) - 1] = got[0]
            if self._slots[slot] is not None:
                new_cap = min(len(r.pages) * ps, e.max_context)
                if new_cap != self._cap_disp[slot]:
                    self._cap_disp[slot] = new_cap
                    self._grow_dirty.add(slot)
        if self._grow_dirty:
            self._dispatch_patch(grow_slots=sorted(self._grow_dirty))
            self._grow_dirty.clear()
        return clean

    def _dispatch_patch(
        self,
        grow_slots: list[int] = (),
        clear_slots: list[int] = (),
        admit: Optional[dict[str, Any]] = None,
    ) -> None:
        B = self._B
        clear = np.zeros(B, bool)
        grow = np.zeros(B, bool)
        for s in clear_slots:
            clear[s] = True
        for s in grow_slots:
            grow[s] = True
        a = admit or {}
        self._dev = self._patch(
            self._dev,
            jnp.asarray(clear),
            jnp.asarray(grow),
            jnp.asarray(self._cap_disp),
            jnp.int32(a.get("slot", B)),
            jnp.int32(a.get("ctx", 1)),
            a.get("tok", jnp.zeros(1, jnp.int32)),
            jnp.asarray(a.get("keys", np.zeros(2, np.uint32))),
            jnp.float32(a.get("temp", 0.0)),
            jnp.int32(a.get("top_k", 0)),
            jnp.float32(a.get("top_p", 1.0)),
            jnp.float32(a.get("freq", 0.0)),
            jnp.float32(a.get("pres", 0.0)),
            jnp.float32(a.get("rep", 1.0)),
        )

    # ---- offload (G2 tier) ----

    def _dispatch_offloads(self) -> None:
        """Batch-gather validated park candidates and fetch them to host
        behind compute. Runs BEFORE admission so same-round allocations
        cannot recycle a candidate page between validation and the gather
        dispatch (device-order then guarantees the gather reads the
        pre-recycle content anyway; validation just avoids wasted work)."""
        if self.offload is None or not self._offload_cands:
            return
        batch: list[tuple[int, int, int]] = []
        while len(batch) < self.ecfg.offload_batch:
            try:
                cand = self._offload_cands.popleft()
            except IndexError:
                break
            page, h, _parent = cand
            if h in self.offload:
                continue
            if self.allocator.page_for_hash(h) != page:
                continue  # evicted/recycled since parking
            batch.append(cand)
        if not batch:
            return
        out = self._gather_padded([p for p, _, _ in batch])
        out.copy_to_host_async()
        self._entries.append(_Entry(
            kind="offload", handle=out, n_steps=len(batch),
            hashes=[h for _, h, _ in batch],
            parents=[par for _, _, par in batch],
        ))

    def _onboard_from_host(
        self, hashes: list[int], matched_pages: list[int]
    ) -> list[int]:
        """Extend a G1 prefix match with a contiguous run held in the G2
        host tier: allocate pages, scatter (async H2D — prefill follows in
        device order), commit under the same chained hashes."""
        if self.offload is None:
            return matched_pages
        m = len(matched_pages)
        run = self.offload.lookup_run(hashes[m:])
        if not run:
            return matched_pages
        pages = self.allocator.allocate(len(run))
        if pages is None:
            return matched_pages
        self._scatter_padded(pages, self.offload.gather([h for h, _ in run]))
        for pg, (h, parent) in zip(pages, run):
            self.allocator.commit(pg, h, parent)
        log.debug("onboarded %d blocks from host tier", len(pages))
        return matched_pages + pages

    # ---- admission / prefill ----

    def _admit(self) -> None:
        kept = []
        for r in self._waiting:
            if r.cancelled:
                if r.pages:  # half-prefilled head: release its pages
                    self.allocator.free(r.pages)
                    r.pages = []
            else:
                kept.append(r)
        self._waiting = kept
        # bounded prefill budget per round: a long prompt advances one
        # chunk at a time with decode rounds in between (ITL isolation,
        # the local form of what disagg provides globally)
        budget = max(1, self.ecfg.prefill_chunks_per_round)
        while budget > 0 and self._waiting and None in self._slots:
            r = self._waiting[0]
            status = self._prefill_step(r)
            budget -= 1
            if status == "blocked":
                return  # head-of-line blocks until pages free up
            if status in ("done", "failed"):
                self._waiting.pop(0)

    def _prefill_step(self, r: _Request) -> str:
        """Advance one prefill chunk; on the final chunk, sample the first
        token on device and assign a slot. Returns blocked | progress |
        done | failed."""
        e = self.ecfg
        ps = e.page_size
        prompt = r.tokens

        if r.prefill_pos < 0:
            # start: prefix match (HBM, then host tier) + full allocation
            hashes = r.seq.block_hashes()
            matchable = hashes[: max(0, (len(prompt) - 1) // ps)]
            matched_pages = self.allocator.match_prefix(matchable)
            matched_pages = self._onboard_from_host(matchable, matched_pages)
            n_total_pages = (len(prompt) + ps - 1) // ps
            if n_total_pages > e.max_pages_per_seq:
                self.allocator.free(matched_pages)
                r.emit(ValueError("prompt does not fit page table"))
                return "failed"
            fresh = self.allocator.allocate(
                n_total_pages - len(matched_pages)
            )
            if fresh is None:
                self.allocator.free(matched_pages)
                return "blocked"
            r.pages = matched_pages + fresh
            r.matched_blocks = len(matched_pages)
            r.prefill_pos = len(matched_pages) * ps

        # one page-aligned continuation chunk (q_start advances); only the
        # final chunk's logits matter
        max_chunk = ((e.prefill_buckets[-1] + ps - 1) // ps) * ps
        start = r.prefill_pos
        chunk = prompt[start : start + max_chunk]
        pad_t = e.bucket_for(len(chunk)) or max_chunk
        pad_t = ((pad_t + ps - 1) // ps) * ps
        toks = np.zeros(pad_t, np.int32)
        toks[: len(chunk)] = chunk
        # width-bucketed table (pow2 cover of pages in play); one
        # compile per (bucket, width) pair
        w = min(pow2_cover(start // ps + pad_t // ps, lo=2),
                e.max_pages_per_seq)
        table = np.zeros(w, np.int32)
        table[: len(r.pages)] = r.pages[:w]
        self.cache, logits = llama.prefill(
            self.config, self.params, self.cache,
            jnp.asarray(toks), jnp.asarray(table),
            jnp.int32(start), jnp.int32(start + len(chunk)),
        )
        r.prefill_pos = start + len(chunk)
        if r.prefill_pos < len(prompt):
            return "progress"  # decode rounds run before the next chunk

        # final chunk: commit complete prompt blocks beyond the match
        for blk in r.seq.blocks[r.matched_blocks:]:
            self.allocator.commit(
                r.pages[blk.position], blk.block_hash, blk.parent_hash
            )

        so = r.req.sampling_options
        if so.seed is not None:
            # seeded: fully reproducible keys derived from the seed alone
            first_key = np.array([_FIRST_TOKEN_KEY_TAG, so.seed], np.uint32)
            step_keys = np.array([0, so.seed], np.uint32)
        else:
            # unseeded: fresh entropy per request — two identical prompts
            # must NOT produce identical outputs (landing on the same slot
            # previously reused the [0, slot+1] key stream)
            nonce = np.frombuffer(os.urandom(8), np.uint32).copy()
            first_key = np.array(
                [_FIRST_TOKEN_KEY_TAG ^ int(nonce[0]), int(nonce[1])], np.uint32
            )
            step_keys = nonce
        want_lp = r.req.output_options.logprobs is not None
        first_tok, first_lp = self._sample_first(
            logits,
            jnp.asarray(first_key),
            jnp.float32(so.temperature or 0.0),
            jnp.int32(so.top_k or 0),
            jnp.float32(so.top_p if so.top_p is not None else 1.0),
            self.config.vocab_size,
            want_lp,
        )

        slot = self._slots.index(None)
        r.slot = slot
        self._slots[slot] = r
        self._pt_disp[slot] = 0
        self._pt_disp[slot, : len(r.pages)] = r.pages
        self._ctx_disp[slot] = len(prompt) + 1
        self._cap_disp[slot] = min(len(r.pages) * ps, e.max_context)
        self._dispatch_patch(
            grow_slots=[slot],
            admit=dict(
                slot=slot,
                ctx=len(prompt) + 1,
                tok=first_tok,
                keys=step_keys,
                temp=so.temperature or 0.0,
                top_k=so.top_k or 0,
                top_p=so.top_p if so.top_p is not None else 1.0,
                freq=so.frequency_penalty or 0.0,
                pres=so.presence_penalty or 0.0,
                rep=so.repetition_penalty or 1.0,
            ),
        )
        # first token reaches the client via the async fetch pipeline
        first_tok.copy_to_host_async()
        if first_lp is not None:
            for arr in first_lp:
                arr.copy_to_host_async()
        self._entries.append(_Entry(
            kind="first", handle=first_tok, request=r, lp_handle=first_lp
        ))
        return "done"

    # ---- processing side (lagged results) ----

    def _process_entries(self, block: bool = False) -> None:
        while self._entries:
            entry = self._entries[0]
            if not block and not entry.handle.is_ready():
                return
            self._entries.pop(0)
            data = np.asarray(entry.handle)
            if entry.kind == "first":
                lp = None
                if entry.lp_handle is not None:
                    chosen, ids, lps = (np.asarray(a) for a in entry.lp_handle)
                    lp = (float(chosen[0]), ids[0], lps[0])
                self._process_first(entry.request, int(data[0]), lp)
            elif entry.kind == "offload":
                self.offload.put_batch(
                    entry.hashes, entry.parents,
                    data[:, :, :, : entry.n_steps],
                )
            else:
                self._process_round(entry, data)
            block = False  # only force at most one blocking wait

    def _lp_payload(self, r: _Request, lp) -> dict:
        """LLMEngineOutput logprob fields for one emitted token."""
        n_req = r.req.output_options.logprobs
        if lp is None or n_req is None:
            return {}
        chosen, ids, lps = lp
        n = min(int(n_req), self.ecfg.max_logprobs)
        pairs = [[int(i), float(v)] for i, v in zip(ids[:n], lps[:n])]
        return {"log_probs": [float(chosen)], "top_logprobs": [pairs]}

    def _process_first(self, r: _Request, tok: int, lp=None) -> None:
        if r.cancelled or r.finished:
            self._finish(r, None)
            return
        if r.first_token_time is None:
            r.first_token_time = time.monotonic()
        sc = r.req.stop_conditions
        if not sc.ignore_eos and tok in (sc.stop_token_ids or []) and (
            sc.min_tokens is None or r.produced >= sc.min_tokens
        ):
            self._finish(r, FinishReason.EOS)
            return
        r.last_token = tok
        r.produced += 1  # may continue a preempted request's count
        r.emit(LLMEngineOutput(token_ids=[tok], **self._lp_payload(r, lp)))
        if r.produced >= r.max_new_tokens(self.ecfg.max_context):
            self._finish(r, FinishReason.LENGTH, emit_empty=True)

    def _process_round(self, entry: _Entry, toks: np.ndarray) -> None:
        lp_arrs = None
        if entry.lp_handle is not None:
            lp_arrs = tuple(np.asarray(a) for a in entry.lp_handle)
        for step in range(entry.n_steps):
            for slot, r in enumerate(entry.slots):
                # identity check doubles as the epoch: a recycled slot holds
                # a different _Request object than the snapshot
                if r is None or r.finished or self._slots[slot] is not r:
                    continue
                if r.cancelled:
                    self._finish(r, None)
                    continue
                lp = None
                if lp_arrs is not None:
                    lp = (float(lp_arrs[0][step, slot]),
                          lp_arrs[1][step, slot], lp_arrs[2][step, slot])
                self._consume_token(r, int(toks[step, slot]), lp)
        self.tokens_generated += int(
            sum(1 for s in entry.slots if s is not None) * entry.n_steps
        )

    def _consume_token(self, r: _Request, tok: int, lp=None) -> None:
        sc = r.req.stop_conditions
        # seal/commit the block completed by the previous token
        if r.last_token >= 0:
            for blk in r.seq.extend([r.last_token]):
                if blk.position < len(r.pages):
                    self.allocator.commit(
                        r.pages[blk.position], blk.block_hash, blk.parent_hash
                    )
        if not sc.ignore_eos and tok in (sc.stop_token_ids or []) and (
            sc.min_tokens is None or r.produced >= sc.min_tokens
        ):
            self._finish(r, FinishReason.EOS, emit_empty=True)
            return
        r.last_token = tok
        r.produced += 1
        if r.produced >= r.max_new_tokens(self.ecfg.max_context):
            r.emit(LLMEngineOutput(token_ids=[tok],
                                   finish_reason=FinishReason.LENGTH,
                                   **self._lp_payload(r, lp)))
            self._finish(r, None)
            return
        r.emit(LLMEngineOutput(token_ids=[tok], **self._lp_payload(r, lp)))

    def _finish(
        self,
        r: _Request,
        reason: Optional[FinishReason],
        emit_empty: bool = False,
    ) -> None:
        """Mark finished on host; slot is reclaimed via a release patch at
        the next round boundary. The final (possibly just-sealed) block is
        NOT committed — in-flight garbage steps may still write its page."""
        if r.finished:
            return
        r.finished = True
        if reason is not None:
            r.emit(LLMEngineOutput(token_ids=[], finish_reason=reason))
        self._to_release.append(r)

    def _apply_releases(self) -> None:
        # also sweep cancelled requests that never got a finish event
        for slot, r in enumerate(self._slots):
            if r is not None and r.cancelled and not r.finished:
                r.finished = True
                self._to_release.append(r)
        if not self._to_release:
            return
        clear_slots = []
        for r in self._to_release:
            self.allocator.free(r.pages)
            r.pages = []
            if r.slot >= 0 and self._slots[r.slot] is r:
                clear_slots.append(r.slot)
                self._slots[r.slot] = None
                self._pt_disp[r.slot] = 0
                self._ctx_disp[r.slot] = 1
                self._cap_disp[r.slot] = self.ecfg.page_size
            r.slot = -1
        self._to_release = []
        if clear_slots:
            self._dispatch_patch(clear_slots=clear_slots)

    # ---- preemption ----

    def _preempt_for_space(self, needing_slot: int) -> None:
        """Free pages by preempting the most recently admitted other request
        (LIFO keeps older requests progressing); preempts `needing_slot`
        itself only when it is the sole occupant."""
        victims = [
            s for s in self._slots
            if s is not None and not s.finished and s.slot != needing_slot
        ]
        victim = max(victims, key=lambda r: r.enqueue_time) if victims else (
            self._slots[needing_slot]
        )
        if victim is None:
            return
        slot = victim.slot
        self.allocator.free(victim.pages)
        victim.pages = []
        # restart = everything processed so far + pending token as new prompt
        new_prompt = victim.seq.tokens + (
            [victim.last_token] if victim.last_token >= 0 else []
        )
        victim.tokens = new_prompt
        victim.seq = TokenBlockSequence.from_tokens(
            new_prompt, self.ecfg.page_size, salt=victim.req.model
        )
        victim.last_token = -1
        victim.matched_blocks = 0
        victim.prefill_pos = -1  # restart prefill from scratch
        self._slots[slot] = None
        self._pt_disp[slot] = 0
        self._ctx_disp[slot] = 1
        self._cap_disp[slot] = self.ecfg.page_size
        victim.slot = -1
        self._dispatch_patch(clear_slots=[slot])
        # never jump AHEAD of a half-prefilled head: it already holds its
        # full page allocation and only needs budget (and the slot this
        # preemption just freed) to finish — queueing the victim in front
        # would deadlock (victim can't allocate, head can't reach budget)
        pos = 1 if (self._waiting
                    and self._waiting[0].prefill_pos >= 0) else 0
        self._waiting.insert(pos, victim)
        log.info("preempted request %s", victim.req.request_id)

    def _fail_all(self, err: Exception) -> None:
        for r in list(self._slots):
            if r is not None:
                r.emit(err)
                r.finished = True
                self.allocator.free(r.pages)
                r.pages = []
        self._slots = [None] * self._B
        for r in self._waiting:
            r.emit(err)
            if r.pages:  # half-prefilled head holds pages
                self.allocator.free(r.pages)
                r.pages = []
        self._waiting = []
        self._entries = []

