"""The TPU engine: continuous batching over a paged KV cache.

This is the layer the reference outsources to vLLM/SGLang/TRT-LLM
subprocesses (SURVEY.md §2.1 L3, launch/dynamo-run/src/subprocess/*). Here it
is native: a JAX model (dynamo_tpu.models) driven by a host-side scheduler —
bucketed prefill, fixed-slot decode batch, page allocator with prefix reuse,
on-device sampling — exposed through the AsyncEngine contract
(generate(PreprocessedRequest) -> stream of LLMEngineOutput).
"""

from dynamo_tpu.engine.config import EngineConfig  # noqa: F401
