"""Engine runtime configuration (the vLLM-engine-args equivalent —
reference MockEngineArgs mocker/protocols.rs:72-94 and vllm_inc.py flags)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


def _default_buckets() -> tuple[int, ...]:
    return (128, 256, 512, 1024, 2048, 4096)


def pow2_cover(n: int, lo: int = 1) -> int:
    """Smallest power of two >= max(n, lo) — the compile-cache bucketing
    used for page-table widths, transfer sizes, and the speculative round
    width (padding always targets scratch page 0 / the scratch lane).
    Lives here (not engine.py) so spec/ can use it without a
    module-scope import of the engine."""
    w = lo
    while w < n:
        w *= 2
    return w


@dataclass
class EngineConfig:
    """Knobs of the continuous-batching TPU engine."""

    # prefix-cache pool (round-4 layout: the paged pool is prefix-cache
    # STORAGE; the serving context is a contiguous per-slot region —
    # models/llama.py module doc)
    num_pages: int = 512          # pool capacity incl. reserved page 0
    page_size: int = 64           # tokens per page (also the router block size)
    # per-slot context capacity in pages: max_context = this * page_size
    # (sizes the contiguous ctx region, (slots+1) * max_context * kv)
    max_pages_per_seq: int = 64

    # batching
    max_decode_slots: int = 8     # fixed decode batch width
    prefill_buckets: tuple[int, ...] = field(default_factory=_default_buckets)

    # pipelining: steps per dispatched round (one fused jit + one stacked
    # token fetch + one ring->ctx flush per round) and rounds allowed in
    # flight before the loop blocks on results.
    # Effective host lag = flush_every * (max_inflight_rounds + 1) steps —
    # finished requests garbage-decode for up to that many steps, so raise
    # these only when D2H latency is high relative to step time.
    flush_every: int = 4
    max_inflight_rounds: int = 2
    # double-buffered round pipelining: dispatch round N+1's fused
    # program BEFORE consuming round N's packed fetch, so round N's
    # host-side bookkeeping (emit, releases, transfers, offload) runs
    # while round N+1 executes on device and steady-state wall-clock
    # approaches max(host, device) instead of host + device. The
    # pipeline flushes (falls back to the strict process-then-dispatch
    # order) whenever slot state is about to change under it:
    # admissions/prefills, pending release patches, seal-queue overflow
    # past the fused width, speculating slots, and drain. `off` restores
    # the pre-pipelining round order exactly (the differential tests
    # compare the two).
    round_pipeline: bool = True
    # prefill chunks dispatched per scheduling round: bounds how long a
    # round can stall decode behind prompt processing (the ITL-interference
    # problem disagg solves globally; this bounds it locally)
    prefill_chunks_per_round: int = 2
    # batched multi-request prefill (models/llama.py batch_prefill — the
    # vLLM max_num_batched_tokens analogue): concurrent same-bucket chunks
    # run as ONE [K, T] program. K is compiled at
    # min(prefill_batch_max, prefill_token_budget // T) and short groups
    # are padded with scratch-lane dummies — one compilation per (T, ctx)
    # shape instead of one per group size (compiles cost 20-40s on the
    # tunneled dev chip). 1 disables batching.
    prefill_batch_max: int = 8
    prefill_token_budget: int = 8192

    # sampling
    max_top_k: int = 64           # static top-k width for top-p/top-k sampling
    # static top-N width for logprobs (OpenAI caps top_logprobs at 20);
    # requests asking for logprobs compile the lp variant of the step
    max_logprobs: int = 20

    # speculative decoding (dynamo_tpu/spec/): "off" | "ngram" | "draft".
    # ngram needs no extra model (prompt-lookup against the request's own
    # history); draft needs a draft_config/draft_params pair passed to
    # TpuEngine (a small model sharing the target tokenizer). Eligible
    # slots (no penalties/logprobs) verify K proposed tokens per target
    # forward instead of taking the fused decode round.
    speculative: str = "off"
    num_speculative_tokens: int = 4   # K proposals per verify step (the CAP
                                      # when spec_adaptive is on)
    spec_ngram_max: int = 3           # longest tail n-gram to match
    spec_ngram_min: int = 1
    # acceptance-adaptive K (spec/decoder.py AdaptiveKController): each
    # slot's effective K walks within [spec_min_k, num_speculative_tokens]
    # on an EWMA of its per-step acceptance fraction — grow above
    # grow_threshold, shrink below shrink_threshold; a slot whose rate
    # stays at/below despec_threshold after spec_min_observations verify
    # steps de-speculates back to the fused decode round (speculation is
    # actively costing it a full forward per ~1 emitted token there).
    # The round's draft/verify width is the bucketed max of the
    # participants' effective K, so an all-low-acceptance batch really
    # does less device work per round.
    spec_adaptive: bool = True
    spec_min_k: int = 1
    spec_grow_threshold: float = 0.8
    spec_shrink_threshold: float = 0.4
    spec_despec_threshold: float = 0.125
    spec_rate_ewma: float = 0.75      # weight of history in the rolling rate
    spec_min_observations: int = 8    # verify steps before despec may fire
    # fuse draft proposing across slots into ONE llama.batch_draft program
    # per round (False = legacy per-slot dispatch loop, kept for A/B
    # dispatch-overhead measurement in bench/profile_round)
    spec_batch_draft: bool = True
    # tree speculation (spec/verifier.py spec_verify_tree): proposals
    # form a packed token tree — up to spec_branches candidates per
    # divergence point — verified in ONE forward under a tree-causal
    # ancestor mask; acceptance walks the deepest surviving root-to-leaf
    # path and commits only that path's KV rows. spec_tree_budget bounds
    # the packed node count (root included) so one compiled verify shape
    # serves every tree; 0 = auto (1 + K * branches, the full comb).
    spec_tree: bool = False
    spec_branches: int = 4
    spec_tree_budget: int = 0
    # acceptance gating: a stream whose live acceptance EWMA stays below
    # spec_gate_acceptance for spec_gate_window consecutive verify steps
    # de-speculates back to the fused round (0.0 disables the gate —
    # adaptive-K despec still applies); it may re-arm after
    # spec_rearm_tokens emitted tokens (doubling each time it re-gates),
    # so chat-shaped traffic stops paying draft overhead while a stream
    # that turns repetitive mid-flight gets another chance
    spec_gate_acceptance: float = 0.0
    spec_gate_window: int = 4
    spec_rearm_tokens: int = 256

    # overload plane (dynamo_tpu/overload/): bounded admission. Intake
    # past either budget raises the retriable EngineOverloadedError
    # (surfacing as HTTP 429 + Retry-After at the frontend) instead of
    # growing the waiting queue — and every admitted request's TTFT —
    # without limit. 0 = unbounded (the pre-overload-plane behavior).
    max_waiting_requests: int = 0
    # prompt-token budget over the same backlog: ten 10k-token prompts
    # are a different storm than ten 10-token ones
    max_waiting_prefill_tokens: int = 0
    # priority preemption, running half: allow a waiting HIGH-priority
    # request to force-evict the lowest-priority RUNNING stream when no
    # lane is free — the victim's stream fails with the retriable
    # PreemptedError, which the router turns into a live migration
    # (replay prompt+emitted on a peer, exactly-once, greedy
    # token-identical). Waiting-entry preemption is always on once
    # budgets are set; this flag gates only the running case.
    preempt_running: bool = False

    # prefix cache
    enable_prefix_caching: bool = True

    # sequence-parallel ring prefill (ops/ring_attention.py): prompts of at
    # least this many tokens run as ONE whole-prompt ring-attention pass
    # over the mesh's `sp` axis instead of chunked local prefill. None
    # disables. Requires the engine mesh to have sp > 1; the long-context
    # path the reference lacks (SURVEY §2.5 SP row).
    sp_prefill_threshold: Optional[int] = None

    # host-DRAM offload tier (KVBM G2): 0 disables. Pages parked in the
    # LRU are asynchronously copied to a host pool of this many pages;
    # prefix misses in HBM onboard from it instead of recomputing.
    host_offload_pages: int = 0
    # mmap-backed disk tier (KVBM G3, reference storage/disk.rs:25): 0
    # disables. G2's LRU evictions spill into it; requires G2 enabled
    # (the tier hierarchy is strict: G1 -> G2 -> G3).
    disk_offload_pages: int = 0
    # backing file for the G3 pool (None = fresh tempfile per engine).
    # With a path the tier is restart-survivable: a sidecar manifest
    # (<path>.manifest) journals slot->(hash, crc) and is replayed at
    # attach (kv_integrity plane).
    disk_offload_path: Optional[str] = None
    # eager G3 startup scrub: re-checksum every manifest entry against
    # the backing file at attach, dropping mismatches (torn writes come
    # back as misses). Off = lazy verify at onboard gather — same
    # safety, the scrub cost is paid per hit instead of up front.
    scrub_on_start: bool = False
    # offload dispatch cap per scheduling round (bounds the per-round
    # gather size; pow2-bucketed for compile-cache reuse)
    offload_batch: int = 8

    # chunk-pipelined KV-transfer plane (kv_transfer.py / disagg.py):
    # bulk KV moves (remote-prefill pushes, G4 peer fetches, G2/G3
    # onboard scatters) run as a pipeline of this many pages per chunk
    # instead of one monolithic blob — transfer overlaps compute and
    # peak host staging drops from O(transfer) to O(chunk). 0 restores
    # the monolithic path.
    kv_transfer_chunk_pages: int = 8
    # chunk gathers/D2H copies allowed in flight per export stream (the
    # double-buffer depth: chunk i's D2H overlaps chunk i+1's gather)
    kv_transfer_inflight_chunks: int = 2
    # deadline for one queued page export/import op (engine._xfer_op).
    # A multi-GiB chunked import on a slow host link can legitimately
    # exceed the old hard-coded 120 s.
    xfer_op_timeout_s: float = 120.0
    # idle-timeout on a chunked export STREAM's backpressure: a receiver
    # that stalls mid-pull (dead peer connection, wedged link) parks the
    # stream with a full chunk queue; after this long without progress
    # the engine reclaims its pinned gather handles/page refs and errors
    # the consumer queue. Separate from xfer_op_timeout_s — a healthy
    # multi-GiB import may take minutes, but a stream that moved NOTHING
    # for 15 s is abandoned.
    kv_transfer_stream_idle_timeout_s: float = 15.0

    # flight recorder (telemetry/flight.py): ring capacity of recent
    # engine-round events served at /debug/flight and dumped to the log
    # when an engine round fails
    flight_recorder_events: int = 256

    # performance-attribution plane (telemetry/prof.py): per-round
    # host-segment timers feeding dynamo_host_round_seconds{segment} and
    # /debug/prof. Always-on by design (near-zero overhead, pinned by
    # tests/test_prof.py); the switch exists for A/B measurement.
    prof_attribution: bool = True
    # SLO targets backing the dynamo_slo_{ttft,itl}_burn_rate gauges:
    # burn rate = frac-of-observations-over-target / (1 - objective),
    # recomputed from the live histograms at the metrics-publish cadence
    slo_ttft_target_s: float = 0.5
    slo_itl_target_s: float = 0.05
    slo_objective: float = 0.99
    # tail-latency forensics (telemetry/forensics.py): fraction of
    # NON-breaching finishes that still get a dossier captured worker-side
    # when no in-process frontend owns the request's trace. SLO breaches
    # are always captured; this adds a healthy-baseline sample for
    # comparison. 0 disables sampling (breach capture stays on).
    forensics_sample_rate: float = 0.0

    # model memory
    cache_dtype: str = "bfloat16"
    # KV quantization: "none" (everything stores cache_dtype, the
    # legacy A/B path) | "int8" (pool AND serving ctx store int8 with
    # per-block-per-layer absmax scales — the ctx scale grid uses
    # group == page_size, so seal/admission pool<->ctx copies are RAW
    # int8 page moves with no quant/dequant pass at all). Prefill/span
    # writes quantize on store, the once-per-round ring flush
    # requantizes the touched scale groups, and the flash-decode kernel
    # dequantizes each KV chunk in VMEM right after the DMA — live-
    # context HBM traffic per step is ~halved while the QK/PV dots stay
    # in the compute precision. Also halves pool HBM residency, G2/G3
    # tier footprint, and the payload bytes of every disagg/G4/offload
    # transfer; greedy outputs stay >=99% decisive-token-identical on
    # the differential harness (tests/test_kv_quant).
    kv_quant: str = "none"

    # multi-tenant serving plane (dynamo_tpu/tenancy/).
    # Resident LoRA adapter bank: >0 allocates a bank of this many
    # adapter slots (row 0 is the all-zeros identity = the base model)
    # at rank lora_rank, riding inside the params pytree so every jitted
    # program (fused round, prefill, batched prefill) serves mixed
    # adapter ids with zero extra dispatches. 0 = no bank: the engine
    # traces the identical pre-tenancy programs.
    lora_adapters: int = 0
    lora_rank: int = 8
    # per-tenant slices of the overload-plane backlog budgets (0 =
    # unbounded). One tenant's storm exhausts ITS slice — and bounces
    # with a Retry-After from that tenant's own observed queue waits —
    # before it can crowd the global queue.
    tenant_max_waiting_requests: int = 0
    tenant_max_waiting_prefill_tokens: int = 0
    # fair-share weights for the SFQ dequeue order (tenant -> weight,
    # default 1.0); weights bias ordering, not the budgets above
    tenant_weights: Optional[dict] = None

    # fleet prefix economy (kv_router/fleet.py): when the frontend's
    # hint digest is applied, dedup-by-hash admission consults it before
    # a G4 probe round — fleet-known holders are probed first, and a
    # demand miss whose blocks the fleet hot set doesn't know at all
    # skips the probe entirely. False restores hint-blind G4.
    kv_dedup_admission: bool = True

    # identity on the control plane
    worker_id: str = ""

    @property
    def max_context(self) -> int:
        return self.max_pages_per_seq * self.page_size

    def bucket_for(self, n_tokens: int) -> Optional[int]:
        """Smallest prefill bucket holding n_tokens (buckets are padded
        shapes; each distinct bucket is one XLA compilation)."""
        for b in self.prefill_buckets:
            if n_tokens <= b:
                return b
        return None
