"""Service-authoring SDK: ``@service`` / ``@endpoint`` / ``depends`` +
build/deploy (reference deploy/sdk/src/dynamo/sdk/core/lib.py:88,121 and
core/protocol/deployment.py — the decorator surface app authors use
instead of wiring runtime components by hand).

TPU-native mapping: a decorated class is a runtime COMPONENT; its
``@endpoint`` methods serve on the push-RPC plane; ``depends(Other)``
resolves to a live endpoint client at serve time (the reference resolves
dependency edges the same way, through discovery — never direct object
references). The same declaration then drives every deploy target:

  serve_graph(...)   in-process: instantiate + register on a runtime
  build(...)         -> launch/serve.py graph dict (the supervisor's and
                        ``--emit-k8s``'s input)
  deploy(...)        -> write the graph spec to the store key the
                        operator-lite reconciler watches (k8s.py)

Example::

    @service(namespace="app")
    class Backend:
        @endpoint()
        async def generate(self, payload):
            yield {"data": ...}

    @service(namespace="app")
    class Api:
        backend = depends(Backend)

        @endpoint()
        async def chat(self, payload):
            async for out in self.backend.generate(payload):
                yield out
"""
from __future__ import annotations

import inspect
import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable, Optional

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Request stats: the SDK-side read of the engine's in-band annotation
# plane. The engine attaches per-request speculation counters to the
# finishing LLMEngineOutput (annotations["spec"]); request_stats folds a
# request's output stream into one record a caller (or the planner) can
# act on — e.g. gate speculation off for workloads whose acceptance rate
# doesn't pay for the verify forwards.

@dataclass
class RequestStats:
    """Per-request generation statistics folded from an output stream."""

    output_tokens: int = 0
    finish_reason: Optional[str] = None
    # speculative decoding (zero when the request didn't speculate)
    spec_proposed: int = 0
    spec_accepted: int = 0
    # per-request timing from the engine's finishing annotation
    # (annotations["timing"], telemetry plane) — None when the engine
    # exported none (e.g. the echo/mocker engines)
    ttft_s: Optional[float] = None
    itl_p50_s: Optional[float] = None
    itl_p95_s: Optional[float] = None
    e2e_s: Optional[float] = None
    queue_s: Optional[float] = None

    @property
    def spec_acceptance_rate(self) -> Optional[float]:
        if self.spec_proposed <= 0:
            return None
        return self.spec_accepted / self.spec_proposed


def request_stats(outputs: Iterable[Any]) -> RequestStats:
    """Fold a request's LLMEngineOutput stream (objects or to_dict()
    payloads) into a RequestStats."""
    st = RequestStats()
    for out in outputs:
        if isinstance(out, dict):
            toks = out.get("token_ids") or []
            ann = out.get("annotations") or {}
            fr = out.get("finish_reason")
        else:
            toks = out.token_ids or []
            ann = out.annotations or {}
            fr = out.finish_reason.value if out.finish_reason else None
        st.output_tokens += len(toks)
        if fr is not None:
            st.finish_reason = fr
        spec = ann.get("spec")
        if spec:
            st.spec_proposed = int(spec.get("proposed", 0))
            st.spec_accepted = int(spec.get("accepted", 0))
        timing = ann.get("timing")
        if timing:
            for key in ("ttft_s", "itl_p50_s", "itl_p95_s", "e2e_s",
                        "queue_s"):
                if timing.get(key) is not None:
                    setattr(st, key, float(timing[key]))
    return st


@dataclass
class ServiceMeta:
    name: str
    namespace: str = "dynamo"
    replicas: int = 1
    tpu_chips: int = 0
    args: list[str] = field(default_factory=list)
    endpoints: dict[str, str] = field(default_factory=dict)  # ep -> method
    dependencies: dict[str, type] = field(default_factory=dict)


class _Depends:
    """Declared dependency edge; resolved to an endpoint-client proxy at
    serve time (class attribute -> instance attribute swap)."""

    def __init__(self, target: type):
        if not hasattr(target, "_dynamo_service"):
            raise TypeError(
                f"depends() target {target!r} is not a @service class"
            )
        self.target = target


def depends(target: type) -> Any:
    return _Depends(target)


def endpoint(name: Optional[str] = None) -> Callable:
    """Mark an async-generator method as a served endpoint."""

    def mark(fn):
        fn._dynamo_endpoint = name or fn.__name__
        return fn

    return mark


def service(
    name: Optional[str] = None,
    *,
    namespace: str = "dynamo",
    replicas: int = 1,
    tpu_chips: int = 0,
    args: Optional[list[str]] = None,
) -> Callable[[type], type]:
    """Class decorator: declare a runtime component."""

    def wrap(cls: type) -> type:
        meta = ServiceMeta(
            name=name or cls.__name__.lower(),
            namespace=namespace,
            replicas=replicas,
            tpu_chips=tpu_chips,
            args=list(args or []),
        )
        for attr, value in list(vars(cls).items()):
            ep = getattr(value, "_dynamo_endpoint", None)
            if ep is not None:
                if not inspect.isasyncgenfunction(value):
                    raise TypeError(
                        f"@endpoint {cls.__name__}.{attr} must be an "
                        "async generator (yield response payloads)"
                    )
                meta.endpoints[ep] = attr
            if isinstance(value, _Depends):
                meta.dependencies[attr] = value.target
        if not meta.endpoints:
            raise TypeError(
                f"@service {cls.__name__} declares no @endpoint methods"
            )
        cls._dynamo_service = meta
        return cls

    return wrap


class _ClientProxy:
    """What a depends() attribute becomes at serve time: endpoint names
    of the target service as async-generator calls."""

    def __init__(self, rt: Any, meta: ServiceMeta):
        self._rt = rt
        self._meta = meta
        self._clients: dict[str, Any] = {}

    def __getattr__(self, ep: str):
        if ep not in self._meta.endpoints:
            raise AttributeError(
                f"service {self._meta.name!r} has no endpoint {ep!r}"
            )

        async def call(payload: dict):
            client = self._clients.get(ep)
            if client is None:
                client = await self._rt.namespace(
                    self._meta.namespace
                ).component(self._meta.name).endpoint(ep).client()
                self._clients[ep] = client
            async for item in client.generate(payload):
                yield item

        return call


@dataclass
class ServedGraph:
    instances: list[Any]
    served: list[Any]

    async def stop(self) -> None:
        for s in self.served:
            await s.shutdown()
        for inst in self.instances:
            stop = getattr(inst, "stop", None)
            if stop is not None:
                await stop()


async def serve_graph(rt: Any, *services: type,
                      worker_id: str = "sdk-0") -> ServedGraph:
    """Instantiate the services and register every @endpoint on the
    runtime; depends() attributes become live client proxies (the
    reference `dynamo serve` in-process path, cli/serving.py:66)."""
    out = ServedGraph([], [])
    for cls in services:
        meta: ServiceMeta = cls._dynamo_service
        inst = cls()
        for attr, target in meta.dependencies.items():
            setattr(inst, attr, _ClientProxy(rt, target._dynamo_service))
        out.instances.append(inst)
        for ep, attr in meta.endpoints.items():
            handler = getattr(inst, attr)
            served = await rt.namespace(meta.namespace).component(
                meta.name
            ).endpoint(ep).serve(
                handler, worker_id=f"{worker_id}-{meta.name}"
            )
            out.served.append(served)
        log.info("sdk: served %s (%s)", meta.name,
                 ", ".join(meta.endpoints))
    return out


def build(*services: type, control_plane_port: int = 7111,
          http_port: int = 8080) -> dict[str, Any]:
    """Declarations -> the launch/serve.py graph dict (``dynamo build``):
    runnable by the supervisor, renderable by --emit-k8s, deployable by
    the operator."""
    if not services:
        raise ValueError("build() needs at least one @service class")
    ns = services[0]._dynamo_service.namespace
    workers = []
    for cls in services:
        meta: ServiceMeta = cls._dynamo_service
        workers.append({
            "name": meta.name,
            "replicas": meta.replicas,
            "tpu_chips": meta.tpu_chips,
            "args": list(meta.args),
        })
    return {
        "namespace": ns,
        "control_plane": {"port": control_plane_port},
        "frontend": {"http_port": http_port},
        "workers": workers,
    }


async def deploy(kv: Any, *services: type, **build_kw) -> str:
    """``dynamo deploy``: publish the built graph to the operator's spec
    key — the reconcile loop (k8s.DynamoOperator) rolls it out."""
    import json

    from dynamo_tpu.k8s import graph_key

    graph = build(*services, **build_kw)
    key = graph_key(graph["namespace"])
    await kv.put(key, json.dumps(graph))
    return key
