"""Standalone metrics re-exporter: worker load plane -> Prometheus.

Parity: reference components/metrics (src/main.rs:258) — a separate
process that consumes the workers' ForwardPassMetrics stream and
re-exposes it as Prometheus gauges, so dashboards/alerting scrape one
place instead of every worker. Here the stream is the store's
``load_metrics.{worker_id}`` topics (NATS-subject parity).

Exposed (all labelled by worker):
  dynamo_worker_active_slots / total_slots / waiting_requests
  dynamo_kv_active_blocks / total_blocks / usage_perc / hit_rate
  dynamo_kv_host_blocks / host_onboard_hits
  dynamo_spec_proposed_total / accepted_total / acceptance_rate
  dynamo_spec_effective_k (mean adaptive K over speculating slots)
  dynamo_request_{ttft,itl,e2e,queue}_seconds / dynamo_engine_round_seconds
      (latency histograms shipped inside ForwardPassMetrics.histograms)
  dynamo_fleet_request_* (the same histograms MERGED across workers —
      telemetry/fleet_feed.py; exemplars preserved under OpenMetrics)
  dynamo_tenant_* (process-local tenant-sliced admission/latency
      families — dynamo_tpu/tenancy/metrics.py)
Run: ``dynamo-tpu metrics --control-plane HOST:PORT --port 9090``.
"""
from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional

from aiohttp import web

from dynamo_tpu.kv_router.metrics_aggregator import MetricsAggregator
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.publisher import METRICS_TOPIC
from dynamo_tpu.telemetry.fleet_feed import FLEET_FEED
from dynamo_tpu.telemetry.forensics import FORENSICS
from dynamo_tpu.telemetry.metrics import render_histogram
from dynamo_tpu.tenancy import TENANT

log = logging.getLogger(__name__)


class MetricsExporter:
    """Subscribe the load-metrics plane; serve Prometheus text format."""

    def __init__(
        self,
        kv: KvClient,
        *,
        host: str = "0.0.0.0",
        port: int = 9090,
        stale_after_s: float = 10.0,
    ):
        self.kv = kv
        self.host = host
        self.port = port
        self.aggregator = MetricsAggregator(stale_after_s=stale_after_s)
        self.app = web.Application()
        self.app.add_routes([web.get("/metrics", self.handle_metrics)])
        self._runner: Optional[web.AppRunner] = None
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "MetricsExporter":
        sub = await self.kv.subscribe(f"{METRICS_TOPIC}.>")
        self._task = asyncio.get_running_loop().create_task(self._follow(sub))
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def _follow(self, sub) -> None:
        async for ev in sub:
            try:
                m = ForwardPassMetrics.from_dict(json.loads(ev["value"]))
            except (KeyError, ValueError, TypeError):
                continue
            self.aggregator.update(m)
            # fleet-merged latency feed: per-worker histogram snapshots
            # sum into the dynamo_fleet_request_* families
            FLEET_FEED.observe(m)

    def render(self, openmetrics: bool = False) -> str:
        snap = self.aggregator.snapshot()
        lines: list[str] = []

        def gauge(name: str, help_: str, values) -> None:
            """Emit one gauge family with HELP/TYPE; ``values`` is either
            a worker->value dict (labelled series) or a scalar."""
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            if isinstance(values, dict):
                for worker, v in sorted(values.items()):
                    lines.append(f'{name}{{worker="{worker}"}} {v}')
            else:
                lines.append(f"{name} {values}")

        gauge("dynamo_worker_active_slots", "requests in decode slots",
              {w: m.worker_stats.request_active_slots
               for w, m in snap.metrics.items()})
        gauge("dynamo_worker_total_slots", "decode slot capacity",
              {w: m.worker_stats.request_total_slots
               for w, m in snap.metrics.items()})
        gauge("dynamo_worker_waiting_requests", "queued requests",
              {w: m.worker_stats.num_requests_waiting
               for w, m in snap.metrics.items()})
        gauge("dynamo_worker_waiting_prefill_tokens",
              "prompt tokens waiting for prefill",
              {w: m.worker_stats.num_waiting_prefill_tokens
               for w, m in snap.metrics.items()})
        gauge("dynamo_worker_max_waiting_requests",
              "admission queue-depth budget (0 = unbounded)",
              {w: m.worker_stats.max_waiting_requests
               for w, m in snap.metrics.items()})
        gauge("dynamo_worker_max_waiting_prefill_tokens",
              "admission prefill-token budget (0 = unbounded)",
              {w: m.worker_stats.max_waiting_prefill_tokens
               for w, m in snap.metrics.items()})
        gauge("dynamo_kv_active_blocks", "KV pages in use",
              {w: m.kv_stats.kv_active_blocks
               for w, m in snap.metrics.items()})
        gauge("dynamo_kv_total_blocks", "KV page capacity",
              {w: m.kv_stats.kv_total_blocks
               for w, m in snap.metrics.items()})
        gauge("dynamo_kv_usage_perc", "KV pool usage fraction",
              {w: m.kv_stats.gpu_cache_usage_perc
               for w, m in snap.metrics.items()})
        gauge("dynamo_kv_hit_rate", "prefix cache hit rate",
              {w: m.kv_stats.gpu_prefix_cache_hit_rate
               for w, m in snap.metrics.items()})
        gauge("dynamo_kv_host_blocks", "host-tier (G2) cached pages",
              {w: m.kv_stats.host_blocks for w, m in snap.metrics.items()})
        gauge("dynamo_kv_host_onboard_hits", "G2 onboard hits",
              {w: m.kv_stats.host_onboard_hits
               for w, m in snap.metrics.items()})
        gauge("dynamo_spec_proposed_total",
              "speculative tokens proposed",
              {w: m.worker_stats.spec_proposed_total
               for w, m in snap.metrics.items()})
        gauge("dynamo_spec_accepted_total",
              "speculative tokens accepted",
              {w: m.worker_stats.spec_accepted_total
               for w, m in snap.metrics.items()})
        gauge("dynamo_spec_acceptance_rate",
              "rolling speculative acceptance rate",
              {w: m.worker_stats.spec_acceptance_rate
               for w, m in snap.metrics.items()})
        gauge("dynamo_spec_effective_k",
              "mean acceptance-adaptive effective K over speculating slots",
              {w: m.worker_stats.spec_effective_k
               for w, m in snap.metrics.items()})
        gauge("dynamo_spec_effective_k_p50",
              "median per-slot effective K over speculating slots",
              {w: m.worker_stats.spec_effective_k_p50
               for w, m in snap.metrics.items()})
        gauge("dynamo_spec_effective_k_p95",
              "p95 per-slot effective K over speculating slots",
              {w: m.worker_stats.spec_effective_k_p95
               for w, m in snap.metrics.items()})
        # latency histograms shipped inside ForwardPassMetrics: one
        # HELP/TYPE block per family, all workers' labelled series under
        # it (the Prometheus text-format grouping requirement)
        families: dict[str, dict[str, dict]] = {}
        for w, m in snap.metrics.items():
            for name, hsnap in (getattr(m, "histograms", None) or {}).items():
                families.setdefault(name, {})[w] = hsnap
        for name in sorted(families):
            per_worker = families[name]
            first = next(iter(per_worker.values()))
            help_ = first.get("help", name)
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} histogram")
            for w in sorted(per_worker):
                # render_histogram's own HELP/TYPE head is dropped: it
                # must appear once per family, not once per worker
                lines.extend(render_histogram(
                    name, help_, per_worker[w], label=f'worker="{w}"',
                    openmetrics=openmetrics,
                )[2:])
        gauge("dynamo_metrics_workers",
              "workers in the last load-plane snapshot", len(snap.metrics))
        # resilience + KV-transfer + overload planes: process-local
        # counters, same families on every scrape surface
        from dynamo_tpu.kv_fleet_metrics import KV_FLEET
        from dynamo_tpu.kv_integrity import KV_INTEGRITY
        from dynamo_tpu.kv_quant import KV_QUANT
        from dynamo_tpu.kv_transfer_metrics import KV_TRANSFER
        from dynamo_tpu.overload import OVERLOAD
        from dynamo_tpu.planner_metrics import PLANNER
        from dynamo_tpu.resilience.metrics import RESILIENCE
        from dynamo_tpu.runtime.store_metrics import STORE
        from dynamo_tpu.spec.metrics import SPEC
        from dynamo_tpu.telemetry.prof import PROF

        return ("\n".join(lines) + "\n" + RESILIENCE.render()
                + KV_TRANSFER.render() + KV_QUANT.render()
                + KV_INTEGRITY.render() + OVERLOAD.render()
                + PROF.render() + STORE.render() + PLANNER.render()
                + KV_FLEET.render() + SPEC.render()
                + FLEET_FEED.render(openmetrics=openmetrics)
                + TENANT.render(openmetrics=openmetrics)
                + FORENSICS.render())

    async def handle_metrics(self, request: web.Request) -> web.Response:
        if "application/openmetrics-text" in request.headers.get(
                "Accept", ""):
            return web.Response(
                text=self.render(openmetrics=True) + "# EOF\n",
                content_type="application/openmetrics-text",
                charset="utf-8",
            )
        return web.Response(
            text=self.render(), content_type="text/plain", charset="utf-8"
        )


async def run_exporter(args) -> None:
    host, _, port = args.control_plane.partition(":")
    kv = await KvClient(host or "127.0.0.1", int(port or 7111)).connect()
    exp = await MetricsExporter(
        kv, host=args.host, port=args.port
    ).start()
    print(f"metrics exporter on http://{args.host}:{exp.port}/metrics")
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await exp.stop()
        await kv.close()
