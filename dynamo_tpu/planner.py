"""Load-based planner v0: observe worker load, scale the fleet.

Parity: reference components/planner load-based mode
(utils/planner_core.py:51,131-168): a control loop that every
``adjustment_interval_s`` observes aggregated worker metrics, decides a
replica count against KV-usage and queue-depth thresholds, and asks a
connector to realize it — LocalConnector spawns/retires ``in=endpoint``
worker subprocesses (the circus-watcher equivalent,
local_connector.py:310); a k8s connector would patch replicas instead.

Scale-up when (avg KV usage > kv_usage_scale_up) OR (total waiting >
waiting_scale_up); scale-down when BOTH avg usage < kv_usage_scale_down
AND waiting == 0. One step per interval, clamped to [min, max]; downscale
requires ``stable_intervals`` consecutive low observations so transient
dips don't flap the fleet.
"""
from __future__ import annotations

import asyncio
import json
import logging
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Optional, Protocol

from dynamo_tpu.kv_router.metrics_aggregator import MetricsAggregator
from dynamo_tpu.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.publisher import METRICS_TOPIC

log = logging.getLogger(__name__)


@dataclass
class PlannerConfig:
    adjustment_interval_s: float = 10.0
    kv_usage_scale_up: float = 0.8
    kv_usage_scale_down: float = 0.3
    waiting_scale_up: int = 4
    min_replicas: int = 1
    max_replicas: int = 8
    stable_intervals: int = 2    # consecutive low loads before downscale
    metrics_stale_after_s: float = 15.0
    # load predictor filtering the observed series before decide() — one of
    # predictors.make_predictor: "constant" (reactive, reference default),
    # "moving_average", "ar"/"arima" (trend-following forecast;
    # reference load_predictor.py:159)
    predictor: str = "constant"
    # predictive mode (fleetsim tentpole): additionally forecast the
    # next-interval concurrent-stream count with the configured predictor
    # and size the fleet for the FORECAST — with a trend-following
    # predictor ("ar") the planner scales ahead of a rising wave instead
    # of after the queue already built. ``streams_per_replica`` is the
    # per-replica capacity the forecast divides by (from a profile sweep
    # or the mocker's decode-slot count); predictive mode is inert at 0.
    predictive: bool = False
    streams_per_replica: float = 0.0
    # live queue-wait scale-up trigger: when a WorkerLoadView is wired
    # and any worker's estimated admission wait exceeds this, scale up
    # even if KV usage and queue depth look fine (0 = disabled)
    queue_wait_scale_up_s: float = 0.0
    # fleet-merged latency triggers (telemetry/fleet_feed.py): the
    # planner keeps its own FleetLatencyFeed over the same metrics
    # subscription and reads interval-delta p99s each decide. Stream
    # counts miss a latency wave that arrives without queue growth
    # (slow rounds, deep prefixes falling off cache); the merged TTFT /
    # queue-wait distribution sees it directly. 0 = disabled.
    fleet_ttft_scale_up_s: float = 0.0
    fleet_queue_scale_up_s: float = 0.0


class Connector(Protocol):
    """Realizes a replica count (LocalConnector / KubernetesConnector)."""

    def current_replicas(self) -> int: ...

    async def set_replicas(self, n: int) -> None: ...


class LocalConnector:
    """Worker pool as local subprocesses of the dynamo-tpu CLI (circus-
    arbiter equivalent). Retirement is newest-first GRACEFUL DRAIN:
    SIGTERM asks the worker to stop admitting, finish its in-flight
    requests and exit (launch/run.py installs the drain handler) — the
    warm KV and live streams survive scale-down. SIGKILL only lands
    after ``drain_grace_s`` as the unresponsive-worker backstop."""

    def __init__(self, worker_cmd: list[str], drain_grace_s: float = 30.0,
                 clock: Optional[Any] = None):
        from dynamo_tpu.fleetsim.clock import REAL_CLOCK

        # e.g. [sys.executable, "-m", "dynamo_tpu.cli", "run",
        #       "in=endpoint", "out=mocker", "--control-plane", addr, ...]
        self.worker_cmd = list(worker_cmd)
        self.drain_grace_s = drain_grace_s
        # drain-grace deadlines are sim-visible: under a compressed clock
        # the grace window must compress too (real clock default)
        self.clock = clock or REAL_CLOCK
        self.procs: list[subprocess.Popen] = []
        self.drains_started = 0
        # retiring workers: drained out of self.procs but possibly still
        # finishing requests; reaped by their grace tasks. The procs are
        # tracked separately so shutdown() can SIGKILL a retiree whose
        # grace task it cancels (a SIGTERM-ignoring worker must never
        # outlive the planner as an orphan).
        self._retiring: list[asyncio.Task] = []
        self._retiring_procs: list[subprocess.Popen] = []

    def current_replicas(self) -> int:
        self.procs = [p for p in self.procs if p.poll() is None]
        return len(self.procs)

    async def _retire(self, proc: subprocess.Popen) -> None:
        """SIGTERM -> wait out the drain grace -> SIGKILL backstop."""
        try:
            proc.terminate()
            deadline = self.clock.monotonic() + self.drain_grace_s
            while proc.poll() is None and self.clock.monotonic() < deadline:
                await self.clock.sleep(0.1)
            if proc.poll() is None:
                log.warning(
                    "planner: worker pid %d ignored drain for %.0fs; "
                    "killing", proc.pid, self.drain_grace_s,
                )
                proc.kill()
        finally:
            if proc in self._retiring_procs:
                self._retiring_procs.remove(proc)

    async def set_replicas(self, n: int) -> None:
        self.current_replicas()  # reap exited
        self._retiring = [t for t in self._retiring if not t.done()]
        while len(self.procs) < n:
            proc = subprocess.Popen(
                self.worker_cmd,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                start_new_session=True,
            )
            self.procs.append(proc)
            log.info("planner: spawned worker pid %d", proc.pid)
        while len(self.procs) > n:
            proc = self.procs.pop()
            log.info("planner: draining worker pid %d (grace %.0fs)",
                     proc.pid, self.drain_grace_s)
            self.drains_started += 1
            self._retiring_procs.append(proc)
            self._retiring.append(
                asyncio.get_running_loop().create_task(self._retire(proc))
            )

    async def shutdown(self) -> None:
        procs = list(self.procs)  # set_replicas(0) empties self.procs
        await self.set_replicas(0)
        for t in self._retiring:
            t.cancel()
        # cancelled grace tasks lose their SIGKILL backstop: kill every
        # still-alive proc, INCLUDING mid-retirement ones
        for p in procs + list(self._retiring_procs):
            if p.poll() is None:
                p.kill()  # shutdown is immediate, not graceful
        self._retiring_procs.clear()


class MultihostLocalConnector:
    """DP replicas OF a cross-host engine (BASELINE config 4 x planner):
    each replica is a GROUP of ``num_nodes`` processes — rank 0 the
    in=endpoint leader, the rest replay followers — spawned and retired
    together. Command args are templated with ``{rank}``, ``{coord}``
    (a fresh coordinator address per group) and ``{replica}`` (unique
    component suffix, so concurrent groups' bring-up barriers and command
    queues never collide)."""

    def __init__(self, cmd_template: list[str], num_nodes: int = 2,
                 host: str = "127.0.0.1",
                 env: Optional[dict[str, str]] = None):
        self.cmd_template = list(cmd_template)
        self.num_nodes = num_nodes
        self.host = host
        self.env = env
        self.groups: list[list[subprocess.Popen]] = []
        self._next_replica = 0

    @staticmethod
    def _free_port() -> int:
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def current_replicas(self) -> int:
        # a group is alive while its LEADER is (followers die with it via
        # the liveness key)
        self.groups = [g for g in self.groups if g[0].poll() is None]
        return len(self.groups)

    async def set_replicas(self, n: int) -> None:
        self.current_replicas()
        while len(self.groups) < n:
            replica = self._next_replica
            self._next_replica += 1
            coord = f"{self.host}:{self._free_port()}"
            group = []
            for rank in range(self.num_nodes):
                cmd = [
                    a.format(rank=rank, coord=coord, replica=replica)
                    for a in self.cmd_template
                ]
                group.append(subprocess.Popen(
                    cmd, stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL, start_new_session=True,
                    env=self.env,
                ))
            self.groups.append(group)
            log.info("planner: spawned multihost group %d (%d procs)",
                     replica, self.num_nodes)
        while len(self.groups) > n:
            group = self.groups.pop()
            log.info("planner: retiring multihost group")
            group[0].terminate()  # leader exit tears the group down

    async def shutdown(self) -> None:
        groups = list(self.groups)
        await self.set_replicas(0)
        for g in groups:
            for p in g:
                if p.poll() is None:
                    p.kill()


class Planner:
    """The observe -> decide -> scale loop (planner_core.py:131-168)."""

    def __init__(
        self,
        kv: KvClient,
        connector: Connector,
        config: Optional[PlannerConfig] = None,
        sla: Optional[Any] = None,  # profiler.SlaCapacity -> SLA mode
        *,
        clock: Optional[Any] = None,       # fleetsim Clock (real default)
        load_view: Optional[Any] = None,   # overload.WorkerLoadView tap
    ):
        from dynamo_tpu.fleetsim.clock import REAL_CLOCK

        self.kv = kv
        self.connector = connector
        self.config = config or PlannerConfig()
        self.sla = sla
        self.clock = clock or REAL_CLOCK
        # live queue-wait view (overload plane): when wired, decide()
        # reads estimated admission waits as an extra scale-up signal
        self.load_view = load_view
        self.aggregator = MetricsAggregator(
            stale_after_s=self.config.metrics_stale_after_s,
            clock=self.clock.monotonic,
        )
        # fleet-merged latency feed (telemetry/fleet_feed.py): a private
        # instance (not the process-global FLEET_FEED) so the planner's
        # advance() interval-delta baseline is its own, and fleetsim's
        # VirtualClock governs staleness
        from dynamo_tpu.telemetry.fleet_feed import FleetLatencyFeed

        self.fleet_feed = FleetLatencyFeed(
            stale_after_s=self.config.metrics_stale_after_s,
            clock=self.clock.monotonic,
        )
        self.decisions: list[tuple[float, int]] = []  # (ts, target) history
        self._low_streak = 0
        self._task: Optional[asyncio.Task] = None
        self._sub_task: Optional[asyncio.Task] = None
        from dynamo_tpu.predictors import make_predictor

        # one predictor per observed series (independent windows)
        self._pred_usage = make_predictor(self.config.predictor)
        self._pred_waiting = make_predictor(self.config.predictor)
        self._pred_streams = make_predictor(self.config.predictor)

    async def start(self) -> "Planner":
        from dynamo_tpu.runtime.tasks import CriticalTask

        sub = await self.kv.subscribe(f"{METRICS_TOPIC}.>")
        # supervised: a dead metrics follower or decide loop must restart,
        # not silently stop autoscaling (reference utils/task.rs:42)
        self._sub_task = CriticalTask(
            lambda: self._follow(sub), "planner-metrics-follow"
        ).start()
        self._task = CriticalTask(self._loop, "planner-adjust-loop").start()
        return self

    async def stop(self) -> None:
        for t in (self._task, self._sub_task):
            if t is not None:
                await t.stop()
        self._task = self._sub_task = None

    async def _follow(self, sub) -> None:
        async for ev in sub:
            try:
                m = ForwardPassMetrics.from_dict(json.loads(ev["value"]))
            except (KeyError, ValueError, TypeError):
                continue
            self.aggregator.update(m)
            self.fleet_feed.observe(m)

    async def _loop(self) -> None:
        while True:
            await self.clock.sleep(self.config.adjustment_interval_s)
            try:
                await self.adjust()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("planner adjustment failed")

    def _streams(self, snap) -> int:
        """Concurrent streams across the fleet (active + queued)."""
        return sum(
            m.worker_stats.request_active_slots
            + m.worker_stats.num_requests_waiting
            for m in snap.metrics.values()
        )

    def _predictive_target(self, snap, current: int) -> int:
        """Forecast next-interval stream count; size the fleet for the
        forecast. With a trend-following predictor the target rises
        BEFORE the wave peaks — the point of predictive mode."""
        import math

        from dynamo_tpu.planner_metrics import PLANNER

        c = self.config
        self._pred_streams.add_data_point(self._streams(snap))
        forecast = self._pred_streams.predict_next()
        PLANNER.set("dynamo_planner_predicted_load", forecast)
        if c.streams_per_replica <= 0:
            return current
        return max(c.min_replicas, min(
            c.max_replicas,
            math.ceil(forecast / c.streams_per_replica),
        ))

    def _fleet_latency_high(self) -> bool:
        """Fleet-merged latency trigger: p99 TTFT / queue wait over the
        LAST DECIDE INTERVAL (advance() deltas, not the cumulative
        distribution) beyond the configured bounds. Runs every decide —
        even with both bounds disabled the gauges still publish, so
        dashboards see what the planner sees."""
        from dynamo_tpu.planner_metrics import PLANNER

        deltas = self.fleet_feed.advance()
        from dynamo_tpu.telemetry.metrics import percentile_from_snapshot

        ttft_p99 = percentile_from_snapshot(
            deltas.get("dynamo_fleet_request_ttft_seconds") or {}, 0.99)
        queue_p99 = percentile_from_snapshot(
            deltas.get("dynamo_fleet_request_queue_seconds") or {}, 0.99)
        PLANNER.set("dynamo_planner_fleet_ttft_p99_seconds",
                    round(ttft_p99 or 0.0, 6))
        PLANNER.set("dynamo_planner_fleet_queue_p99_seconds",
                    round(queue_p99 or 0.0, 6))
        c = self.config
        if (c.fleet_ttft_scale_up_s > 0 and ttft_p99 is not None
                and ttft_p99 > c.fleet_ttft_scale_up_s):
            return True
        return (c.fleet_queue_scale_up_s > 0 and queue_p99 is not None
                and queue_p99 > c.fleet_queue_scale_up_s)

    def _queue_wait_high(self, snap) -> bool:
        """Live overload-plane trigger: any worker's estimated admission
        wait beyond the configured bound."""
        c = self.config
        if self.load_view is None or c.queue_wait_scale_up_s <= 0:
            return False
        for wid in snap.metrics:
            est = self.load_view.est_wait_s(wid)
            if est is not None and est > c.queue_wait_scale_up_s:
                return True
        return False

    def decide(self) -> int:
        """Pure decision from the current snapshot (unit-testable)."""
        c = self.config
        snap = self.aggregator.snapshot()
        current = self.connector.current_replicas()
        if self.sla is not None:
            # SLA mode (reference planner_sla.py): size the fleet so the
            # observed stream count fits within profiled SLA capacity.
            # Scale-up is immediate (SLA protection); scale-down steps one
            # replica per stable_intervals of consistently-lower targets so
            # a stale/empty metrics snapshot can't collapse the fleet.
            from dynamo_tpu.planner_metrics import PLANNER

            self._pred_streams.add_data_point(self._streams(snap))
            streams = self._pred_streams.predict_next()
            PLANNER.set("dynamo_planner_predicted_load", streams)
            target = min(c.max_replicas,
                         self.sla.replicas_for(streams, c.min_replicas))
            if target >= current:
                self._low_streak = 0
                return target
            self._low_streak += 1
            if self._low_streak >= c.stable_intervals:
                self._low_streak = 0
                return current - 1
            return current
        self._pred_usage.add_data_point(snap.load_avg())
        self._pred_waiting.add_data_point(sum(
            m.worker_stats.num_requests_waiting
            for m in snap.metrics.values()
        ))
        usage = self._pred_usage.predict_next()
        waiting = self._pred_waiting.predict_next()
        # evaluated unconditionally (not short-circuited inside the
        # ``or``): advance() must step its interval baseline and publish
        # the fleet p99 gauges exactly once per decide
        fleet_high = self._fleet_latency_high()
        target = current
        if (usage > c.kv_usage_scale_up or waiting > c.waiting_scale_up
                or self._queue_wait_high(snap) or fleet_high):
            target = current + 1
            self._low_streak = 0
        elif usage < c.kv_usage_scale_down and waiting < 0.5:
            self._low_streak += 1
            if self._low_streak >= c.stable_intervals:
                target = current - 1
                self._low_streak = 0
        else:
            self._low_streak = 0
        if not c.predictive:
            from dynamo_tpu.planner_metrics import PLANNER

            PLANNER.set("dynamo_planner_predicted_load", usage)
        else:
            # predictive floor: never below what the forecast needs, and
            # a forecast above current load cancels a pending downscale
            pred = self._predictive_target(snap, current)
            if pred > target:
                target = pred
                self._low_streak = 0
        return max(c.min_replicas, min(c.max_replicas, target))

    async def adjust(self) -> int:
        from dynamo_tpu.planner_metrics import PLANNER

        target = self.decide()
        current = self.connector.current_replicas()
        PLANNER.inc("dynamo_planner_decisions_total")
        PLANNER.set("dynamo_planner_replicas", target)
        if target != current:
            log.info("planner: scaling %d -> %d", current, target)
            PLANNER.inc("dynamo_planner_scale_ups_total"
                        if target > current
                        else "dynamo_planner_scale_downs_total")
            await self.connector.set_replicas(target)
        self.decisions.append((self.clock.monotonic(), target))
        return target


async def run_planner(args) -> None:
    """CLI entry: planner over a local worker pool. SLA flags validate
    BEFORE connecting so misconfiguration fails fast."""
    sla = _build_sla(args)
    host, _, port = args.control_plane.partition(":")
    kv = await KvClient(host or "127.0.0.1", int(port or 7111)).connect()
    if getattr(args, "connector", "local") == "kubernetes":
        # scale the worker Deployment through the k8s API (reference
        # kubernetes_connector.py; in-cluster SA credentials by default)
        from dynamo_tpu.k8s import KubernetesConnector

        if not args.k8s_deployment:
            raise SystemExit("--connector kubernetes needs --k8s-deployment")
        connector = await KubernetesConnector(
            args.k8s_deployment, args.k8s_namespace
        ).start()
    else:
        worker_cmd = [sys.executable, "-m", "dynamo_tpu.cli", "run",
                      "in=endpoint", f"out={args.engine}",
                      "--control-plane", args.control_plane,
                      "--model-name", args.model_name,
                      "--namespace", args.namespace]
        connector = LocalConnector(worker_cmd)
    cfg = PlannerConfig(
        adjustment_interval_s=args.adjustment_interval,
        min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        predictor=getattr(args, "predictor", "constant"),
        predictive=getattr(args, "predictive", False),
        streams_per_replica=getattr(args, "streams_per_replica", 0.0),
        fleet_ttft_scale_up_s=getattr(args, "fleet_ttft_scale_up", 0.0),
        fleet_queue_scale_up_s=getattr(
            args, "fleet_queue_scale_up", 0.0),
    )
    if connector.current_replicas() < cfg.min_replicas:
        await connector.set_replicas(cfg.min_replicas)
    planner = await Planner(kv, connector, cfg, sla=sla).start()
    mode = "sla" if sla else "load"
    print(f"planner ({mode}) managing '{args.model_name}' workers "
          f"[{cfg.min_replicas}, {cfg.max_replicas}]")
    try:
        while True:
            await asyncio.sleep(3600)
    finally:
        await planner.stop()
        down = getattr(connector, "shutdown", None) or connector.close
        await down()
        await kv.close()


def _build_sla(args):
    sla = None
    if getattr(args, "sla_profile", None):
        from dynamo_tpu.profiler import SlaCapacity

        if args.ttft_sla is None and args.itl_sla is None:
            raise SystemExit(
                "--sla-profile requires --ttft-sla and/or --itl-sla "
                "(otherwise no SLA would be enforced)"
            )
        with open(args.sla_profile) as f:
            profile = json.load(f)
        names = [c.get("name") for c in profile.get("configs", [])]
        config_name = getattr(args, "sla_config", None)
        if config_name is None:
            if len(names) != 1:
                raise SystemExit(
                    f"profile has configs {names}; pass --sla-config to "
                    "pick the one your deployed workers actually run"
                )
            config_name = names[0]
        elif config_name not in names:
            raise SystemExit(
                f"--sla-config {config_name!r} not in profile ({names})"
            )
        sla = SlaCapacity(
            profile=profile,
            ttft_sla_s=args.ttft_sla,
            itl_sla_s=args.itl_sla,
            config_name=config_name,
        )
        if sla.max_concurrency() <= 0:
            raise SystemExit(
                f"SLA unmeetable: no profiled point of {config_name!r} "
                f"satisfies ttft<={args.ttft_sla} itl<={args.itl_sla} — "
                "re-profile or relax the targets"
            )
    elif args.ttft_sla is not None or args.itl_sla is not None:
        raise SystemExit("--ttft-sla/--itl-sla need --sla-profile")
    return sla
