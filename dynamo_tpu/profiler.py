"""Pre-deployment SLA profiler: sweep serving configs, measure TTFT/ITL.

Parity: reference benchmarks/profiler/profile_sla.py — before deploying,
sweep engine parallelism/config against genai-perf load to find the
cheapest config meeting TTFT/ITL SLAs, emitting interpolation tables the
SLA planner consumes (docs/architecture/load_planner.md:40-60). Here the
load generator is built in (no genai-perf): for each config and each
concurrency level it drives the engine with synthetic prompts and records
TTFT p50/p99, ITL p50/p99, and throughput.

Output (JSON): {"configs": [{"name", "config", "points": [{"concurrency",
"ttft_p50_s", "ttft_p99_s", "itl_p50_s", "itl_p99_s", "tok_s"}]}]}
The SLA planner (planner.py SlaCapacity) reads this to answer "how many
concurrent streams can one replica hold within SLA?".
"""
from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from dynamo_tpu.protocols.common import PreprocessedRequest, StopConditions


def _pct(vals: list[float], q: float) -> Optional[float]:
    if not vals:
        return None
    import numpy as np

    return float(np.percentile(vals, q * 100.0))


@dataclass
class ProfilePoint:
    concurrency: int
    ttft_p50_s: float
    ttft_p99_s: float
    itl_p50_s: float
    itl_p99_s: float
    tok_s: float

    def to_dict(self) -> dict[str, Any]:
        return {
            "concurrency": self.concurrency,
            "ttft_p50_s": round(self.ttft_p50_s, 5),
            "ttft_p99_s": round(self.ttft_p99_s, 5),
            "itl_p50_s": round(self.itl_p50_s, 5),
            "itl_p99_s": round(self.itl_p99_s, 5),
            "tok_s": round(self.tok_s, 2),
        }


async def measure_point(
    engine: Any,
    *,
    concurrency: int,
    isl: int,
    osl: int,
    rounds: int = 2,
    vocab: int = 250,
) -> ProfilePoint:
    """Drive `concurrency` simultaneous streams through the engine and
    measure TTFT/ITL/throughput over `rounds` waves."""
    import numpy as np

    rng = np.random.RandomState(7)
    ttfts: list[float] = []
    itls: list[float] = []
    total_tokens = 0
    t_start = time.monotonic()

    async def one() -> None:
        nonlocal total_tokens
        req = PreprocessedRequest(
            token_ids=rng.randint(1, vocab, size=isl).tolist(),
            stop_conditions=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        t0 = time.monotonic()
        prev = None
        async for out in engine.generate(req):
            now = time.monotonic()
            for _ in out.token_ids:
                if prev is None:
                    ttfts.append(now - t0)
                else:
                    itls.append(now - prev)
                prev = now
                total_tokens += 1

    for _ in range(rounds):
        await asyncio.gather(*[one() for _ in range(concurrency)])
    wall = time.monotonic() - t_start
    ttfts.sort()
    itls.sort()
    return ProfilePoint(
        concurrency=concurrency,
        ttft_p50_s=_pct(ttfts, 0.5) or 0.0,
        ttft_p99_s=_pct(ttfts, 0.99) or 0.0,
        itl_p50_s=_pct(itls, 0.5) or 0.0,
        itl_p99_s=_pct(itls, 0.99) or 0.0,
        tok_s=total_tokens / wall if wall else 0.0,
    )


async def profile_engine(
    make_engine: Callable[[dict], Any],
    configs: list[dict],
    *,
    concurrencies: tuple[int, ...] = (1, 2, 4, 8),
    isl: int = 64,
    osl: int = 32,
    rounds: int = 2,
) -> dict[str, Any]:
    """Sweep configs × concurrency levels; returns the profile table."""
    out: list[dict[str, Any]] = []
    for cfg in configs:
        engine = make_engine(cfg)
        start = getattr(engine, "start", None)
        if start:
            start()
        points = []
        try:
            # warmup at the HIGHEST measured concurrency: compiles are per
            # (bucket, width) shape, and the widest shapes only appear at
            # full batch — a narrow warmup would leave compile stalls
            # inside the measured latencies
            await measure_point(engine, concurrency=max(concurrencies),
                                isl=isl, osl=4, rounds=1)
            for c in concurrencies:
                pt = await measure_point(
                    engine, concurrency=c, isl=isl, osl=osl, rounds=rounds
                )
                points.append(pt.to_dict())
        finally:
            stop = getattr(engine, "stop", None)
            if stop:
                res = stop()
                if asyncio.iscoroutine(res):
                    await res
        out.append({
            "name": cfg.get("name", "config"),
            "config": {k: v for k, v in cfg.items() if k != "name"},
            "points": points,
        })
    return {"isl": isl, "osl": osl, "configs": out}


@dataclass
class SlaCapacity:
    """Answers 'how many concurrent streams fit one replica within SLA?'
    from a profile table (the planner-side consumer,
    reference utils/perf_interpolation.py)."""

    profile: dict[str, Any]
    ttft_sla_s: Optional[float] = None
    itl_sla_s: Optional[float] = None
    config_name: Optional[str] = None
    percentile: str = "p50"  # p50 | p99

    def _config_points(self) -> list[list[dict[str, Any]]]:
        """Per-config point lists (each sorted by concurrency). Each config
        is its own latency curve — merging them would let one bad config
        poison another's capacity."""
        cfgs = self.profile.get("configs", [])
        if self.config_name is not None:
            cfgs = [c for c in cfgs if c.get("name") == self.config_name]
        return [
            sorted(c.get("points", []), key=lambda p: p["concurrency"])
            for c in cfgs if c.get("points")
        ]

    def interpolate(
        self, concurrency: float, pts: Optional[list[dict[str, Any]]] = None
    ) -> tuple[Optional[float], Optional[float]]:
        """(ttft, itl) at a concurrency level, piecewise-linear between
        profiled points (reference utils/perf_interpolation.py: the SLA
        planner reads the profiled latency SURFACE, not just the grid).
        Clamps outside the profiled range to the nearest endpoint. With
        several configs selected, reads the FIRST config's curve unless
        `pts` picks one."""
        if pts is None:
            groups = self._config_points()
            pts = groups[0] if groups else []
        if not pts:
            return None, None

        def interp(key: str) -> Optional[float]:
            xs = [p["concurrency"] for p in pts if p.get(key) is not None]
            ys = [p[key] for p in pts if p.get(key) is not None]
            if not xs:
                return None
            if concurrency <= xs[0]:
                return ys[0]
            if concurrency >= xs[-1]:
                return ys[-1]
            for (x0, y0), (x1, y1) in zip(zip(xs, ys), zip(xs[1:], ys[1:])):
                if x0 <= concurrency <= x1:
                    if x1 == x0:
                        return max(y0, y1)
                    t = (concurrency - x0) / (x1 - x0)
                    return y0 + t * (y1 - y0)
            return ys[-1]

        return (interp(f"ttft_{self.percentile}_s"),
                interp(f"itl_{self.percentile}_s"))

    def _point_ok(self, pt: dict[str, Any]) -> bool:
        ttft = pt.get(f"ttft_{self.percentile}_s")
        itl = pt.get(f"itl_{self.percentile}_s")
        good = True
        if self.ttft_sla_s is not None:
            # a point MISSING the measurement cannot prove the SLA
            good = good and ttft is not None and ttft <= self.ttft_sla_s
        if self.itl_sla_s is not None:
            good = good and itl is not None and itl <= self.itl_sla_s
        return good

    def max_concurrency(self) -> int:
        """Highest concurrency meeting the SLA (0 if no profiled point
        does). Base semantics: the highest PASSING PROFILED point of any
        selected config (noise at low load never zeroes out capacity a
        higher point proved). Interpolation then refines INTO the segment
        between that point and the next profiled point, finding the SLA
        crossing on the piecewise-linear curve (reference
        utils/perf_interpolation.py reads the surface, not just the grid)."""
        best = 0
        for pts in self._config_points():
            passing = [i for i, p in enumerate(pts) if self._point_ok(p)]
            if not passing:
                continue
            i = passing[-1]
            cap = float(pts[i]["concurrency"])
            if i + 1 < len(pts):
                # refine toward the next (failing) profiled point
                def ok(c: float, pts=pts) -> bool:
                    ttft, itl = self.interpolate(c, pts)
                    good = True
                    if self.ttft_sla_s is not None:
                        good = (good and ttft is not None
                                and ttft <= self.ttft_sla_s)
                    if self.itl_sla_s is not None:
                        good = (good and itl is not None
                                and itl <= self.itl_sla_s)
                    return good

                flo, fhi = cap, float(pts[i + 1]["concurrency"])
                for _ in range(40):
                    mid = (flo + fhi) / 2
                    if ok(mid):
                        flo = mid
                    else:
                        fhi = mid
                cap = flo
            best = max(best, int(cap))
        return best

    def replicas_for(self, concurrent_streams: int,
                     min_replicas: int = 1) -> int:
        cap = self.max_concurrency()
        if cap <= 0:
            return max(min_replicas, 1)
        import math

        return max(min_replicas, math.ceil(concurrent_streams / cap))


async def run_profile(args) -> None:
    """CLI entry: profile the mocker (CPU) or a tiny/real TPU engine."""
    def make(cfg: dict):
        if args.engine == "mocker":
            from dynamo_tpu.mocker import MockerArgs, MockerEngine

            return MockerEngine(MockerArgs(
                speedup_ratio=cfg.get("speedup_ratio", 1.0),
                max_decode_slots=cfg.get("max_decode_slots", 8),
                page_size=cfg.get("page_size", 16),
                num_pages=cfg.get("num_pages", 512),
            ))
        from dynamo_tpu.engine.config import EngineConfig
        from dynamo_tpu.engine.engine import TpuEngine
        from dynamo_tpu.models.config import ModelConfig
        from dynamo_tpu.parallel.mesh import MeshConfig

        mc = getattr(ModelConfig, args.model_config)()
        return TpuEngine(
            mc,
            EngineConfig(
                num_pages=cfg.get("num_pages", 512),
                page_size=cfg.get("page_size", 64),
                max_decode_slots=cfg.get("max_decode_slots", 8),
                prefill_buckets=(128,),
                cache_dtype=cfg.get("cache_dtype", "bfloat16"),
            ),
            mesh_config=MeshConfig(tp=cfg.get("tp", 1)),
        )

    configs = [
        {"name": f"slots{s}", "max_decode_slots": s}
        for s in args.slots
    ]
    table = await profile_engine(
        make, configs,
        concurrencies=tuple(args.concurrency),
        isl=args.isl, osl=args.osl,
    )
    with open(args.output, "w") as f:
        json.dump(table, f, indent=1)
    print(f"profile written to {args.output} "
          f"({len(configs)} configs x {len(args.concurrency)} points)")
