"""dynamo-tpu: TPU-native distributed LLM inference-serving framework.

A ground-up rebuild of the capabilities of NVIDIA Dynamo (the reference
distributed inference stack) designed TPU-first:

- OpenAI-compatible HTTP frontend with SSE streaming.
- Distributed runtime: namespace/component/endpoint discovery with
  lease-based liveness, push RPC, streamed responses over TCP — backed by a
  native C++ control-plane server (the etcd+NATS-equivalent).
- KV-cache-aware routing over a global prefix radix tree.
- Disaggregated prefill/decode with worker-to-worker KV-block migration
  (ICI within a slice, host-staged DCN across slices).
- Multi-tier KV block manager (G1 HBM -> G2 host DRAM -> G3 mmap disk).
- A real JAX/XLA engine: continuous batching over contiguous per-slot KV
  with a paged prefix-cache pool, pjit/GSPMD tensor/expert parallelism
  over the ICI mesh, a Pallas flash-decode kernel, on-device (greedy-
  gated) sampling, MoE serving, and sequence-parallel ring prefill for
  long prompts.
- SLA/load planner (constant/moving-average/AR load prediction) that
  autoscales workers locally or through the Kubernetes API.

Layer map mirrors SURVEY.md section 1 (reference layers L0-L7).
"""

__version__ = "0.1.0"
