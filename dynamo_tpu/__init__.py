"""dynamo-tpu: TPU-native distributed LLM inference-serving framework.

A ground-up rebuild of the capabilities of NVIDIA Dynamo (the reference
distributed inference stack) designed TPU-first:

- OpenAI-compatible HTTP frontend with SSE streaming.
- Distributed runtime: namespace/component/endpoint discovery with
  lease-based liveness, push RPC, streamed responses over TCP — backed by a
  native C++ control-plane server (the etcd+NATS-equivalent).
- KV-cache-aware routing over a global prefix radix tree.
- Disaggregated prefill/decode with worker-to-worker KV-block migration
  (ICI within a slice, host-staged DCN across slices).
- Multi-tier KV block manager (HBM -> host DRAM -> SSD).
- A real JAX/XLA engine: continuous batching over a paged KV cache held as
  a sharded HBM tensor, pjit/GSPMD tensor parallelism over the ICI mesh,
  Pallas paged-attention kernels, on-device sampling.
- SLA/load planner that autoscales workers.

Layer map mirrors SURVEY.md section 1 (reference layers L0-L7).
"""

__version__ = "0.1.0"
