"""Tail-latency forensics: SLO-breach dossiers in a bounded outlier ring.

The span/prof/flight planes can explain a request you *choose* to look
at; nothing caught the p99 outlier *for* you — by the time a burn-rate
gauge moves, the trace that explains it was sampled away or evicted.
This module closes that loop:

- ``ForensicsCapture.on_finish`` runs on every finishing request. The
  no-capture path is two float compares against the SLO targets plus an
  optional coin flip — always-on-cheap. On a breach (TTFT/ITL/e2e over
  target) or a ``--forensics-sample-rate`` hit it PROMOTES the trace
  (``TRACES.promote`` — shells buffer spans precisely so this late
  promotion recovers the whole path) and marks the request pending.
- ``on_trace_finished`` (called where the trace is finished) assembles
  the *dossier*: the merged span tree, the host-round segment records
  and flight-recorder / kv-stream events overlapping the request's
  lifetime, its KV path distilled from the spans (prefix-hit depth,
  G2/G3/G4 fetches, migrations, overload bounces), queue wait and
  worker id — into the bounded ``OUTLIERS`` ring served at
  ``GET /debug/outliers`` and exportable as a single-request Perfetto
  timeline (``Dossier.to_dict()`` is exactly the pre-merged bundle
  shape ``tools/trace_export.py`` already builds).
"""
from __future__ import annotations

import logging
import random
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from dynamo_tpu.telemetry.metrics import CounterRegistry
from dynamo_tpu.telemetry.trace import TRACES, Trace

log = logging.getLogger(__name__)

# (name, type, help) — metrics contract: README rows + all three scrape
# surfaces (tests/test_metrics_contract.py, dynlint DTL005)
FAMILIES: tuple[tuple[str, str, str], ...] = (
    ("dynamo_forensics_dossiers_total", "counter",
     "SLO-breach/sampled dossiers captured into the outlier ring"),
    ("dynamo_forensics_breaches_total", "counter",
     "finishing requests whose TTFT/ITL/e2e crossed the SLO target"),
    ("dynamo_forensics_sampled_total", "counter",
     "dossiers captured by the forensics-sample-rate coin flip"),
    ("dynamo_forensics_dossiers_evicted_total", "counter",
     "dossiers evicted from the bounded outlier ring"),
    ("dynamo_forensics_ring_size", "gauge",
     "dossiers currently retained in the outlier ring"),
)

FORENSICS = CounterRegistry(FAMILIES, label="forensics")

# window slop when clipping ring events to the request lifetime: ring
# timestamps are end-stamped, the trace start is frontend-stamped —
# clock skew between them must not drop boundary events
_WINDOW_SLOP_S = 0.25


def kv_path_from_spans(spans: list[dict[str, Any]]) -> dict[str, Any]:
    """Distill a request's KV journey from its (flat or nested) span
    dicts: where it routed, how deep the prefix hit was, what the KV
    tiers fetched, whether it migrated or bounced off overload."""
    path: dict[str, Any] = {
        "worker": None,
        "prefix_hit_blocks": 0,
        "route_attempts": 0,
        "migrations": [],
        "overload_bounces": 0,
        "g2_onboard_blocks": 0,
        "g4_fetch_blocks": 0,
        "disagg": False,
        "queue_wait_s": None,
    }

    def walk(sp: dict[str, Any]) -> None:
        name = sp.get("name", "")
        attrs = sp.get("attrs") or {}
        if name == "route":
            path["route_attempts"] += 1
            path["worker"] = attrs.get("worker", path["worker"])
            path["prefix_hit_blocks"] = int(
                attrs.get("overlap_blocks", 0) or 0)
        elif name == "migrate":
            path["migrations"].append({
                "from_worker": attrs.get("from_worker"),
                "replayed_tokens": attrs.get("replayed_tokens", 0),
            })
        elif name == "overload_bounce":
            path["overload_bounces"] += 1
        elif name == "g2_onboard":
            path["g2_onboard_blocks"] += int(attrs.get("blocks", 0) or 0)
        elif name == "g4_fetch":
            path["g4_fetch_blocks"] += int(attrs.get("blocks", 0) or 0)
        elif name in ("remote_prefill", "disagg_kv_transfer", "kv_chunk"):
            path["disagg"] = True
        elif name == "queue":
            path["queue_wait_s"] = round(
                float(sp.get("duration_s", 0.0)), 6)
        for child in sp.get("children") or []:
            walk(child)

    for sp in spans or []:
        walk(sp)
    return path


@dataclass
class Dossier:
    """Everything known about one slow request, joined under its
    trace_id. ``to_dict()`` is the pre-merged bundle shape
    ``tools/trace_export.build`` turns into a Perfetto timeline."""

    request_id: str
    reason: str                      # ttft_breach|itl_breach|e2e_breach|sampled
    captured_s: float = field(default_factory=time.time)
    worker_id: str = ""
    timing: dict[str, Any] = field(default_factory=dict)
    trace: dict[str, Any] = field(default_factory=dict)
    kv_path: dict[str, Any] = field(default_factory=dict)
    # RoundProf.recent() records [(end_unix_s, wall_s, [seg_s, ...]), ...]
    rounds: list = field(default_factory=list)
    flight: list = field(default_factory=list)
    stream: list = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "request_id": self.request_id,
            "reason": self.reason,
            "captured_s": round(self.captured_s, 6),
            "worker_id": self.worker_id,
            "timing": self.timing,
            "kv_path": self.kv_path,
            "trace": self.trace,
            "rounds": [list(r) for r in self.rounds],
            "flight": self.flight,
            "stream": self.stream,
        }

    def summary(self) -> dict[str, Any]:
        return {
            "request_id": self.request_id,
            "reason": self.reason,
            "captured_s": round(self.captured_s, 3),
            "worker_id": self.worker_id,
            "ttft_s": self.timing.get("ttft_s"),
            "e2e_s": self.timing.get("e2e_s"),
            "queue_s": self.timing.get("queue_s"),
            "spans": len(self.trace.get("spans") or []),
            "rounds": len(self.rounds),
            "flight_events": len(self.flight),
        }


class DossierRing:
    """Bounded id-addressable ring of dossiers (oldest evicted);
    thread-safe — capture runs in request handlers / the engine thread,
    the debug endpoints read from asyncio."""

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        self._ring: OrderedDict[str, Dossier] = OrderedDict()
        self.captured_total = 0
        self.evicted_total = 0
        self._lock = threading.Lock()

    def add(self, dossier: Dossier) -> None:
        with self._lock:
            self._ring[dossier.request_id] = dossier
            self._ring.move_to_end(dossier.request_id)
            self.captured_total += 1
            while len(self._ring) > self.capacity:
                self._ring.popitem(last=False)
                self.evicted_total += 1
                FORENSICS.inc("dynamo_forensics_dossiers_evicted_total")
            FORENSICS.set("dynamo_forensics_ring_size", len(self._ring))
        FORENSICS.inc("dynamo_forensics_dossiers_total")

    def get(self, request_id: str) -> Optional[Dossier]:
        with self._lock:
            return self._ring.get(request_id)

    def recent(self, n: int = 0) -> list[Dossier]:
        """Newest first; ``n<=0`` returns everything retained."""
        with self._lock:
            out = list(self._ring.values())
        out.reverse()
        return out[:n] if n > 0 else out

    def oldest_id(self) -> Optional[str]:
        with self._lock:
            return next(iter(self._ring), None)

    def index(self) -> dict[str, Any]:
        """The ``GET /debug/outliers`` body."""
        with self._lock:
            dossiers = list(self._ring.values())
        dossiers.reverse()
        return {
            "capacity": self.capacity,
            "captured_total": self.captured_total,
            "evicted_total": self.evicted_total,
            "outliers": [d.summary() for d in dossiers],
        }

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.captured_total = 0
            self.evicted_total = 0
        FORENSICS.set("dynamo_forensics_ring_size", 0)


# process-wide outlier ring: the frontend capture path, the worker-side
# engine capture path, and the /debug/outliers endpoints share it
OUTLIERS = DossierRing()


def _clip(events: list, lo: float, hi: float, key: str = "ts") -> list:
    lo, hi = lo - _WINDOW_SLOP_S, hi + _WINDOW_SLOP_S
    return [e for e in events if lo <= float(e.get(key, 0.0)) <= hi]


def _clip_rounds(records: list, lo: float, hi: float) -> list:
    lo, hi = lo - _WINDOW_SLOP_S, hi + _WINDOW_SLOP_S
    return [r for r in records if lo <= float(r[0]) <= hi]


class ForensicsCapture:
    """Per-process breach detector + dossier assembler.

    ``engines_fn`` yields in-process engine-like objects (anything with
    optional ``prof``/``flight`` attributes) whose rings are clipped to
    the request lifetime; a pure frontend has none and its dossiers
    carry the merged spans only (worker rounds ride the worker's own
    ring). SLO targets default to the live PROF targets so
    ``--slo-ttft-target`` / ``--slo-itl-target`` govern both burn rates
    and forensics."""

    def __init__(
        self,
        ring: Optional[DossierRing] = None,
        *,
        sample_rate: float = 0.0,
        ttft_target_s: Optional[float] = None,
        itl_target_s: Optional[float] = None,
        e2e_target_s: Optional[float] = None,
        engines_fn: Optional[Callable[[], list]] = None,
        traces=None,
        seed: Optional[int] = None,
    ):
        self.ring = ring if ring is not None else OUTLIERS
        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self._ttft_target_s = ttft_target_s
        self._itl_target_s = itl_target_s
        self.e2e_target_s = e2e_target_s
        self.engines_fn = engines_fn
        self.traces = traces if traces is not None else TRACES
        self._rng = random.Random(seed)
        # rid -> (reason, timing dict, worker_id) awaiting trace finish
        self._pending: dict[str, tuple[str, dict[str, Any], str]] = {}
        self._lock = threading.Lock()

    @property
    def ttft_target_s(self) -> float:
        if self._ttft_target_s is not None:
            return self._ttft_target_s
        from dynamo_tpu.telemetry.prof import PROF
        return PROF.ttft_target_s

    @property
    def itl_target_s(self) -> float:
        if self._itl_target_s is not None:
            return self._itl_target_s
        from dynamo_tpu.telemetry.prof import PROF
        return PROF.itl_target_s

    def breach_reason(
        self,
        ttft_s: Optional[float] = None,
        itl_p95_s: Optional[float] = None,
        e2e_s: Optional[float] = None,
    ) -> Optional[str]:
        """The always-on-cheap check: a couple of float compares."""
        if ttft_s is not None and ttft_s > self.ttft_target_s:
            return "ttft_breach"
        if itl_p95_s is not None and itl_p95_s > self.itl_target_s:
            return "itl_breach"
        if (self.e2e_target_s is not None and e2e_s is not None
                and e2e_s > self.e2e_target_s):
            return "e2e_breach"
        return None

    def _decide(
        self,
        ttft_s: Optional[float],
        itl_p95_s: Optional[float],
        e2e_s: Optional[float],
    ) -> Optional[str]:
        """Breach check + sample coin flip, with counter bookkeeping."""
        reason = self.breach_reason(ttft_s, itl_p95_s, e2e_s)
        if reason is not None:
            FORENSICS.inc("dynamo_forensics_breaches_total")
        elif self.sample_rate > 0.0 and (
                self.sample_rate >= 1.0
                or self._rng.random() < self.sample_rate):
            reason = "sampled"
            FORENSICS.inc("dynamo_forensics_sampled_total")
        return reason

    def on_finish(
        self,
        request_id: str,
        *,
        ttft_s: Optional[float] = None,
        itl_p95_s: Optional[float] = None,
        e2e_s: Optional[float] = None,
        queue_s: Optional[float] = None,
        worker_id: str = "",
        timing: Optional[dict[str, Any]] = None,
    ) -> Optional[str]:
        """Breach/sample decision for a finishing request. On capture,
        promotes the trace (adopting any shell-buffered spans) and marks
        the id pending; returns the reason, else None."""
        if not request_id:
            return None
        reason = self._decide(ttft_s, itl_p95_s, e2e_s)
        if reason is None:
            return None
        self.traces.promote(request_id)
        t = dict(timing or {})
        for k, v in (("ttft_s", ttft_s), ("itl_p95_s", itl_p95_s),
                     ("e2e_s", e2e_s), ("queue_s", queue_s)):
            if v is not None and k not in t:
                t[k] = round(v, 6)
        with self._lock:
            self._pending[request_id] = (reason, t, worker_id)
        return reason

    def pending(self, request_id: str) -> bool:
        with self._lock:
            return request_id in self._pending

    def on_trace_finished(
        self, request_id: str, trace: Optional[Trace]
    ) -> Optional[Dossier]:
        """Assemble and ring-park the dossier for a pending id; call
        with TRACES.finish()'s return value."""
        with self._lock:
            entry = self._pending.pop(request_id, None)
        if entry is None:
            return None
        reason, timing, worker_id = entry
        trace_dict = trace.to_dict() if trace is not None else {
            "trace_id": request_id, "spans": [], "finished": True,
        }
        return self._assemble(
            request_id, reason, timing, worker_id, trace_dict)

    def capture_direct(
        self,
        request_id: str,
        reason: str,
        timing: dict[str, Any],
        worker_id: str,
        trace_dict: dict[str, Any],
    ) -> Dossier:
        """Worker-side path: the engine already holds the span dicts for
        a finishing request — no TraceStore round trip needed."""
        return self._assemble(request_id, reason, timing, worker_id,
                              trace_dict)

    def worker_finish(
        self,
        request_id: str,
        *,
        timing: dict[str, Any],
        worker_id: str,
        trace_spans: list,
    ) -> Optional[Dossier]:
        """One-shot worker-side finish: breach/sample decision against
        the engine's own timing annotation, then direct dossier assembly
        from its span dicts (the frontend lives in another process, so
        nothing will call on_trace_finished here)."""
        if not request_id:
            return None
        reason = self._decide(
            timing.get("ttft_s"), timing.get("itl_p95_s"),
            timing.get("e2e_s"))
        if reason is None:
            return None
        return self.capture_direct(
            request_id, reason, dict(timing), worker_id,
            {"trace_id": request_id, "finished": True,
             "spans": list(trace_spans)},
        )

    def _assemble(
        self,
        request_id: str,
        reason: str,
        timing: dict[str, Any],
        worker_id: str,
        trace_dict: dict[str, Any],
    ) -> Dossier:
        now = time.time()
        lo = float(trace_dict.get("created_s") or 0.0)
        spans = trace_dict.get("spans") or []
        if not lo:
            starts = [float(s.get("start_s", now)) for s in spans]
            lo = min(starts) if starts else now - float(
                timing.get("e2e_s") or 0.0)
        kv_path = kv_path_from_spans(spans)
        if kv_path.get("queue_wait_s") is None and "queue_s" in timing:
            kv_path["queue_wait_s"] = timing["queue_s"]
        rounds: list = []
        flight: list = []
        for eng in (self.engines_fn() if self.engines_fn else []):
            prof = getattr(eng, "prof", None)
            if prof is not None:
                try:
                    rounds.extend(_clip_rounds(prof.recent(256), lo, now))
                except Exception as e:  # noqa: BLE001 — never throws
                    log.debug("forensics: prof clip failed: %s", e)
            fl = getattr(eng, "flight", None)
            if fl is not None:
                try:
                    flight.extend(_clip(fl.snapshot(), lo, now))
                except Exception as e:  # noqa: BLE001 — never throws
                    log.debug("forensics: flight clip failed: %s", e)
        from dynamo_tpu.telemetry.timeline import STREAM_EVENTS
        stream = _clip(STREAM_EVENTS.snapshot(), lo, now)
        dossier = Dossier(
            request_id=request_id,
            reason=reason,
            worker_id=worker_id or str(kv_path.get("worker") or ""),
            timing=timing,
            trace=trace_dict,
            kv_path=kv_path,
            rounds=rounds,
            flight=flight,
            stream=stream,
        )
        self.ring.add(dossier)
        return dossier
