"""Trace context: one span tree per request, assembled across processes.

The frontend mints a trace keyed by the request's ``request_id`` (which
already travels through the runtime protocol in every frame and in the
PreprocessedRequest payload — no extra wire field needed). Stages in the
frontend process (tokenize, route) record spans directly; the worker
engine accumulates its spans (queue wait, prefill, decode/verify rounds,
G2 onboard) on the request and ships them back on the finishing
LLMEngineOutput under ``annotations["trace"]`` — the frontend merges them
into its tree. A worker that owns no active trace for the request id
(i.e. the frontend is a different process) registers the spans in its
OWN store, so the per-worker system server can serve
``/debug/trace/{request_id}`` too.

Completed traces park in a bounded ring (oldest evicted); everything is
lock-guarded because the engine thread records while the asyncio side
serves.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Span:
    """One timed stage. ``start_s`` is unix time; ``duration_s`` wall."""

    name: str
    start_s: float
    duration_s: float = 0.0
    attrs: dict[str, Any] = field(default_factory=dict)
    children: list["Span"] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_s": round(self.duration_s, 6),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Span":
        return cls(
            name=str(d.get("name", "")),
            start_s=float(d.get("start_s", 0.0)),
            duration_s=float(d.get("duration_s", 0.0)),
            attrs=dict(d.get("attrs") or {}),
            children=[cls.from_dict(c) for c in d.get("children") or []],
        )


def span_now(name: str, t0_monotonic: float, **attrs: Any) -> Span:
    """Span ending now that began at monotonic time ``t0_monotonic``."""
    dur = time.monotonic() - t0_monotonic
    return Span(name=name, start_s=time.time() - dur, duration_s=dur,
                attrs=attrs)


@dataclass
class Trace:
    """One request's span tree (flat span list; stage order by start).

    ``sampled=False`` traces are shells: span recording no-ops and the
    trace is dropped at finish instead of parking in the completed ring —
    the high-QPS sampling mode (--trace-sample-rate) pays one dict entry
    per request, not span assembly. A shell can be PROMOTED mid-request
    (migration/failure paths always trace) and collects spans from then
    on."""

    trace_id: str
    created_s: float = field(default_factory=time.time)
    spans: list[Span] = field(default_factory=list)
    finished: bool = False
    sampled: bool = True

    def add(self, span: Span) -> None:
        if self.sampled:
            self.spans.append(span)

    def merge_dicts(self, span_dicts: list[dict[str, Any]]) -> None:
        """Fold worker-side spans (annotation payload) into the tree."""
        for d in span_dicts:
            try:
                self.spans.append(Span.from_dict(d))
            except (TypeError, ValueError):
                continue

    def span_names(self) -> list[str]:
        return [s.name for s in sorted(self.spans, key=lambda s: s.start_s)]

    def to_dict(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "created_s": round(self.created_s, 6),
            "finished": self.finished,
            "spans": [
                s.to_dict()
                for s in sorted(self.spans, key=lambda s: s.start_s)
            ],
        }


class TraceStore:
    """Active traces + a bounded ring of completed ones."""

    def __init__(self, max_completed: int = 512, max_active: int = 4096):
        self.max_completed = max_completed
        self.max_active = max_active
        self._active: dict[str, Trace] = {}
        # secondary ids resolving onto an active trace — the n>1 fanout
        # gives each extra choice its own request_id; their spans belong
        # on the parent request's tree
        self._aliases: dict[str, str] = {}
        self._completed: OrderedDict[str, Trace] = OrderedDict()
        self._lock = threading.Lock()

    def start(self, trace_id: str, sampled: bool = True) -> Trace:
        tr = Trace(trace_id=trace_id, sampled=sampled)
        with self._lock:
            # leak bound: a caller that never finishes its traces (crashed
            # stream, test teardown) must not grow the store unboundedly
            if len(self._active) >= self.max_active:
                self._active.pop(next(iter(self._active)))
            self._active[trace_id] = tr
        return tr

    def alias(self, trace_id: str, parent_id: str) -> None:
        """Route ``trace_id``'s spans onto ``parent_id``'s active trace
        (dropped when the parent finishes)."""
        with self._lock:
            if parent_id in self._active:
                self._aliases[trace_id] = parent_id

    def _resolve(self, trace_id: str) -> Optional[Trace]:
        return self._active.get(
            self._aliases.get(trace_id, trace_id)
        )

    def has_active(self, trace_id: str) -> bool:
        with self._lock:
            return self._resolve(trace_id) is not None

    def add_span(self, trace_id: str, span: Span) -> bool:
        """Record onto an ACTIVE trace; no-op (False) when none exists —
        stages call this unconditionally and remote-frontend cases fall
        through to the annotation path."""
        with self._lock:
            tr = self._resolve(trace_id)
            if tr is None or not tr.sampled:
                return False
            tr.add(span)
            return True

    def promote(self, trace_id: str) -> bool:
        """Turn an unsampled shell into a full trace mid-request —
        migrated/failed requests are always traced regardless of the
        sample rate. True if an active trace exists."""
        with self._lock:
            tr = self._resolve(trace_id)
            if tr is None:
                return False
            tr.sampled = True
            return True

    def merge(self, trace_id: str, span_dicts: list[dict[str, Any]]) -> None:
        with self._lock:
            tr = self._resolve(trace_id)
        if tr is not None and tr.sampled:
            tr.merge_dicts(span_dicts)

    def finish(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            tr = self._active.pop(trace_id, None)
            if tr is None:
                return None
            self._aliases = {
                a: p for a, p in self._aliases.items() if p != trace_id
            }
            tr.finished = True
            if not tr.sampled:
                return tr  # shell: dropped, never parked in the ring
            self._completed[trace_id] = tr
            while len(self._completed) > self.max_completed:
                self._completed.popitem(last=False)
            return tr

    def record_remote(
        self, trace_id: str, span_dicts: list[dict[str, Any]]
    ) -> None:
        """Worker-local registration: a finished trace built from the
        engine's own spans, for processes where no frontend owns the
        trace (the per-worker ``/debug/trace`` view)."""
        tr = Trace(trace_id=trace_id, finished=True)
        tr.merge_dicts(span_dicts)
        with self._lock:
            self._completed[trace_id] = tr
            self._completed.move_to_end(trace_id)
            while len(self._completed) > self.max_completed:
                self._completed.popitem(last=False)

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            return self._completed.get(trace_id) or self._active.get(trace_id)

    def recent_ids(self, n: int = 50) -> list[str]:
        with self._lock:
            return list(self._completed)[-n:]

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._aliases.clear()
            self._completed.clear()


# process-wide store: the frontend, router, engine, and debug endpoints in
# one process share trace context through it (cross-process assembly rides
# the request_id + output annotations instead)
TRACES = TraceStore()
