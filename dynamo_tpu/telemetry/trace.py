"""Trace context: one span tree per request, assembled across processes.

The frontend mints a trace keyed by the request's ``request_id`` (which
already travels through the runtime protocol in every frame and in the
PreprocessedRequest payload — no extra wire field needed). Stages in the
frontend process (tokenize, route) record spans directly; the worker
engine accumulates its spans (queue wait, prefill, decode/verify rounds,
G2 onboard) on the request and ships them back on the finishing
LLMEngineOutput under ``annotations["trace"]`` — the frontend merges them
into its tree. A worker that owns no active trace for the request id
(i.e. the frontend is a different process) registers the spans in its
OWN store, so the per-worker system server can serve
``/debug/trace/{request_id}`` too.

Completed traces park in a bounded ring (oldest evicted); everything is
lock-guarded because the engine thread records while the asyncio side
serves.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Span:
    """One timed stage. ``start_s`` is unix time; ``duration_s`` wall."""

    name: str
    start_s: float
    duration_s: float = 0.0
    attrs: dict[str, Any] = field(default_factory=dict)
    children: list["Span"] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_s": round(self.duration_s, 6),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Span":
        return cls(
            name=str(d.get("name", "")),
            start_s=float(d.get("start_s", 0.0)),
            duration_s=float(d.get("duration_s", 0.0)),
            attrs=dict(d.get("attrs") or {}),
            children=[cls.from_dict(c) for c in d.get("children") or []],
        )


def span_now(name: str, t0_monotonic: float, **attrs: Any) -> Span:
    """Span ending now that began at monotonic time ``t0_monotonic``."""
    dur = time.monotonic() - t0_monotonic
    return Span(name=name, start_s=time.time() - dur, duration_s=dur,
                attrs=attrs)


# spans a shell trace buffers while unsampled, so a LATE promotion (an
# SLO breach only detectable at finish) still recovers the request's
# whole path; bounded so a pathological span source can't grow a shell
_SHELL_BUFFER_CAP = 160


@dataclass
class Trace:
    """One request's span tree (flat span list; stage order by start).

    ``sampled=False`` traces are shells: spans park in a small bounded
    side buffer and the trace is dropped at finish instead of parking in
    the completed ring — the high-QPS sampling mode (--trace-sample-rate)
    never pays completed-ring assembly for unsampled requests. A shell
    can be PROMOTED at any point before finish (migration/failure paths
    always trace; SLO-breach forensics promotes at finish time) and the
    buffered spans are adopted, so even a promotion on the request's
    last instruction yields a complete tree."""

    trace_id: str
    created_s: float = field(default_factory=time.time)
    spans: list[Span] = field(default_factory=list)
    finished: bool = False
    sampled: bool = True
    buffered: list[Span] = field(default_factory=list)

    def add(self, span: Span) -> None:
        if self.sampled:
            self.spans.append(span)
        elif len(self.buffered) < _SHELL_BUFFER_CAP:
            self.buffered.append(span)

    def adopt_buffer(self) -> None:
        """Promote: fold the shell's buffered spans into the real tree."""
        if self.buffered:
            self.spans.extend(self.buffered)
            self.buffered = []

    def merge_dicts(self, span_dicts: list[dict[str, Any]]) -> None:
        """Fold worker-side spans (annotation payload) into the tree."""
        for d in span_dicts:
            try:
                self.spans.append(Span.from_dict(d))
            except (TypeError, ValueError):
                continue

    def span_names(self) -> list[str]:
        return [s.name for s in sorted(self.spans, key=lambda s: s.start_s)]

    def to_dict(self) -> dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "created_s": round(self.created_s, 6),
            "finished": self.finished,
            "spans": [
                s.to_dict()
                for s in sorted(self.spans, key=lambda s: s.start_s)
            ],
        }


class TraceStore:
    """Active traces + a bounded ring of completed ones."""

    def __init__(self, max_completed: int = 512, max_active: int = 4096):
        self.max_completed = max_completed
        self.max_active = max_active
        self._active: dict[str, Trace] = {}
        # secondary ids resolving onto an active trace — the n>1 fanout
        # gives each extra choice its own request_id; their spans belong
        # on the parent request's tree
        self._aliases: dict[str, str] = {}
        self._completed: OrderedDict[str, Trace] = OrderedDict()
        # ids we saw but no longer hold, mapped to WHY ("evicted" ring
        # overflow vs "unsampled" shell drop) — lets /debug/trace 404s
        # distinguish "gone" from "never existed"; bounded like the ring
        self._gone: OrderedDict[str, str] = OrderedDict()
        self.evicted_total = 0
        self._lock = threading.Lock()

    def _note_gone(self, trace_id: str, reason: str) -> None:
        # caller holds self._lock
        self._gone[trace_id] = reason
        self._gone.move_to_end(trace_id)
        while len(self._gone) > 8 * self.max_completed:
            self._gone.popitem(last=False)

    def start(self, trace_id: str, sampled: bool = True) -> Trace:
        tr = Trace(trace_id=trace_id, sampled=sampled)
        with self._lock:
            # leak bound: a caller that never finishes its traces (crashed
            # stream, test teardown) must not grow the store unboundedly
            if len(self._active) >= self.max_active:
                self._active.pop(next(iter(self._active)))
            self._active[trace_id] = tr
        return tr

    def alias(self, trace_id: str, parent_id: str) -> None:
        """Route ``trace_id``'s spans onto ``parent_id``'s active trace
        (dropped when the parent finishes)."""
        with self._lock:
            if parent_id in self._active:
                self._aliases[trace_id] = parent_id

    def _resolve(self, trace_id: str) -> Optional[Trace]:
        return self._active.get(
            self._aliases.get(trace_id, trace_id)
        )

    def has_active(self, trace_id: str) -> bool:
        with self._lock:
            return self._resolve(trace_id) is not None

    def add_span(self, trace_id: str, span: Span) -> bool:
        """Record onto an ACTIVE trace; no-op (False) when none exists —
        stages call this unconditionally and remote-frontend cases fall
        through to the annotation path."""
        with self._lock:
            tr = self._resolve(trace_id)
            if tr is None:
                return False
            tr.add(span)  # shells buffer (bounded) for late promotion
            return tr.sampled

    def promote(self, trace_id: str) -> bool:
        """Turn an unsampled shell into a full trace mid-request —
        migrated/failed requests are always traced regardless of the
        sample rate. True if an active trace exists."""
        with self._lock:
            tr = self._resolve(trace_id)
            if tr is None:
                return False
            tr.sampled = True
            tr.adopt_buffer()
            return True

    def merge(self, trace_id: str, span_dicts: list[dict[str, Any]]) -> None:
        with self._lock:
            tr = self._resolve(trace_id)
        if tr is None:
            return
        if tr.sampled:
            tr.merge_dicts(span_dicts)
        else:
            # shell: park worker spans in the bounded buffer so a
            # finish-time promotion recovers them
            room = _SHELL_BUFFER_CAP - len(tr.buffered)
            if room > 0:
                shadow = Trace(trace_id=trace_id)
                shadow.merge_dicts(span_dicts[:room])
                tr.buffered.extend(shadow.spans)

    def finish(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            tr = self._active.pop(trace_id, None)
            if tr is None:
                return None
            self._aliases = {
                a: p for a, p in self._aliases.items() if p != trace_id
            }
            tr.finished = True
            if not tr.sampled:
                self._note_gone(trace_id, "unsampled")
                return tr  # shell: dropped, never parked in the ring
            self._completed[trace_id] = tr
            while len(self._completed) > self.max_completed:
                gone_id, _ = self._completed.popitem(last=False)
                self.evicted_total += 1
                self._note_gone(gone_id, "evicted")
            return tr

    def record_remote(
        self, trace_id: str, span_dicts: list[dict[str, Any]]
    ) -> None:
        """Worker-local registration: a finished trace built from the
        engine's own spans, for processes where no frontend owns the
        trace (the per-worker ``/debug/trace`` view)."""
        tr = Trace(trace_id=trace_id, finished=True)
        tr.merge_dicts(span_dicts)
        with self._lock:
            self._completed[trace_id] = tr
            self._completed.move_to_end(trace_id)
            while len(self._completed) > self.max_completed:
                gone_id, _ = self._completed.popitem(last=False)
                self.evicted_total += 1
                self._note_gone(gone_id, "evicted")

    def get(self, trace_id: str) -> Optional[Trace]:
        with self._lock:
            return self._completed.get(trace_id) or self._active.get(trace_id)

    def describe_missing(self, trace_id: str) -> dict[str, Any]:
        """404 body for /debug/trace/{id}: says WHY the trace is absent —
        ``evicted`` (ring overflow), ``unsampled`` (shell dropped at
        finish), or ``never_seen`` — plus enough ring state to judge
        whether raising --trace-sample-rate or the ring size would have
        kept it."""
        with self._lock:
            reason = self._gone.get(trace_id, "never_seen")
            oldest = next(iter(self._completed), None)
            return {
                "error": f"no trace for request {trace_id!r}",
                "reason": reason,
                "ring_capacity": self.max_completed,
                "retained": len(self._completed),
                "oldest_retained_id": oldest,
                "evicted_total": self.evicted_total,
            }

    def recent_ids(self, n: int = 50) -> list[str]:
        with self._lock:
            return list(self._completed)[-n:]

    def clear(self) -> None:
        with self._lock:
            self._active.clear()
            self._aliases.clear()
            self._completed.clear()
            self._gone.clear()
            self.evicted_total = 0


# process-wide store: the frontend, router, engine, and debug endpoints in
# one process share trace context through it (cross-process assembly rides
# the request_id + output annotations instead)
TRACES = TraceStore()
