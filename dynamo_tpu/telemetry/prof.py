"""Performance-attribution plane: where the engine's host milliseconds go.

BENCH_r07 left the host loop as the bottleneck (host_ms_per_step 1.57 vs
device 1.03) with no way to say WHERE inside `TpuEngine._round` the time
is spent. This module attributes every host-side slice of the serving
round to a named segment with a flat current-segment switch model:
``enter(seg)`` charges the elapsed time since the previous switch to the
previous segment, so the per-round segment sums equal the measured round
wall EXACTLY (self-coverage ~1.0 by construction) and the cost per
switch is one ``time.monotonic()`` call plus a float add — cheap enough
to stay always-on.

Per-round records accumulate in a bounded per-engine ring
(:class:`RoundProf`) and fold into the process-global :data:`PROF`
registry at the engine's metrics-publish cadence (~10 Hz), which renders
``dynamo_host_round_seconds{segment=...}`` histograms, a coverage-ratio
gauge, and the SLO burn-rate gauges on all three scrape surfaces (same
pattern as the RESILIENCE / KV_TRANSFER plane registries). ``/debug/prof``
serves the live top-segment summary.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Optional

import numpy as np

from .metrics import Histogram, render_histogram

# the host-round segment enum — the contract shared by the engine's
# enter() calls, `dynamo_host_round_seconds{segment=...}`, the
# `host_breakdown` JSON field (tools/profile_round.py --dispatch-budget,
# bench.py), and /debug/prof. Order is the approximate order the
# segments run inside _round.
SEGMENTS = (
    "intake",         # _drain_intake: waiting-queue pulls
    "slot_scan",      # bounds enforcement + active/inflight slot scans
    "fetch",          # _process_entries: result fetch + token emission
    "annotate",       # _final_annotations: finishing-output assembly
    "releases",       # _apply_releases: freed-lane patches
    "transfer",       # _process_transfers + export-stream servicing
    "offload",        # _dispatch_offloads + _drain_host_ingest
    "admit",          # _admit: prefill dispatch + admission patches
    "seal_assembly",  # _take_seal_batch: seal-batch packing
    "dispatch",       # _dispatch_round: fused-round program launch
    "spec_dispatch",  # _dispatch_spec: draft + verify launches
    "seal_flush",     # _flush_seals: standalone overflow seal dispatch
    "metrics_fold",   # metrics build/publish + prof fold
    "other",          # unattributed remainder of the round
)
_SEG_INDEX = {s: i for i, s in enumerate(SEGMENTS)}
_N_SEG = len(SEGMENTS)
_OTHER = _SEG_INDEX["other"]

# host segments run at µs scale — DEFAULT_TIME_BUCKETS' 0.5 ms floor
# would flatten the whole distribution into one bucket. Same ~1.6x step
# ladder, shifted three decades down, topping out at 0.1 s (a host slice
# beyond that is a bug the +Inf bucket makes visible).
HOST_BUCKETS = (
    0.000002, 0.000005, 0.00001, 0.00002, 0.000035, 0.00005, 0.000075,
    0.0001, 0.0002, 0.00035, 0.0005, 0.00075,
    0.001, 0.002, 0.0035, 0.005, 0.0075,
    0.01, 0.02, 0.035, 0.05, 0.1,
)

HOST_ROUND = ("dynamo_host_round_seconds",
              "host wall time per engine round by attribution segment")
COVERAGE = ("dynamo_host_round_coverage_ratio",
            "sum of attributed segment time / measured round wall "
            "(1.0 = fully attributed)")
SLO_TTFT_BURN = ("dynamo_slo_ttft_burn_rate",
                 "TTFT SLO burn rate: fraction of requests over the "
                 "target divided by the error budget (1-objective); "
                 ">1 burns budget")
SLO_ITL_BURN = ("dynamo_slo_itl_burn_rate",
                "ITL SLO burn rate: fraction of token gaps over the "
                "target divided by the error budget (1-objective); "
                ">1 burns budget")


class RoundProf:
    """Per-engine round-segment accumulator (flat switch model).

    Single-writer (the engine thread); readers take snapshots of the
    totals under the GIL via plain dict/list copies — per-field tearing
    across a read is acceptable for a profiler. ``enabled=False`` turns
    every method into an early-out so `prof_attribution=false` engines
    pay one attribute load + branch per call site.
    """

    RING = 256  # recent per-round records kept for /debug/prof + timeline

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._acc = [0.0] * _N_SEG     # current round, per segment
        self._seg = _OTHER
        self._t = 0.0
        self._t_begin = 0.0
        self._in_round = False
        # cumulative since engine start (fold-independent, what
        # host_breakdown deltas read)
        self.total = np.zeros(_N_SEG)
        self.rounds = 0
        self.wall_total = 0.0
        # recent rounds live in PREALLOCATED numpy rings (metrics_fold
        # diet: end_round writes one row, no per-round tuple/list churn,
        # and the fold side reads whole columns vectorized). Record k
        # occupies row k % RING; _rec_n counts records ever written and
        # _fold_mark the count already drained — the unfolded window is
        # the (at most RING) rows between them.
        self._ring_ts = np.zeros(self.RING)       # end unix time
        self._ring_wall = np.zeros(self.RING)     # round wall seconds
        self._ring_acc = np.zeros((self.RING, _N_SEG))
        self._rec_n = 0
        self._fold_mark = 0

    # -- engine-thread hot path ----------------------------------------

    def begin_round(self) -> None:
        if not self.enabled:
            return
        t = time.monotonic()
        self._acc = [0.0] * _N_SEG
        self._seg = _OTHER
        self._t = t
        self._t_begin = t
        self._in_round = True

    def enter(self, seg: int) -> None:
        """Charge time since the last switch to the PREVIOUS segment and
        make ``seg`` (an index into SEGMENTS) current."""
        if not self.enabled or not self._in_round:
            return
        t = time.monotonic()
        self._acc[self._seg] += t - self._t
        self._t = t
        self._seg = seg

    def push(self, seg: int) -> int:
        """Nested attribution (e.g. annotation build inside the fetch
        segment): switch to ``seg``, return the segment to restore."""
        prev = self._seg
        self.enter(seg)
        return prev

    def end_round(self, record: bool = True) -> None:
        if not self.enabled or not self._in_round:
            return
        self.enter(_OTHER)  # close the open segment
        self._in_round = False
        if not record:
            return  # idle spin — keep µs no-op rounds out of the stats
        wall = self._t - self._t_begin
        row = self._rec_n % self.RING
        self._ring_acc[row] = self._acc
        self._ring_wall[row] = wall
        self._ring_ts[row] = time.time()
        self._rec_n += 1
        self.total += self._ring_acc[row]
        self.rounds += 1
        self.wall_total += wall

    # -- fold / read side ----------------------------------------------

    def _rows(self, n: int) -> np.ndarray:
        """Ring rows of the newest ``n`` records, oldest first."""
        return np.arange(self._rec_n - n, self._rec_n) % self.RING

    def drain_arrays(self) -> Optional[np.ndarray]:
        """Unfolded per-round segment matrix [n, N_SEG] (None if empty)
        — the vectorized-fold feed. Advances the fold mark."""
        n = min(self._rec_n - self._fold_mark, self.RING)
        self._fold_mark = self._rec_n
        if n <= 0:
            return None
        return self._ring_acc[self._rows(n)]

    def drain(self) -> list[tuple]:
        """Unfolded rounds as (end_unix_s, wall_s, (per-seg s, ...))
        tuples — the legacy wire form (tests, ad-hoc tooling); the hot
        fold path uses drain_arrays() and never builds these."""
        n = min(self._rec_n - self._fold_mark, self.RING)
        rows = self._rows(n)
        self._fold_mark = self._rec_n
        return [
            (float(self._ring_ts[r]), float(self._ring_wall[r]),
             tuple(self._ring_acc[r]))
            for r in rows
        ]

    def recent(self, n: int = 64) -> list[tuple]:
        n = min(n, self._rec_n, self.RING)
        return [
            (float(self._ring_ts[r]), float(self._ring_wall[r]),
             tuple(self._ring_acc[r]))
            for r in self._rows(n)
        ]

    def totals(self) -> dict[str, Any]:
        """Cumulative attribution since engine start (seconds)."""
        return {
            "rounds": self.rounds,
            "wall_s": self.wall_total,
            "segments": {
                s: float(self.total[i]) for i, s in enumerate(SEGMENTS)
            },
        }

    def coverage(self) -> float:
        return (float(self.total.sum()) / self.wall_total
                if self.wall_total > 0 else 1.0)

    def summary(self, top: int = 0) -> dict[str, Any]:
        """The /debug/prof payload: cumulative per-segment share plus a
        recent-window (ring) per-round mean, sorted hottest first."""
        totals = self.totals()
        wall = totals["wall_s"]
        n_recent = min(self._rec_n, self.RING)
        rows_idx = self._rows(n_recent)
        r_wall = float(self._ring_wall[rows_idx].sum())
        r_seg = self._ring_acc[rows_idx].sum(axis=0)
        rows = []
        for i, s in enumerate(SEGMENTS):
            tot = totals["segments"][s]
            rows.append({
                "segment": s,
                "total_s": round(tot, 6),
                "share": round(tot / wall, 4) if wall > 0 else 0.0,
                "recent_mean_us": round(
                    float(r_seg[i]) / n_recent * 1e6, 2
                ) if n_recent else 0.0,
            })
        rows.sort(key=lambda r: r["total_s"], reverse=True)
        if top:
            rows = rows[:top]
        return {
            "enabled": self.enabled,
            "rounds": totals["rounds"],
            "wall_s": round(wall, 6),
            "recent_rounds": n_recent,
            "recent_wall_ms_per_round": round(
                r_wall / n_recent * 1e3, 4) if n_recent else 0.0,
            "coverage_ratio": round(self.coverage(), 4),
            "segments": rows,
        }


class ProfRegistry:
    """Process-global render surface for the attribution plane: one
    ``dynamo_host_round_seconds`` histogram per segment plus the
    coverage and SLO burn-rate gauges. Appended to all three scrape
    surfaces exactly like the RESILIENCE / KV_TRANSFER registries —
    live in engine processes, zeros elsewhere."""

    def __init__(self) -> None:
        self._hists = {
            s: Histogram(HOST_ROUND[0], HOST_ROUND[1], HOST_BUCKETS)
            for s in SEGMENTS
        }
        self._lock = threading.Lock()
        self._coverage = 1.0
        self._burn = {"ttft": 0.0, "itl": 0.0}
        # SLO targets (EngineConfig/RuntimeConfig slo_* knobs); engines
        # and frontends configure() at init so scrape-time refreshes use
        # the deployed targets
        self.ttft_target_s = 0.5
        self.itl_target_s = 0.05
        self.objective = 0.99

    def configure(
        self,
        ttft_target_s: float,
        itl_target_s: float,
        objective: float,
    ) -> None:
        with self._lock:
            self.ttft_target_s = ttft_target_s
            self.itl_target_s = itl_target_s
            self.objective = objective

    def fold(self, prof: RoundProf) -> None:
        """Drain a RoundProf's unfolded rounds into the histograms —
        called from the engine thread inside the metrics_fold segment, at
        the publish cadence rather than per round. Vectorized: one
        observe_many per segment COLUMN of the drained [n, N_SEG] matrix
        instead of a Python observe per (round, segment) cell."""
        accs = prof.drain_arrays()
        if accs is not None:
            hists = self._hists
            for i, s in enumerate(SEGMENTS):
                col = accs[:, i]
                hists[s].observe_many(col[col > 0.0])
        with self._lock:
            self._coverage = prof.coverage()

    def fold_burn_rates(
        self,
        ttft_snap: Optional[dict[str, Any]],
        itl_snap: Optional[dict[str, Any]],
        ttft_target_s: Optional[float] = None,
        itl_target_s: Optional[float] = None,
        objective: Optional[float] = None,
    ) -> dict[str, float]:
        """Recompute the SLO burn-rate gauges from live TTFT/ITL
        histogram snapshots. Burn rate = (fraction of observations over
        the target) / (1 - objective): 1.0 means the error budget is
        being consumed exactly at the sustainable rate, >1 faster.
        Targets default to the configure()d ones."""
        with self._lock:
            if ttft_target_s is None:
                ttft_target_s = self.ttft_target_s
            if itl_target_s is None:
                itl_target_s = self.itl_target_s
            if objective is None:
                objective = self.objective
        budget = max(1.0 - objective, 1e-9)
        burn = {
            "ttft": frac_over_target(ttft_snap, ttft_target_s) / budget,
            "itl": frac_over_target(itl_snap, itl_target_s) / budget,
        }
        with self._lock:
            self._burn = burn
        return burn

    def burn_rates(self) -> dict[str, float]:
        with self._lock:
            return dict(self._burn)

    def coverage_ratio(self) -> float:
        with self._lock:
            return self._coverage

    def snapshot(self) -> dict[str, dict[str, Any]]:
        return {s: h.snapshot() for s, h in self._hists.items()}

    def reset(self) -> None:
        for h in self._hists.values():
            h.reset()
        with self._lock:
            self._coverage = 1.0
            self._burn = {"ttft": 0.0, "itl": 0.0}

    def render(self) -> str:
        lines: list[str] = []
        for i, s in enumerate(SEGMENTS):
            seg_lines = render_histogram(
                HOST_ROUND[0], HOST_ROUND[1],
                self._hists[s].snapshot(), label=f'segment="{s}"',
            )
            # one HELP/TYPE head for the family; later segments drop it
            lines.extend(seg_lines if i == 0 else seg_lines[2:])
        with self._lock:
            cov, burn = self._coverage, dict(self._burn)
        for (name, help_), v in (
            (COVERAGE, cov),
            (SLO_TTFT_BURN, burn["ttft"]),
            (SLO_ITL_BURN, burn["itl"]),
        ):
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name} {round(v, 6)}")
        return "\n".join(lines) + "\n"


def frac_over_target(
    snap: Optional[dict[str, Any]], target_s: float
) -> float:
    """Fraction of a histogram snapshot's observations above ``target_s``,
    linearly interpolated inside the bucket the target falls in (the
    CDF complement of histogram_quantile's estimator). 0.0 when empty."""
    if not snap:
        return 0.0
    total = snap.get("count", 0)
    buckets = snap.get("buckets") or []
    counts = snap.get("counts") or []
    if not total or not buckets or len(counts) != len(buckets) + 1:
        return 0.0
    prev_cum = 0
    lo = 0.0
    for edge, cum in zip(buckets, counts[:-1]):
        if target_s <= edge:
            in_bucket = cum - prev_cum
            width = edge - lo
            frac = (target_s - lo) / width if width > 0 else 1.0
            cum_at = prev_cum + in_bucket * frac
            return max(0.0, min(1.0, (total - cum_at) / total))
        prev_cum = cum
        lo = edge
    # target beyond the top finite edge: only +Inf observations exceed it
    return (total - counts[-2]) / total if len(counts) >= 2 else 0.0


PROF = ProfRegistry()

__all__ = [
    "SEGMENTS",
    "HOST_BUCKETS",
    "HOST_ROUND",
    "COVERAGE",
    "SLO_TTFT_BURN",
    "SLO_ITL_BURN",
    "RoundProf",
    "ProfRegistry",
    "frac_over_target",
    "PROF",
]
