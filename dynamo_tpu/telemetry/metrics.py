"""Explicit-bucket Prometheus histograms for the request latency plane.

Hand-rolled rather than prometheus_client because the engine thread
observes them, three different servers render them (frontend, per-worker
system server, aggregating exporter), and their SNAPSHOTS must travel
inside ForwardPassMetrics across the pub/sub plane — a plain
dict-of-counts representation does all three; a client registry does
none of them cleanly.

Buckets follow the Prometheus contract: ``le``-labelled CUMULATIVE
counts with a ``+Inf`` terminal bucket, plus ``_sum`` and ``_count``.
"""
from __future__ import annotations

import math
import threading
import time
from typing import Any, Optional

import numpy as np

# decode steps run ~1-100 ms, TTFT ~10 ms-10 s, E2E up to minutes: a
# 1-2-3.5-5-7.5 per-decade ladder covers every request-latency series.
# Resolution matters beyond dashboards — bench.py reports percentiles
# interpolated from these buckets, so each step is kept under ~1.6x
# (a within-bucket shift quantizes to at most that).
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.002, 0.0035, 0.005, 0.0075,
    0.01, 0.02, 0.035, 0.05, 0.075,
    0.1, 0.2, 0.35, 0.5, 0.75,
    1.0, 2.0, 3.5, 5.0, 7.5,
    10.0, 20.0, 35.0, 60.0, 120.0,
)


class Histogram:
    """One histogram series (no labels — renderers attach the worker
    label). Thread-safe: observed from the engine thread, rendered from
    asyncio handlers."""

    def __init__(
        self,
        name: str,
        help_: str,
        buckets: tuple[float, ...] = DEFAULT_TIME_BUCKETS,
    ):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        # bucket index -> (exemplar_id, value, unix_ts): the LAST observed
        # id per bucket, rendered as an OpenMetrics exemplar so a heatmap
        # cell links to a concrete request's dossier
        self._exemplars: dict[int, tuple[str, float, float]] = {}
        self._lock = threading.Lock()

    def observe(
        self, value: float, n: int = 1, exemplar_id: Optional[str] = None
    ) -> None:
        """Record ``value`` ``n`` times (n>1: a batch of identical
        observations, e.g. per-token gaps derived from one round).
        ``exemplar_id`` tags the target bucket with a trace id."""
        if n <= 0 or not math.isfinite(value):
            return
        i = len(self.buckets)
        for j, b in enumerate(self.buckets):
            if value <= b:
                i = j
                break
        with self._lock:
            self._counts[i] += n
            self._sum += value * n
            self._count += n
            if exemplar_id:
                self._exemplars[i] = (exemplar_id, value, time.time())

    def observe_many(self, values) -> None:
        """Vectorized observe for a 1-D numpy batch: one searchsorted +
        bincount and ONE lock acquisition instead of a Python bucket
        scan per value (the prof-fold path observes up to 256 rounds x
        14 segments per publish tick)."""
        values = np.asarray(values, np.float64)
        values = values[np.isfinite(values)]
        n = int(values.size)
        if not n:
            return
        # side="left": first edge with value <= edge, matching observe()
        idx = np.searchsorted(np.asarray(self.buckets), values, side="left")
        binc = np.bincount(idx, minlength=len(self.buckets) + 1)
        total = float(values.sum())
        with self._lock:
            for i in np.flatnonzero(binc):
                self._counts[i] += int(binc[i])
            self._sum += total
            self._count += n

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict[str, Any]:
        """Wire form: cumulative counts aligned with ``buckets`` + +Inf.
        When exemplars were observed, an ``exemplars`` key maps bucket
        index (stringified for JSON round-trips) to [id, value, ts]."""
        with self._lock:
            cum = []
            total = 0
            for c in self._counts:
                total += c
                cum.append(total)
            snap: dict[str, Any] = {
                "buckets": list(self.buckets),
                "counts": cum,        # cumulative, last entry == count
                "sum": self._sum,
                "count": self._count,
            }
            if self._exemplars:
                snap["exemplars"] = {
                    str(i): [eid, v, ts]
                    for i, (eid, v, ts) in self._exemplars.items()
                }
            return snap

    def percentile(self, q: float) -> Optional[float]:
        return percentile_from_snapshot(self.snapshot(), q)

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * (len(self.buckets) + 1)
            self._sum = 0.0
            self._count = 0
            self._exemplars.clear()

    def render(self, label: str = "", openmetrics: bool = False) -> list[str]:
        return render_histogram(
            self.name, self.help, self.snapshot(), label,
            openmetrics=openmetrics,
        )


class CounterRegistry:
    """Thread-safe fixed-family counter/gauge registry with optional
    explicit-bucket histograms, rendered as one Prometheus text block.

    Subsystem metric planes (resilience, kv-transfer) instantiate this
    with their family set so the locking, the unknown-series assert and
    the HELP/TYPE rendering live in one place instead of one copy per
    plane. Families are ``(name, type, help)`` tuples; histograms are
    ``(name, help)`` tuples using the default time buckets."""

    def __init__(
        self,
        families: tuple[tuple[str, str, str], ...],
        histograms: tuple[tuple[str, str], ...] = (),
        label: str = "registry",
    ):
        self._families = tuple(families)
        self._known = {name for name, _, _ in self._families}
        self._label = label
        self._values: dict[str, float] = {n: 0.0 for n in self._known}
        self._lock = threading.Lock()
        self._hists: dict[str, Histogram] = {
            name: Histogram(name, help_) for name, help_ in histograms
        }

    def inc(self, name: str, n: float = 1.0) -> None:
        assert name in self._known, \
            f"unknown {self._label} series {name!r}"
        with self._lock:
            self._values[name] += n

    def set(self, name: str, v: float) -> None:
        assert name in self._known, \
            f"unknown {self._label} series {name!r}"
        with self._lock:
            self._values[name] = float(v)

    def get(self, name: str) -> float:
        with self._lock:
            return self._values[name]

    def observe(
        self, name: str, value: float, n: int = 1,
        exemplar_id: Optional[str] = None,
    ) -> None:
        self._hists[name].observe(value, n, exemplar_id=exemplar_id)

    def histogram(self, name: str) -> Histogram:
        return self._hists[name]

    def reset(self) -> None:
        with self._lock:
            for name in self._values:
                self._values[name] = 0.0
        for h in self._hists.values():
            h.reset()

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return dict(self._values)

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text for every family (trailing newline included)."""
        snap = self.snapshot()
        lines: list[str] = []
        for name, typ, help_ in self._families:
            lines.append(f"# HELP {name} {help_}")
            lines.append(f"# TYPE {name} {typ}")
            v = snap[name]
            lines.append(f"{name} {int(v) if v == int(v) else v}")
        for h in self._hists.values():
            lines.extend(h.render(openmetrics=openmetrics))
        return "\n".join(lines) + "\n"


def percentile_from_snapshot(
    snap: dict[str, Any], q: float
) -> Optional[float]:
    """Estimate the q-th percentile (0..1) from cumulative bucket counts
    by linear interpolation inside the target bucket (the standard
    ``histogram_quantile`` estimator). None when empty; observations in
    the +Inf bucket clamp to the top finite edge."""
    total = snap.get("count", 0)
    buckets = snap.get("buckets") or []
    counts = snap.get("counts") or []
    if not total or not buckets or len(counts) != len(buckets) + 1:
        return None
    rank = q * total
    prev_cum = 0
    lo = 0.0
    for edge, cum in zip(buckets, counts[:-1]):
        if rank <= cum:
            in_bucket = cum - prev_cum
            frac = (rank - prev_cum) / in_bucket if in_bucket else 0.0
            return lo + (edge - lo) * frac
        prev_cum = cum
        lo = edge
    return buckets[-1]


def weighted_percentile(
    pairs: list, q: float
) -> Optional[float]:
    """q-th percentile (0..1) over (value, weight) pairs — the
    per-request ITL estimator shared by the engine's timing annotation
    and the frontend's llm_metrics event."""
    if not pairs:
        return None
    pairs = sorted(pairs)
    total = sum(n for _, n in pairs)
    if total <= 0:
        return None
    rank = q * total
    seen = 0
    for value, n in pairs:
        seen += n
        if seen >= rank:
            return value
    return pairs[-1][0]


def render_histogram(
    name: str, help_: str, snap: dict[str, Any], label: str = "",
    *, openmetrics: bool = False,
) -> list[str]:
    """Prometheus text-format lines for one snapshot. ``label`` is a
    pre-rendered extra label pair (e.g. ``worker="w0"``) or empty.

    ``openmetrics=True`` appends ``# {trace_id="..."} value ts`` exemplar
    suffixes to bucket lines that carry one (the OpenMetrics exposition
    format); the default plain Prometheus text output is byte-identical
    to what it always was — exemplars only ship to scrapers that
    negotiated ``application/openmetrics-text``."""

    def fmt(le: str) -> str:
        pairs = f'le="{le}"' if not label else f'{label},le="{le}"'
        return f"{name}_bucket{{{pairs}}}"

    exemplars = snap.get("exemplars") or {} if openmetrics else {}

    def ex(i: int) -> str:
        e = exemplars.get(str(i)) or exemplars.get(i)
        if not e:
            return ""
        eid, value, ts = e
        return f' # {{trace_id="{eid}"}} {value} {ts:.3f}'

    lines = [f"# HELP {name} {help_}", f"# TYPE {name} histogram"]
    for i, (edge, cum) in enumerate(zip(snap["buckets"], snap["counts"][:-1])):
        lines.append(f"{fmt(repr(float(edge)))} {cum}{ex(i)}")
    n_b = len(snap["buckets"])
    lines.append(f"{fmt('+Inf')} {snap['counts'][-1]}{ex(n_b)}")
    suffix = f"{{{label}}}" if label else ""
    lines.append(f"{name}_sum{suffix} {snap['sum']}")
    lines.append(f"{name}_count{suffix} {snap['count']}")
    return lines


class TelemetryRegistry:
    """Ordered set of histograms with one render/snapshot surface."""

    def __init__(self) -> None:
        self._hists: dict[str, Histogram] = {}

    def histogram(
        self,
        name: str,
        help_: str,
        buckets: tuple[float, ...] = DEFAULT_TIME_BUCKETS,
    ) -> Histogram:
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = Histogram(name, help_, buckets)
        return h

    def get(self, name: str) -> Optional[Histogram]:
        return self._hists.get(name)

    def snapshot(self) -> dict[str, dict[str, Any]]:
        """name -> {help, buckets, counts, sum, count} — the wire form
        carried in ForwardPassMetrics.histograms."""
        return {
            name: dict(h.snapshot(), help=h.help)
            for name, h in self._hists.items()
        }

    def render(self, label: str = "", openmetrics: bool = False) -> str:
        lines: list[str] = []
        for h in self._hists.values():
            lines.extend(h.render(label, openmetrics=openmetrics))
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        for h in self._hists.values():
            h.reset()


# canonical request-latency series (names are the metrics contract —
# tests/test_metrics_contract.py asserts they render with HELP/TYPE and
# are documented in README)
TTFT = ("dynamo_request_ttft_seconds",
        "time from request receipt to first emitted token")
ITL = ("dynamo_request_itl_seconds",
       "inter-token latency (per-token gaps within one generation)")
E2E = ("dynamo_request_e2e_seconds",
       "end-to-end request latency (receipt to finish)")
QUEUE = ("dynamo_request_queue_seconds",
         "admission queue wait (enqueue to prefill start)")
ROUND = ("dynamo_engine_round_seconds",
         "engine round wall time (dispatch to result processed)")


def request_histograms(
    reg: TelemetryRegistry, *, engine: bool = False
) -> TelemetryRegistry:
    """Install the canonical request series on ``reg``. ``engine=True``
    adds the engine-only series (queue wait, round time)."""
    for name, help_ in (TTFT, ITL, E2E):
        reg.histogram(name, help_)
    if engine:
        for name, help_ in (QUEUE, ROUND):
            reg.histogram(name, help_)
    return reg
