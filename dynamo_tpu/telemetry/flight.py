"""Flight recorder: a fixed-size ring of recent engine-round events.

The engine records one entry per device-work dispatch (fused decode
round, spec verify, prefill chunk / batch, sp prefill) with the slot
set, speculative participation, and the host wall time the dispatch
took. The ring is served live at ``/debug/flight`` and dumped to the
log when an engine round fails — the last N dispatches before a crash
are exactly what postmortems need and exactly what logs never have.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Optional


class FlightRecorder:
    """Bounded ring of event dicts; thread-safe (engine thread writes,
    asyncio debug handlers read)."""

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        self._ring: list[Optional[dict[str, Any]]] = [None] * self.capacity
        self._next = 0          # ring write index
        self._seq = 0           # monotonically increasing event id
        self._lock = threading.Lock()

    def record(self, kind: str, **fields: Any) -> None:
        # the dict is assembled OUTSIDE the lock (it's built from
        # caller-local data; only the seq stamp and ring write need
        # exclusion) — record() sits on the engine's dispatch hot path
        ev = {"seq": 0, "ts": round(time.time(), 6), "kind": kind,
              **fields}
        with self._lock:
            ev["seq"] = self._seq
            self._seq += 1
            self._ring[self._next] = ev
            self._next = (self._next + 1) % self.capacity

    def __len__(self) -> int:
        with self._lock:
            return min(self._seq, self.capacity)

    @property
    def recorded_total(self) -> int:
        with self._lock:
            return self._seq

    def snapshot(self) -> list[dict[str, Any]]:
        """Events oldest -> newest."""
        with self._lock:
            # strict <: at exactly `capacity` events _next has wrapped to
            # 0 and the ring is full — the sliced-prefix form would
            # return nothing
            if self._seq < self.capacity:
                out = self._ring[: self._next]
            else:
                out = self._ring[self._next:] + self._ring[: self._next]
            return [dict(e) for e in out if e is not None]

    def dump(self, log: Any, reason: str = "") -> None:
        """Write the ring to ``log`` (error level) — called on engine
        failure so the crash report carries the recent dispatch history."""
        events = self.snapshot()
        log.error(
            "flight recorder dump (%d of %d events)%s",
            len(events), self.recorded_total,
            f": {reason}" if reason else "",
        )
        for ev in events:
            log.error("  flight %s", ev)
