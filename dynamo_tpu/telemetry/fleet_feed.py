"""Fleet-merged request-latency histograms: workers -> one distribution.

Per-worker latency histograms already travel inside
``ForwardPassMetrics.histograms`` (telemetry/metrics.py snapshots over
the store's load-metrics topics). Until now every consumer rendered them
per-worker; the planner's predictive mode saw only stream counts and
``WorkerLoadView`` queue-wait point estimates. This module merges the
per-worker cumulative snapshots into ``dynamo_fleet_request_*`` families
— identical bucket ladders sum bucket-wise — and exposes the result
both ways:

- scrape surface: ``FLEET_FEED.render()`` on the frontend ``/metrics``,
  the per-worker system server and the aggregating exporter (the
  metrics contract's three-surface rule), exemplars preserved (the
  freshest per bucket across workers) when OpenMetrics is negotiated;
- programmatic feed: ``merged()`` cumulative snapshots,
  ``percentile()``, and ``advance()`` interval-delta snapshots — the
  planner reads the RECENT window, not the all-time distribution a
  cumulative histogram converges to.

Fed from whatever sees the load plane: ``ModelWatcher._follow_metrics``
(frontend), ``MetricsExporter._follow`` (exporter), and the system
server's own engine at scrape time (a fleet of one).
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Optional

from dynamo_tpu.telemetry.metrics import (
    percentile_from_snapshot,
    render_histogram,
)

# worker family -> (fleet family, help). Merging is restricted to this
# map: the request-latency series are the fleet-meaningful ones, and the
# explicit literals keep the metrics contract (README rows, DTL005)
# checkable statically.
FLEET_FAMILIES: dict[str, tuple[str, str]] = {
    "dynamo_request_ttft_seconds": (
        "dynamo_fleet_request_ttft_seconds",
        "fleet-merged time to first token (sum of worker histograms)"),
    "dynamo_request_itl_seconds": (
        "dynamo_fleet_request_itl_seconds",
        "fleet-merged inter-token latency (sum of worker histograms)"),
    "dynamo_request_e2e_seconds": (
        "dynamo_fleet_request_e2e_seconds",
        "fleet-merged end-to-end request latency (sum of worker "
        "histograms)"),
    "dynamo_request_queue_seconds": (
        "dynamo_fleet_request_queue_seconds",
        "fleet-merged admission queue wait (sum of worker histograms)"),
    "dynamo_engine_round_seconds": (
        "dynamo_fleet_engine_round_seconds",
        "fleet-merged engine round wall time (sum of worker histograms)"),
}

_WORKERS_GAUGE = (
    "dynamo_fleet_feed_workers",
    "workers contributing fresh histogram snapshots to the fleet merge")


def _merge_snaps(snaps: list[dict[str, Any]]) -> Optional[dict[str, Any]]:
    """Sum cumulative snapshots with identical bucket ladders; snapshots
    on a different ladder are skipped (a mixed-version fleet must not
    corrupt the merge). Exemplars keep the freshest entry per bucket."""
    base: Optional[dict[str, Any]] = None
    for snap in snaps:
        buckets = snap.get("buckets") or []
        counts = snap.get("counts") or []
        if not buckets or len(counts) != len(buckets) + 1:
            continue
        if base is None:
            base = {
                "buckets": list(buckets),
                "counts": list(counts),
                "sum": float(snap.get("sum", 0.0)),
                "count": int(snap.get("count", 0)),
            }
            if snap.get("exemplars"):
                base["exemplars"] = dict(snap["exemplars"])
            continue
        if list(buckets) != base["buckets"]:
            continue
        base["counts"] = [a + b for a, b in zip(base["counts"], counts)]
        base["sum"] += float(snap.get("sum", 0.0))
        base["count"] += int(snap.get("count", 0))
        for i, e in (snap.get("exemplars") or {}).items():
            cur = base.setdefault("exemplars", {}).get(i)
            if cur is None or e[2] > cur[2]:
                base["exemplars"][i] = e
    return base


def _delta_snap(
    cur: dict[str, Any], prev: Optional[dict[str, Any]]
) -> dict[str, Any]:
    """Interval delta of two cumulative snapshots. A regressed count
    (worker left the fleet / restarted) resets the baseline: the current
    cumulative snapshot is returned whole rather than a negative delta."""
    if prev is None or prev.get("buckets") != cur.get("buckets"):
        return cur
    d_count = cur["count"] - prev["count"]
    d_counts = [a - b for a, b in zip(cur["counts"], prev["counts"])]
    if d_count < 0 or any(c < 0 for c in d_counts):
        return cur
    out: dict[str, Any] = {
        "buckets": list(cur["buckets"]),
        "counts": d_counts,
        "sum": cur["sum"] - prev["sum"],
        "count": d_count,
    }
    if cur.get("exemplars"):
        out["exemplars"] = dict(cur["exemplars"])
    return out


class FleetLatencyFeed:
    """Latest per-worker histogram snapshots + the fleet-wide merge.

    Thread-safe: store-follower tasks observe while scrape handlers and
    the planner read. ``clock`` is injectable (monotonic seconds) so
    fleetsim's VirtualClock governs staleness."""

    def __init__(
        self,
        stale_after_s: float = 10.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self.stale_after_s = stale_after_s
        self._clock = clock or time.monotonic
        # worker -> (observed_at, {worker family name: snapshot})
        self._per_worker: dict[str, tuple[float, dict[str, dict]]] = {}
        # advance() baseline: fleet family name -> last cumulative merge
        self._prev_merged: dict[str, dict[str, Any]] = {}
        self._lock = threading.Lock()

    def observe(self, m: Any) -> None:
        """Fold one ForwardPassMetrics-shaped update (anything with
        ``worker_id`` and ``histograms``) into the per-worker table."""
        hists = getattr(m, "histograms", None)
        if not hists:
            return
        worker = str(getattr(m, "worker_id", "") or "")
        keep = {n: s for n, s in hists.items() if n in FLEET_FAMILIES}
        if not keep:
            return
        with self._lock:
            self._per_worker[worker] = (self._clock(), keep)

    def _fresh(self) -> dict[str, dict[str, dict]]:
        now = self._clock()
        with self._lock:
            stale = [w for w, (ts, _) in self._per_worker.items()
                     if now - ts > self.stale_after_s]
            for w in stale:
                del self._per_worker[w]
            return {w: snaps for w, (_, snaps) in self._per_worker.items()}

    def workers(self) -> list[str]:
        return sorted(self._fresh())

    def merged(self) -> dict[str, dict[str, Any]]:
        """Fleet family name -> merged cumulative snapshot (with
        ``help``), summed over non-stale workers."""
        per_worker = self._fresh()
        out: dict[str, dict[str, Any]] = {}
        for worker_name, (fleet_name, help_) in FLEET_FAMILIES.items():
            snaps = [snaps[worker_name] for snaps in per_worker.values()
                     if worker_name in snaps]
            merged = _merge_snaps(snaps)
            if merged is not None:
                merged["help"] = help_
                out[fleet_name] = merged
        return out

    def percentile(self, fleet_name: str, q: float) -> Optional[float]:
        snap = self.merged().get(fleet_name)
        return percentile_from_snapshot(snap, q) if snap else None

    def advance(self) -> dict[str, dict[str, Any]]:
        """Interval-delta snapshots since the previous ``advance()`` —
        the planner's read: what the fleet's latency looked like over
        the last decide interval, not since process start."""
        cur = self.merged()
        with self._lock:
            prev, self._prev_merged = self._prev_merged, cur
        return {name: _delta_snap(snap, prev.get(name))
                for name, snap in cur.items()}

    def render(self, openmetrics: bool = False) -> str:
        """Prometheus text for the merged families + the contributing
        worker-count gauge (same families on every scrape surface)."""
        merged = self.merged()
        lines: list[str] = [
            f"# HELP {_WORKERS_GAUGE[0]} {_WORKERS_GAUGE[1]}",
            f"# TYPE {_WORKERS_GAUGE[0]} gauge",
            f"{_WORKERS_GAUGE[0]} {len(self._fresh())}",
        ]
        for name in sorted(merged):
            snap = merged[name]
            lines.extend(render_histogram(
                name, snap.get("help", name), snap,
                openmetrics=openmetrics,
            ))
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        with self._lock:
            self._per_worker.clear()
            self._prev_merged.clear()


# process-wide feed shared by the frontend watcher, the scrape surfaces
# and any in-process planner consumer (planners running their OWN store
# subscription construct a private instance instead)
FLEET_FEED = FleetLatencyFeed()
