"""Request-lifecycle telemetry: trace spans, latency histograms, the
per-worker flight recorder, and the performance-attribution plane.

Parity: the reference Dynamo stack's observability plane (Prometheus +
Grafana dashboards fed by per-worker ForwardPassMetrics, request
annotations carrying per-request timings, and the planner consuming the
resulting distributions). Five pieces:

  trace.py    trace context minted at the frontend, spans recorded at
              every pipeline stage, worker spans returned in-band via
              output annotations and merged into one tree served at
              ``/debug/trace/{request_id}``
  metrics.py  explicit-bucket Prometheus histograms (TTFT / ITL / E2E /
              queue wait / engine round) rendered by the frontend, the
              per-worker system server, and the aggregating exporter
  flight.py   fixed-size ring of recent engine-round events served at
              ``/debug/flight`` and dumped to the log on engine failure
  prof.py     per-round host-segment attribution (where the host
              milliseconds go): ``dynamo_host_round_seconds{segment}``
              histograms, the SLO burn-rate gauges, ``/debug/prof``
  timeline.py Perfetto/Chrome-trace assembly merging spans, round
              segments, flight events, and kv-transfer stream events
              (tools/trace_export.py is the CLI)
"""
from dynamo_tpu.telemetry.fleet_feed import FLEET_FEED, FleetLatencyFeed
from dynamo_tpu.telemetry.flight import FlightRecorder
from dynamo_tpu.telemetry.forensics import (
    FORENSICS,
    OUTLIERS,
    Dossier,
    DossierRing,
    ForensicsCapture,
    kv_path_from_spans,
)
from dynamo_tpu.telemetry.metrics import (
    DEFAULT_TIME_BUCKETS,
    Histogram,
    TelemetryRegistry,
    percentile_from_snapshot,
    request_histograms,
)
from dynamo_tpu.telemetry.prof import (
    HOST_BUCKETS,
    PROF,
    SEGMENTS,
    ProfRegistry,
    RoundProf,
)
from dynamo_tpu.telemetry.timeline import (
    STREAM_EVENTS,
    StreamEventRing,
    to_chrome_trace,
    trace_to_chrome,
)
from dynamo_tpu.telemetry.trace import TRACES, Span, Trace, TraceStore

__all__ = [
    "DEFAULT_TIME_BUCKETS",
    "Dossier",
    "DossierRing",
    "FLEET_FEED",
    "FleetLatencyFeed",
    "FlightRecorder",
    "FORENSICS",
    "ForensicsCapture",
    "kv_path_from_spans",
    "OUTLIERS",
    "Histogram",
    "HOST_BUCKETS",
    "PROF",
    "ProfRegistry",
    "RoundProf",
    "SEGMENTS",
    "Span",
    "STREAM_EVENTS",
    "StreamEventRing",
    "TelemetryRegistry",
    "Trace",
    "TraceStore",
    "TRACES",
    "percentile_from_snapshot",
    "request_histograms",
    "to_chrome_trace",
    "trace_to_chrome",
]
