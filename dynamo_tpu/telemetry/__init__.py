"""Request-lifecycle telemetry: trace spans, latency histograms, and the
per-worker flight recorder.

Parity: the reference Dynamo stack's observability plane (Prometheus +
Grafana dashboards fed by per-worker ForwardPassMetrics, request
annotations carrying per-request timings, and the planner consuming the
resulting distributions). Three pieces:

  trace.py    trace context minted at the frontend, spans recorded at
              every pipeline stage, worker spans returned in-band via
              output annotations and merged into one tree served at
              ``/debug/trace/{request_id}``
  metrics.py  explicit-bucket Prometheus histograms (TTFT / ITL / E2E /
              queue wait / engine round) rendered by the frontend, the
              per-worker system server, and the aggregating exporter
  flight.py   fixed-size ring of recent engine-round events served at
              ``/debug/flight`` and dumped to the log on engine failure
"""
from dynamo_tpu.telemetry.flight import FlightRecorder
from dynamo_tpu.telemetry.metrics import (
    DEFAULT_TIME_BUCKETS,
    Histogram,
    TelemetryRegistry,
    percentile_from_snapshot,
    request_histograms,
)
from dynamo_tpu.telemetry.trace import TRACES, Span, Trace, TraceStore

__all__ = [
    "DEFAULT_TIME_BUCKETS",
    "FlightRecorder",
    "Histogram",
    "Span",
    "TelemetryRegistry",
    "Trace",
    "TraceStore",
    "TRACES",
    "percentile_from_snapshot",
    "request_histograms",
]
