"""Perfetto/Chrome-trace timeline assembly for one request or bench phase.

Merges four event sources into a single ``trace.json`` loadable at
ui.perfetto.dev (or chrome://tracing):

- the request span tree (telemetry/trace.py — frontend + worker spans,
  including disagg kv-chunk and spec draft/verify children),
- per-round host-segment breakdowns (telemetry/prof.py RoundProf ring),
- flight-recorder dispatch events (telemetry/flight.py),
- kv_transfer / disagg STREAM events recorded here: frame sends/recvs,
  eof-ack waits and commit-event wakeups — the micro-events that make
  the disagg overlap gaps visible as timeline holes rather than one
  overlap ratio.

Everything renders as standard Trace Event Format: ``X`` (complete)
events with µs timestamps on per-source tracks, ``i`` (instant) events
for the flight recorder. ``tools/trace_export.py`` is the CLI.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Optional

from .prof import SEGMENTS

# stream-event kinds (the kv_transfer/disagg instrumentation contract)
FRAME_SEND = "frame_send"        # PageStreamWriter.write_chunk
FRAME_RECV = "frame_recv"        # BlockTransferServer streamed write_pages
EOF_ACK_WAIT = "eof_ack_wait"    # PageStreamWriter.commit ack wait
COMMIT_WAKEUP = "commit_wakeup"  # disagg PrefillWorker._wait_progress


class StreamEventRing:
    """Bounded ring of kv-transfer/disagg stream events; process-global
    (stream endpoints live in several classes across two modules — a ring
    per object would fragment the timeline). Thread-safe: asyncio
    handlers and the engine thread both record."""

    def __init__(self, capacity: int = 2048):
        self.capacity = max(1, int(capacity))
        self._ring: list[Optional[dict[str, Any]]] = [None] * self.capacity
        self._next = 0
        self._seq = 0
        self._lock = threading.Lock()

    def record(self, kind: str, dur_s: float = 0.0, **attrs: Any) -> None:
        """Record an event ENDING now that lasted ``dur_s`` seconds."""
        ts = time.time() - dur_s
        with self._lock:
            ev = {"seq": self._seq, "kind": kind,
                  "ts": round(ts, 6), "dur_s": round(dur_s, 6), **attrs}
            self._seq += 1
            self._ring[self._next] = ev
            self._next = (self._next + 1) % self.capacity

    def snapshot(self) -> list[dict[str, Any]]:
        with self._lock:
            if self._seq < self.capacity:
                out = self._ring[: self._next]
            else:
                out = self._ring[self._next:] + self._ring[: self._next]
            return [dict(e) for e in out if e is not None]

    def clear(self) -> None:
        with self._lock:
            self._ring = [None] * self.capacity
            self._next = 0
            self._seq = 0


STREAM_EVENTS = StreamEventRing()

# track (pid, name) layout of the merged timeline
_PID_SPANS = 1
_PID_ROUNDS = 2
_PID_FLIGHT = 3
_PID_STREAM = 4
_TRACK_NAMES = {
    _PID_SPANS: "request spans",
    _PID_ROUNDS: "engine host rounds",
    _PID_FLIGHT: "flight recorder",
    _PID_STREAM: "kv_transfer streams",
}


def _us(unix_s: float) -> int:
    return int(unix_s * 1e6)


def _span_events(span: dict[str, Any], tid: int,
                 out: list[dict[str, Any]]) -> None:
    """One span dict (telemetry.trace.Span.to_dict form) + children →
    nested ``X`` events on one track (Chrome nests by time containment)."""
    start = float(span.get("start_s", 0.0))
    dur = max(float(span.get("duration_s", 0.0)), 0.0)
    out.append({
        "ph": "X", "pid": _PID_SPANS, "tid": tid,
        "ts": _us(start), "dur": max(_us(start + dur) - _us(start), 1),
        "name": str(span.get("name", "span")), "cat": "span",
        "args": dict(span.get("attrs") or {}),
    })
    for child in span.get("children") or []:
        _span_events(child, tid, out)


def _round_events(records: list[tuple],
                  out: list[dict[str, Any]]) -> None:
    """RoundProf ring records (end_unix_s, wall_s, per-seg seconds) →
    one ``host_round`` event per round with sequential per-segment
    children in enum order (the flat switch model keeps totals, not
    intervals — within-round layout is therefore approximate; the
    durations are exact)."""
    for end_s, wall_s, acc in records:
        start = end_s - wall_s
        out.append({
            "ph": "X", "pid": _PID_ROUNDS, "tid": 1,
            "ts": _us(start), "dur": max(_us(end_s) - _us(start), 1),
            "name": "host_round", "cat": "round",
            "args": {
                "wall_us": round(wall_s * 1e6, 1),
                **{s: round(acc[i] * 1e6, 1)
                   for i, s in enumerate(SEGMENTS) if acc[i] > 0},
            },
        })
        t = start
        for i, seg in enumerate(SEGMENTS):
            d = acc[i]
            if d <= 0.0:
                continue
            out.append({
                "ph": "X", "pid": _PID_ROUNDS, "tid": 2,
                "ts": _us(t), "dur": max(int(d * 1e6), 1),
                "name": seg, "cat": "round_segment", "args": {},
            })
            t += d


def _flight_events(events: list[dict[str, Any]],
                   out: list[dict[str, Any]]) -> None:
    for ev in events:
        args = {k: v for k, v in ev.items()
                if k not in ("ts", "kind", "seq")}
        out.append({
            "ph": "i", "pid": _PID_FLIGHT, "tid": 1, "s": "t",
            "ts": _us(float(ev.get("ts", 0.0))),
            "name": str(ev.get("kind", "event")), "cat": "flight",
            "args": args,
        })


def _stream_events(events: list[dict[str, Any]],
                   out: list[dict[str, Any]]) -> None:
    tids: dict[str, int] = {}
    for ev in events:
        kind = str(ev.get("kind", "stream"))
        tid = tids.setdefault(kind, len(tids) + 1)
        args = {k: v for k, v in ev.items()
                if k not in ("ts", "dur_s", "kind", "seq")}
        start = float(ev.get("ts", 0.0))
        dur_us = max(int(float(ev.get("dur_s", 0.0)) * 1e6), 1)
        out.append({
            "ph": "X", "pid": _PID_STREAM, "tid": tid,
            "ts": _us(start), "dur": dur_us,
            "name": kind, "cat": "kv_stream", "args": args,
        })


def to_chrome_trace(
    spans: Optional[list[dict[str, Any]]] = None,
    round_records: Optional[list[tuple]] = None,
    flight_events: Optional[list[dict[str, Any]]] = None,
    stream_events: Optional[list[dict[str, Any]]] = None,
    label: str = "",
) -> dict[str, Any]:
    """Merge the four sources into one Trace Event Format document.
    Every argument is optional — pass what the caller has (a request's
    span dicts, a RoundProf ring snapshot, FlightRecorder.snapshot(),
    STREAM_EVENTS.snapshot())."""
    events: list[dict[str, Any]] = []
    for pid, name in _TRACK_NAMES.items():
        events.append({
            "ph": "M", "pid": pid, "tid": 0,
            "name": "process_name", "args": {"name": name},
        })
    for sp in spans or []:
        _span_events(sp, tid=1, out=events)
    _round_events(round_records or [], events)
    _flight_events(flight_events or [], events)
    _stream_events(stream_events or [], events)
    doc: dict[str, Any] = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
    }
    if label:
        doc["otherData"] = {"label": label}
    return doc


def trace_to_chrome(trace_dict: dict[str, Any],
                    **extra: Any) -> dict[str, Any]:
    """Convenience: a ``/debug/trace/{id}`` response body (Trace.to_dict
    form) → Chrome trace, optionally merged with the other sources via
    keyword passthrough to :func:`to_chrome_trace`."""
    return to_chrome_trace(
        spans=list(trace_dict.get("spans") or []),
        label=str(trace_dict.get("trace_id", "")),
        **extra,
    )


__all__ = [
    "FRAME_SEND",
    "FRAME_RECV",
    "EOF_ACK_WAIT",
    "COMMIT_WAKEUP",
    "StreamEventRing",
    "STREAM_EVENTS",
    "to_chrome_trace",
    "trace_to_chrome",
]
