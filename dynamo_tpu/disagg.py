"""Disaggregated prefill/decode — the framework's defining feature.

Reference flow (disagg_router.rs:25-120, examples/llm/components/
prefill_worker.py:157-211, utils/prefill_queue.py:27-49,
docs/architecture/disagg_serving.md:74): a decode worker receiving a
request decides — against a store-watched threshold and the global prefill
queue depth — whether to prefill locally or enqueue a RemotePrefillRequest;
a dedicated prefill worker dequeues it, runs the prefill forward pass, and
writes the KV blocks directly into the decode worker's pre-allocated
blocks; decode then continues from local KV.

TPU redesign: the KV handoff rides the block-transfer plane
(kv_transfer.py — host-staged pages over TCP, ICI-local inside a mesh) and
lands in the decode engine's *prefix cache*: the transferred blocks are
committed under their chained token-block hashes, so the decode engine's
ordinary admission path (`_try_prefill` prefix match) picks them up and
computes only the sub-page tail. That keeps the engine loop disagg-unaware
— remote prefill is a cache warmer with completion semantics — and
degrades gracefully: on any failure/timeout the request simply prefills
locally.

The prefill queue and done-notifications use the store's durable FIFO
queue ops (JetStream work-queue parity).
"""
from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Any, AsyncIterator, Optional

from dynamo_tpu.kv_transfer import (
    PageStreamWriter,
    get_descriptor,
    write_remote_pages,
)
from dynamo_tpu.kv_transfer_metrics import KV_TRANSFER
from dynamo_tpu.protocols.common import LLMEngineOutput, PreprocessedRequest
from dynamo_tpu.telemetry import timeline as tl
from dynamo_tpu.runtime.client import KvClient
from dynamo_tpu.runtime.component import DistributedRuntime
from dynamo_tpu.tokens import TokenBlockSequence

log = logging.getLogger(__name__)


def disagg_conf_key(namespace: str) -> str:
    return f"dynamo://{namespace}/_disagg/conf"


def prefill_queue_name(namespace: str) -> str:
    return f"{namespace}.prefill"


def prefill_done_queue(namespace: str, request_id: str) -> str:
    return f"{namespace}.prefill_done.{request_id}"


@dataclass
class DisaggConfig:
    """Store-watched disagg thresholds (DisaggRouterConf,
    disagg_router.rs:25-35)."""

    max_local_prefill_length: int = 512
    max_prefill_queue_size: int = 16

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "DisaggConfig":
        return cls(**json.loads(s))


async def set_disagg_config(
    kv: KvClient, namespace: str, conf: DisaggConfig
) -> None:
    await kv.put(disagg_conf_key(namespace), conf.to_json())


class DisaggConfigWatcher:
    """Live view of the disagg config (etcd-watched conf,
    disagg_router.rs:38-120). Missing key -> defaults."""

    def __init__(self, kv: KvClient, namespace: str,
                 default: Optional[DisaggConfig] = None):
        self.kv = kv
        self.namespace = namespace
        self.current = default or DisaggConfig()
        self._task: Optional[asyncio.Task] = None

    async def start(self) -> "DisaggConfigWatcher":
        watch = await self.kv.watch_prefix(disagg_conf_key(self.namespace))
        for _, v, _ in watch.initial:
            self._apply(v)
        self._task = asyncio.get_running_loop().create_task(self._follow(watch))
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _follow(self, watch) -> None:
        async for ev in watch:
            if ev.get("event") == "put":
                self._apply(ev.get("value"))

    def _apply(self, value: Optional[str]) -> None:
        if not value:
            return
        try:
            self.current = DisaggConfig.from_json(value)
            log.info("disagg config updated: %s", self.current)
        except (ValueError, TypeError):
            log.warning("bad disagg config value ignored: %r", value)


@dataclass
class RemotePrefillRequest:
    """One prefill job on the queue (RemotePrefillRequest equivalent,
    worker.py:187-196): which tokens, and which of the decode worker's
    pages to fill (block m..n of the prompt's chained blocks)."""

    request_id: str
    token_ids: list[int]
    salt: str                      # block-hash salt (= model name)
    dst_worker_id: str             # blockset descriptor key on the store
    dst_pages: list[int]           # decode-side pre-allocated page ids
    first_block: int               # transfer covers blocks [first, first+len)
    done_queue: str
    # unix time after which the decode side has given up (local fallback):
    # workers drop expired jobs instead of wasting a prefill + leaking a
    # done-queue entry nobody will pop. 0 = never expires.
    expires_at: float = 0.0

    def to_json(self) -> str:
        return json.dumps(asdict(self))

    @classmethod
    def from_json(cls, s: str) -> "RemotePrefillRequest":
        return cls(**json.loads(s))


# ---------------------------------------------------------------------------
# Prefill worker


class PrefillWorker:
    """Consumes the prefill queue: prefill locally, STREAM KV pages into
    the decode worker's pool chunk by chunk while the prefill forward is
    still computing, notify on the final frame (prefill_worker.py:157-211
    + the DistServe/Mooncake chunk-pipelined KV movement).

    The engine commits complete prefix blocks incrementally per prefill
    chunk (TpuEngine._seal_prefilled); this worker subscribes to the
    engine's COMMIT EVENT (TpuEngine.subscribe_commits — fired when a
    seal batch's pool copy is dispatched) and exports+ships each new run
    as its own stream frame — so remote-prefill TTFT approaches
    max(prefill, transfer) instead of prefill + transfer, and host
    staging is O(chunk). Engines without the event (mocks) fall back to
    the legacy fixed-cadence committed-prefix poll; either way
    ``commit_wakeups``/``timeout_wakeups``/``poll_wakeups_saved`` count
    how many poll-cadence wakeups the event plane avoided. With
    ``kv_transfer_chunk_pages == 0`` on the engine config, the legacy
    monolithic gather -> one-blob write path is used instead."""

    def __init__(
        self,
        rt: DistributedRuntime,
        engine: Any,                 # TpuEngine (needs allocator+export_pages)
        namespace: str = "dynamo",
        poll_timeout_s: float = 1.0,
        stream_poll_s: float = 0.002,
    ):
        self.rt = rt
        self.engine = engine
        self.namespace = namespace
        self.poll_timeout_s = poll_timeout_s
        # cadence of the committed-prefix poll while prefill runs
        # (fallback when the engine exposes no commit event; also the
        # unit the saved-wakeup accounting is expressed in)
        self.stream_poll_s = stream_poll_s
        self.jobs_handled = 0
        self.jobs_failed = 0
        self.jobs_expired = 0
        # commit-event accounting: wakeups driven by the engine's seal
        # event vs safety-timeout wakeups, and how many fixed-cadence
        # poll wakeups the event subscription avoided
        self.commit_wakeups = 0
        self.timeout_wakeups = 0
        self.poll_wakeups_saved = 0
        self._commit_evt: Optional[asyncio.Event] = None
        self._commit_cb: Optional[Any] = None
        # chunk-pipeline stats (bench disagg phase + tests read these):
        # transfer seconds spent while the prefill forward was STILL
        # computing count as hidden — overlap_ratio = hidden / total
        self.chunks_streamed = 0
        self.transfer_seconds_total = 0.0
        self.transfer_seconds_hidden = 0.0
        # cross-host clock-skew grace before declaring a job expired
        self.expiry_skew_s = 5.0
        self._task: Optional[asyncio.Task] = None
        self._stopping = False

    @property
    def transfer_overlap_ratio(self) -> Optional[float]:
        if self.transfer_seconds_total <= 0:
            return None
        return self.transfer_seconds_hidden / self.transfer_seconds_total

    async def start(self) -> "PrefillWorker":
        start = getattr(self.engine, "start", None)
        if start is not None:
            start()
        subscribe = getattr(self.engine, "subscribe_commits", None)
        if subscribe is not None:
            # engine-side commit event: the seal flush wakes us exactly
            # when the committed prefix grew (thread -> loop handoff)
            loop = asyncio.get_running_loop()
            evt = asyncio.Event()
            self._commit_evt = evt

            def _on_commit() -> None:
                loop.call_soon_threadsafe(evt.set)

            self._commit_cb = _on_commit
            subscribe(_on_commit)
        self._task = asyncio.get_running_loop().create_task(self._loop())
        return self

    async def stop(self) -> None:
        self._stopping = True
        if self._commit_cb is not None:
            unsub = getattr(self.engine, "unsubscribe_commits", None)
            if unsub is not None:
                unsub(self._commit_cb)
            self._commit_cb = None
        if self._task is not None:
            self._task.cancel()
            self._task = None

    async def _wait_progress(self, gen_task, pending_task) -> None:
        """Park until the committed prefix may have grown: the engine's
        commit event when subscribed (plus the prefill/export tasks and
        a safety timeout — a commit fired between waits stays latched in
        the Event), else the legacy fixed-cadence sleep. Counts how many
        fixed-cadence wakeups the event plane saved."""
        if self._commit_evt is None:
            await asyncio.sleep(self.stream_poll_s)
            return
        t0 = time.monotonic()
        evt_task = asyncio.ensure_future(self._commit_evt.wait())
        wait_set = {evt_task}
        for t in (gen_task, pending_task):
            if t is not None and not t.done():
                wait_set.add(t)
        # The safety timeout is a FALLBACK for a commit notification
        # lost between waits, not the expected wake path — but when the
        # engine batches several blocks into one seal the event can
        # legitimately lag a full fused round, and at the old
        # max(25x, 50 ms) every missed edge stalled the export stream
        # long enough to erase the chunked-streaming TTFT win entirely
        # (BENCH_r07's 0.9x regression). 5x the poll cadence floors at
        # 10 ms: late commits still coalesce, a lost edge costs at most
        # one round-ish of extra latency.
        done, _ = await asyncio.wait(
            wait_set, timeout=max(self.stream_poll_s * 5, 0.01),
            return_when=asyncio.FIRST_COMPLETED,
        )
        if evt_task in done:
            self.commit_wakeups += 1
            self._commit_evt.clear()
            wake = "commit"
        else:
            # leave the latch alone: a commit that fired while we woke
            # for a task completion must wake the NEXT wait immediately
            evt_task.cancel()
            wake = "task"
            if not done:
                self.timeout_wakeups += 1
                wake = "timeout"
        waited = time.monotonic() - t0
        tl.STREAM_EVENTS.record(tl.COMMIT_WAKEUP, waited, wake=wake)
        self.poll_wakeups_saved += max(
            0, int(waited / self.stream_poll_s) - 1
        )

    async def _loop(self) -> None:
        queue = prefill_queue_name(self.namespace)
        while not self._stopping:
            try:
                raw = await self.rt.kv.qpop(queue, timeout_s=self.poll_timeout_s)
            except (ConnectionError, OSError):
                await asyncio.sleep(0.5)
                continue
            if raw is None:
                continue
            try:
                job = RemotePrefillRequest.from_json(raw)
            except (ValueError, TypeError):
                log.warning("malformed prefill job dropped: %.200r", raw)
                continue
            if job.expires_at and time.time() > job.expires_at + self.expiry_skew_s:
                # the decode side already fell back locally: skip the
                # wasted prefill and don't push to a done queue nobody pops
                self.jobs_expired += 1
                log.info("dropping expired prefill job %s", job.request_id)
                continue
            try:
                await self._handle(job)
                self.jobs_handled += 1
            except Exception as e:  # noqa: BLE001 — report, keep consuming
                self.jobs_failed += 1
                log.exception("prefill job %s failed", job.request_id)
                try:
                    await self.rt.kv.qpush(job.done_queue, json.dumps(
                        {"ok": False, "error": str(e)}
                    ))
                except (ConnectionError, OSError):
                    pass

    async def _handle(self, job: RemotePrefillRequest) -> None:
        t0 = time.monotonic()
        ps = self.engine.ecfg.page_size
        n_blocks = job.first_block + len(job.dst_pages)
        seq = TokenBlockSequence.from_tokens(job.token_ids, ps, salt=job.salt)
        hashes = seq.block_hashes()[:n_blocks]
        chunk_pages = int(getattr(
            self.engine.ecfg, "kv_transfer_chunk_pages", 0
        ))

        # the prefill forward pass through the engine (one sampled token,
        # discarded — the decode side samples its own first token after
        # its tail prefill); the engine commits each chunk's complete
        # blocks into this worker's prefix cache AS PREFILL ADVANCES
        req = PreprocessedRequest(
            token_ids=list(job.token_ids),
            model=job.salt,
        )
        req.stop_conditions.max_tokens = 1
        req.stop_conditions.ignore_eos = True

        async def run_prefill() -> None:
            async for _ in self.engine.generate(req):
                pass

        # descriptor BEFORE prefill: the stream starts mid-compute
        desc = await get_descriptor(self.rt.kv, self.namespace,
                                    job.dst_worker_id)
        if desc is None:
            raise RuntimeError(
                f"no blockset descriptor for {job.dst_worker_id}"
            )

        chunk_spans: list[dict] = []
        overlap: Optional[float] = None
        if chunk_pages <= 0:
            n_send = await self._push_monolithic(job, hashes, run_prefill,
                                                 desc)
        else:
            n_send, chunk_spans, overlap = await self._push_stream(
                job, hashes, run_prefill, desc, chunk_pages
            )
        from dynamo_tpu.telemetry.trace import span_now

        # the prefill worker's own span (per-chunk children for the
        # streamed path), folded into the decode side's trace payload
        # (DisaggDecodeEngine.generate)
        span = span_now(
            "remote_prefill", t0,
            tokens=len(job.token_ids), blocks=n_send,
            chunks=max(len(chunk_spans), 1),
        ).to_dict()
        if chunk_spans:
            span["children"] = chunk_spans
        msg = {
            "ok": True,
            "blocks": n_send,
            "chunks": max(len(chunk_spans), 1),
            "prefill_ms": (time.monotonic() - t0) * 1e3,
            "span": span,
        }
        if overlap is not None:
            msg["overlap_ratio"] = round(overlap, 4)
        await self.rt.kv.qpush(job.done_queue, json.dumps(msg))
        log.info(
            "remote prefill %s: %d tokens, %d blocks (%d chunks) -> %s "
            "in %.1f ms (overlap %s)",
            job.request_id, len(job.token_ids), n_send,
            max(len(chunk_spans), 1), job.dst_worker_id,
            (time.monotonic() - t0) * 1e3,
            f"{overlap:.2f}" if overlap is not None else "n/a",
        )

    async def _push_monolithic(
        self, job: RemotePrefillRequest, hashes: list[int],
        run_prefill, desc,
    ) -> int:
        """Legacy path (kv_transfer_chunk_pages == 0): full prefill, one
        gather, one blob on the wire."""
        await run_prefill()
        src_pages = self.engine.allocator.match_prefix(hashes)
        try:
            # under cache pressure some blocks may already be evicted; send
            # the contiguous run we still have from first_block on
            have = src_pages[job.first_block:]
            n_send = min(len(have), len(job.dst_pages))
            if n_send == 0:
                raise RuntimeError("prefilled blocks evicted before export")
            data = await asyncio.to_thread(
                self.engine.export_pages, have[:n_send]
            )
        finally:
            self.engine.allocator.free(src_pages)
        await write_remote_pages(
            desc.host, desc.port, job.dst_pages[:n_send], data,
            job_id=job.request_id,
        )
        return n_send

    async def _push_stream(
        self, job: RemotePrefillRequest, hashes: list[int],
        run_prefill, desc, chunk_pages: int,
    ) -> tuple[int, list[dict], Optional[float]]:
        """Chunk-pipelined push: poll the committed prefix while the
        prefill forward runs; export+ship every newly complete run of
        ``chunk_pages`` blocks as one stream frame (sub-chunk remainders
        flush once prefill finishes). The decode side scatters each frame
        on arrival and its admission fires on the eof ack — transfer
        rides BEHIND compute instead of after it."""
        from dynamo_tpu.resilience.chaos import CHAOS
        from dynamo_tpu.telemetry.trace import span_now

        first = job.first_block
        n_blocks = len(hashes)
        alloc = self.engine.allocator
        gen_task = asyncio.get_running_loop().create_task(run_prefill())
        writer = PageStreamWriter(desc.host, desc.port,
                                  job_id=job.request_id)
        sent = first                   # blocks written to the wire
        chunk_spans: list[dict] = []
        xfer_total = 0.0
        xfer_hidden = 0.0
        evicted = False
        # sender-side double buffer: one export dispatched beyond the
        # chunk being written, so the gather/D2H of run i+1 overlaps run
        # i's wire drain instead of queueing behind it — without it the
        # stream falls one export+drain behind prefill per chunk and the
        # tail ships after compute ends. (lo, hi, t_start, task)
        pending: Optional[tuple] = None
        t_pf_end: Optional[float] = None  # first observation of done
        try:
            while True:
                prefill_done = gen_task.done()
                if prefill_done:
                    if t_pf_end is None:
                        t_pf_end = time.monotonic()
                    await gen_task  # surface prefill failures
                avail = min(alloc.cached_prefix_len(hashes), n_blocks)
                exported_to = pending[1] if pending is not None else sent
                if (pending is None and not evicted
                        and (avail - exported_to >= chunk_pages
                             or (prefill_done and avail > exported_to))):
                    hi = min(exported_to + chunk_pages, avail)
                    pending = (exported_to, hi, time.monotonic(),
                               asyncio.ensure_future(self._export_run(
                                   hashes, exported_to, hi)))
                    continue
                if pending is not None and pending[3].done():
                    lo, hi, tc, task = pending
                    pending = None
                    data = await task
                    if data is None:
                        evicted = True  # pressure-evicted mid-stream
                        continue
                    # dispatch the NEXT export before awaiting this
                    # chunk's socket drain — that order is the double
                    # buffer (gather/D2H of run i+1 under run i's wire
                    # time); dispatching after the drain would serialize
                    # export and wire again
                    avail = min(alloc.cached_prefix_len(hashes), n_blocks)
                    if (avail - hi >= chunk_pages
                            or (gen_task.done() and avail > hi)):
                        hi2 = min(hi + chunk_pages, avail)
                        pending = (hi, hi2, time.monotonic(),
                                   asyncio.ensure_future(self._export_run(
                                       hashes, hi, hi2)))
                    await writer.write_chunk(
                        job.dst_pages[lo - first: hi - first], data
                    )
                    now = time.monotonic()
                    dur = now - tc
                    xfer_total += dur
                    if t_pf_end is None:
                        # the whole hop ran behind prefill compute
                        xfer_hidden += dur
                    else:
                        # straddling hop: credit the portion that ran
                        # while prefill was still computing
                        xfer_hidden += min(dur, max(0.0, t_pf_end - tc))
                    chunk_spans.append(span_now(
                        "kv_chunk", tc, blocks=hi - lo, first_block=lo,
                    ).to_dict())
                    sent = hi
                    # mid-stream chaos (stall_stream): wedged-link shape —
                    # the decode side's timeout must fire and fall back
                    await CHAOS.maybe_stall(
                        "stall_stream", writer.chunks_sent)
                    continue
                if pending is None and (evicted
                                        or (prefill_done and avail <= sent)):
                    break
                await self._wait_progress(
                    gen_task, pending[3] if pending is not None else None
                )
            if sent <= first:
                raise RuntimeError("prefilled blocks evicted before export")
            # wire-time accounting fix: write_chunk's drain() returns
            # when the KERNEL buffers the bytes, not when the peer has
            # them — the tail of the stream (several chunks of socket
            # buffer on a slow link) used to drain after prefill ended
            # without being counted at all, flattering the overlap
            # ratio. The eof ack arrives only after the receiver has
            # read AND scattered every chunk, so the commit wait IS the
            # unmeasured wire tail; count it (hidden only for whatever
            # part ran before prefill finished — normally none).
            t_commit = time.monotonic()
            await writer.commit()
            tail = time.monotonic() - t_commit
            xfer_total += tail
            if t_pf_end is None:
                xfer_hidden += tail
            else:
                xfer_hidden += min(tail, max(0.0, t_pf_end - t_commit))
        finally:
            if pending is not None:
                pending[3].cancel()
            await writer.close()
            if not gen_task.done():
                gen_task.cancel()
            elif not gen_task.cancelled():
                gen_task.exception()  # retrieve, never leave it unread
        self.chunks_streamed += len(chunk_spans)
        self.transfer_seconds_total += xfer_total
        self.transfer_seconds_hidden += xfer_hidden
        overlap = xfer_hidden / xfer_total if xfer_total > 0 else None
        return sent - first, chunk_spans, overlap

    async def _export_run(
        self, hashes: list[int], lo: int, hi: int
    ):
        """Pin + gather blocks [lo, hi) of the chained run; None when the
        run is no longer fully committed (evicted under pressure).

        The gather goes through export_pages_stream, not export_pages:
        the engine loop dispatches the gather with an ASYNC D2H copy and
        keeps running prefill rounds while the copy completes (this
        worker thread blocks on the chunk queue, which is fine) — a
        synchronous export would stall the forward pass once per chunk
        and eat the very overlap the stream exists to create."""

        def pin_and_export():
            pages = self.engine.allocator.match_prefix(hashes[:hi])
            try:
                if len(pages) < hi:
                    return None
                return next(iter(self.engine.export_pages_stream(
                    pages[lo:hi], chunk_pages=hi - lo,
                )))
            finally:
                self.engine.allocator.free(pages)

        return await asyncio.to_thread(pin_and_export)


# ---------------------------------------------------------------------------
# Decode-side wrapper


class DisaggDecodeEngine:
    """AsyncEngine wrapper adding the conditional-disagg decision to a
    TpuEngine (worker.py:199-248 VllmWorker.generate decision point).

    remote iff  (prompt_len − cached_prefix_tokens) > max_local_prefill_length
            and prefill_queue_len < max_prefill_queue_size
    (multimodal/components/disagg_router.py:48-66). On the remote path the
    transferred blocks enter the local prefix cache before admission, so the
    wrapped engine computes only the sub-page tail."""

    def __init__(
        self,
        engine: Any,
        rt: DistributedRuntime,
        namespace: str = "dynamo",
        worker_id: str = "",
        conf: Optional[DisaggConfigWatcher] = None,
        prefill_timeout_s: float = 60.0,
    ):
        self.engine = engine
        self.rt = rt
        self.namespace = namespace
        self.worker_id = worker_id
        self.conf = conf
        self.prefill_timeout_s = prefill_timeout_s
        self._draining = False
        # live remote-prefill jobs: a write for a job not in here is
        # REJECTED — protects against a stale queued job scribbling over
        # pages that were freed on fallback and reallocated to another
        # request. The lock guards only set membership (never held across
        # device I/O); a fallback racing an in-flight write defers the page
        # free to the writer.
        self._jobs_lock = threading.Lock()
        self._pending_jobs: set[str] = set()
        self._in_write: set[str] = set()
        self._deferred_free: dict[str, list[int]] = {}
        # counters (exposed via metrics/tests); fallbacks also feed the
        # dynamo_disagg_fallback_total series (kv_transfer_metrics)
        self.remote_prefills = 0
        self.local_prefills = 0
        self.remote_fallbacks = 0
        self.last_transfer_chunks = 0
        self.last_overlap_ratio: Optional[float] = None
        # prefill-worker spans shipped back on the done queue, keyed by
        # request id until generate() folds them into the trace payload
        self._remote_spans: dict[str, dict] = {}

    # engine delegation so register_llm/serve_engine treat us as the engine
    @property
    def allocator(self):
        return self.engine.allocator

    @property
    def flight(self):
        """Flight recorder passthrough: /debug/flight must keep working
        when the system server holds this wrapper, not the TpuEngine."""
        return getattr(self.engine, "flight", None)

    @property
    def on_metrics(self):
        return self.engine.on_metrics

    @on_metrics.setter
    def on_metrics(self, sink):
        self.engine.on_metrics = sink

    def start(self) -> None:
        start = getattr(self.engine, "start", None)
        if start is not None:
            start()

    # graceful-drain passthrough (resilience/drain.py contract): the
    # DrainController holds this wrapper when the worker runs disagg.
    # The wrapper keeps its own flag so generate() rejects BEFORE the
    # remote-prefill decision — otherwise a draining worker would pay a
    # full cross-worker KV transfer for a request it then refuses.
    def begin_drain(self) -> None:
        self._draining = True
        begin = getattr(self.engine, "begin_drain", None)
        if begin is not None:
            begin()

    def drained(self) -> bool:
        fn = getattr(self.engine, "drained", None)
        return bool(fn()) if fn is not None else True

    async def stop(self) -> None:
        await self.engine.stop()

    def metrics(self):
        return self.engine.metrics()

    def guarded_import(self, pages, data, job_id=None) -> None:
        """Transfer-server write hook: scatter only while the job is still
        pending (write_fn contract in kv_transfer.py). The scatter runs
        OUTSIDE the jobs lock — holding it across device I/O would stall
        the event loop's own lock acquisitions for the whole transfer."""
        if job_id is None:
            self.engine.import_pages(pages, data)
            return
        with self._jobs_lock:
            if job_id not in self._pending_jobs:
                raise RuntimeError(f"job {job_id} cancelled; write rejected")
            self._in_write.add(job_id)
        try:
            self.engine.import_pages(pages, data)
        finally:
            with self._jobs_lock:
                self._in_write.discard(job_id)
                late_free = self._deferred_free.pop(job_id, None)
            if late_free is not None:
                # fallback cancelled mid-write: the write landed in pages
                # still held for this job; release them now (uncommitted ->
                # straight back to the free list)
                self.engine.allocator.free(late_free)

    async def generate(
        self, request: PreprocessedRequest
    ) -> AsyncIterator[LLMEngineOutput]:
        from dynamo_tpu.telemetry.trace import span_now

        if self._draining:
            from dynamo_tpu.resilience.drain import WorkerDrainingError

            raise WorkerDrainingError(
                "worker draining: not admitting new requests"
            )
        t0 = time.monotonic()
        spans: list = []
        if await self._maybe_remote_prefill(request):
            self.remote_prefills += 1
            # trace the remote KV transfer: injected into the finishing
            # output's span payload so the frontend's span tree carries
            # it alongside the engine's queue/prefill spans. The prefill
            # worker's own remote_prefill span (shipped back on the done
            # queue) rides along, so the remote hop is visible
            # end-to-end in /debug/trace/{request_id}.
            spans.append(span_now("disagg_kv_transfer", t0).to_dict())
            remote_span = self._remote_spans.pop(request.request_id, None)
            if remote_span:
                spans.append(remote_span)
        else:
            self.local_prefills += 1
            self._remote_spans.pop(request.request_id, None)
        async for out in self.engine.generate(request):
            if spans and out.finish_reason is not None:
                tr = out.annotations.setdefault("trace", {})
                tr["spans"] = spans + tr.get("spans", [])
            yield out

    async def _should_remote(self, request: PreprocessedRequest,
                             n_cached_blocks: int) -> bool:
        conf = self.conf.current if self.conf else DisaggConfig()
        ps = self.engine.ecfg.page_size
        effective = len(request.token_ids) - n_cached_blocks * ps
        if effective <= conf.max_local_prefill_length:
            return False
        try:
            qlen = await self.rt.kv.qlen(prefill_queue_name(self.namespace))
        except (ConnectionError, OSError):
            return False
        return qlen < conf.max_prefill_queue_size

    async def _maybe_remote_prefill(self, request: PreprocessedRequest) -> bool:
        """Try the remote path; True if the prefix cache was warmed
        remotely. Any failure falls back to local prefill."""
        alloc = self.engine.allocator
        ps = self.engine.ecfg.page_size
        tokens = request.token_ids
        n_blocks = max(0, (len(tokens) - 1) // ps)
        if n_blocks == 0:
            return False
        seq = TokenBlockSequence.from_tokens(tokens, ps, salt=request.model)
        hashes = seq.block_hashes()[:n_blocks]

        # blocks already cached locally need no transfer (stat-neutral peek
        # — the engine's admission match does the counted lookup)
        m = alloc.cached_prefix_len(hashes)
        if not await self._should_remote(request, m):
            return False
        if m >= n_blocks:
            return False

        dst = alloc.allocate(n_blocks - m)
        if dst is None:
            return False  # no room: let admission/preemption deal with it
        rid = request.request_id
        done_q = prefill_done_queue(self.namespace, rid)
        job = RemotePrefillRequest(
            request_id=rid,
            token_ids=list(tokens),
            salt=request.model,
            dst_worker_id=self.worker_id,
            dst_pages=dst,
            first_block=m,
            done_queue=done_q,
            expires_at=time.time() + self.prefill_timeout_s,
        )
        with self._jobs_lock:
            self._pending_jobs.add(rid)
        settled = False  # success path freed/committed dst itself
        try:
            await self.rt.kv.qpush(prefill_queue_name(self.namespace),
                                   job.to_json())
            raw = await self.rt.kv.qpop(
                done_q, timeout_s=self.prefill_timeout_s
            )
            resp = json.loads(raw) if raw else None
            if not resp or not resp.get("ok"):
                raise RuntimeError(
                    (resp or {}).get("error", "remote prefill timed out")
                )
            n_got = int(resp.get("blocks", 0))
            self.last_transfer_chunks = int(resp.get("chunks", 1))
            self.last_overlap_ratio = resp.get("overlap_ratio")
            if resp.get("span"):
                self._remote_spans[rid] = resp["span"]
            with self._jobs_lock:
                self._pending_jobs.discard(rid)
            # commit the transferred blocks under their chained hashes; the
            # engine's admission prefix-match picks them up
            committed = []
            for pg, blk in zip(dst[:n_got], seq.blocks[m:m + n_got]):
                if alloc.commit(pg, blk.block_hash, blk.parent_hash):
                    committed.append(pg)
            alloc.free(dst)  # committed pages park in LRU; rest return free
            settled = True
            return bool(committed)
        except Exception:  # noqa: BLE001 — disagg is best-effort
            self.remote_fallbacks += 1
            # scraped as dynamo_disagg_fallback_total on every surface
            KV_TRANSFER.inc("dynamo_disagg_fallback_total")
            log.exception("remote prefill failed for %s; local fallback", rid)
            return False
        finally:
            if not settled:
                # runs for BOTH the except path and CancelledError (client
                # dropped while awaiting the done queue): cancel the job and
                # release its pages exactly once. If a guarded write is in
                # flight, the writer frees them after its scatter.
                with self._jobs_lock:
                    self._pending_jobs.discard(rid)
                    if rid in self._in_write:
                        self._deferred_free[rid] = dst
                        dst = None
                if dst is not None:
                    alloc.free(dst)
